"""AOT-compile (no run) the jitted CPC round at a given width.

Separates compile cost from the heavy LBFGS runtime: builds the same
round fn CPCTrainer.run uses, lowers it with real-shaped abstract args,
and times .compile() alone.

Usage: python artifacts/probe_cpc_aot.py <Lc> [batch] [Niter] [mdl] [ci]
"""
import sys
import time

import jax
import numpy as np

from federated_pytorch_test_tpu.data.lofar import CPCDataSource
from federated_pytorch_test_tpu.train.cpc_engine import CPCTrainer
from federated_pytorch_test_tpu.utils.compile_cache import (
    enable_persistent_compile_cache,
)

Lc = int(sys.argv[1]) if len(sys.argv) > 1 else 256
batch = int(sys.argv[2]) if len(sys.argv) > 2 else 128
niter = int(sys.argv[3]) if len(sys.argv) > 3 else 10
mdl = sys.argv[4] if len(sys.argv) > 4 else "encoder"
ci = int(sys.argv[5]) if len(sys.argv) > 5 else 0

enable_persistent_compile_cache()
src = CPCDataSource([f"bench{i}.h5" for i in range(4)], ["0"] * 4,
                    batch_size=batch, patch_size=32)
trainer = CPCTrainer(src, latent_dim=Lc, reduced_dim=32,
                     lbfgs_history=7, lbfgs_max_iter=2, Niter=niter,
                     num_devices=1)
px, py, data = src.round_batches(niter)
print(f"data shape {data.shape} px={px} py={py}", flush=True)

fn, init_fn, N = trainer._build_round(mdl, ci, px, py)
state = trainer.state0

t0 = time.perf_counter()
opt_shape = jax.eval_shape(init_fn, state)
z = jax.ShapeDtypeStruct((N,), np.float32)
lowered = fn.lower(state, z, opt_shape, jax.ShapeDtypeStruct(
    data.shape, np.float32))
print(f"lowered in {time.perf_counter() - t0:.1f}s", flush=True)
t0 = time.perf_counter()
lowered.compile()
print(f"COMPILED {mdl}/{ci} Lc={Lc} B={batch} Niter={niter} N={N} "
      f"in {time.perf_counter() - t0:.1f}s", flush=True)
