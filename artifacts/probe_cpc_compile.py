"""Isolate which piece of the CPC graph compiles pathologically on TPU.

Usage: python artifacts/probe_cpc_compile.py <piece> <Lc> [batch]

Pieces: enc_fwd, enc_grad, stem_fwd, stem_grad, trunk_fwd, trunk_grad,
        full_fwd, full_grad
Each run jits ONE piece and prints the compile wall-clock; the caller
bounds it with a subprocess timeout so a >20 min pathological compile
just shows up as a kill.
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from federated_pytorch_test_tpu.models.cpc import (
    ContextgenCNN,
    EncoderCNN,
    PredictorCNN,
)
from federated_pytorch_test_tpu.utils.compile_cache import (
    enable_persistent_compile_cache,
)

piece = sys.argv[1]
Lc = int(sys.argv[2])
batch = int(sys.argv[3]) if len(sys.argv) > 3 else 128
Rc = 32

rng = jax.random.PRNGKey(0)
x = jnp.asarray(np.random.default_rng(0).normal(size=(batch, 32, 32, 8)),
                jnp.float32)

enc = EncoderCNN(latent_dim=Lc)
enc_p, _ = enc.init_variables(rng, x)


import flax.linen as _nn


class Stem(EncoderCNN):
    """Just the five dilated convs + concat."""

    @_nn.compact
    def __call__(self, x, train=True):  # noqa: D102
        import flax.linen as nn

        from federated_pytorch_test_tpu.models.base import elu
        from federated_pytorch_test_tpu.models.cpc import _pad
        xs = []
        for d, p in ((1, 1), (2, 3), (4, 6), (8, 12), (16, 24)):
            xs.append(elu(nn.Conv(8, (4, 4), strides=(2, 2),
                                  kernel_dilation=(d, d), padding=_pad(p),
                                  name=f"conv1_{d}")(x)))
        return jnp.concatenate(xs, axis=-1)


class Trunk(EncoderCNN):
    """conv2..conv4 + pool on a pre-made [B,16,16,40] input."""

    @_nn.compact
    def __call__(self, x, train=True):  # noqa: D102
        import flax.linen as nn

        from federated_pytorch_test_tpu.models.base import elu
        from federated_pytorch_test_tpu.models.cpc import _pad
        x = elu(nn.Conv(self.latent_dim // 4, (4, 4), strides=(2, 2),
                        padding=_pad(1), name="conv2")(x))
        x = elu(nn.Conv(self.latent_dim // 2, (4, 4), strides=(2, 2),
                        padding=_pad(1), name="conv3")(x))
        x = elu(nn.Conv(self.latent_dim, (4, 4), strides=(2, 2),
                        padding=_pad(1), name="conv4")(x))
        x = nn.avg_pool(x, window_shape=(2, 2), strides=(2, 2))
        return x.reshape((x.shape[0], -1))


def timed(tag, fn, *args):
    enable_persistent_compile_cache()
    t0 = time.perf_counter()
    r = jax.block_until_ready(jax.jit(fn)(*args))
    # relay block_until_ready may not block; force host fetch
    jax.tree.map(np.asarray, r)
    print(f"{tag}: compile+run {time.perf_counter() - t0:.1f}s",
          flush=True)


if piece == "enc_fwd":
    timed(f"enc_fwd Lc={Lc} B={batch}",
          lambda p, x: enc.apply({"params": p}, x), enc_p, x)
elif piece == "enc_grad":
    timed(f"enc_grad Lc={Lc} B={batch}",
          jax.grad(lambda p, x: enc.apply({"params": p}, x).sum()), enc_p, x)
elif piece in ("stem_fwd", "stem_grad"):
    stem = Stem(latent_dim=Lc)
    sp, _ = stem.init_variables(rng, x)
    f = lambda p, x: stem.apply({"params": p}, x)  # noqa: E731
    if piece == "stem_grad":
        f = jax.grad(lambda p, x: stem.apply({"params": p}, x).sum())
    timed(f"{piece} Lc={Lc} B={batch}", f, sp, x)
elif piece in ("trunk_fwd", "trunk_grad"):
    trunk = Trunk(latent_dim=Lc)
    xt = jnp.zeros((batch, 16, 16, 40), jnp.float32)
    tp, _ = trunk.init_variables(rng, xt)
    f = lambda p, x: trunk.apply({"params": p}, x)  # noqa: E731
    if piece == "trunk_grad":
        f = jax.grad(lambda p, x: trunk.apply({"params": p}, x).sum())
    timed(f"{piece} Lc={Lc} B={batch}", f, tp, xt)
elif piece in ("full_fwd", "full_grad"):
    # encoder -> grid reshape -> contextgen -> predictor -> InfoNCE
    from federated_pytorch_test_tpu.ops.infonce import info_nce_fused

    ctx = ContextgenCNN(latent_dim=Lc)
    pred = PredictorCNN(latent_dim=Lc, reduced_dim=Rc)
    px = py = 4
    lat0 = jnp.zeros((batch // (px * py), px, py, Lc), jnp.float32)
    ctx_p, _ = ctx.init_variables(rng, lat0)
    pred_p, _ = pred.init_variables(rng, lat0, lat0)

    def loss(params, x):
        ep, cp, pp = params
        lat = enc.apply({"params": ep}, x)
        lat = lat.reshape((-1, px, py, Lc))
        c = ctx.apply({"params": cp}, lat)
        rl, pr = pred.apply({"params": pp}, lat, c)
        return info_nce_fused(rl, pr)

    f = loss if piece == "full_fwd" else jax.grad(loss)
    timed(f"{piece} Lc={Lc} B={batch}", f, (enc_p, ctx_p, pred_p), x)
else:
    raise SystemExit(f"unknown piece {piece}")
