"""Compile+run ONE full CPC rotation at the given width on the live
backend, printing per-round progress so a pathological compile is
attributable to a specific (model, block) round.

Usage: python artifacts/probe_cpc_round.py <Lc> [batch] [Niter]
"""
import sys
import time

from federated_pytorch_test_tpu.data.lofar import CPCDataSource
from federated_pytorch_test_tpu.train.cpc_engine import CPCTrainer
from federated_pytorch_test_tpu.utils.compile_cache import (
    enable_persistent_compile_cache,
)

Lc = int(sys.argv[1]) if len(sys.argv) > 1 else 256
batch = int(sys.argv[2]) if len(sys.argv) > 2 else 128
niter = int(sys.argv[3]) if len(sys.argv) > 3 else 10

enable_persistent_compile_cache()
src = CPCDataSource([f"bench{i}.h5" for i in range(4)], ["0"] * 4,
                    batch_size=batch, patch_size=32)
trainer = CPCTrainer(src, latent_dim=Lc, reduced_dim=32,
                     lbfgs_history=7, lbfgs_max_iter=2, Niter=niter,
                     num_devices=1)
t0 = time.perf_counter()


def log(m):
    print(f"[{time.perf_counter() - t0:7.1f}s] {m}", flush=True)


_, hist = trainer.run(Nloop=1, Nadmm=1, log=log)
print(f"DONE rotation Lc={Lc} B={batch}: {time.perf_counter() - t0:.1f}s "
      f"({len(hist)} rounds)", flush=True)
