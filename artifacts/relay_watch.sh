#!/bin/bash
# Poll the axon relay; at the first healthy window run (1) the
# reference-width CPC round AOT-compile probe, (2) a fresh full bench.
# Each step bounded; output under artifacts/.
cd /root/repo
for i in $(seq 1 90); do
  if timeout 60 python -c "import jax; assert jax.devices()[0].platform == 'tpu'" 2>/dev/null; then
    echo "$(date -u +%H:%M:%S) relay healthy (attempt $i)" >> artifacts/relay_watch.log
    echo "== AOT probe Lc=256" >> artifacts/relay_watch.log
    PYTHONPATH=/root/repo:/root/.axon_site timeout 1500 python artifacts/probe_cpc_aot.py 256 128 10 encoder 0 >> artifacts/relay_watch.log 2>&1
    echo "rc=$?" >> artifacts/relay_watch.log
    echo "== bench attempt 2" >> artifacts/relay_watch.log
    timeout 5400 python bench.py > artifacts/bench_r05_attempt2.out 2> artifacts/bench_r05_attempt2.err
    echo "bench rc=$?" >> artifacts/relay_watch.log
    exit 0
  fi
  echo "$(date -u +%H:%M:%S) relay wedged (attempt $i)" >> artifacts/relay_watch.log
  sleep 240
done
echo "gave up after 90 attempts" >> artifacts/relay_watch.log
