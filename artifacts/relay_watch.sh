#!/bin/bash
# Poll the axon relay; at the first healthy window run the
# reference-width CPC AOT probe and a fresh bench (reference CPC width
# if the probe passed).  DEADLINE: no new work STARTS after 14:15 UTC so
# a late recovery cannot contend with the driver's end-of-round bench
# (checked before the probe AND again before the bench launch).
cd /root/repo
DEADLINE=$(date -u -d "today 14:15" +%s 2>/dev/null || echo 0)
past_deadline() {
  [ "$DEADLINE" != 0 ] && [ "$(date -u +%s)" -gt "$DEADLINE" ]
}
for i in $(seq 1 90); do
  if past_deadline; then
    echo "$(date -u +%H:%M:%S) deadline passed; watcher exiting" >> artifacts/relay_watch.log
    exit 0
  fi
  if timeout 60 python -c "import jax; assert jax.devices()[0].platform == 'tpu'" 2>/dev/null; then
    echo "$(date -u +%H:%M:%S) relay healthy (attempt $i)" >> artifacts/relay_watch.log
    echo "== AOT probe Lc=256" >> artifacts/relay_watch.log
    CPC_ENV=""
    if PYTHONPATH=/root/repo:/root/.axon_site timeout 1200 python artifacts/probe_cpc_aot.py 256 128 10 encoder 0 >> artifacts/relay_watch.log 2>&1; then
      CPC_ENV="FEDTPU_BENCH_CPC_LC=256 FEDTPU_BENCH_CPC_BATCH=128"
    fi
    if past_deadline; then
      echo "deadline passed after probe; skipping bench" >> artifacts/relay_watch.log
      exit 0
    fi
    echo "== bench (${CPC_ENV:-reduced width})" >> artifacts/relay_watch.log
    env $CPC_ENV timeout 5400 python bench.py > artifacts/bench_r05_attempt2.out 2> artifacts/bench_r05_attempt2.err
    echo "bench rc=$?" >> artifacts/relay_watch.log
    exit 0
  fi
  echo "$(date -u +%H:%M:%S) relay wedged (attempt $i)" >> artifacts/relay_watch.log
  sleep 240
done
echo "gave up after 90 attempts" >> artifacts/relay_watch.log
