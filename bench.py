"""Headline benchmark: federated CIFAR10 training throughput on TPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

The reference publishes no quantitative numbers (BASELINE.md); the driver-set
target is >=5,000 CIFAR10 images/sec/chip for the consensus ResNet18 config
(BASELINE.json), so ``vs_baseline`` is value / 5000.

Measures the real production path — the jitted shard_map training epoch of
the ADMM-consensus ResNet18 driver (local Adam steps + masked block grads)
with data staged once — on however many chips are visible (1 under axon).
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np

TARGET = 5000.0  # images/sec/chip (BASELINE.json north star)


def main():
    from federated_pytorch_test_tpu.data.cifar10 import FederatedCifar10
    from federated_pytorch_test_tpu.models.resnet import ResNet18
    from federated_pytorch_test_tpu.train import (
        AdmmConsensus,
        BlockwiseFederatedTrainer,
        FederatedConfig,
    )

    import jax.numpy as jnp

    n_chips = len(jax.devices())
    K = 16 * n_chips                    # 16 clients per chip (throughput knee)
    batch = 128
    steps = 8                           # minibatches per client per epoch

    cfg = FederatedConfig(K=K, default_batch=batch, check_results=False,
                          use_resnet=True, admm_rho0=0.1, bf16=True)
    data = FederatedCifar10(K=K, batch=batch,
                            limit_per_client=steps * batch, limit_test=batch)
    # bf16 conv/dense compute (params, BN and head stay f32) feeds the MXU
    # at full rate: ~1.5x over f32 on v5e
    trainer = BlockwiseFederatedTrainer(ResNet18(dtype=jnp.bfloat16), cfg,
                                        data, AdmmConsensus())

    ci = 0                              # first ResNet block (stem): N=1856
    train_epoch, comm_fns, init_opt = trainer._build_fns(ci)
    N = trainer.block_size(ci)
    state = trainer.init_state()
    state = state._replace(opt_state=init_opt(state.params))
    from federated_pytorch_test_tpu.parallel.mesh import client_sharding
    csh = client_sharding(trainer.mesh)
    rsh = jax.sharding.NamedSharding(trainer.mesh, jax.sharding.PartitionSpec())
    z = jax.device_put(jnp.zeros((N,), jnp.float32), rsh)
    y = jax.device_put(jnp.zeros((K, N), jnp.float32), csh)
    rho = jax.device_put(jnp.float32(cfg.admm_rho0), rsh)
    xb, yb = trainer._stage_epoch()
    keys = trainer._epoch_keys()

    def epoch(state):
        return train_epoch(state, y, trainer.client_mean, keys, xb, yb, z, rho)

    # warm-up / compile.  NOTE: under the axon relay block_until_ready does
    # not actually block, so benchmarks must force a host fetch of a value
    # that depends on the full computation.
    state, losses = epoch(state)
    np.asarray(losses)

    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        state, losses = epoch(state)
    np.asarray(losses)          # sync: losses depend on every local step
    dt = time.perf_counter() - t0

    images = reps * K * steps * batch
    per_chip = images / dt / n_chips
    print(json.dumps({
        "metric": "cifar10_resnet18_consensus_train_throughput",
        "value": round(per_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / TARGET, 3),
    }))


if __name__ == "__main__":
    main()
