"""Headline benchmark: federated CIFAR10 training throughput on TPU.

Prints ONE JSON line with the headline metric plus characterization fields:

  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N,
   "full_round_ips_chip": N, "big_block_ips_chip": N, "big_block_N": N,
   "mfu": N, "chip": "...", "infonce_pallas_us": N, "infonce_xla_us": N,
   "infonce_speedup": N}

(the infonce_* fields — the Pallas-fused CPC loss kernel vs its XLA path,
ops/infonce.py — appear only on TPU and are try/except-guarded so they can
never break the headline artifact)

The reference publishes no quantitative numbers (BASELINE.md); the driver-set
target is >=5,000 CIFAR10 images/sec/chip for the consensus ResNet18 config
(BASELINE.json), so ``vs_baseline`` is value / 5000.

Three measurements on the real production path (jitted shard_map epoch of the
ADMM-consensus ResNet18 driver), all with data staged once:

  * headline: local-epoch throughput on the stem block ci=0 (N=1,856) — the
    same sliver round 1/2 measured, kept for cross-round comparability;
  * big block: the LARGEST ResNet18 partition (reference block [54,59],
    N=4,720,640 of 11.2M params, resnet18_partition consensus path) —
    masked grads + Adam epoch on a communication-heavy block;
  * full consensus round: Nepoch local epoch + ADMM comm round (psum
    average, dual update, z write-back).  Data is staged once and PRNG
    keys reused, so per-epoch host->device staging is NOT in this number
    (a production round additionally pays one uint8 epoch copy).

MFU is computed from the analytic ResNet18 model-FLOP count against the
chip's peak bf16 rate (XLA's cost_analysis undercounts fused TPU
convolutions ~13x here and recompiling the executable to query it blows
the bench's time budget, so it is not used).
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np

TARGET = 5000.0  # images/sec/chip (BASELINE.json north star)

# peak dense bf16 FLOP/s per chip by device kind (public spec sheets);
# default is TPU v5e
_PEAK_BF16 = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "")
    for k, v in _PEAK_BF16.items():
        if kind.startswith(k):
            return v
    return 197e12


def main():
    # the bench is compile-dominated (3 block specialisations of the
    # ResNet18 epoch); share the persistent cache across driver runs
    from federated_pytorch_test_tpu.utils.compile_cache import (
        enable_persistent_compile_cache,
    )

    enable_persistent_compile_cache()
    from federated_pytorch_test_tpu.data.cifar10 import FederatedCifar10
    from federated_pytorch_test_tpu.models.resnet import ResNet18
    from federated_pytorch_test_tpu.parallel.mesh import client_sharding
    from federated_pytorch_test_tpu.train import (
        AdmmConsensus,
        BlockwiseFederatedTrainer,
        FederatedConfig,
    )

    import jax.numpy as jnp

    n_chips = len(jax.devices())
    K = 16 * n_chips                    # 16 clients per chip (throughput knee)
    batch = 128
    steps = 8                           # minibatches per client per epoch

    cfg = FederatedConfig(K=K, default_batch=batch, check_results=False,
                          use_resnet=True, admm_rho0=0.1, bf16=True)
    data = FederatedCifar10(K=K, batch=batch,
                            limit_per_client=steps * batch, limit_test=batch)
    # bf16 conv/dense compute (params, BN and head stay f32) feeds the MXU
    # at full rate: ~1.5x over f32 on v5e
    trainer = BlockwiseFederatedTrainer(ResNet18(dtype=jnp.bfloat16), cfg,
                                        data, AdmmConsensus())

    csh = client_sharding(trainer.mesh)
    rsh = jax.sharding.NamedSharding(trainer.mesh, jax.sharding.PartitionSpec())
    xb, yb, wb = trainer._stage_epoch()
    keys = trainer._epoch_keys()
    images_per_epoch = K * steps * batch

    def bench_block(ci, reps=5, with_comm=False):
        """images/sec/chip for block ci's local epoch; when ``with_comm``
        also runs the ADMM comm round (+write-back) each rep."""
        train_epoch, comm_fns, init_opt = trainer._build_fns(ci)
        N = trainer.block_size(ci)
        state = trainer.init_state()
        state = state._replace(opt_state=init_opt(state.params))
        z = jax.device_put(jnp.zeros((N,), jnp.float32), rsh)
        y = jax.device_put(jnp.zeros((K, N), jnp.float32), csh)
        rho = jax.device_put(jnp.float32(cfg.admm_rho0), rsh)
        x0 = jax.device_put(jnp.zeros((K, 1), jnp.float32), csh)
        yhat0 = jax.device_put(jnp.zeros((K, 1), jnp.float32), csh)

        def round_(state, z, y, rho):
            state, losses = train_epoch(state, y, trainer.client_norm, keys,
                                        xb, yb, wb, z, rho)
            diag = None
            if with_comm:
                state, z, y, rho, _, _, diag = comm_fns["plain"](
                    state, z, y, rho, x0, yhat0)
            return state, z, y, rho, losses, diag

        # warm-up / compile.  NOTE: under the axon relay block_until_ready
        # does not actually block; force a host fetch of a value that
        # depends on the full computation instead.
        state, z, y, rho, losses, diag = round_(state, z, y, rho)
        np.asarray(losses)
        if diag is not None:
            jax.tree.map(np.asarray, diag)

        t0 = time.perf_counter()
        for _ in range(reps):
            state, z, y, rho, losses, diag = round_(state, z, y, rho)
        np.asarray(losses)          # sync: depends on every local step
        if diag is not None:
            jax.tree.map(np.asarray, diag)
        dt = time.perf_counter() - t0
        return reps * images_per_epoch / dt / n_chips

    # block sizes across the sweep; biggest = reference block [54,59]
    sizes = [trainer.block_size(ci) for ci in range(trainer.L)]
    big_ci = int(np.argmax(sizes))

    headline = bench_block(0)
    big_block = bench_block(big_ci)
    full_round = bench_block(big_ci, with_comm=True)

    def bench_infonce():
        """Pallas-fused vs XLA InfoNCE forward (ops/infonce.py) at a
        grid-spanning shape (P=256 -> two row tiles); microseconds/call."""
        from federated_pytorch_test_tpu.ops.infonce import (
            force_infonce_impl,
            info_nce_fused,
        )

        rng = np.random.default_rng(0)
        z = jnp.asarray(rng.normal(size=(16, 16, 16, 32)).astype(np.float32))
        zh = jnp.asarray(rng.normal(size=(16, 16, 16, 32)).astype(np.float32))
        out = {}
        for impl in ("pallas", "xla"):
            with force_infonce_impl(impl):
                # fresh lambda per impl: JAX's jaxpr cache is keyed on the
                # raw function object and does not see _FORCE_IMPL, so
                # jitting info_nce_fused directly would reuse the first
                # impl's trace for both timings
                fn = jax.jit(lambda a, b: info_nce_fused(a, b))
                np.asarray(fn(z, zh))          # compile + sync
                t0 = time.perf_counter()
                r = None
                for _ in range(30):
                    r = fn(z, zh)
                np.asarray(r)                  # host fetch = real sync
                out[impl] = (time.perf_counter() - t0) / 30 * 1e6
        return out

    infonce = {}
    try:                       # never let the kernel microbench break the
        if jax.default_backend() == "tpu":     # headline artifact
            t = bench_infonce()
            infonce = {"infonce_pallas_us": round(t["pallas"], 1),
                       "infonce_xla_us": round(t["xla"], 1),
                       "infonce_speedup": round(t["xla"] / t["pallas"], 3)}
    except Exception as e:
        # stderr, not stdout: the artifact stays one JSON line, but a
        # kernel regression is visible instead of reading like a CPU run
        import sys
        print(f"bench_infonce failed: {type(e).__name__}: {e}",
              file=sys.stderr)

    dev = jax.devices()[0]
    # MFU from the analytic model-FLOP count (the standard definition):
    # CIFAR ResNet18 forward ~0.56 GMAC/image (3x3 stem @32x32: 1.8 MMAC;
    # layer1 4x 3x3x64x64 @32x32: 151 MMAC; layers2-4 ~134 MMAC each after
    # the stride-2 downsamples), train step ~3x forward (fwd + 2x bwd) at
    # 2 FLOPs/MAC
    step_flops_per_image = 3 * 2 * 0.56e9
    mfu = headline * step_flops_per_image / _peak_flops(dev)

    print(json.dumps({
        "metric": "cifar10_resnet18_consensus_train_throughput",
        "value": round(headline, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(headline / TARGET, 3),
        "full_round_ips_chip": round(full_round, 1),
        "big_block_ips_chip": round(big_block, 1),
        "big_block_N": sizes[big_ci],
        "mfu": round(mfu, 4),
        "chip": getattr(dev, "device_kind", str(dev)),
        **infonce,
    }))


if __name__ == "__main__":
    main()
