"""Headline benchmark: federated CIFAR10 training throughput on TPU.

Prints ONE JSON line, ALWAYS — even when the TPU backend is unreachable
(the axon relay is known to wedge transiently; rounds 1 and 3 lost their
perf artifact to an unguarded first device query).  Backend acquisition is
a bounded subprocess probe + retry; on genuine unavailability the artifact
still appears, with an ``"error"`` field and ``value = 0``:

  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N,
   "measured": bool, "staging": "device"|"host", "stem_block_ips_chip": N,
   "big_block_ips_chip": N, "big_block_N": N, "no_consensus_ips_chip": N,
   "mfu": N, "chip": "...",
   "infonce_pallas_us": N, "infonce_xla_us": N, "infonce_speedup": N,
   "infonce_grad_pallas_us": N, "infonce_grad_xla_us": N,
   "infonce_grad_speedup": N}

``"measured"`` is True iff the headline was actually timed on a live
backend; ``value = 0, measured = false`` is the wedged-relay signature
(round 4's all-zeros artifact was misreadable as "measured 0").  An
unmeasured artifact additionally carries ``"last_measured"`` when a
previous run's TPU-measured artifact of the same headline metric exists
under artifacts/: ``{path, value, vs_baseline, metric, chip, git,
mtime}`` — describing THAT earlier run, not this one (see
:func:`_last_measured_artifact`).  When that earlier artifact was
captured at EXACTLY this clean commit (``git`` describe strings equal,
no ``-dirty``), its headline value/vs_baseline are additionally promoted
into this artifact with ``"promoted_from_artifact"`` naming the source —
identical code, so the measurement still stands; ``measured`` stays
false because nothing was timed in this run.

The reference publishes no quantitative numbers (BASELINE.md); the
driver-set target is >=5,000 CIFAR10 images/sec/chip for the consensus
ResNet18 config (BASELINE.json), so ``vs_baseline`` is value / 5000.

HEADLINE (``value``): sustained throughput of one FULL consensus round on
the largest ResNet18 partition — Nepoch=1 local epoch + ADMM collective +
dual update + z write-back, INCLUDING the per-epoch staging a production
round pays.  With the default device-resident data path (train/engine.py
``_setup_device_data``: raw uint8 shards live in HBM, each epoch is an
on-device permutation gather) staging is device-side work; datasets over
the HBM budget fall back to host shuffle + H2D copy, which this same
timed region then measures.  This is what a user of the reference's
end-to-end loop (federated_multi.py:143-220) experiences.  Side fields
characterise the parts:

  * stem_block_ips_chip: local-epoch-only throughput on the stem block
    ci=0 (N=1,856), data staged once — the sliver rounds 1-3 headlined,
    kept for cross-round comparability.  It flatters: gradient masking
    lets XLA prune most of the backward.
  * big_block_ips_chip: local-epoch-only throughput on the LARGEST
    ResNet18 partition (reference block [54,59], N=4,720,640), staged
    once.
  * no_consensus_ips_chip: full-net epoch (every parameter trainable,
    the no_consensus driver's path), staged once.

MFU is computed from ``no_consensus_ips_chip`` ONLY: with the whole net
trainable the executed graph is the full fwd + 2x bwd, so the analytic
ResNet18 model-FLOP count is the FLOPs actually executed (XLA's
cost_analysis undercounts fused TPU convolutions ~13x here, so the
analytic count is used).  Masked-block throughputs are NOT converted to
MFU — their backward is partially pruned and any full-FLOP MFU would
overstate sustained throughput (this replaces the round-2/3 headline MFU,
which multiplied the pruned stem-block rate by unpruned FLOPs).

The infonce_* fields time the Pallas-fused CPC loss kernel against its
XLA path (ops/infonce.py) — forward alone and value_and_grad (the CPC
LBFGS closure evaluates the latter, so the grad timing is the one the
training loop feels).  The cpc_* fields time one full federated-CPC
rotation (3 sub-models, every block, LBFGS closures) on synthetic LOFAR
cubes: ``cpc_rotation_seconds`` (warm) and ``cpc_patches_per_sec_chip``,
at the reduced dims recorded in ``cpc_config`` (see ``_bench_cpc`` for
why not reference width).  Both groups are TPU-only and
try/except-guarded so a workload regression can never break the
headline artifact.

Validation without a TPU: ``FEDTPU_BENCH_FORCE_CPU=1`` and
``FEDTPU_BENCH_MEASURE_ON_CPU=1`` plus the scale knobs
``FEDTPU_BENCH_CLIENTS_PER_CHIP`` / ``FEDTPU_BENCH_BATCH`` /
``FEDTPU_BENCH_STEPS`` / ``FEDTPU_BENCH_REPS`` run the FULL measurement
path at toy scale on the CPU backend (numbers meaningless, plumbing
real).
"""

from __future__ import annotations

import calendar
import json
import os
import subprocess
import sys
import time
from typing import Optional

import numpy as np

TARGET = 5000.0  # images/sec/chip (BASELINE.json north star)

_HEADLINE_METRIC = "cifar10_resnet18_consensus_full_round_throughput"

# peak dense bf16 FLOP/s per chip by device kind (public spec sheets);
# default is TPU v5e
_PEAK_BF16 = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
}

# analytic CIFAR ResNet18 step FLOPs/image: forward ~0.56 GMAC (3x3 stem
# @32x32: 1.8 MMAC; layer1 4x 3x3x64x64 @32x32: 151 MMAC; layers2-4 ~134
# MMAC each after stride-2 downsamples), train step ~3x forward (fwd +
# 2x bwd) at 2 FLOPs/MAC
_STEP_FLOPS_PER_IMAGE = 3 * 2 * 0.56e9

_PROBE = "import jax; d = jax.devices(); assert d[0].platform == 'tpu', d"

# health pre-check: plugin registration only, NO device query.  The axon
# plugin registers at interpreter startup (keyed on PALLAS_AXON_POOL_IPS);
# the r03-r05 wedge variants hang either there or at the first device
# query, so a bounded bare import distinguishes "relay answers and the
# full probe is worth its 75s budget" from "wedged before we even get a
# backend" in seconds instead of minutes.
_PRECHECK = "import jax"

#: structured relay-health record of the LAST _acquire_backend call;
#: main() embeds a copy in the artifact (``relay_status``) so a
#: ``measured: false`` artifact self-describes WHY nothing was timed
#: (r03-r05 artifacts needed session-log archaeology to distinguish a
#: wedged relay from a broken bench).  Module-level so the artifact path
#: works even though _acquire_backend returns only ``(err, probes)`` —
#: that 2-tuple contract is pinned by tests and external drivers.
_RELAY_STATUS: dict = {}


def _probe_timeout() -> float:
    """Per-probe timeout in seconds (``FEDTPU_BENCH_PROBE_TIMEOUT_S``
    overrides; default 75).  Pod-scale relays can legitimately take longer
    than the laptop-class default to hand out a backend, and the artifact
    records the value used (``probe_timeout_s``) so a timeout-tuned run is
    distinguishable from a default one."""
    return float(os.environ.get("FEDTPU_BENCH_PROBE_TIMEOUT_S", 75.0))


def _precheck_timeout() -> float:
    """Health pre-check budget in seconds (``FEDTPU_BENCH_PRECHECK_TIMEOUT_S``
    overrides; 0 disables the pre-check).  Deliberately short: a healthy
    relay answers the bare-import pre-check in low single-digit seconds,
    so 20s is generous — and a hang here is the wedged-relay signature,
    not a slow handout."""
    return float(os.environ.get("FEDTPU_BENCH_PRECHECK_TIMEOUT_S", 20.0))


#: environment prefixes that decide relay/backend behavior — the wedge
#: diagnosis snapshots these so a wedged artifact records WHICH relay the
#: process was pointed at (r03-r05 needed session-log archaeology for it)
_RELAY_ENV_PREFIXES = ("PALLAS_AXON", "JAX_", "TPU_", "XLA_")


def _diagnose_wedge(pid: int) -> dict:
    """Structured snapshot of a STILL-RUNNING hung pre-check child:
    where in the kernel it is blocked and what relay configuration it
    inherited.  Reads /proc (state, wchan, the blocked syscall number,
    thread count) — the no-ptrace equivalent of ``strace -p``, which the
    sandboxed bench box typically cannot run — plus the relay-relevant
    environment.  Every read is best-effort: the child can die between
    reads, and a partial snapshot still beats the r03-r05 situation
    (wedge closed from symptoms with zero forensics).  The caller embeds
    the dict under ``relay_status.diagnosis`` and summarizes it into
    ``relay_status.last_error``."""
    diag: dict = {"pid": pid}

    def read(name):
        try:
            with open(f"/proc/{pid}/{name}") as f:
                return f.read().strip()
        except OSError:
            return None

    status = read("status") or ""
    for line in status.splitlines():
        if line.startswith("State:"):
            diag["proc_state"] = line.split(":", 1)[1].strip()
        elif line.startswith("Threads:"):
            diag["threads"] = line.split(":", 1)[1].strip()
    # which kernel wait channel the main thread sleeps in (e.g.
    # futex_wait / unix_stream_read_generic / poll_schedule_timeout):
    # distinguishes "waiting on the relay socket" from "deadlocked on an
    # in-process lock" — THE question r03-r05 could not answer
    diag["wchan"] = read("wchan")
    # /proc/<pid>/syscall: "<nr> args... sp pc" for a blocked thread —
    # readable same-user without ptrace on most kernels
    sc = read("syscall")
    if sc:
        diag["syscall"] = sc.split()[0]
    env = read("environ")
    if env is not None:
        diag["env"] = {
            k: v for k, v in
            (kv.split("=", 1) for kv in env.split("\0") if "=" in kv)
            if k.startswith(_RELAY_ENV_PREFIXES)}
    else:
        # child env unreadable (already reaped / hardened /proc): fall
        # back to our own — the child inherited it
        diag["env"] = {k: v for k, v in os.environ.items()
                       if k.startswith(_RELAY_ENV_PREFIXES)}
    return diag


def _acquire_backend(attempts: int = 3, probe_timeout: Optional[float] = None,
                     backoff: float = 15.0) -> tuple:
    """Probe the TPU backend in a SUBPROCESS (bounded; the axon relay wedge
    hangs the first in-process device query indefinitely, so an in-process
    try/except cannot implement a retry).  On success return
    ``(None, probes_consumed)`` and leave the environment alone; after
    ``attempts`` failures force the CPU backend for this process and
    return ``(error_string, attempts)``.  The caller records the probe
    count in the artifact (``relay_attempts``) so a flaky-but-eventually-
    healthy relay is visible in the perf record, not just a wedged one.

    A short bare-import HEALTH PRE-CHECK (``_precheck_timeout``; default
    20s) runs before the probe loop: if even ``import jax`` hangs in a
    subprocess, the relay is wedged in the r03-r05 way and no 75s probe
    will fare better — fall back to CPU immediately with a structured
    ``state="wedged"`` verdict instead of burning the full probe budget.
    Every outcome lands in the module-level ``_RELAY_STATUS`` dict
    (state: healthy|unavailable|wedged|skipped, precheck: ok|failed|
    hung|skipped, probes_used, budgets, last_error), which ``main``
    copies into the artifact as ``relay_status``.

    Defaults bound the worst case at ~4.5 min before the artifact falls
    back to CPU (3 x 75s probes + 15s, 30s exponential backoff): healthy
    relay probes connect in ~10-30s, and the caller's own timeout must not
    expire before the one-line artifact is emitted.  ``probe_timeout``
    defaults from ``FEDTPU_BENCH_PROBE_TIMEOUT_S`` (_probe_timeout).

    Must run before this process's first DEVICE QUERY: the fallback pins
    the platform via ``jax.config.update``, which only takes effect if it
    lands before backend initialization (importing jax earlier is fine).
    """
    if probe_timeout is None:
        probe_timeout = _probe_timeout()
    pre_timeout = _precheck_timeout()
    _RELAY_STATUS.clear()
    _RELAY_STATUS.update(state="unknown", precheck="skipped", probes_used=0,
                         precheck_timeout_s=pre_timeout,
                         probe_timeout_s=probe_timeout, last_error=None)
    used = 0
    if os.environ.get("FEDTPU_BENCH_FORCE_CPU") == "1":
        err = "TPU skipped: FEDTPU_BENCH_FORCE_CPU=1"
        _RELAY_STATUS.update(state="skipped", last_error=err)
    else:
        # bounded health pre-check BEFORE the full probe loop: the wedged
        # relay (r03-r05) hangs everything indefinitely, so each 75s probe
        # plus backoff would burn ~4.5 min learning what a 20s bare-import
        # pre-check already proves.  Pre-check hang -> structured CPU
        # fallback immediately; pre-check fast-FAIL (env-level breakage)
        # proceeds to the probe loop, which then also fails fast and
        # records the real error.
        wedged = False
        if pre_timeout > 0:
            # Popen (not subprocess.run) so a hung child is still ALIVE
            # when we snapshot it: the r03-r05 wedges were closed as
            # "relay wedged" from symptoms alone because by the time
            # anyone looked, the hung process was gone — _diagnose_wedge
            # reads /proc/<pid> (state, wchan, blocking syscall, child
            # threads) and the relay-relevant environment BEFORE the kill
            p = subprocess.Popen(
                [sys.executable, "-c", _PRECHECK],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
            try:
                p.communicate(timeout=pre_timeout)
                _RELAY_STATUS["precheck"] = ("ok" if p.returncode == 0
                                             else "failed")
            except subprocess.TimeoutExpired:
                _RELAY_STATUS["precheck"] = "hung"
                _RELAY_STATUS["diagnosis"] = _diagnose_wedge(p.pid)
                p.kill()
                p.communicate()
                wedged = True
        if wedged:
            diag = _RELAY_STATUS.get("diagnosis") or {}
            err = (f"tpu relay pre-check hung >{pre_timeout:.0f}s "
                   "(wedged-relay signature); skipping probes"
                   + (f"; pid {diag.get('pid')} "
                      f"state={diag.get('proc_state')} "
                      f"wchan={diag.get('wchan')} "
                      f"syscall={diag.get('syscall')}"
                      if diag else ""))
            _RELAY_STATUS.update(state="wedged", last_error=err)
            print(f"bench: {err}", file=sys.stderr)
        else:
            last = None
            for attempt in range(attempts):
                if attempt:
                    # exponential: a relay mid-restart needs tens of
                    # seconds, not another immediate poke — backoff,
                    # 2x backoff, ...
                    time.sleep(backoff * 2 ** (attempt - 1))
                used = attempt + 1
                p = subprocess.Popen(
                    [sys.executable, "-c", _PROBE],
                    stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                    text=True)
                try:
                    _, perr = p.communicate(timeout=probe_timeout)
                    if p.returncode == 0:
                        _RELAY_STATUS.update(state="healthy",
                                             probes_used=used)
                        return None, used
                    last = ((perr or "").strip().splitlines()
                            or ["rc=%d" % p.returncode])[-1]
                except subprocess.TimeoutExpired:
                    # a probe that hangs AFTER a passing pre-check is the
                    # other wedge variant (import fine, first device
                    # query never returns): snapshot it alive too
                    diag = _diagnose_wedge(p.pid)
                    _RELAY_STATUS["diagnosis"] = diag
                    p.kill()
                    p.communicate()
                    last = (f"TPU probe hung >{probe_timeout:.0f}s "
                            f"(relay wedged?); pid {diag.get('pid')} "
                            f"state={diag.get('proc_state')} "
                            f"wchan={diag.get('wchan')} "
                            f"syscall={diag.get('syscall')}")
                print(f"bench: TPU probe {attempt + 1}/{attempts} failed: "
                      f"{last}", file=sys.stderr)
            err = f"tpu backend unavailable after {attempts} probes: {last}"
            _RELAY_STATUS.update(state="unavailable", probes_used=used,
                                 last_error=err)
    # decouple from the axon plugin: sitecustomize already registered it at
    # interpreter startup (it keys on PALLAS_AXON_POOL_IPS) and registration
    # forces the platform list, so mutating env vars here is NOT enough —
    # the config update below is what actually pins this process to CPU
    # (it wins as long as it lands before the first device query).  The env
    # vars still matter for any subprocess this process spawns.
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    # silently succeeds even if a backend is already up (jax 0.9: the
    # update then only governs later re-initialization) — in the
    # production path nothing has queried devices yet, so it pins CPU
    jax.config.update("jax_platforms", "cpu")
    return err, used


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "")
    for k, v in _PEAK_BF16.items():
        if kind.startswith(k):
            return v
    return 197e12


def _bench_scale() -> tuple:
    """(clients_per_chip*n_chips, batch, steps, reps) — production scale
    with FEDTPU_BENCH_* overrides so the FULL measurement path can be
    validated end-to-end at toy scale on CPU (the artifact records
    whatever scale actually ran via the knobs)."""
    import jax

    n_chips = len(jax.devices())
    K = int(os.environ.get("FEDTPU_BENCH_CLIENTS_PER_CHIP", 16)) * n_chips
    batch = int(os.environ.get("FEDTPU_BENCH_BATCH", 128))
    steps = int(os.environ.get("FEDTPU_BENCH_STEPS", 8))
    reps = int(os.environ.get("FEDTPU_BENCH_REPS", 5))
    return K, batch, steps, reps


#: RunRecorder for the current measurement suite (obs/): every timed
#: region emits one schema-validated round record into
#: artifacts/bench.jsonl, and the throughput fields the artifact
#: publishes are DERIVED from those records (report.record_ips), so the
#: JSONL is the primary perf evidence and the JSON artifact a view of it.
_BENCH_OBS = None


def _open_bench_obs(out: dict):
    """Open the bench RunRecorder (never let obs break the artifact)."""
    global _BENCH_OBS
    try:
        from federated_pytorch_test_tpu.obs import make_recorder

        art = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "artifacts")
        obs = make_recorder("jsonl", art, run_name="bench", engine="bench")
        obs.open(config={k: v for k, v in os.environ.items()
                         if k.startswith("FEDTPU_BENCH")})
        if obs.jsonl_path:
            out["obs_jsonl"] = os.path.join(
                "artifacts", os.path.basename(obs.jsonl_path))
        _BENCH_OBS = obs
    except Exception as e:      # noqa: BLE001 — telemetry is best-effort
        print(f"bench: obs recorder unavailable: {type(e).__name__}: {e}",
              file=sys.stderr)
        _BENCH_OBS = None
    return _BENCH_OBS


def _close_bench_obs(status: str = "completed") -> None:
    global _BENCH_OBS
    if _BENCH_OBS is not None:
        try:
            _BENCH_OBS.close(status=status)
        except Exception:       # noqa: BLE001
            pass
        _BENCH_OBS = None


#: last record built by _obs_emit_round — sections that publish a field
#: of the record (e.g. compression bytes/round) read it from here so the
#: artifact value and the telemetry value share one source
_LAST_OBS_ROUND: dict = {}


def _obs_emit_round(**fields) -> dict:
    """Emit one bench timed-region record; returns the record either way
    so callers derive their published numbers from it (record_ips)."""
    obs = _BENCH_OBS
    rec = dict(fields)
    _LAST_OBS_ROUND.clear()
    _LAST_OBS_ROUND.update(rec)
    if obs is not None and obs.enabled:
        try:
            idx = getattr(obs, "_bench_next_index", 0)
            obs._bench_next_index = idx + 1
            emitted = obs.round(dict(rec, round_index=idx))
            if emitted is not None:
                return emitted
        except Exception as e:  # noqa: BLE001 — telemetry is best-effort
            print(f"bench: obs round emit failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
    return rec


def _bench_round(trainer, ci, *, reps, with_comm=False, with_staging=False,
                 label=None):
    """images/sec/chip for block ci's local epoch under ``trainer``'s
    algorithm.  ``with_comm`` adds the comm round (+write-back) per
    rep; ``with_staging`` pays the per-epoch staging inside the timed
    region, exactly as a production round does — an on-device
    permutation gather under the default device-resident data path,
    or host shuffle + uint8 H2D copy on the fallback.

    The timed region lands in the bench obs JSONL as one round record
    (``label`` names it) and the returned throughput is computed FROM
    that record, so artifact and telemetry cannot disagree.

    Module-level (not a closure of ``_measure``) so the VAE and
    compression sections bench their trainers through the identical
    timed region."""
    import jax
    import jax.numpy as jnp

    from federated_pytorch_test_tpu.parallel.mesh import (
        client_sharding,
        replicated_sharding,
    )

    K = trainer.cfg.K
    images_per_epoch = K * trainer.data.steps * trainer.data.batch
    csh = client_sharding(trainer.mesh)
    rsh = replicated_sharding(trainer.mesh)
    # epoch prefetch (the production path) stays on only when staging
    # is part of the measurement; otherwise the worker thread would
    # build a never-consumed epoch during the timed region
    trainer._prefetch_epochs = with_staging
    if not with_staging:        # with_staging re-stages inside the loop
        xb, yb, wb = trainer._stage_epoch()
        keys = trainer._epoch_keys()
    train_epoch, comm_fns, init_opt = trainer._build_fns(ci)
    N = trainer.block_size(ci)
    state = trainer.init_state()
    state = state._replace(opt_state=init_opt(state.params),
                           comp=trainer._init_comp_state(ci))
    # a non-communicating algorithm ignores z/y (penalty 0): keep them
    # token-sized exactly like engine.run_independent does
    zdim = N if trainer.algo.communicates else 1
    ydim = N if trainer.algo.needs_dual else 1
    z = jax.device_put(jnp.zeros((zdim,), jnp.float32), rsh)
    y = jax.device_put(jnp.zeros((K, ydim), jnp.float32), csh)
    rho = jax.device_put(jnp.float32(trainer.cfg.admm_rho0), rsh)
    x0 = jax.device_put(jnp.zeros((K, 1), jnp.float32), csh)
    yhat0 = jax.device_put(jnp.zeros((K, 1), jnp.float32), csh)

    # every loop-carried array is threaded THROUGH round_ and rebound,
    # x0/yhat0 included: with --donate the comm fn donates all six block
    # vars, so reusing a stale captured buffer on the next rep would hit
    # a deleted-array error (and silently measure nothing on backends
    # that tolerate it)
    def round_(state, z, y, rho, x0, yhat0):
        if with_staging:
            bx, by, bw = trainer._stage_epoch()
            ks = trainer._epoch_keys()
        else:
            bx, by, bw, ks = xb, yb, wb, keys
        state, losses = train_epoch(state, y, trainer.client_norm, ks,
                                    bx, by, bw, z, rho,
                                    trainer._ones_mask)
        diag, extras = None, ()
        if with_comm:
            # the comm fn's output is variadic past the base 7-tuple
            # (client-ledger probes, guard verdicts); keep the tail so
            # the last rep's per-client norms can land in the artifact
            outs = comm_fns["plain"](
                state, z, y, rho, x0, yhat0, trainer._ones_mask,
                trainer._zero_corrupt, trainer._inf_bound)
            state, z, y, rho, x0, yhat0, diag = outs[:7]
            extras = outs[7:]
        return state, z, y, rho, x0, yhat0, losses, diag, extras

    def sync(losses, diag, extras=()):
        # NOTE: under the axon relay block_until_ready does not
        # actually block; force a host fetch of values that depend on
        # the full computation instead.
        np.asarray(losses)
        if diag is not None:
            jax.tree.map(np.asarray, diag)

    # warm-up / compile
    carry = round_(state, z, y, rho, x0, yhat0)
    sync(*carry[6:])

    t0 = time.perf_counter()
    for _ in range(reps):
        carry = round_(*carry[:6])
    sync(*carry[6:])
    dt = time.perf_counter() - t0

    from federated_pytorch_test_tpu.obs.report import record_ips

    fields = dict(label=label or f"block_{ci}", N=int(N), K=int(K),
                  round_seconds=dt, images=reps * images_per_epoch,
                  nadmm=reps,
                  # schema-v5 span bounds: the timed region itself (the
                  # recorder derives t_end = t_start + round_seconds, and
                  # obs/trace.py exports it to a Chrome trace timeline)
                  t_start=t0,
                  # jitted dispatches the host issued inside the timed
                  # region: one epoch (+ one comm) per rep — the fused
                  # engine path collapses the same work to 1/round
                  host_dispatches=reps * (2 if with_comm else 1))
    if trainer._sentinel is not None:
        # cumulative across the trainer: any growth between sections
        # means a timed region recompiled mid-measurement
        fields["jit_retraces"] = trainer._sentinel.retraces
    # sync/async throughput must be distinguishable in the artifact:
    # async rounds skip the per-round barrier, so their img/s is not
    # comparable to a synchronous number with the same label
    fields["async_mode"] = bool(trainer.cfg.async_rounds)
    if trainer.cfg.async_rounds:
        fields["max_staleness"] = int(trainer.cfg.max_staleness)
        fields["admission_rejected"] = int(trainer._async_rejected)
    # elastic federation: a churned roster changes the work per round, so
    # the live-member count must ride next to any throughput number
    if trainer.faults.churn_enabled:
        fields["members_active"] = int(trainer._members.sum())
    if with_comm and trainer.algo.communicates:
        fields["bytes_on_wire"] = reps * trainer.round_bytes_on_wire(N, K)
        fields["bytes_dense"] = reps * 4 * N * K
    rec = _obs_emit_round(**fields)
    _emit_client_grain(trainer, rec, carry[8], N, K, with_comm)
    return record_ips(rec, trainer.D)


#: per-client aggregates from the most recent comm-bearing timed region
#: (cleared on each _bench_round) — _measure publishes them into the
#: artifact so the bench.jsonl client record and the JSON summary agree
_LAST_CLIENT_AGG: dict = {}


def _emit_client_grain(trainer, rec, extras, N, K, with_comm) -> None:
    """Land the last rep's client-ledger probe outputs as a ``client``
    record next to the bench round record, plus host-side aggregates
    (norm skew, bytes per client) for the artifact summary."""
    _LAST_CLIENT_AGG.clear()
    if not (with_comm and getattr(trainer, "_client_probe", False)
            and len(extras) >= 2):
        return
    try:
        cl_nrm = np.asarray(extras[0], np.float64)
        cl_dist = np.asarray(extras[1], np.float64)
        bytes_per_client = int(trainer.round_bytes_on_wire(N, 1))
        finite = cl_nrm[np.isfinite(cl_nrm)]
        med = float(np.median(finite)) if finite.size else 0.0
        agg = {
            "client_norm_max": round(float(finite.max()), 6)
            if finite.size else None,
            "client_norm_median": round(med, 6) if finite.size else None,
            # max/median spread of per-client update norms: ~1 means the
            # synthetic shards pull evenly; a big skew means one client
            # dominates the consensus step
            "client_norm_skew": round(float(finite.max()) / med, 4)
            if finite.size and med > 0 else None,
            "client_bytes": bytes_per_client,
            "clients": int(K),
        }
        _LAST_CLIENT_AGG.update(agg)
        obs = _BENCH_OBS
        if obs is not None and obs.enabled:
            from federated_pytorch_test_tpu.obs.clients import (
                client_round_fields,
            )
            obs.client_event(client_round_fields(
                int(rec.get("round_index", 0)), int(K),
                update_norm=cl_nrm, dist_z=cl_dist,
                payload_bytes=bytes_per_client))
    except Exception as e:      # noqa: BLE001 — telemetry is best-effort
        print(f"bench: client-grain emit failed: {type(e).__name__}: {e}",
              file=sys.stderr)


def _measure(out: dict, progress=lambda: None) -> None:
    """All measurements; fills ``out`` incrementally so a late failure
    still leaves the fields measured so far in the artifact.
    ``progress()`` is called after each completed field group — the
    --measure child prints the partial dict there, so even a
    timeout-KILLED attempt (e.g. a pathological relay compile) loses only
    the group in flight, not the whole attempt."""
    import jax
    import jax.numpy as jnp

    from federated_pytorch_test_tpu.data.cifar10 import FederatedCifar10
    from federated_pytorch_test_tpu.models.resnet import ResNet18
    from federated_pytorch_test_tpu.train import (
        AdmmConsensus,
        BlockwiseFederatedTrainer,
        FederatedConfig,
        NoConsensus,
    )

    n_chips = len(jax.devices())
    K, batch, steps, reps = _bench_scale()
    _open_bench_obs(out)

    # retrace sentinel is free after compile (the counting wrapper only
    # runs when jit traces) and turns a silent recompile regression into
    # a visible nonzero jit_retraces field in the artifact
    cfg = FederatedConfig(K=K, default_batch=batch, check_results=False,
                          use_resnet=True, admm_rho0=0.1, bf16=True,
                          retrace_sentinel=True)
    data = FederatedCifar10(K=K, batch=batch,
                            limit_per_client=steps * batch, limit_test=batch)
    # bf16 conv/dense compute (params, BN and head stay f32) feeds the MXU
    # at full rate: ~1.5x over f32 on v5e
    trainer = BlockwiseFederatedTrainer(ResNet18(dtype=jnp.bfloat16), cfg,
                                        data, AdmmConsensus())

    def bench_block(trainer, ci, reps=reps, **kw):
        return _bench_round(trainer, ci, reps=reps, **kw)

    # block sizes across the sweep; biggest = reference block [54,59]
    sizes = [trainer.block_size(ci) for ci in range(trainer.L)]
    big_ci = int(np.argmax(sizes))
    out["big_block_N"] = sizes[big_ci]
    dev = jax.devices()[0]
    out["chip"] = getattr(dev, "device_kind", str(dev))
    # which staging path the headline's timed region pays (engine auto:
    # device-resident when the raw shards fit the HBM budget)
    out["staging"] = ("device" if trainer._dev_gather is not None
                      else "host")

    out["stem_block_ips_chip"] = round(
        bench_block(trainer, 0, label="stem_block"), 1)
    progress()
    out["big_block_ips_chip"] = round(
        bench_block(trainer, big_ci, label="big_block"), 1)
    progress()

    # HEADLINE: the full production consensus round on the biggest block,
    # staging included
    headline = bench_block(trainer, big_ci, with_comm=True,
                           with_staging=True, label="headline_full_round")
    out["value"] = round(headline, 1)
    out["vs_baseline"] = round(headline / TARGET, 3)
    out["measured"] = True
    # nonzero here = the headline's timed reps recompiled (perf numbers
    # then include trace time and are not comparable run-to-run)
    out["jit_retraces"] = trainer._sentinel.retraces
    # elastic-federation posture of this run: whether reshape resume and
    # bounded barriers were armed, and whether any collective actually
    # tripped the timeout (nonzero = the numbers above span a reshape)
    from federated_pytorch_test_tpu.parallel.mesh import (
        barrier_timeout, collective_timeout_count)
    out["elastic"] = {
        "elastic_resume": bool(trainer.cfg.elastic_resume),
        "barrier_timeout_s": float(barrier_timeout()),
        "collective_timeouts": int(collective_timeout_count()),
        "members_joined": int(trainer._members_joined),
        "members_left": int(trainer._members_left),
    }
    # client-grain summary of the headline round (the comm-bearing timed
    # region): norm dispersion across the K shards + bytes each client
    # ships per round; the per-client vectors are in bench.jsonl as a
    # ``client`` record (see obs/clients.py)
    if _LAST_CLIENT_AGG:
        out["client_grain"] = dict(_LAST_CLIENT_AGG)
    progress()

    # full-net epoch (the no_consensus driver's path): every parameter
    # trainable and NO consensus penalty, so the executed graph is the
    # full fwd + 2x bwd — the ONLY config whose analytic FLOP count equals
    # executed FLOPs, hence the MFU basis
    trainer_nc = BlockwiseFederatedTrainer(ResNet18(dtype=jnp.bfloat16),
                                           cfg, data, NoConsensus())
    full_net = bench_block(trainer_nc, None, label="no_consensus_full_net")
    out["no_consensus_ips_chip"] = round(full_net, 1)
    out["mfu"] = round(full_net * _STEP_FLOPS_PER_IMAGE / _peak_flops(dev), 4)
    progress()

    try:                       # never let the kernel microbench break the
        if jax.default_backend() == "tpu":     # headline artifact
            out.update(_bench_infonce())
            progress()
    except Exception as e:
        # stderr, not stdout: the artifact stays one JSON line, but a
        # kernel regression is visible instead of reading like a CPU run
        print(f"bench_infonce failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:                       # CPC workload round, same guard discipline
        if (jax.default_backend() == "tpu"
                and os.environ.get("FEDTPU_BENCH_CPC") != "0"):
            out.update(_bench_cpc())
            progress()
    except Exception as e:
        print(f"bench_cpc failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:                       # VAE workloads, same guard discipline
        # (FEDTPU_BENCH_VAE=1 forces them on the CPU validation path)
        if (os.environ.get("FEDTPU_BENCH_VAE") != "0"
                and (jax.default_backend() == "tpu"
                     or os.environ.get("FEDTPU_BENCH_VAE") == "1")):
            out.update(_bench_vae())
            progress()
    except Exception as e:
        print(f"bench_vae failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:                       # compressed-comm settings on the headline
        if os.environ.get("FEDTPU_BENCH_COMPRESS") != "0":   # block
            out.update(_bench_compression(cfg, data, big_ci))
            progress()
    except Exception as e:
        print(f"bench_compression failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:                       # persistent-cache + cost-ledger attribution
        from federated_pytorch_test_tpu.utils.compile_cache import cache_stats

        out["compile_cache"] = cache_stats()
        ledger = getattr(trainer, "_ledger", None)
        if ledger is not None:
            rate = ledger.cache_hit_rate()
            if rate is not None:
                out["cache_hit_rate"] = round(rate, 4)
            totals = ledger.totals()
            out["compile_events"] = totals["compile_events"]
            out["compile_seconds"] = round(totals["compile_seconds"], 3)
    except Exception as e:
        print(f"bench compile-cache stats failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    _close_bench_obs()


def _bench_cpc() -> dict:
    """One full federated-CPC rotation (3 sub-models, every block, K=4
    clients, LBFGSNew(h=7, m=2), Niter=10 fresh minibatches — the
    reference loop shape, federated_cpc.py:194-304) on synthetic LOFAR
    visibility cubes.  Reports wall-clock for the warm rotation (a
    warm-up rotation pays the compiles) and the patch throughput the
    LBFGS closures sustain; the artifact records the dims it ran at.

    Defaults to Lc=64, batch 32 — NOT the reference's Lc=256/batch 128:
    at that width the jitted CPC round (LBFGS closure re-evaluations x
    wide encoder) has triggered a pathological XLA:TPU compile that
    exceeds the relay compiler's budget (observed: >20 min, then
    compiler-host death; round-5 session log — see README "Known
    issues" for the isolation results).  The reduced dims compile in
    seconds and exercise the identical graph shape.  Override with
    FEDTPU_BENCH_CPC_LC / FEDTPU_BENCH_CPC_BATCH (e.g. 256/128 for
    reference width once a relay window permits); skip entirely with
    FEDTPU_BENCH_CPC=0."""
    from federated_pytorch_test_tpu.data.lofar import CPCDataSource
    from federated_pytorch_test_tpu.train.cpc_engine import CPCTrainer

    Lc = int(os.environ.get("FEDTPU_BENCH_CPC_LC", 64))
    batch = int(os.environ.get("FEDTPU_BENCH_CPC_BATCH", 32))
    # reference pairing: Rc=32 at Lc=256 (federated_cpc.py:27-29);
    # scale Rc down with Lc below that
    Rc, niter = min(32, max(Lc // 4, 8)), 10
    src = CPCDataSource([f"bench{i}.h5" for i in range(4)], ["0"] * 4,
                        batch_size=batch, patch_size=32)
    trainer = CPCTrainer(src, latent_dim=Lc, reduced_dim=Rc,
                         lbfgs_history=7, lbfgs_max_iter=2, Niter=niter,
                         num_devices=1)
    # patches per staged minibatch (batch_size * patchx * patchy)
    px, py, y0 = src.minibatch(0)
    patches_per_batch = int(y0.shape[0])

    def rotation():
        t0 = time.perf_counter()
        state, hist = trainer.run(Nloop=1, Nadmm=1, log=lambda m: None)
        # the run's own per-round fetches sync each round, but the FINAL
        # round's write-back is still in flight at return: close it out
        # so the rotation time covers all dispatched work
        jax.block_until_ready(state)
        return time.perf_counter() - t0, hist

    rotation()                       # warm-up: pays the LBFGS compiles
    dt, hist = rotation()
    # every (model, block) round runs Niter minibatches on each of the
    # trainer.K clients; clients run data-parallel across the trainer's
    # OWN mesh (trainer.D devices), so that is the per-chip divisor
    patches = len(hist) * niter * trainer.K * patches_per_batch
    return {
        "cpc_rotation_seconds": round(dt, 2),
        "cpc_patches_per_sec_chip": round(patches / dt / trainer.D, 1),
        "cpc_rounds": len(hist),
        "cpc_config": f"Lc={Lc},Rc={Rc},batch={batch},Niter={niter}",
    }


def _bench_vae() -> dict:
    """Round throughput of the two VAE workloads (federated_vae /
    federated_vae_cl drivers) at the headline scale: largest-layer local
    epoch + FedAvg collective + write-back, data staged once.  The plain
    VAE sweeps layers under Adam; the clustering VAE's encoder block runs
    the LBFGS closure path, so its number carries the line-search cost the
    reference driver pays (federated_vae_cl.py:200-205).  TPU-only unless
    forced (FEDTPU_BENCH_VAE=1); skip with FEDTPU_BENCH_VAE=0."""
    from federated_pytorch_test_tpu.data.cifar10 import FederatedCifar10
    from federated_pytorch_test_tpu.models.vae import AutoEncoderCNN
    from federated_pytorch_test_tpu.models.vae_cl import AutoEncoderCNNCL
    from federated_pytorch_test_tpu.train import FederatedConfig
    from federated_pytorch_test_tpu.train.algorithms import FedAvg
    from federated_pytorch_test_tpu.train.vae_engine import (
        VAECLTrainer,
        VAETrainer,
    )

    K, batch, steps, reps = _bench_scale()
    reps = max(2, reps // 2)        # side fields: bound the extra wall-clock
    data = FederatedCifar10(K=K, batch=batch,
                            limit_per_client=steps * batch, limit_test=batch)
    out = {}

    cfg = FederatedConfig(K=K, default_batch=batch, check_results=False)
    trainer = VAETrainer(AutoEncoderCNN(), cfg, data, FedAvg())
    sizes = [trainer.block_size(ci) for ci in range(trainer.L)]
    big_ci = int(np.argmax(sizes))
    out["vae_block_N"] = sizes[big_ci]
    out["vae_ips_chip"] = round(
        _bench_round(trainer, big_ci, reps=reps, with_comm=True,
                     label="vae_big_block"), 1)

    # reference clustering-VAE shape: Kc=10 clusters, Lc=32 latent,
    # lambda2=1e-3 (federated_vae_cl.py:12,22-23); encoder block ci=0
    # runs LBFGS
    cfg_cl = FederatedConfig(K=K, default_batch=batch, check_results=False,
                             lambda2=1e-3)
    trainer_cl = VAECLTrainer(AutoEncoderCNNCL(K=10, L=32), cfg_cl, data,
                              FedAvg())
    out["vaecl_block_N"] = trainer_cl.block_size(0)
    out["vaecl_ips_chip"] = round(
        _bench_round(trainer_cl, 0, reps=reps, with_comm=True,
                     label="vaecl_encoder_block"), 1)
    return out


def _bench_compression(cfg, data, big_ci) -> dict:
    """The compressed-communication settings (--compress) on the headline
    workload: full consensus round on the largest ResNet18 block at each
    setting, staged data, same timed region as ``big_block_ips_chip`` +
    comm — so ``compress_none_round_ips_chip`` is the dense comparator and
    the others show what the encode/decode work costs end-to-end.  Per
    setting: round throughput, measured uplink bytes/round (K clients x
    bytes_on_wire(N)), and a single-vector jitted encode+decode
    microbench (``*_encdec_us``).  Skip with FEDTPU_BENCH_COMPRESS=0."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from federated_pytorch_test_tpu.compress import make_compressor
    from federated_pytorch_test_tpu.models.resnet import ResNet18
    from federated_pytorch_test_tpu.train import (
        AdmmConsensus,
        BlockwiseFederatedTrainer,
    )

    _, _, _, reps = _bench_scale()
    reps = max(2, reps // 2)        # side fields: bound the extra wall-clock
    settings = (("none", {}),
                ("q8", {"compress": "q8"}),
                ("q4", {"compress": "q4"}),
                ("topk", {"compress": "topk", "topk_frac": 0.01,
                          "error_feedback": True}))
    out = {}
    for name, kw in settings:
        cfg_c = dataclasses.replace(cfg, **kw)
        trainer = BlockwiseFederatedTrainer(ResNet18(dtype=jnp.bfloat16),
                                            cfg_c, data, AdmmConsensus())
        N = trainer.block_size(big_ci)
        out.setdefault("compress_block_N", N)
        ips = _bench_round(trainer, big_ci, reps=reps, with_comm=True,
                           label=f"compress_{name}")
        out[f"compress_{name}_round_ips_chip"] = round(ips, 1)
        # published bytes/round come from the emitted obs record (the
        # timed region covers ``reps`` comm rounds)
        out[f"compress_{name}_bytes_round"] = (
            _LAST_OBS_ROUND["bytes_on_wire"] // reps
            if _LAST_OBS_ROUND.get("bytes_on_wire")
            else trainer.round_bytes_on_wire(N, cfg.K))
        if name != "none":       # encode+decode overhead in isolation
            comp = make_compressor(kw["compress"],
                                   topk_frac=kw.get("topk_frac", 0.01),
                                   quant_chunk=cfg.quant_chunk)
            st = comp.init_state(N, jax.random.key_data(jax.random.PRNGKey(0)))

            @jax.jit
            def encdec(v, st, comp=comp, N=N):
                payload, st = comp.encode(v, st)
                return comp.decode(payload, N), st

            v = jnp.asarray(np.random.default_rng(0).normal(size=(N,)),
                            jnp.float32)
            d, st2 = encdec(v, st)
            np.asarray(d)                              # compile + sync
            t0 = time.perf_counter()
            for _ in range(30):
                d, st = encdec(v, st)
            np.asarray(d)
            out[f"compress_{name}_encdec_us"] = round(
                (time.perf_counter() - t0) / 30 * 1e6, 1)
    return out


def _bench_infonce() -> dict:
    """Pallas-fused vs XLA InfoNCE (ops/infonce.py) at a grid-spanning
    shape (P=256 -> two row tiles; D=512): microseconds/call for the
    forward alone and for value_and_grad — the CPC LBFGS closure evaluates
    the latter on every (re-)evaluation, so the grad number is the one the
    training loop feels."""
    import jax
    import jax.numpy as jnp

    from federated_pytorch_test_tpu.ops.infonce import (
        force_infonce_impl,
        info_nce_fused,
    )

    rng = np.random.default_rng(0)
    z = jnp.asarray(rng.normal(size=(16, 16, 16, 32)).astype(np.float32))
    zh = jnp.asarray(rng.normal(size=(16, 16, 16, 32)).astype(np.float32))
    fwd_us, grad_us = {}, {}
    for impl in ("pallas", "xla"):
        with force_infonce_impl(impl):
            # fresh lambdas per impl: JAX's jaxpr cache is keyed on the
            # raw function object and does not see _FORCE_IMPL, so jitting
            # info_nce_fused directly would reuse the first impl's trace
            # for both timings
            fns = {
                "fwd": jax.jit(lambda a, b: info_nce_fused(a, b)),
                "grad": jax.jit(
                    lambda a, b: jax.value_and_grad(info_nce_fused,
                                                    argnums=(0, 1))(a, b)),
            }
            for name, fn in fns.items():
                jax.tree.map(np.asarray, fn(z, zh))    # compile + sync
                t0 = time.perf_counter()
                r = None
                for _ in range(30):
                    r = fn(z, zh)
                jax.tree.map(np.asarray, r)            # host fetch = sync
                us = (time.perf_counter() - t0) / 30 * 1e6
                (fwd_us if name == "fwd" else grad_us)[impl] = us
    return {
        "infonce_pallas_us": round(fwd_us["pallas"], 1),
        "infonce_xla_us": round(fwd_us["xla"], 1),
        "infonce_speedup": round(fwd_us["xla"] / fwd_us["pallas"], 3),
        "infonce_grad_pallas_us": round(grad_us["pallas"], 1),
        "infonce_grad_xla_us": round(grad_us["xla"], 1),
        "infonce_grad_speedup": round(grad_us["xla"] / grad_us["pallas"], 3),
    }


def _measure_child() -> int:
    """``bench.py --measure``: run the measurements in THIS process and
    print the field dict as a JSON line after every completed group (the
    parent parses stdout's LAST parsable line, so a timeout-KILL loses
    only the group in flight).  The parent keeps artifact-printing duty;
    a wedge that hangs this process is bounded by the parent's timeout."""
    out: dict = {}
    rc = 0
    try:
        from federated_pytorch_test_tpu.utils.compile_cache import (
            enable_persistent_compile_cache,
        )

        enable_persistent_compile_cache()
        _measure(out, progress=lambda: print(json.dumps(out), flush=True))
    except Exception as e:          # noqa: BLE001 — report partial fields
        out["error"] = f"{type(e).__name__}: {e}"
        rc = 1
    _close_bench_obs(status="completed" if rc == 0 else "aborted")
    print(json.dumps(out), flush=True)
    return rc


def _run_measurement(out: dict, attempts: Optional[int] = None,
                     backoff: float = 30.0,
                     timeout: Optional[float] = None) -> None:
    """Run the measurement suite in a bounded subprocess, retrying on
    failure.  Round 5 observed the relay dying MID-measurement (a
    remote_compile stream error after a healthy probe) and r01/r03 lost
    artifacts to hangs; a subprocess bounds the hang and makes the whole
    suite retryable without poisoned in-process backend state."""
    if attempts is None:
        attempts = int(os.environ.get("FEDTPU_BENCH_MEASURE_ATTEMPTS", 3))
    if timeout is None:
        timeout = float(os.environ.get("FEDTPU_BENCH_MEASURE_TIMEOUT", 1500))
    def last_json(stdout) -> dict:
        """The LAST parsable JSON *dict* line of child stdout — the child
        reprints its partial dict after every field group, so even a
        killed child yields everything up to the group in flight.  Non-dict
        parsable lines (stray library prints like a bare number) are
        skipped, not returned — ``out.update`` needs a mapping."""
        if isinstance(stdout, bytes):
            stdout = stdout.decode(errors="replace")
        for ln in reversed((stdout or "").strip().splitlines()):
            try:
                v = json.loads(ln)
            except ValueError:
                continue
            if isinstance(v, dict):
                return v
        return {}

    last = None
    for attempt in range(attempts):
        if attempt:
            time.sleep(backoff)
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--measure"],
                timeout=timeout, capture_output=True, text=True)
        except subprocess.TimeoutExpired as e:
            last = f"measurement hung >{timeout:.0f}s (relay wedged?)"
            print(f"bench: measure attempt {attempt + 1}/{attempts}: {last}",
                  file=sys.stderr)
            # salvage the progress lines captured before the kill
            out.update(last_json(e.stdout))
            continue
        sys.stderr.write(r.stderr)      # child diagnostics stay visible
        child = last_json(r.stdout)
        if r.returncode == 0 and child:
            out.update(child)
            return
        last = child.get("error") or f"measure child rc={r.returncode}"
        print(f"bench: measure attempt {attempt + 1}/{attempts} failed: "
              f"{last}", file=sys.stderr)
        # keep any fields the failed attempt did land (partial artifact
        # beats none), but let a later attempt overwrite them
        child.pop("error", None)
        out.update(child)
    out["error"] = f"measurement failed after {attempts} attempts: {last}"


def main():
    out = {
        "metric": _HEADLINE_METRIC,
        "value": 0.0,
        "unit": "images/sec/chip",
        "vs_baseline": 0.0,
        # flipped to True the moment the headline is actually measured, so
        # a relay-wedged all-zeros artifact is self-describing (r04 was
        # misreadable as "measured 0")
        "measured": False,
    }
    # probe BEFORE importing jax (the wedge hangs in-process init)
    out["probe_timeout_s"] = _probe_timeout()
    _RELAY_STATUS.clear()
    err, out["relay_attempts"] = _acquire_backend()
    if _RELAY_STATUS:
        out["relay_status"] = dict(_RELAY_STATUS)
    else:
        # _acquire_backend was replaced by a stub (tests, external
        # drivers): synthesize the structured status from its pinned
        # (err, probes) contract so the artifact ALWAYS carries one
        out["relay_status"] = {
            "state": "healthy" if err is None else "unavailable",
            "precheck": "unknown",
            "probes_used": out["relay_attempts"],
            "last_error": err,
        }
    if err is not None:
        out["error"] = err
    try:
        if err is None or os.environ.get("FEDTPU_BENCH_MEASURE_ON_CPU") == "1":
            # on CPU fallback the measurements are normally skipped (a
            # 1-core run of the production config would take hours and
            # mean nothing) — the artifact itself still appears, rc=0.
            # FEDTPU_BENCH_MEASURE_ON_CPU=1 (with the FEDTPU_BENCH_*
            # scale knobs) forces them anyway so the full measurement
            # path can be validated without a TPU.
            _run_measurement(out)
    except Exception as e:          # noqa: BLE001 — artifact must survive
        out["error"] = f"{type(e).__name__}: {e}"
    out["captured_utc"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        time.gmtime())
    # which code produced this artifact (self-description);
    # --dirty so an uncommitted tree cannot masquerade as its HEAD
    out["git"] = _git_describe()
    # compare-ready baseline pointer: `python -m
    # federated_pytorch_test_tpu.obs.compare <artifact>` resolves its
    # baseline from here with no flags — the newest prior measured TPU
    # artifact, else the published-numbers file
    _prior = _last_measured_artifact()
    out["baseline_ref"] = (_prior["path"] if _prior is not None
                           else "BASELINE.json")
    if not out.get("measured"):
        ref = _last_measured_artifact()
        if ref is not None:
            out["last_measured"] = ref
            # SAME-COMMIT REUSE: a clean tree at exactly the commit that
            # produced the newest measured TPU artifact ran identical
            # code, so that headline still describes this code — promote
            # it instead of shipping value 0 (rounds 1/3/4 lost their
            # whole perf record to exactly this: relay wedged at capture
            # time, artifact chain read "0").  ``measured`` stays False
            # (nothing was timed NOW) and ``promoted_from_artifact``
            # names the evidence.
            if (ref.get("git") and out.get("git")
                    and ref["git"] == out["git"]
                    and "dirty" not in out["git"]):
                out["value"] = ref["value"]
                if ref.get("vs_baseline") is not None:
                    out["vs_baseline"] = ref["vs_baseline"]
                else:
                    out["vs_baseline"] = round(ref["value"] / TARGET, 3)
                out["promoted_from_artifact"] = ref["path"]
    print(json.dumps(out))


def _git_describe() -> Optional[str]:
    try:
        return subprocess.run(
            ["git", "-C", os.path.dirname(os.path.abspath(__file__)),
             "describe", "--always", "--dirty"],
            capture_output=True, text=True, timeout=10).stdout.strip() or None
    except (OSError, subprocess.SubprocessError):
        return None


def _parse_utc(stamp) -> Optional[float]:
    """``captured_utc`` ("%Y-%m-%dT%H:%M[:%S]Z") -> epoch seconds, or
    None when absent/malformed."""
    if not isinstance(stamp, str):
        return None
    for fmt in ("%Y-%m-%dT%H:%M:%SZ", "%Y-%m-%dT%H:%MZ"):
        try:
            return float(calendar.timegm(time.strptime(stamp, fmt)))
        except ValueError:
            continue
    return None


def _last_measured_artifact() -> Optional[dict]:
    """Pointer to the newest ``measured: true`` bench artifact under
    artifacts/, embedded when THIS run could not measure — a relay wedge
    at capture time (it cost round 4 its whole perf record) then cannot
    erase hardware evidence captured earlier at the same or nearby HEAD.
    Informational, except that ``main`` promotes the value when the
    artifact's ``git`` exactly equals this clean tree's (same code =>
    the measurement still describes it); otherwise ``value``/``measured``
    keep describing this run."""
    base = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "artifacts")
    best = None
    try:
        for name in os.listdir(base):
            if not name.endswith(".json"):
                continue
            p = os.path.join(base, name)
            try:
                with open(p) as f:
                    d = json.load(f)
                mt = os.path.getmtime(p)
            except (ValueError, OSError):
                continue
            # same headline metric AND a recorded chip: a CPU validation
            # run (FEDTPU_BENCH_MEASURE_ON_CPU=1 marks measured but has
            # meaningless numbers and records no TPU chip) or a
            # different-metric artifact must not masquerade as prior
            # hardware evidence
            if not (isinstance(d, dict) and d.get("measured")
                    and d.get("value")
                    and d.get("metric") == _HEADLINE_METRIC
                    and str(d.get("chip", "")).startswith("TPU")):
                continue
            # chronology: stamped artifacts win over unstamped
            # CATEGORICALLY (an unstamped file's mtime collapses to
            # checkout time on a fresh clone, which would beat every
            # genuine capture stamp), then the capture stamp (or mtime
            # among unstamped), then name to break exact ties
            stamp = _parse_utc(d.get("captured_utc"))
            key = (stamp is not None, stamp if stamp is not None else mt,
                   name)
            if best is None or key > best[0]:
                best = (key, {"path": f"artifacts/{name}",
                                   "value": d["value"],
                                   "vs_baseline": d.get("vs_baseline"),
                                   "metric": d.get("metric"),
                                   "chip": d.get("chip"),
                                   "captured_utc": d.get("captured_utc"),
                                   "git": d.get("git"),
                                   "mtime": int(mt)})
    except OSError:
        return None
    return None if best is None else best[1]


_SMOKE_BASELINE = "artifacts/SMOKE_BASELINE.json"
_SMOKE_METRIC = "smoke_fused_q8_wire_savings_ratio"


def _smoke_predicted() -> dict:
    """Pure-math predicted comm-path metrics at a STATIC geometry
    (N=8192, K=8, D=8, chunk=256) — no timing, no hardware, so the
    numbers are bit-reproducible on any CI box and a delta can only mean
    the byte model (compress/ payload shapes or ops/packed_reduce.py hop
    accounting) actually changed."""
    from federated_pytorch_test_tpu.compress import make_compressor
    from federated_pytorch_test_tpu.ops.packed_reduce import (
        fused_bytes_on_wire,
    )

    N, K, D, chunk = 8192, 8, 8, 256
    seg = -(-N // D)
    out = {"smoke_geometry": f"N={N},K={K},D={D},chunk={chunk}"}
    # dense comparator: the SAME butterfly movement pattern at f32 with
    # no scale sidecar — what an unfused all-reduce moves for this
    # geometry (2 phases x D devices x (D-1) hop-halves x f32 segment)
    out["smoke_dense_collective_wire_bytes"] = 2 * D * (D - 1) * seg * 4
    for name in ("q8", "q4"):
        comp = make_compressor(name, quant_chunk=chunk)
        out[f"smoke_fused_{name}_wire_bytes"] = int(
            fused_bytes_on_wire(comp, N, D, K))
        out[f"smoke_{name}_uplink_wire_bytes"] = K * comp.bytes_on_wire(N)
    topk = make_compressor("topk", topk_frac=0.01)
    out["smoke_fused_topk_wire_bytes"] = int(
        fused_bytes_on_wire(topk, N, D, K))
    # chunked robust aggregation (--robust-chunked): predicted per-device
    # gathered working set from the pure byte model — dense materializes
    # the [K, N] all-gather, chunked owns a [K, ceil(N/D)] segment slab
    # (parallel/comm.py robust_gather_bytes); the compiled
    # memory_analysis counterpart is gated below (_smoke_robust_memory)
    from federated_pytorch_test_tpu.parallel.comm import robust_gather_bytes
    for kind in ("trim", "krum"):
        dense = robust_gather_bytes(kind, K, N, D, chunked=False)
        chunk_b = robust_gather_bytes(kind, K, N, D, chunked=True)
        out[f"smoke_robust_{kind}_dense_gather_bytes"] = int(dense)
        out[f"smoke_robust_{kind}_chunked_gather_bytes"] = int(chunk_b)
        out[f"smoke_robust_{kind}_gather_savings_ratio"] = round(
            dense / chunk_b, 4)
    return out


def _smoke_robust_memory() -> dict:
    """Compiled-memory gate for the chunked robust-agg path: lower each
    estimator through jit on the forced 8-device CPU mesh at the static
    smoke geometry and read ``memory_analysis`` peak bytes (argument +
    output + temp, the obs/costs.py definition) for the dense all-gather
    formulation vs the ``--robust-chunked`` segment-owned one.  These are
    compiler facts, not timings — deterministic for a fixed jax/XLA
    build, so the committed-baseline diff holds them down like the
    predicted byte fields; the hard "chunked strictly lower" assertion
    lives in tests/test_comm_kernels.py."""
    import jax
    import jax.numpy as jnp

    from federated_pytorch_test_tpu.parallel.comm import (
        make_robust_mean,
    )
    from federated_pytorch_test_tpu.parallel.mesh import (
        CLIENT_AXIS,
        client_mesh,
        shard_map,
    )

    P = jax.sharding.PartitionSpec
    N, K, D = 8192, 8, 8
    mesh = client_mesh(D)
    out = {}

    def peak(kind, chunked):
        mf = make_robust_mean(kind, trim_frac=0.1, chunked=chunked, D=D)
        fn = shard_map(lambda s, w: mf(s, w), mesh=mesh,
                       in_specs=(P(CLIENT_AXIS), P(CLIENT_AXIS)),
                       out_specs=P(), check_vma=False)
        shapes = (jax.ShapeDtypeStruct((K, N), jnp.float32),
                  jax.ShapeDtypeStruct((K,), jnp.float32))
        stats = jax.jit(fn).lower(*shapes).compile().memory_analysis()
        return int(stats.argument_size_in_bytes
                   + stats.output_size_in_bytes
                   + stats.temp_size_in_bytes)

    for kind in ("trim", "krum"):
        out[f"smoke_robust_{kind}_dense_peak_device_bytes"] = peak(
            kind, False)
        out[f"smoke_robust_{kind}_chunked_peak_device_bytes"] = peak(
            kind, True)
    return out


def _smoke_engine_run() -> dict:
    """Tiny REAL engine run (``--compress q8 --fused-collective``) on the
    forced 8-device CPU mesh: proves the fused comm path executes
    end-to-end (shard_map butterfly, packed hops, telemetry) and
    publishes its deterministic byte fields for the gate; the wall-clock
    is info-only (CI boxes are too noisy to gate on)."""
    import flax.linen as nn

    from federated_pytorch_test_tpu.data.cifar10 import FederatedCifar10
    from federated_pytorch_test_tpu.models.base import (
        BlockModule,
        elu,
        flatten,
        max_pool_2x2,
        pairs,
    )
    from federated_pytorch_test_tpu.train import (
        AdmmConsensus,
        BlockwiseFederatedTrainer,
        FederatedConfig,
    )

    class SmokeNet(BlockModule):
        @nn.compact
        def __call__(self, x, train=True):
            x = max_pool_2x2(elu(nn.Conv(4, (5, 5), strides=(2, 2),
                                         name="conv1")(x)))
            return nn.Dense(10, name="fc1")(flatten(x))

        def param_order(self):
            return pairs("conv1", "fc1")

        def train_order_block_ids(self):
            return [[0, 1], [2, 3]]

        def linear_layer_ids(self):
            return [1]

    K = 8
    cfg = FederatedConfig(K=K, Nloop=1, Nepoch=1, Nadmm=1, default_batch=16,
                          check_results=False, admm_rho0=0.1, seed=0,
                          compress="q8", fused_collective=True)
    data = FederatedCifar10(K=K, batch=16, limit_per_client=16,
                            limit_test=16)
    # informational wall-clock (compare direction 0): run() fetches the
    # round diagnostics to host before returning, which is sync enough
    t0 = time.perf_counter()  # graftlint: disable=JG104
    trainer = BlockwiseFederatedTrainer(SmokeNet(), cfg, data,
                                        AdmmConsensus())
    _, hist = trainer.run(log=lambda m: None)
    dt = time.perf_counter() - t0
    rec = next(r for r in hist if r.get("bytes_fused"))
    return {
        "smoke_engine_fused_wire_bytes": int(rec["bytes_fused"]),
        "smoke_engine_uplink_wire_bytes": int(rec["bytes_on_wire"]),
        "smoke_run_seconds": round(dt, 2),
    }


def _smoke() -> int:
    """``bench.py --smoke``: the no-TPU CI gate for the roofline comm
    path.  Emits a bench-shaped artifact (``artifacts/smoke.json``) whose
    headline is the predicted dense/q8-fused wire-byte ratio at a static
    geometry, plus the per-codec predicted byte fields and a tiny real
    engine run's telemetry, then diffs it against the committed
    ``artifacts/SMOKE_BASELINE.json`` via obs/compare.py — exit 1 on
    regression (ratio down, any ``*_wire_bytes`` up), exit 0 otherwise.
    ``measured`` is true in the bench-artifact sense of "this run
    produced its own numbers", but every gated field is deterministic
    byte accounting, not a timing (the unit string says so)."""
    # must land before this process's first jax import
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    out = {
        "metric": _SMOKE_METRIC,
        "unit": "x (dense/fused wire bytes, predicted)",
        "measured": True,
        "baseline_ref": _SMOKE_BASELINE,
    }
    out.update(_smoke_predicted())
    out["value"] = round(out["smoke_dense_collective_wire_bytes"]
                         / out["smoke_fused_q8_wire_bytes"], 4)
    try:
        out.update(_smoke_robust_memory())
    except Exception as e:      # noqa: BLE001 — predicted gate still runs
        out["error"] = f"smoke robust memory failed: {type(e).__name__}: {e}"
    try:
        out.update(_smoke_engine_run())
    except Exception as e:      # noqa: BLE001 — predicted gate still runs
        out["error"] = f"smoke engine run failed: {type(e).__name__}: {e}"
    out["captured_utc"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    out["git"] = _git_describe()
    art_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "artifacts")
    path = os.path.join(art_dir, "smoke.json")
    try:
        os.makedirs(art_dir, exist_ok=True)
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
    except OSError as e:
        print(f"bench: cannot write smoke artifact: {e}", file=sys.stderr)
        return 1
    print(json.dumps(out))
    if out.get("error"):
        return 1
    baseline = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            _SMOKE_BASELINE)
    if not os.path.exists(baseline):
        print(f"bench: no committed {_SMOKE_BASELINE}; smoke gate skipped "
              "(commit the emitted artifacts/smoke.json there to arm it)",
              file=sys.stderr)
        return 0
    from federated_pytorch_test_tpu.obs import compare as obs_compare

    return obs_compare.main([path, "--baseline", baseline,
                             "--threshold", "2"])


_POPULATION_BASELINE = "artifacts/POPULATION_BASELINE.json"
_POPULATION_METRIC = "population_sublinearity_savings_ratio"


def _population_round_seconds(population: int) -> float:
    """Steady-state per-round wall clock (median over the run's round
    records, which rides out the per-block compile rounds) for a tiny
    real engine with ``population`` registered clients sampled down to
    the fixed 8-slot cohort on the forced 8-device CPU mesh."""
    import numpy as np

    import flax.linen as nn

    from federated_pytorch_test_tpu.data.cifar10 import FederatedCifar10
    from federated_pytorch_test_tpu.models.base import (
        BlockModule,
        elu,
        flatten,
        max_pool_2x2,
        pairs,
    )
    from federated_pytorch_test_tpu.train import (
        AdmmConsensus,
        BlockwiseFederatedTrainer,
        FederatedConfig,
    )

    class PopNet(BlockModule):
        @nn.compact
        def __call__(self, x, train=True):
            x = max_pool_2x2(elu(nn.Conv(4, (5, 5), strides=(2, 2),
                                         name="conv1")(x)))
            return nn.Dense(10, name="fc1")(flatten(x))

        def param_order(self):
            return pairs("conv1", "fc1")

        def train_order_block_ids(self):
            return [[0, 1], [2, 3]]

        def linear_layer_ids(self):
            return [1]

    K = 8
    cfg = FederatedConfig(K=K, Nloop=1, Nepoch=1, Nadmm=6, default_batch=16,
                          check_results=False, admm_rho0=0.1, seed=0,
                          population=population)
    data = FederatedCifar10(K=K, batch=16, limit_per_client=16,
                            limit_test=16)
    trainer = BlockwiseFederatedTrainer(PopNet(), cfg, data,
                                        AdmmConsensus())
    _, hist = trainer.run(log=lambda m: None)
    secs = [float(r["round_seconds"]) for r in hist
            if "round_seconds" in r and "nadmm" in r]
    if not secs:
        raise RuntimeError("population bench run produced no round records")
    return float(np.median(secs))


def _population_bench() -> int:
    """``bench.py --population-bench``: the no-TPU CI gate for population
    federation (population/).  Registers K virtual clients for K in
    {256, 2048, 10240} over a FIXED 8-slot cohort on the forced 8-device
    CPU mesh, times steady-state rounds, and emits a bench-shaped
    artifact (``artifacts/population.json``) whose headline is the
    sublinearity ratio

        (K_hi / K_lo) / (wall_hi / wall_lo)

    — the factor of the 40x registry growth that per-round wall clock
    did NOT pay.  40 means rounds cost the same at 10,240 registered
    clients as at 256 (perfectly cohort-bounded); 1 would mean rounds
    scale linearly in K.  Every number here is a CPU-box timing, so the
    committed-baseline gate runs with a WIDE threshold: it exists to
    catch the subsystem going accidentally linear-in-K, not 10%% drift.
    """
    # must land before this process's first jax import
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    populations = [256, 2048, 10240]
    out = {
        "metric": _POPULATION_METRIC,
        "unit": "x (K-growth over wall-growth, steady-state rounds)",
        "measured": True,
        "baseline_ref": _POPULATION_BASELINE,
        "population_cohort": 8,
        "population_registered_max": populations[-1],
    }
    walls = {}
    try:
        for pop in populations:
            walls[pop] = _population_round_seconds(pop)
            out[f"population_K{pop}_round_seconds"] = round(walls[pop], 4)
    except Exception as e:      # noqa: BLE001 — report, don't traceback
        out["error"] = (
            f"population bench run failed: {type(e).__name__}: {e}")
    if not out.get("error"):
        lo, hi = populations[0], populations[-1]
        out["value"] = round((hi / lo) / (walls[hi] / walls[lo]), 4)
        out["population_round_throughput"] = round(1.0 / walls[hi], 4)
        # human-readable section mirroring the gated flat fields
        out["population"] = {
            "registered": populations,
            "cohort": 8,
            "rounds_per_second_at_max_K": out["population_round_throughput"],
            "round_seconds": {str(p): out[f"population_K{p}_round_seconds"]
                              for p in populations},
            "sublinearity_ratio": out["value"],
        }
    out["captured_utc"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    out["git"] = _git_describe()
    art_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "artifacts")
    path = os.path.join(art_dir, "population.json")
    try:
        os.makedirs(art_dir, exist_ok=True)
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
    except OSError as e:
        print(f"bench: cannot write population artifact: {e}",
              file=sys.stderr)
        return 1
    print(json.dumps(out))
    if out.get("error"):
        return 1
    baseline = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            _POPULATION_BASELINE)
    if not os.path.exists(baseline):
        print(f"bench: no committed {_POPULATION_BASELINE}; population gate "
              "skipped (commit the emitted artifacts/population.json there "
              "to arm it)", file=sys.stderr)
        return 0
    from federated_pytorch_test_tpu.obs import compare as obs_compare

    # timings on shared CI boxes: gate only on halving/doubling-scale
    # movement of the ratio and throughput, anything subtler is info
    return obs_compare.main([path, "--baseline", baseline,
                             "--threshold", "45"])


_SOAK_BASELINE = "artifacts/SOAK_BASELINE.json"
_SOAK_METRIC = "soak_availability_pct"
#: the nightly campaign: 48 virtual hours of diurnal load with churn
#: waves, straggler storms, correlated corruption bursts, and two
#: deterministic preemptions (virtual hours 12 and 30) that force two
#: supervised restarts with elastic mesh reshapes.  accel=600 turns the
#: seeded restart backoffs into milliseconds of wall clock without
#: touching any recorded value (PARITY.md v0.13).
_SOAK_SPEC = ("hours=48,round_minutes=30,diurnal=0.6,drop=0.15,"
              "straggle=0.1,mode=scale,scale=50,join=0.1,leave=0.1,"
              "storm=0.2,storm_len=2,storm_straggle=0.6,burst=0.25,"
              "burst_len=2,burst_corrupt=0.4,preempt_at=12+30,seed=11,"
              "accel=600")


def _soak_engine_run(tmp: str):
    """Run the seeded 48-virtual-hour campaign unattended; returns the
    stitched multi-segment JSONL path."""
    import flax.linen as nn

    from federated_pytorch_test_tpu.campaign.harness import run_soak
    from federated_pytorch_test_tpu.data.cifar10 import FederatedCifar10
    from federated_pytorch_test_tpu.models.base import (
        BlockModule,
        elu,
        flatten,
        max_pool_2x2,
        pairs,
    )
    from federated_pytorch_test_tpu.train import (
        AdmmConsensus,
        BlockwiseFederatedTrainer,
        FederatedConfig,
    )

    class SoakNet(BlockModule):
        @nn.compact
        def __call__(self, x, train=True):
            x = max_pool_2x2(elu(nn.Conv(4, (5, 5), strides=(2, 2),
                                         name="conv1")(x)))
            return nn.Dense(10, name="fc1")(flatten(x))

        def param_order(self):
            return pairs("conv1", "fc1")

        def train_order_block_ids(self):
            return [[0, 1], [2, 3]]

        def linear_layer_ids(self):
            return [1]

    K = 8
    # Nloop * blocks * Nadmm = 8 * 2 * 6 = 96 rounds = 48 virtual hours
    # at 30-minute rounds, covering the full campaign span
    cfg = FederatedConfig(K=K, Nloop=8, Nepoch=1, Nadmm=6,
                          default_batch=16, check_results=False,
                          admm_rho0=0.1, seed=11,
                          campaign_spec=_SOAK_SPEC, control="act",
                          max_restarts=3, restart_backoff=1.0,
                          elastic_resume=True,
                          obs_dir=os.path.join(tmp, "obs"),
                          obs_sinks="jsonl")
    data = FederatedCifar10(K=K, batch=16, limit_per_client=16,
                            limit_test=16)

    def build(c, attempt):
        t = BlockwiseFederatedTrainer(SoakNet(), c, data, AdmmConsensus())
        t.obs_run_name = "soak"
        return t

    run_soak(build, cfg, os.path.join(tmp, "ck"),
             run_kwargs={"log": lambda m: None}, log=lambda m: None)
    return os.path.join(tmp, "obs", "soak.jsonl")


def _soak() -> int:
    """``bench.py --soak``: the nightly no-TPU availability gate for soak
    campaigns (campaign/).  Runs the seeded accelerated 48-virtual-hour
    campaign (diurnal load, churn waves, storms, corruption bursts, two
    deterministic preemptions -> two supervised restarts with elastic
    reshapes), verifies the stitched stream with ``control.replay``
    (any divergence fails the gate), and emits a bench-shaped artifact
    (``artifacts/soak.json``) whose headline is availability %% —
    distinct rounds over distinct + lost (replayed + restarts) — diffed
    against the committed ``artifacts/SOAK_BASELINE.json`` via
    obs/compare.py (availability down or rounds-lost up is exit 1).
    The campaign is a pure function of its seeds, so the gated numbers
    are deterministic, not timings."""
    # must land before this process's first jax import
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import tempfile

    out = {
        "metric": _SOAK_METRIC,
        "unit": "percent (distinct rounds / (distinct + lost))",
        "measured": True,
        "baseline_ref": _SOAK_BASELINE,
        "soak_spec": _SOAK_SPEC,
    }
    t0 = time.perf_counter()  # graftlint: disable=JG104
    try:
        with tempfile.TemporaryDirectory() as tmp:
            path = _soak_engine_run(tmp)
            from federated_pytorch_test_tpu.control.replay import replay
            from federated_pytorch_test_tpu.obs.report import (
                read_records,
                summarize,
            )

            records = read_records(path)
            s = summarize(records)
            errors, stats = replay(records)
    except Exception as e:      # noqa: BLE001 — report, don't traceback
        out["error"] = f"soak campaign run failed: {type(e).__name__}: {e}"
    else:
        out["value"] = s.get("availability_pct")
        out["soak_rounds_lost"] = s.get("rounds_lost")
        out["soak_rounds_distinct"] = s.get("rounds_distinct")
        out["soak_segments"] = s.get("segments")
        out["soak_restarts"] = s.get("restarts")
        out["soak_reshapes"] = s.get("reshapes")
        out["soak_campaign_records"] = s.get("campaign_records")
        out["soak_virtual_hours"] = s.get("campaign_virtual_hours")
        out["soak_replay_errors"] = len(errors)
        out["soak_replay_records"] = stats
        if errors:
            out["error"] = ("soak stream failed replay verification: "
                            + errors[0])
        elif s.get("restarts", 0) < 2 or not s.get("reshapes"):
            out["error"] = (
                "soak campaign did not exercise the restart path "
                f"(restarts={s.get('restarts')}, "
                f"reshapes={s.get('reshapes')}); the schedule's "
                "preempt_at events must force >= 2 supervised restarts "
                "with >= 1 mesh reshape")
    out["soak_wall_seconds"] = round(time.perf_counter() - t0, 2)  # graftlint: disable=JG104
    out["captured_utc"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    out["git"] = _git_describe()
    art_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "artifacts")
    path = os.path.join(art_dir, "soak.json")
    try:
        os.makedirs(art_dir, exist_ok=True)
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
    except OSError as e:
        print(f"bench: cannot write soak artifact: {e}", file=sys.stderr)
        return 1
    print(json.dumps(out))
    if out.get("error"):
        return 1
    baseline = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            _SOAK_BASELINE)
    if not os.path.exists(baseline):
        print(f"bench: no committed {_SOAK_BASELINE}; soak gate skipped "
              "(commit the emitted artifacts/soak.json there to arm it)",
              file=sys.stderr)
        return 0
    from federated_pytorch_test_tpu.obs import compare as obs_compare

    # the campaign is seed-deterministic; the band only absorbs
    # rounding of the availability percentage
    return obs_compare.main([path, "--baseline", baseline,
                             "--threshold", "5"])


_SERVE_BASELINE = "artifacts/SERVE_BASELINE.json"
_SERVE_METRIC = "serve_qps_chip"
#: the serving gate's traffic: seeded constant-rate requests (the ±10%%
#: per-round jitter still applies), pad-to-bucket batching over three
#: static shapes, a hot-swap every 2 rounds, and a total label shift
#: injected from round 4 on so the served eval stream drifts and the
#: watchdog/policy loop (health window 2, streak 1, act mode) has
#: something to close on.  Every non-timing field in the record stream
#: is a pure function of this spec (PARITY.md v0.14), so replay
#: verification gates exact values; only qps/p99/swap-gap are timings.
_SERVE_SPEC = ("qps=16,round_minutes=0.5,buckets=8+32+128,swap_every=2,"
               "drift_at=4,seed=3")


def _serve_engine_run(tmp: str):
    """Tiny REAL training run with the serving plane on: 8 rounds of
    the 2-block net, consensus weights hot-swapped every 2 rounds,
    seeded traffic served at every round boundary, drift injected from
    round 4.  Returns the run's JSONL path."""
    import flax.linen as nn

    from federated_pytorch_test_tpu.data.cifar10 import FederatedCifar10
    from federated_pytorch_test_tpu.models.base import (
        BlockModule,
        elu,
        flatten,
        max_pool_2x2,
        pairs,
    )
    from federated_pytorch_test_tpu.train import (
        AdmmConsensus,
        BlockwiseFederatedTrainer,
        FederatedConfig,
    )

    class ServeNet(BlockModule):
        @nn.compact
        def __call__(self, x, train=True):
            x = max_pool_2x2(elu(nn.Conv(4, (5, 5), strides=(2, 2),
                                         name="conv1")(x)))
            return nn.Dense(10, name="fc1")(flatten(x))

        def param_order(self):
            return pairs("conv1", "fc1")

        def train_order_block_ids(self):
            return [[0, 1], [2, 3]]

        def linear_layer_ids(self):
            return [1]

    K = 8
    # Nloop * blocks * Nadmm = 2 * 2 * 2 = 8 rounds: enough for 4 swaps
    # and 4 drifted serving rounds after drift_at=4
    cfg = FederatedConfig(K=K, Nloop=2, Nepoch=1, Nadmm=2,
                          default_batch=16, check_results=False,
                          admm_rho0=0.1, seed=0,
                          serve_spec=_SERVE_SPEC, control="act",
                          health_action="warn", health_window=2,
                          health_streak=1, health_tput_frac=0.75,
                          obs_dir=os.path.join(tmp, "obs"),
                          obs_sinks="jsonl")
    data = FederatedCifar10(K=K, batch=16, limit_per_client=16,
                            limit_test=16)
    trainer = BlockwiseFederatedTrainer(ServeNet(), cfg, data,
                                        AdmmConsensus())
    trainer.obs_run_name = "serve"
    trainer.run(log=lambda m: None)
    return os.path.join(tmp, "obs", "serve.jsonl")


def _serve_bench() -> int:
    """``bench.py --serve-bench``: the no-TPU CI gate for the serving
    plane (serve/).  Runs a tiny training run with seeded traffic
    served at every round boundary, verifies the stream with
    ``control.replay`` (the pure serve fields must re-derive from the
    header config alone — any divergence fails the gate), and emits a
    bench-shaped artifact (``artifacts/serve.json``) whose headline is
    sustained QPS per chip, plus p99 latency and the worst hot-swap
    publish gap, diffed against the committed
    ``artifacts/SERVE_BASELINE.json`` via obs/compare.py — exit 1 on
    regression (QPS down, p99/swap-gap up).  The request counts,
    batching plan, swap sequence, and drift schedule are seed-
    deterministic; only the latency/QPS numbers are timings, hence the
    wide noise band."""
    # must land before this process's first jax import
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import tempfile

    out = {
        "metric": _SERVE_METRIC,
        "unit": "requests/sec/chip (batched online inference)",
        "measured": True,
        "baseline_ref": _SERVE_BASELINE,
        "serve_spec": _SERVE_SPEC,
    }
    t0 = time.perf_counter()  # graftlint: disable=JG104
    try:
        with tempfile.TemporaryDirectory() as tmp:
            path = _serve_engine_run(tmp)
            import jax

            from federated_pytorch_test_tpu.control.replay import replay
            from federated_pytorch_test_tpu.obs.report import (
                read_records,
                summarize,
            )

            n_chips = jax.device_count()
            records = read_records(path)
            s = summarize(records)
            errors, stats = replay(records)
    except Exception as e:      # noqa: BLE001 — report, don't traceback
        out["error"] = f"serve bench run failed: {type(e).__name__}: {e}"
    else:
        qps = s.get("serve_qps_mean") or 0.0
        out["value"] = round(qps / max(n_chips, 1), 3)
        out["serve_p99_ms"] = s.get("serve_p99_ms_max")
        out["serve_swap_gap_seconds"] = s.get("serve_swap_gap_max")
        out["serve_qps_mean"] = s.get("serve_qps_mean")
        out["serve_p50_ms_mean"] = s.get("serve_p50_ms_mean")
        # deterministic section (seed-derived, replay-checked): info
        # direction in the diff, but divergence already failed replay
        out["serve_records"] = s.get("serve_records")
        out["serve_requests_total"] = s.get("serve_requests_total")
        out["serve_batches_total"] = s.get("serve_batches_total")
        out["serve_padding_waste_frac"] = s.get("serve_padding_waste_frac")
        out["serve_swaps"] = s.get("serve_swaps")
        out["serve_drift_rounds"] = s.get("serve_drift_rounds")
        out["serve_drift_alerts"] = s.get("serve_drift_alerts")
        out["serve_forced_refreshes"] = s.get("serve_forced_refreshes")
        out["serve_replay_errors"] = len(errors)
        out["serve_replay_records"] = stats
        if errors:
            out["error"] = ("serve stream failed replay verification: "
                            + errors[0])
        elif (s.get("serve_swaps", 0) < 2
                or not s.get("serve_drift_rounds")):
            out["error"] = (
                "serve bench did not exercise the hot-swap/drift path "
                f"(swaps={s.get('serve_swaps')}, "
                f"drift_rounds={s.get('serve_drift_rounds')}); the "
                "serve_spec must force >= 2 swaps and a drifted tail")
    out["serve_wall_seconds"] = round(time.perf_counter() - t0, 2)  # graftlint: disable=JG104
    out["captured_utc"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    out["git"] = _git_describe()
    art_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "artifacts")
    path = os.path.join(art_dir, "serve.json")
    try:
        os.makedirs(art_dir, exist_ok=True)
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
    except OSError as e:
        print(f"bench: cannot write serve artifact: {e}", file=sys.stderr)
        return 1
    print(json.dumps(out))
    if out.get("error"):
        return 1
    baseline = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            _SERVE_BASELINE)
    if not os.path.exists(baseline):
        print(f"bench: no committed {_SERVE_BASELINE}; serve gate skipped "
              "(commit the emitted artifacts/serve.json there to arm it)",
              file=sys.stderr)
        return 0
    from federated_pytorch_test_tpu.obs import compare as obs_compare

    # qps/p99/swap-gap are timings on shared CI boxes: gate only on
    # halving/doubling-scale movement, anything subtler is info
    return obs_compare.main([path, "--baseline", baseline,
                             "--threshold", "50"])


if __name__ == "__main__":
    if "--measure" in sys.argv[1:]:
        sys.exit(_measure_child())
    if "--smoke" in sys.argv[1:]:
        sys.exit(_smoke())
    if "--population-bench" in sys.argv[1:]:
        sys.exit(_population_bench())
    if "--soak" in sys.argv[1:]:
        sys.exit(_soak())
    if "--serve-bench" in sys.argv[1:]:
        sys.exit(_serve_bench())
    main()
