"""federated_pytorch_test_tpu — a TPU-native federated-learning framework.

A from-scratch JAX/XLA re-design of the capabilities of
SarodYatawatta/federated-pytorch-test (reference mounted at /root/reference):
train K models on disjoint 1/K data shards, exchanging only a *subset* of
parameters per round (blockwise federation) under FedAvg / FedProx / adaptive-rho
ADMM consensus, plus federated VAE, clustering-VAE and CPC workloads, and a
stochastic L-BFGS optimizer.

Design (see /root/repo/SURVEY.md section 7):
  * the K clients live on a ``jax.sharding.Mesh`` axis ``'clients'`` instead of a
    sequential Python loop (reference: federated_multi.py:168);
  * blockwise freezing (reference: simple_utils.py:34-45) becomes static boolean
    leaf-masks over the parameter pytree;
  * parameter averaging (reference: federated_multi.py:208-211) becomes
    ``lax.pmean``/``lax.psum`` collectives over ICI;
  * the stochastic L-BFGS (reference: lbfgsnew.py) becomes a jit-compatible
    solver on flat masked parameter vectors.
"""

try:  # single source of truth: pyproject.toml via installed metadata
    from importlib.metadata import PackageNotFoundError, version

    __version__ = version("federated-pytorch-test-tpu")
except PackageNotFoundError:  # uninstalled source checkout: no duplicate
    __version__ = "0.0.0+uninstalled"  # version literal to keep in sync

from federated_pytorch_test_tpu.utils import tree as tree_utils  # noqa: F401
