"""graftcheck — JAX-aware static analysis + runtime sanitizers.

Static side (``core.py`` + ``rules.py`` + ``lint.py``): an AST lint
engine with rules targeting the trace-time failure classes that have
actually bitten this codebase — host syncs inside jitted round loops,
wall-clock timers around async-dispatched computations, PRNG key reuse,
Python control flow on traced values, recompilation hazards, and
missing buffer donation.  Run it as::

    python -m federated_pytorch_test_tpu.analysis.lint \
        federated_pytorch_test_tpu bench.py

Runtime side (``sanitize.py``): ``jax.experimental.checkify`` wiring
(NaN/inf + out-of-bounds index checks) and a retrace sentinel for the
engines, both default-off with the dense path bit-identical — the same
contract as the compress/faults/obs subsystems.
"""

from .core import (  # noqa: F401
    Severity,
    Finding,
    Rule,
    LintEngine,
    load_baseline,
    save_baseline,
)
