"""graftcheck — JAX-aware static analysis + runtime sanitizers.

Static side (``core.py`` + ``rules.py`` + ``flow.py`` + ``lint.py``):
an AST lint engine with rules targeting the trace-time failure classes
that have actually bitten this codebase — host syncs inside jitted
round loops, wall-clock timers around async-dispatched computations,
PRNG key reuse, Python control flow on traced values, recompilation
hazards, and missing buffer donation — plus the interprocedural layer
in ``flow.py``: a whole-program call graph that chases traced values,
donation facts, and PRNG key lineage across function boundaries
(JG108-JG111).  Run it as::

    python -m federated_pytorch_test_tpu.analysis.lint \
        federated_pytorch_test_tpu bench.py

Runtime side (``sanitize.py``): ``jax.experimental.checkify`` wiring
(NaN/inf + out-of-bounds index checks) and a retrace sentinel for the
engines, both default-off with the dense path bit-identical — the same
contract as the compress/faults/obs subsystems.
"""

from .core import (  # noqa: F401
    Severity,
    Finding,
    Rule,
    ProgramRule,
    LintEngine,
    load_baseline,
    save_baseline,
)
from .flow import ALL_RULES  # noqa: F401
