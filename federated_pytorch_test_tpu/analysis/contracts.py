"""graftcheck determinism-contract pass: JG117-JG121.

Every recorded telemetry field is contractually a pure function of
(seed, config, round coordinates) — that is what lets control/replay.py
re-derive control/cohort/campaign/serve records bit-exactly across
kill/resume.  Until now the contract was enforced only dynamically, by
tests that happen to tamper with the right field.  This pass proves it
statically, on the same whole-program summaries the flow rules use:

* **JG117** — wall-clock/OS entropy (``time.time``, ``datetime.now``,
  ``os.urandom``, ``uuid.*``, the process-global ``random`` /
  ``np.random`` draws) reaching a recorded field through any chain of
  call edges.  Fields in ``obs.schema.ADVISORY_FIELDS`` (declared
  timing/diagnostic telemetry) and ``ENVELOPE_FIELDS`` (run identity)
  are exempt — the whole point is that the exemption is *declared*,
  not inferred.
* **JG118** — the schema contract itself: the ``VERSION_LADDER`` in
  obs/schema.py must be strictly additive, every record kind needs a
  non-empty ``REQUIRED`` core, every emitted kind needs a ``check_*``
  checker registered in control/replay.py's ``REPLAY_CHECKERS`` (or an
  explicit exemption), and every registered checker must still exist.
* **JG119** — iteration over an unordered collection (set, dict view,
  ``os.listdir``/glob) feeding a recorded field, or a float ``sum()``
  straight over one, without ``sorted()``.
* **JG120** — the checkpoint-meta contract: keys written on the save
  path must be read on some restore path (and vice versa for
  unconditional reads), and the reserved additive namespaces
  (``pop_*``, ``geom_*``, ledger keys) stay with their owner modules.
* **JG121** — PRNG lineage for records: key material that reaches a
  record-feeding draw must descend from the seeded lineage
  (``PRNGKey``/``fold_in``/``split`` of config seed + round
  coordinates), never from an unseeded generator, entropy, or
  iteration order.

Like every graftcheck pass this one is purely syntactic: the contract
tables are read from the *source* of obs/schema.py and
control/replay.py via ``ast.literal_eval`` (summary ``tables``), never
by importing them.  When the declaring modules are not part of the lint
run (single-fixture invocations), ``DEFAULT_TABLES`` — cross-checked
against the live modules by ``lint --selftest`` — stands in, and the
declaration-site checks are skipped.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .core import Finding, ModuleContext, ProgramRule, Rule, Severity
from .flow import _label, _mk_finding, _program_of, Program

#: fallback contract tables for lint runs that do not include
#: obs/schema.py / control/replay.py (fixture runs, --changed slices).
#: ``lint --selftest`` asserts these mirror the declaring modules, so
#: they cannot drift silently.
DEFAULT_TABLES: Dict[str, object] = {
    "ADVISORY_FIELDS": (
        "time_unix", "round_seconds", "stage_seconds", "train_seconds",
        "comm_seconds", "sync_seconds", "compute_seconds",
        "epoch_seconds", "ckpt_write_seconds", "overlap_seconds",
        "overlap_dispatch_seconds", "compile_seconds", "t_start", "t_end",
        "serve_p50_ms", "serve_p99_ms", "serve_qps", "swap_gap_seconds",
        "serve_accuracy", "drift_score", "forced_refresh",
        "total_seconds", "round_seconds_total", "stage_seconds_total",
        "comm_seconds_total", "compile_seconds_total",
        "rounds_per_sec", "images_per_sec", "comm_overhead_frac",
        "captured_utc", "last_error",
    ),
    "ENVELOPE_FIELDS": (
        "event", "schema", "run_id", "run_name", "span_id",
        "parent_span", "engine", "algorithm", "host", "pid", "git_rev",
        "devices", "local_devices", "platform", "jax_version",
        "jaxlib_version", "resumed", "rounds_prior", "config",
        "mesh_shape",
    ),
    "DIAGNOSTIC_KINDS": ("sink_degraded",),
    "RESERVED_META_NAMESPACES": (
        ("pop_", ("population.registry",)),
        ("geom_", ("utils.checkpoint",)),
        ("members", ("utils.checkpoint",)),
    ),
    "EVENTS": ("run_header", "round", "summary", "span", "alert",
               "compile", "control", "client", "campaign", "serve"),
    "REPLAY_CHECKERS": {
        "control": ("check_policy_records", "check_supervisor_records",
                    "check_reshape_records"),
        "client": ("check_cohort_records",),
        "campaign": ("check_campaign_records",),
        "serve": ("check_serve_records",),
    },
    "REPLAY_EXEMPT_KINDS": ("run_header", "round", "summary", "span",
                            "alert", "compile"),
}

#: which module declares each table — a declaration from the canonical
#: owner wins over any other (fixture) declaration in the same run
_TABLE_OWNERS = {
    "ADVISORY_FIELDS": "obs.schema", "ENVELOPE_FIELDS": "obs.schema",
    "VERSION_LADDER": "obs.schema", "SCHEMA_VERSION": "obs.schema",
    "EVENTS": "obs.schema", "REQUIRED": "obs.schema",
    "DIAGNOSTIC_KINDS": "obs.schema",
    "RESERVED_META_NAMESPACES": "obs.schema",
    "REPLAY_CHECKERS": "control.replay",
    "REPLAY_EXEMPT_KINDS": "control.replay",
}


# ================================================================ model

def _blocked(fn: dict) -> Set[str]:
    """Names statically known to carry *seeded* rng lineage: entropy
    and iteration-order taint stops there (JG121 owns them instead)."""
    out: Set[str] = set()
    for rc in fn.get("rng_ctors", ()):
        out.add(rc["name"])
    for kd in fn.get("key_derives", ()):
        out.add(kd["name"])
    return out


def _closure_reasons(fn: dict, seed: Dict[str, str],
                     blocked: Set[str]) -> Dict[str, str]:
    """Close a name->reason taint map over the function's derives."""
    out = {n: r for n, r in seed.items() if n not in blocked}
    derives = fn.get("derives", ())
    for _ in range(len(derives) + 1):
        changed = False
        for target, srcs in derives:
            if target in blocked or target in out:
                continue
            hit = next((s for s in srcs if s in out), None)
            if hit is not None:
                out[target] = out[hit]
                changed = True
        if not changed:
            break
    return out


def _site(fn: dict, line: int) -> str:
    return "%s:%d" % (_label(fn).split(":")[0], line)


class _Model:
    """Whole-program contract state, built once per lint run: the
    declared tables (with provenance) and the three taint families."""

    def __init__(self, prog: Program, live: Dict[str, ModuleContext]):
        self.prog = prog
        self.live = live

        # -------- contract tables: every declaration, with provenance
        self.declared: Dict[str, List[Tuple[object, str, int, str]]] = {}
        for s in sorted(prog.summaries, key=lambda s: s["path"]):
            for name, val in (s.get("tables") or {}).items():
                self.declared.setdefault(name, []).append(
                    (val[0], s["path"], val[1], s["module_name"]))

        # -------- taint: entropy / bad-rng / iteration-order
        fns = [f for f in prog.all_fns()]
        self.ent: Dict[int, Dict[str, str]] = {id(f): {} for f in fns}
        self.bad: Dict[int, Dict[str, str]] = {id(f): {} for f in fns}
        self.order: Dict[int, Dict[str, str]] = {}
        self.ent_ret: Dict[int, Optional[str]] = {id(f): None for f in fns}
        self.bad_ret: Dict[int, Optional[str]] = {id(f): None for f in fns}
        self._ent_params: Dict[int, Dict[str, str]] = \
            {id(f): {} for f in fns}
        self._bad_params: Dict[int, Dict[str, str]] = \
            {id(f): {} for f in fns}
        self._resolved: Dict[Tuple[int, int], list] = {}

        for f in fns:
            seeds: Dict[str, str] = {}
            for u in f.get("unordered", ()):
                why = "iterates %s at %s" % (u["src"], _site(f, u["line"]))
                for t in u["targets"]:
                    seeds.setdefault(t, why)
            self.order[id(f)] = _closure_reasons(f, seeds, _blocked(f))

        for _ in range(20):
            if not self._iterate(fns):
                break

    # ------------------------------------------------------- fixpoint

    def _targets(self, fn: dict, idx: int, call: dict) -> list:
        key = (id(fn), idx)
        if key not in self._resolved:
            try:
                self._resolved[key] = self.prog.resolve(fn, call["callee"])
            except RecursionError:          # pathological alias cycles
                self._resolved[key] = []
        return self._resolved[key]

    def _iterate(self, fns: List[dict]) -> bool:
        changed = False
        for f in fns:
            fid = id(f)
            blocked = _blocked(f)
            order = self.order[fid]

            ent_seed: Dict[str, str] = dict(self._ent_params[fid])
            for name, src, line in f.get("entropy", ()):
                ent_seed.setdefault(
                    name, "%s at %s" % (src, _site(f, line)))
            for idx, call in enumerate(f.get("calls", ())):
                assigned = call.get("assigned")
                if not assigned:
                    continue
                for tgt in self._targets(f, idx, call):
                    why = self.ent_ret.get(id(tgt.fn))
                    if why:
                        for n in assigned:
                            ent_seed.setdefault(
                                n, "%s (returned by %s)"
                                % (why, _label(tgt.fn)))
            ent = _closure_reasons(f, ent_seed, blocked)
            if ent.keys() != self.ent[fid].keys():
                self.ent[fid] = ent
                changed = True
            else:
                self.ent[fid] = ent

            bad_seed: Dict[str, str] = dict(self._bad_params[fid])
            for rc in f.get("rng_ctors", ()):
                why = None
                if rc.get("unseeded"):
                    why = "unseeded %s() at %s" % (rc["ctor"],
                                                   _site(f, rc["line"]))
                elif rc.get("esrc"):
                    why = "%s seeded from %s at %s" % (
                        rc["ctor"], rc["esrc"][0], _site(f, rc["line"]))
                else:
                    hit = next((n for n in rc.get("feeds", ())
                                if n in ent or n in order), None)
                    if hit is not None:
                        why = "%s seeded from tainted %r (%s) at %s" % (
                            rc["ctor"], hit,
                            ent.get(hit) or order.get(hit),
                            _site(f, rc["line"]))
                if why:
                    bad_seed.setdefault(rc["name"], why)
            for kd in f.get("key_derives", ()):
                hit = next((n for n in kd.get("feeds", ())
                            if n in ent or n in order), None)
                if kd.get("esrc"):
                    bad_seed.setdefault(
                        kd["name"], "key folded with %s at %s"
                        % (kd["esrc"][0], _site(f, kd["line"])))
                elif hit is not None:
                    bad_seed.setdefault(
                        kd["name"], "key folded with tainted %r (%s) at %s"
                        % (hit, ent.get(hit) or order.get(hit),
                           _site(f, kd["line"])))
            for idx, call in enumerate(f.get("calls", ())):
                assigned = call.get("assigned")
                if not assigned:
                    continue
                for tgt in self._targets(f, idx, call):
                    why = self.bad_ret.get(id(tgt.fn))
                    if why:
                        for n in assigned:
                            bad_seed.setdefault(
                                n, "%s (returned by %s)"
                                % (why, _label(tgt.fn)))
            bad = _closure_reasons(f, bad_seed, set())
            if bad.keys() != self.bad[fid].keys():
                self.bad[fid] = bad
                changed = True
            else:
                self.bad[fid] = bad

            # ---- returns
            ent_ret = next(iter(f.get("ret_esrc", ())), None)
            if ent_ret:
                ent_ret = "%s returned by %s" % (ent_ret, _label(f))
            bad_ret = None
            for n in f.get("ret_loads", ()):
                if ent_ret is None and n in ent:
                    ent_ret = ent[n]
                if bad_ret is None and n in bad:
                    bad_ret = bad[n]
            if ent_ret != self.ent_ret[fid]:
                self.ent_ret[fid] = ent_ret
                changed = True
            if bad_ret != self.bad_ret[fid]:
                self.bad_ret[fid] = bad_ret
                changed = True

            # ---- caller -> callee argument taint
            for idx, call in enumerate(f.get("calls", ())):
                targets = self._targets(f, idx, call)
                if not targets:
                    continue
                for pos, arg in enumerate(call.get("args", ())):
                    loads = arg.get("loads") or ()
                    e_hit = next((n for n in loads if n in ent), None)
                    b_hit = next((n for n in loads if n in bad), None)
                    if e_hit is None and b_hit is None:
                        continue
                    for tgt in targets:
                        param = tgt.param_for_pos(pos)
                        if param is None:
                            continue
                        tp = id(tgt.fn)
                        if e_hit is not None and \
                                param not in self._ent_params[tp]:
                            self._ent_params[tp][param] = \
                                "%s (passed by %s)" % (ent[e_hit],
                                                       _label(f))
                            changed = True
                        if b_hit is not None and \
                                param not in self._bad_params[tp]:
                            self._bad_params[tp][param] = \
                                "%s (passed by %s)" % (bad[b_hit],
                                                       _label(f))
                            changed = True
                for kwname, desc in (call.get("kw") or {}).items():
                    loads = (desc or {}).get("loads") or ()
                    e_hit = next((n for n in loads if n in ent), None)
                    b_hit = next((n for n in loads if n in bad), None)
                    if e_hit is None and b_hit is None:
                        continue
                    for tgt in targets:
                        if kwname not in tgt.fn["params"]:
                            continue
                        tp = id(tgt.fn)
                        if e_hit is not None and \
                                kwname not in self._ent_params[tp]:
                            self._ent_params[tp][kwname] = \
                                "%s (passed by %s)" % (ent[e_hit],
                                                       _label(f))
                            changed = True
                        if b_hit is not None and \
                                kwname not in self._bad_params[tp]:
                            self._bad_params[tp][kwname] = \
                                "%s (passed by %s)" % (bad[b_hit],
                                                       _label(f))
                            changed = True
        return changed

    # --------------------------------------------------------- tables

    def table(self, name: str):
        """The consumed value of one contract table: the canonical
        owner's declaration if present, else any declaration, else the
        DEFAULT_TABLES mirror."""
        decls = self.declared.get(name, ())
        owner = _TABLE_OWNERS.get(name)
        for val, _path, _line, modname in decls:
            if owner and (modname == owner
                          or modname.endswith("." + owner)):
                return val
        if decls:
            return decls[0][0]
        return DEFAULT_TABLES.get(name)

    def exempt_fields(self) -> Set[str]:
        adv = self.table("ADVISORY_FIELDS") or ()
        env = self.table("ENVELOPE_FIELDS") or ()
        return set(adv) | set(env)

    # ---------------------------------------------------------- sinks

    def sinks(self, fn: dict) -> Iterator[Tuple[str, dict]]:
        """(record kind, store fact) for every recorded-field store in
        ``fn``: stores into a dict that carries a literal ``"event"``
        kind or is passed to a recorder method, plus inline dict-literal
        entries at the recorder call itself."""
        kinds: Dict[str, str] = dict(fn.get("dkinds") or {})
        for rc in fn.get("rec_calls", ()):
            if rc.get("var"):
                kinds.setdefault(rc["var"], rc["kind"])
        if kinds:
            for ds in fn.get("dstores", ()):
                var = ds.get("var")
                if var is not None and var in kinds:
                    yield kinds[var], ds
        for rc in fn.get("rec_calls", ()):
            for e in rc.get("entries", ()):
                yield rc["kind"], e

    def emit_sites(self, fn: dict) -> Iterator[Tuple[str, int, int]]:
        """(kind, line, col) for every record-emission site in ``fn``."""
        for ds in fn.get("dstores", ()):
            var = ds.get("var")
            if (var is not None and ds["key"] == "event"
                    and (fn.get("dkinds") or {}).get(var)):
                yield fn["dkinds"][var], ds["line"], ds["col"]
        for rc in fn.get("rec_calls", ()):
            if rc.get("var") or rc.get("entries"):
                yield rc["kind"], rc["line"], rc["col"]


def _model_of(modules: Sequence[ModuleContext],
              extra_summaries: Sequence[dict], state: dict) -> _Model:
    if "contract_model" not in state:
        prog, live = _program_of(modules, extra_summaries, state)
        state["contract_model"] = _Model(prog, live)
    return state["contract_model"]


def _live_fns(model: _Model) -> Iterator[dict]:
    for fn in model.prog.all_fns():
        if fn["_path"] in model.live:
            yield fn


# ================================================================ JG117

class EntropyIntoRecord(ProgramRule):
    """Wall-clock / OS entropy flowing into a replay-checked record
    field.  Core record fields must be pure functions of (seed, config,
    round coordinates); timing telemetry belongs in a field declared in
    ``obs.schema.ADVISORY_FIELDS``.  This is the rule that catches
    ``time.time()`` leaking into ``observed`` — or a wall-clock
    ``backoff_seconds`` replacing the seeded one."""

    id = "JG117"
    severity = Severity.ERROR

    def check_program(self, modules, extra_summaries, state):
        model = _model_of(modules, extra_summaries, state)
        exempt = model.exempt_fields()
        for fn in _live_fns(model):
            ent = model.ent[id(fn)]
            for kind, ds in model.sinks(fn):
                if ds["key"] in exempt:
                    continue
                why = None
                if ds.get("esrc"):
                    why = "%s called inline" % ds["esrc"][0]
                else:
                    hit = next((n for n in ds.get("loads", ())
                                if n in ent), None)
                    if hit is not None:
                        why = "%r carries %s" % (hit, ent[hit])
                    else:
                        for d in ds.get("calls", ()):
                            for tgt in model.prog.resolve(
                                    fn, {"k": "dotted", "v": d}):
                                r = model.ent_ret.get(id(tgt.fn))
                                if r:
                                    why = "%s() returns %s" % (d, r)
                                    break
                            if why:
                                break
                if why is None:
                    continue
                yield _mk_finding(
                    self, model.live, fn["_path"], ds["line"], ds["col"],
                    "entropy reaches recorded field %r of a %r record: "
                    "%s. Core fields must re-derive from (seed, config, "
                    "round coords) for control.replay; wall-clock "
                    "telemetry belongs in an ADVISORY_FIELDS field "
                    "(obs/schema.py)." % (ds["key"], kind, why),
                    (_label(fn),))


# ================================================================ JG118

_LADDER_KEYS = {"version", "added_kinds", "added_fields"}


class SchemaContract(ProgramRule):
    """The additive-schema + replay-coverage contract.

    Declaration-site checks (only when the declaring module is in the
    lint run): the ``VERSION_LADDER`` must be strictly increasing,
    carry no ``removed_fields``/``removed_kinds`` rungs, top out at
    ``SCHEMA_VERSION``, introduce every ``EVENTS`` kind exactly once,
    and every kind needs a non-empty ``REQUIRED`` core.  Every checker
    named in ``REPLAY_CHECKERS`` must exist in the declaring module.
    Emit-site check (always): a record kind emitted anywhere must be
    replay-checked, replay-exempt, or a declared diagnostic."""

    id = "JG118"
    severity = Severity.ERROR

    def check_program(self, modules, extra_summaries, state):
        model = _model_of(modules, extra_summaries, state)
        yield from self._check_ladders(model)
        yield from self._check_checkers(model)
        yield from self._check_emits(model)

    # ------------------------------------------------- ladder shape

    def _sibling(self, model: _Model, path: str, name: str):
        for val, p, _line, _mod in model.declared.get(name, ()):
            if p == path:
                return val
        return None

    def _check_ladders(self, model: _Model) -> Iterator[Finding]:
        for val, path, line, _mod in model.declared.get(
                "VERSION_LADDER", ()):
            if path not in model.live:
                continue

            def bad(msg: str, ln: int = line) -> Finding:
                return _mk_finding(self, model.live, path, ln, 0,
                                   "schema contract violated: " + msg,
                                   ())

            if not isinstance(val, (list, tuple)) or not val or \
                    not all(isinstance(r, dict) for r in val):
                yield bad("VERSION_LADDER must be a non-empty tuple of "
                          "rung dicts")
                continue
            versions = [r.get("version") for r in val]
            if not all(isinstance(v, int) for v in versions) or \
                    any(b <= a for a, b in zip(versions, versions[1:])):
                yield bad("VERSION_LADDER versions must be strictly "
                          "increasing ints (got %r)" % (versions,))
            for rung in val:
                extra = set(rung) - _LADDER_KEYS
                removed = {k for k in extra if k.startswith("removed")}
                if removed:
                    yield bad(
                        "rung v%r is non-additive: %s. The schema only "
                        "ever *adds* kinds/fields — removing one breaks "
                        "every reader of an older stream"
                        % (rung.get("version"), ", ".join(sorted(removed))))
            schema_version = self._sibling(model, path, "SCHEMA_VERSION")
            if isinstance(schema_version, int) and versions and \
                    isinstance(versions[-1], int) and \
                    versions[-1] != schema_version:
                yield bad("VERSION_LADDER tops out at v%r but "
                          "SCHEMA_VERSION is %r — the ladder must "
                          "record every bump" % (versions[-1],
                                                 schema_version))
            events = self._sibling(model, path, "EVENTS")
            required = self._sibling(model, path, "REQUIRED")
            if isinstance(events, (list, tuple)):
                for kind in events:
                    rungs = [r.get("version") for r in val
                             if isinstance(r.get("added_kinds"),
                                           (list, tuple))
                             and kind in r["added_kinds"]]
                    if len(rungs) != 1:
                        yield bad("record kind %r must be introduced by "
                                  "exactly one ladder rung (found in %r)"
                                  % (kind, rungs))
                    if isinstance(required, dict) and \
                            not required.get(kind):
                        yield bad("record kind %r has no REQUIRED core "
                                  "— every kind needs a stable required-"
                                  "field set" % (kind,))

    # --------------------------------------------- checker existence

    def _check_checkers(self, model: _Model) -> Iterator[Finding]:
        for val, path, line, _mod in model.declared.get(
                "REPLAY_CHECKERS", ()):
            if path not in model.live or not isinstance(val, dict):
                continue
            summary = model.prog.by_path.get(path)
            fns = summary["functions"] if summary else {}
            for kind in sorted(val):
                names = val[kind]
                if not isinstance(names, (list, tuple)):
                    continue
                for nm in names:
                    if nm not in fns:
                        yield _mk_finding(
                            self, model.live, path, line, 0,
                            "REPLAY_CHECKERS registers %r for kind %r "
                            "but no such function exists in this module "
                            "— the replay contract for %r records is "
                            "silently unenforced" % (nm, kind, kind), ())

    # ------------------------------------------------ emit coverage

    def _check_emits(self, model: _Model) -> Iterator[Finding]:
        events = set(model.table("EVENTS") or ())
        checkers = set((model.table("REPLAY_CHECKERS") or {}).keys())
        exempt = set(model.table("REPLAY_EXEMPT_KINDS") or ())
        diagnostic = set(model.table("DIAGNOSTIC_KINDS") or ())
        covered = checkers | exempt | diagnostic
        for fn in _live_fns(model):
            for kind, line, col in model.emit_sites(fn):
                if kind in events and kind not in covered:
                    yield _mk_finding(
                        self, model.live, fn["_path"], line, col,
                        "record kind %r is emitted here but has no "
                        "check_* checker in control/replay.py's "
                        "REPLAY_CHECKERS and is not REPLAY_EXEMPT — "
                        "its records would never be replay-verified"
                        % (kind,), (_label(fn),))


# ================================================================ JG119

class UnorderedIntoRecord(ProgramRule):
    """Set/dict-order nondeterminism feeding a recorded field, or a
    float ``sum()`` taken straight over an unordered source.  Iteration
    order over sets (and, through them, any hash-order artifact) is not
    a function of (seed, config, round coords); ``sorted()`` restores
    the contract."""

    id = "JG119"
    severity = Severity.WARNING

    def check_program(self, modules, extra_summaries, state):
        model = _model_of(modules, extra_summaries, state)
        exempt = model.exempt_fields()
        for fn in _live_fns(model):
            order = model.order[id(fn)]
            for kind, ds in model.sinks(fn):
                if ds["key"] in exempt:
                    continue
                hit = next((n for n in ds.get("loads", ())
                            if n in order), None)
                if hit is None:
                    continue
                yield _mk_finding(
                    self, model.live, fn["_path"], ds["line"], ds["col"],
                    "recorded field %r of a %r record depends on "
                    "iteration order: %r %s. Wrap the iteration in "
                    "sorted() so the record re-derives bit-exactly."
                    % (ds["key"], kind, hit, order[hit]), (_label(fn),))
            for us in fn.get("usums", ()):
                if us.get("fn") != "sum":
                    continue
                yield _mk_finding(
                    self, model.live, fn["_path"], us["line"], us["col"],
                    "float reduction sum() over %s accumulates in "
                    "iteration order — float addition is not "
                    "associative, so the result is not a pure function "
                    "of the inputs. Reduce over sorted(...) instead."
                    % (us["src"],), (_label(fn),))


# ================================================================ JG120

class CheckpointMetaContract(ProgramRule):
    """Checkpoint-meta balance: every key written on a save path must
    be read by some restore path (and every unconditional restore read
    needs a writer), and reserved namespaces stay with their owners.
    Guarded reads (``meta.get(k, d)``, ``"k" in meta``, or a subscript
    dominated by a same-function membership test) are optional by
    design and never demand a writer."""

    id = "JG120"
    severity = Severity.WARNING

    def _carriers(self, fn: dict) -> Set[str]:
        out: Set[str] = set()
        if "meta" in fn.get("params", ()):
            out.add("meta")
        for ds in fn.get("dstores", ()):
            if ds.get("var") == "meta":
                out.add("meta")
        for dl in fn.get("dloads", ()):
            if dl.get("var") == "meta":
                out.add("meta")
        name = fn.get("name") or ""
        if name == "meta" or name.endswith("_meta"):
            for ret in fn.get("returns", ()):
                for elt in ret:
                    if elt.get("k") == "name":
                        out.add(elt["v"])
        return out

    def check_program(self, modules, extra_summaries, state):
        model = _model_of(modules, extra_summaries, state)
        writes: Dict[str, List[tuple]] = {}
        reads: Dict[str, List[tuple]] = {}
        soft: Set[Tuple[int, str]] = set()
        for fn in model.prog.all_fns():
            carriers = self._carriers(fn)
            if not carriers:
                continue
            for ds in fn.get("dstores", ()):
                if ds.get("var") in carriers and ds["key"] != "event":
                    writes.setdefault(ds["key"], []).append(
                        (fn, ds["line"], ds["col"]))
            for dl in fn.get("dloads", ()):
                if dl.get("var") not in carriers:
                    continue
                reads.setdefault(dl["key"], []).append(
                    (fn, dl["line"], dl["col"], dl.get("hard", False)))
                if not dl.get("hard", False):
                    soft.add((id(fn), dl["key"]))

        if writes and reads:
            for key in sorted(writes):
                if key in reads:
                    continue
                for fn, line, col in writes[key]:
                    if fn["_path"] not in model.live:
                        continue
                    yield _mk_finding(
                        self, model.live, fn["_path"], line, col,
                        "checkpoint-meta key %r is written on the save "
                        "path but never read on any restore path — "
                        "either dead weight in every checkpoint or a "
                        "restore-side check that silently never "
                        "happens" % (key,), (_label(fn),))
            for key in sorted(reads):
                if key in writes:
                    continue
                for fn, line, col, hard in reads[key]:
                    if not hard or fn["_path"] not in model.live:
                        continue
                    if (id(fn), key) in soft:
                        continue        # membership-guarded: optional
                    yield _mk_finding(
                        self, model.live, fn["_path"], line, col,
                        "checkpoint-meta key %r is read unconditionally "
                        "on the restore path but no save path writes it "
                        "— restore would KeyError on every real "
                        "checkpoint" % (key,), (_label(fn),))

        namespaces = model.table("RESERVED_META_NAMESPACES") or ()
        for key in sorted(writes):
            for ns_entry in namespaces:
                ns, owners = ns_entry[0], tuple(ns_entry[1])
                match = (key.startswith(ns) if ns.endswith("_")
                         else key == ns)
                if not match:
                    continue
                for fn, line, col in writes[key]:
                    if fn["_path"] not in model.live:
                        continue
                    modname = fn["_mod"]["module_name"]
                    if any(modname == o or modname.endswith("." + o)
                           for o in owners):
                        continue
                    yield _mk_finding(
                        self, model.live, fn["_path"], line, col,
                        "checkpoint-meta key %r collides with the "
                        "reserved namespace %r owned by %s — pick a "
                        "different prefix or move the write into the "
                        "owner" % (key, ns, "/".join(owners)),
                        (_label(fn),))


# ================================================================ JG121

class RoguePrngIntoRecord(ProgramRule):
    """A recorded field fed by a draw whose key material does not
    descend from the seeded lineage.  Record-feeding randomness must
    derive from ``cfg.seed`` + round coordinates via
    ``fold_in``/``split`` (or a seeded ``PRNGKey``/``default_rng``);
    an unseeded generator — or one seeded from entropy or iteration
    order — breaks bit-exact replay even though the value *looks*
    random either way."""

    id = "JG121"
    severity = Severity.ERROR

    def check_program(self, modules, extra_summaries, state):
        model = _model_of(modules, extra_summaries, state)
        exempt = model.exempt_fields()
        for fn in _live_fns(model):
            ent = model.ent[id(fn)]
            bad = model.bad[id(fn)]
            for kind, ds in model.sinks(fn):
                if ds["key"] in exempt:
                    continue
                if ds.get("esrc"):
                    continue            # JG117 owns inline entropy
                if any(n in ent for n in ds.get("loads", ())):
                    continue            # JG117 owns entropy taint
                why = None
                hit = next((n for n in ds.get("loads", ())
                            if n in bad), None)
                if hit is not None:
                    why = "%r carries %s" % (hit, bad[hit])
                else:
                    for d in ds.get("calls", ()):
                        for tgt in model.prog.resolve(
                                fn, {"k": "dotted", "v": d}):
                            r = model.bad_ret.get(id(tgt.fn))
                            if r:
                                why = "%s() returns %s" % (d, r)
                                break
                        if why:
                            break
                if why is None:
                    continue
                yield _mk_finding(
                    self, model.live, fn["_path"], ds["line"], ds["col"],
                    "recorded field %r of a %r record is fed by PRNG "
                    "material outside the seeded lineage: %s. Derive "
                    "record-feeding keys from cfg.seed + round coords "
                    "via fold_in/split so replay re-draws the same "
                    "value." % (ds["key"], kind, why), (_label(fn),))


CONTRACT_RULES: Tuple[Rule, ...] = (
    EntropyIntoRecord(), SchemaContract(), UnorderedIntoRecord(),
    CheckpointMetaContract(), RoguePrngIntoRecord(),
)
