"""Rule framework for graftcheck: findings, suppressions, baselines.

Design:

- A :class:`Rule` inspects one parsed module (:class:`ModuleContext`)
  and yields :class:`Finding`\\ s.  Rules are pure AST passes — no
  imports of the linted code, so the linter can run on trees that do
  not import (and on fixture snippets that would crash at runtime).
- Per-line suppression: ``# graftlint: disable=JG101`` (comma list, or
  ``all``) on the flagged line silences the finding.
- A :class:`ProgramRule` inspects the *whole program* at once (every
  module handed to one lint run, plus cached summaries of unchanged
  modules in ``--changed`` mode) and may anchor a finding in any of
  the live modules.  The interprocedural rules in ``flow.py`` are
  program rules.
- Baseline: a committed JSON file of finding *fingerprints* —
  ``sha1(normalized path :: rule :: stripped source line :: chain)``
  — so grandfathered findings survive line drift but resurface when
  the line changes.  Paths are normalized to posix form relative to
  the working directory, so fingerprints are stable across checkouts
  and across the files a call chain spans.  The shipped baseline is
  empty: every finding of the shipped rules was fixed, not baselined.
- Exit policy: findings at or above the ``fail_on`` severity
  (default WARNING) that are neither suppressed nor baselined fail the
  run.  ADVICE findings report but never fail at the default level.
"""

from __future__ import annotations

import ast
import enum
import hashlib
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import (Dict, Iterable, Iterator, List, Optional, Sequence, Set,
                    Tuple)


class Severity(enum.IntEnum):
    """Ordered so ``severity >= fail_on`` is the exit-code test."""

    ADVICE = 10
    WARNING = 20
    ERROR = 30

    @classmethod
    def parse(cls, name: str) -> "Severity":
        try:
            return cls[name.upper()]
        except KeyError:
            raise ValueError(
                f"unknown severity {name!r}; expected one of "
                f"{[s.name.lower() for s in cls]}") from None


def norm_path(path: str) -> str:
    """Posix path relative to the working directory when it is under it
    (absolute and relative spellings of the same file fingerprint
    identically; checkouts rooted elsewhere still agree with each
    other)."""
    pp = Path(path)
    try:
        pp = pp.resolve().relative_to(Path.cwd().resolve())
    except (ValueError, OSError):
        pass
    return pp.as_posix()


@dataclass(frozen=True)
class Finding:
    path: str                 # as given on the command line (relative ok)
    line: int                 # 1-based
    col: int                  # 0-based (ast convention)
    rule_id: str              # "JG101"
    severity: Severity
    message: str
    source_line: str = ""     # stripped text of the flagged line
    call_chain: Tuple[str, ...] = ()   # interprocedural path, outermost first

    def fingerprint(self) -> str:
        """Stable id for baselining: survives line-number drift, breaks
        when the flagged line's content changes.  The call chain is part
        of the identity — two hazards reached through different chains
        are different findings even when anchored on the same line."""
        key = f"{norm_path(self.path)}::{self.rule_id}::{self.source_line}"
        if self.call_chain:
            key += "::" + " -> ".join(self.call_chain)
        return hashlib.sha1(key.encode("utf-8")).hexdigest()

    def to_json(self) -> Dict[str, object]:
        return {
            "path": norm_path(self.path),
            "line": self.line,
            "col": self.col,
            "rule": self.rule_id,
            "severity": self.severity.name.lower(),
            "message": self.message,
            "source_line": self.source_line,
            "call_chain": list(self.call_chain),
            "fingerprint": self.fingerprint(),
        }

    def render(self) -> str:
        chain = (f"  [via {' -> '.join(self.call_chain)}]"
                 if self.call_chain else "")
        return (f"{self.path}:{self.line}:{self.col + 1}: "
                f"[{self.rule_id} {self.severity.name.lower()}] "
                f"{self.message}{chain}")


@dataclass
class ModuleContext:
    """One parsed source file handed to every rule."""

    path: str
    source: str
    tree: ast.Module
    lines: List[str] = field(default_factory=list)

    def __post_init__(self):
        if not self.lines:
            self.lines = self.source.splitlines()

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


class Rule:
    """Base class: subclasses set ``id``/``severity`` and implement
    :meth:`check`."""

    id: str = "JG000"
    severity: Severity = Severity.WARNING
    summary: str = ""

    def check(self, module: ModuleContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, module: ModuleContext, node: ast.AST,
                message: str,
                call_chain: Sequence[str] = ()) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(path=module.path, line=line, col=col,
                       rule_id=self.id, severity=self.severity,
                       message=message,
                       source_line=module.line_text(line),
                       call_chain=tuple(call_chain))


class ProgramRule(Rule):
    """A rule that sees the whole program at once.

    ``check_program`` receives every live :class:`ModuleContext` of the
    lint run plus ``extra_summaries`` — pre-extracted, JSON-shaped
    module summaries standing in for files that were *not* re-parsed
    (the ``--changed`` fast path).  Findings must anchor in one of the
    live modules; the engine drops any finding anchored elsewhere.
    ``state`` is a per-run scratch dict shared by all program rules so
    expensive artifacts (the call graph) are built once.
    """

    def check(self, module: ModuleContext) -> Iterator[Finding]:
        return iter(())

    def check_program(self, modules: Sequence[ModuleContext],
                      extra_summaries: Sequence[dict],
                      state: dict) -> Iterator[Finding]:
        raise NotImplementedError


# --------------------------------------------------------------- suppression

_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\s]+)")


def suppressed_rules_by_line(source: str) -> Dict[int, Set[str]]:
    """Map 1-based line -> set of rule ids (or {"all"}) disabled there."""
    out: Dict[int, Set[str]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if m:
            ids = {t.strip() for t in m.group(1).split(",") if t.strip()}
            out[i] = {t.lower() if t.lower() == "all" else t.upper()
                      for t in ids}
    return out


def is_suppressed(finding: Finding,
                  suppressions: Dict[int, Set[str]]) -> bool:
    ids = suppressions.get(finding.line)
    if not ids:
        return False
    return "all" in ids or finding.rule_id in ids


# ----------------------------------------------------------------- baseline

BASELINE_VERSION = 1


def load_baseline(path: Path) -> Set[str]:
    data = json.loads(Path(path).read_text())
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: version {data.get('version')!r} "
            f"!= {BASELINE_VERSION}")
    fps = data.get("findings", [])
    if not isinstance(fps, list):
        raise ValueError(f"baseline {path}: 'findings' must be a list")
    return set(fps)


def save_baseline(path: Path, findings: Iterable[Finding]) -> None:
    fps = sorted({f.fingerprint() for f in findings})
    Path(path).write_text(json.dumps(
        {"version": BASELINE_VERSION, "findings": fps}, indent=2) + "\n")


# ------------------------------------------------------------------- engine

@dataclass
class LintResult:
    findings: List[Finding]           # reportable (not suppressed/baselined)
    suppressed: int = 0
    baselined: int = 0

    def failing(self, fail_on: Severity = Severity.WARNING) -> List[Finding]:
        return [f for f in self.findings if f.severity >= fail_on]


class LintEngine:
    """Runs a rule set over files/trees and applies the filtering
    pipeline (syntax -> module rules -> program rules -> suppressions
    -> baseline)."""

    def __init__(self, rules: Sequence[Rule],
                 baseline: Optional[Set[str]] = None):
        self.rules = [r for r in rules if not isinstance(r, ProgramRule)]
        self.program_rules = [r for r in rules if isinstance(r, ProgramRule)]
        self.baseline = baseline or set()

    def _parse(self, source: str, path: str
               ) -> Tuple[Optional[ModuleContext], Optional[Finding]]:
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as exc:
            f = Finding(path=path, line=exc.lineno or 1,
                        col=(exc.offset or 1) - 1, rule_id="JG000",
                        severity=Severity.ERROR,
                        message=f"syntax error: {exc.msg}",
                        source_line="")
            return None, f
        return ModuleContext(path=path, source=source, tree=tree), None

    def lint_modules(self, modules: Sequence[ModuleContext],
                     extra_summaries: Sequence[dict] = ()) -> LintResult:
        """The full pipeline over already-parsed modules.  Program
        rules see ``modules + extra_summaries`` but may only anchor
        findings inside ``modules`` (the live set); anything anchored
        in a summary-only file is dropped — a full run owns those."""
        supp = {m.path: suppressed_rules_by_line(m.source) for m in modules}
        live = set(supp)
        raw: List[Finding] = []
        for module in modules:
            for rule in self.rules:
                raw.extend(rule.check(module))
        state: dict = {}
        for rule in self.program_rules:
            raw.extend(f for f in rule.check_program(modules,
                                                     extra_summaries, state)
                       if f.path in live)
        kept: List[Finding] = []
        n_sup = n_base = 0
        for finding in raw:
            if is_suppressed(finding, supp.get(finding.path, {})):
                n_sup += 1
            elif finding.fingerprint() in self.baseline:
                n_base += 1
            else:
                kept.append(finding)
        kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule_id))
        return LintResult(findings=kept, suppressed=n_sup, baselined=n_base)

    def lint_source(self, source: str, path: str) -> LintResult:
        module, syntax = self._parse(source, path)
        if module is None:
            return LintResult(findings=[syntax])
        return self.lint_modules([module])

    def lint_file(self, path: Path) -> LintResult:
        return self.lint_source(Path(path).read_text(), str(path))

    def lint_paths(self, paths: Sequence[str],
                   extra_summaries: Sequence[dict] = ()) -> LintResult:
        modules: List[ModuleContext] = []
        syntax: List[Finding] = []
        for p in sorted(expand_paths(paths)):
            module, err = self._parse(Path(p).read_text(), str(p))
            if module is None:
                syntax.append(err)
            else:
                modules.append(module)
        result = self.lint_modules(modules, extra_summaries)
        result.findings.extend(syntax)
        result.findings.sort(
            key=lambda f: (f.path, f.line, f.col, f.rule_id))
        return result


def expand_paths(paths: Sequence[str]) -> List[Path]:
    """Files as-is; directories recurse to ``*.py``."""
    out: List[Path] = []
    for p in paths:
        pp = Path(p)
        if pp.is_dir():
            out.extend(sorted(pp.rglob("*.py")))
        else:
            out.append(pp)
    return out


# ----------------------------------------------------------------- reporting

def render_text(result: LintResult, fail_on: Severity) -> str:
    lines = [f.render() for f in result.findings]
    n_fail = len(result.failing(fail_on))
    lines.append(
        f"graftcheck: {len(result.findings)} finding(s) "
        f"({n_fail} at/above {fail_on.name.lower()}), "
        f"{result.suppressed} suppressed, {result.baselined} baselined")
    return "\n".join(lines)


#: JSON output schema version; bumped only on breaking changes (new
#: finding fields are additive and do not bump it).
JSON_SCHEMA_VERSION = 2


def render_json(result: LintResult, fail_on: Severity) -> str:
    return json.dumps({
        "schema_version": JSON_SCHEMA_VERSION,
        "findings": [f.to_json() for f in result.findings],
        "suppressed": result.suppressed,
        "baselined": result.baselined,
        "failing": len(result.failing(fail_on)),
        "fail_on": fail_on.name.lower(),
    }, indent=2)


_SARIF_LEVELS = {Severity.ADVICE: "note", Severity.WARNING: "warning",
                 Severity.ERROR: "error"}


def render_sarif(result: LintResult, rules: Sequence[Rule]) -> str:
    """SARIF 2.1.0 so findings render inline in code-review UIs."""
    rule_meta = {}
    for r in rules:
        rule_meta.setdefault(r.id, {
            "id": r.id,
            "shortDescription": {"text": r.summary or r.id},
            "defaultConfiguration": {"level": _SARIF_LEVELS[r.severity]},
        })
    results = []
    for f in result.findings:
        rule_meta.setdefault(f.rule_id, {
            "id": f.rule_id,
            "shortDescription": {"text": f.rule_id},
            "defaultConfiguration": {"level": _SARIF_LEVELS[f.severity]},
        })
        msg = f.message
        if f.call_chain:
            msg += f" [via {' -> '.join(f.call_chain)}]"
        results.append({
            "ruleId": f.rule_id,
            "level": _SARIF_LEVELS[f.severity],
            "message": {"text": msg},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": norm_path(f.path)},
                    "region": {"startLine": f.line,
                               "startColumn": f.col + 1},
                },
            }],
            "partialFingerprints": {
                "graftcheckFingerprint/v1": f.fingerprint(),
            },
        })
    doc = {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftcheck",
                "informationUri": (
                    "federated_pytorch_test_tpu/analysis/README"),
                "rules": sorted(rule_meta.values(),
                                key=lambda r: r["id"]),
            }},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2)
