"""Rule framework for graftcheck: findings, suppressions, baselines.

Design:

- A :class:`Rule` inspects one parsed module (:class:`ModuleContext`)
  and yields :class:`Finding`\\ s.  Rules are pure AST passes — no
  imports of the linted code, so the linter can run on trees that do
  not import (and on fixture snippets that would crash at runtime).
- Per-line suppression: ``# graftlint: disable=JG101`` (comma list, or
  ``all``) on the flagged line silences the finding.
- Baseline: a committed JSON file of finding *fingerprints* —
  ``sha1(path :: rule :: stripped source line)`` — so grandfathered
  findings survive line drift but resurface when the line changes.
  The shipped baseline is empty: every finding of the shipped rules
  was fixed, not baselined.
- Exit policy: findings at or above the ``fail_on`` severity
  (default WARNING) that are neither suppressed nor baselined fail the
  run.  ADVICE findings report but never fail at the default level.
"""

from __future__ import annotations

import ast
import enum
import hashlib
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set


class Severity(enum.IntEnum):
    """Ordered so ``severity >= fail_on`` is the exit-code test."""

    ADVICE = 10
    WARNING = 20
    ERROR = 30

    @classmethod
    def parse(cls, name: str) -> "Severity":
        try:
            return cls[name.upper()]
        except KeyError:
            raise ValueError(
                f"unknown severity {name!r}; expected one of "
                f"{[s.name.lower() for s in cls]}") from None


@dataclass(frozen=True)
class Finding:
    path: str                 # as given on the command line (relative ok)
    line: int                 # 1-based
    col: int                  # 0-based (ast convention)
    rule_id: str              # "JG101"
    severity: Severity
    message: str
    source_line: str = ""     # stripped text of the flagged line

    def fingerprint(self) -> str:
        """Stable id for baselining: survives line-number drift, breaks
        when the flagged line's content changes."""
        key = f"{self.path}::{self.rule_id}::{self.source_line}"
        return hashlib.sha1(key.encode("utf-8")).hexdigest()

    def to_json(self) -> Dict[str, object]:
        return {
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "rule": self.rule_id,
            "severity": self.severity.name.lower(),
            "message": self.message,
            "fingerprint": self.fingerprint(),
        }

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col + 1}: "
                f"[{self.rule_id} {self.severity.name.lower()}] "
                f"{self.message}")


@dataclass
class ModuleContext:
    """One parsed source file handed to every rule."""

    path: str
    source: str
    tree: ast.Module
    lines: List[str] = field(default_factory=list)

    def __post_init__(self):
        if not self.lines:
            self.lines = self.source.splitlines()

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


class Rule:
    """Base class: subclasses set ``id``/``severity`` and implement
    :meth:`check`."""

    id: str = "JG000"
    severity: Severity = Severity.WARNING
    summary: str = ""

    def check(self, module: ModuleContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, module: ModuleContext, node: ast.AST,
                message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(path=module.path, line=line, col=col,
                       rule_id=self.id, severity=self.severity,
                       message=message,
                       source_line=module.line_text(line))


# --------------------------------------------------------------- suppression

_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\s]+)")


def suppressed_rules_by_line(source: str) -> Dict[int, Set[str]]:
    """Map 1-based line -> set of rule ids (or {"all"}) disabled there."""
    out: Dict[int, Set[str]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if m:
            ids = {t.strip() for t in m.group(1).split(",") if t.strip()}
            out[i] = {t.lower() if t.lower() == "all" else t.upper()
                      for t in ids}
    return out


def is_suppressed(finding: Finding,
                  suppressions: Dict[int, Set[str]]) -> bool:
    ids = suppressions.get(finding.line)
    if not ids:
        return False
    return "all" in ids or finding.rule_id in ids


# ----------------------------------------------------------------- baseline

BASELINE_VERSION = 1


def load_baseline(path: Path) -> Set[str]:
    data = json.loads(Path(path).read_text())
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: version {data.get('version')!r} "
            f"!= {BASELINE_VERSION}")
    fps = data.get("findings", [])
    if not isinstance(fps, list):
        raise ValueError(f"baseline {path}: 'findings' must be a list")
    return set(fps)


def save_baseline(path: Path, findings: Iterable[Finding]) -> None:
    fps = sorted({f.fingerprint() for f in findings})
    Path(path).write_text(json.dumps(
        {"version": BASELINE_VERSION, "findings": fps}, indent=2) + "\n")


# ------------------------------------------------------------------- engine

@dataclass
class LintResult:
    findings: List[Finding]           # reportable (not suppressed/baselined)
    suppressed: int = 0
    baselined: int = 0

    def failing(self, fail_on: Severity = Severity.WARNING) -> List[Finding]:
        return [f for f in self.findings if f.severity >= fail_on]


class LintEngine:
    """Runs a rule set over files/trees and applies the filtering
    pipeline (syntax -> rules -> suppressions -> baseline)."""

    def __init__(self, rules: Sequence[Rule],
                 baseline: Optional[Set[str]] = None):
        self.rules = list(rules)
        self.baseline = baseline or set()

    def lint_source(self, source: str, path: str) -> LintResult:
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as exc:
            f = Finding(path=path, line=exc.lineno or 1,
                        col=(exc.offset or 1) - 1, rule_id="JG000",
                        severity=Severity.ERROR,
                        message=f"syntax error: {exc.msg}",
                        source_line="")
            return LintResult(findings=[f])
        module = ModuleContext(path=path, source=source, tree=tree)
        suppressions = suppressed_rules_by_line(source)
        kept: List[Finding] = []
        n_sup = n_base = 0
        for rule in self.rules:
            for finding in rule.check(module):
                if is_suppressed(finding, suppressions):
                    n_sup += 1
                elif finding.fingerprint() in self.baseline:
                    n_base += 1
                else:
                    kept.append(finding)
        kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule_id))
        return LintResult(findings=kept, suppressed=n_sup, baselined=n_base)

    def lint_file(self, path: Path) -> LintResult:
        return self.lint_source(Path(path).read_text(), str(path))

    def lint_paths(self, paths: Sequence[str]) -> LintResult:
        findings: List[Finding] = []
        n_sup = n_base = 0
        for p in sorted(expand_paths(paths)):
            res = self.lint_file(p)
            findings.extend(res.findings)
            n_sup += res.suppressed
            n_base += res.baselined
        return LintResult(findings=findings, suppressed=n_sup,
                          baselined=n_base)


def expand_paths(paths: Sequence[str]) -> List[Path]:
    """Files as-is; directories recurse to ``*.py``."""
    out: List[Path] = []
    for p in paths:
        pp = Path(p)
        if pp.is_dir():
            out.extend(sorted(pp.rglob("*.py")))
        else:
            out.append(pp)
    return out


# ----------------------------------------------------------------- reporting

def render_text(result: LintResult, fail_on: Severity) -> str:
    lines = [f.render() for f in result.findings]
    n_fail = len(result.failing(fail_on))
    lines.append(
        f"graftcheck: {len(result.findings)} finding(s) "
        f"({n_fail} at/above {fail_on.name.lower()}), "
        f"{result.suppressed} suppressed, {result.baselined} baselined")
    return "\n".join(lines)


def render_json(result: LintResult, fail_on: Severity) -> str:
    return json.dumps({
        "findings": [f.to_json() for f in result.findings],
        "suppressed": result.suppressed,
        "baselined": result.baselined,
        "failing": len(result.failing(fail_on)),
        "fail_on": fail_on.name.lower(),
    }, indent=2)
