"""Interprocedural graftcheck: whole-program flow rules (JG108-JG111).

The lexical rules in :mod:`.rules` see one jit context at a time; this
module sees the *program*.  It runs in two phases:

1. **Extraction** — each module is reduced to a JSON-shaped
   :func:`extract_module_summary`: per-function params, hazards (host
   syncs / traced branches with the names that feed them), derives
   (local dataflow), ordered load/store/call event streams, callable
   aliases (``f = jax.jit(g, donate_argnums=...)``, partials, donating
   dict entries), return shapes, and PRNG facts.  Summaries are pure
   data, so ``lint --changed`` can cache them per file (keyed on the
   content sha1) and re-extract only what the diff touched.
2. **Resolution + rules** — a :class:`Program` links summaries into a
   call graph: bare names resolve through nesting scopes, module
   functions, and imports (dotted module names are suffix-matched, so
   absolute and relative spellings of ``..parallel.comm`` agree);
   ``functools.partial`` shifts positional bindings; ``jax.vmap`` /
   ``shard_map`` / ``*_jit``-convention wrappers are seen through; and
   ``obj.meth(...)`` on an untyped object resolves to every program
   class defining ``meth`` (the engines' method names are unique, so in
   practice this is exact).

Rules on top:

- **JG108** — a JG101/JG102 hazard (host sync, traced-value branch)
  reachable from a jit root *through call edges*: traced params are
  propagated across resolved calls and closed over local derives; the
  finding anchors at the outermost call site inside the jit context and
  prints the call chain.  Hazards lexically inside a jit context are
  the lexical rules' job and are not re-reported.
- **JG109** — use-after-donate: a buffer passed at a ``donate_argnums``
  position and then read again in the caller (the ``_bench_round`` bug
  class from PR 5).  Donation facts flow through factory returns
  (``train_epoch, comm_fns, _ = trainer._build_fns(ci)``), donating
  dict entries (``comm_fns[mode](...)``), and call-of-call subscripts
  (``self._build_fused(ci)[mode](...)``).  A store in *any* branch
  counts as a rebind (deliberate under-approximation: the rule is
  tuned for zero false positives on the shipped tree).
- **JG110** — interprocedural PRNG key lineage: the same key reaching
  two consuming sites where at least one is across a function boundary,
  without a ``split``/``fold_in``.  "Consuming" is a whole-program
  fixpoint: a callee param consumes when it feeds a ``jax.random``
  sampler directly or is passed bare to a consuming param of a resolved
  callee.  Unresolved calls never count, so handing a key to flax's
  ``Module.init`` (external) stays quiet.
- **JG111** — discarded pure result: a statement-level ``.at[...]``
  update or ``jnp.*`` call whose value is never used — a silent no-op
  under tracing.  ``np.asarray(...)`` / ``jax.tree.map(np.asarray, _)``
  statements are *not* flagged: that is this repo's deliberate
  force-a-host-fetch idiom (see bench.py).
"""

from __future__ import annotations

import ast
import hashlib
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .core import (Finding, ModuleContext, ProgramRule, Rule, Severity,
                   suppressed_rules_by_line)
from .rules import (FunctionNode, MODULE_RULES, _donate_ints, _dotted,
                    _is_jit_call, _is_partial_call, _last_name,
                    _SAMPLER_EXEMPT, _walk_scope, build_index,
                    _fn_param_names)

#: bump when the summary shape changes; stale cache entries re-extract
#: (v3 added the determinism-contract facts consumed by JG117-JG121:
#: entropy sources, dict-field stores/loads, recorder emit sites, rng
#: constructions, key derivations, unordered iteration, literal tables)
SUMMARY_VERSION = 3

#: bump whenever extraction *logic* or any rule changes behaviour without
#: changing the summary shape — ``lint --cache`` folds this into its
#: cache-validity check, so a rule edit invalidates sha1-matched entries
#: that would otherwise serve stale summaries (the shape-only
#: SUMMARY_VERSION cannot catch logic changes)
ANALYSIS_VERSION = 3

#: callable wrappers that pass their first argument's signature through
_TRANSPARENT_WRAPPERS = {"vmap", "pmap", "jit", "pjit", "shard_map",
                         "remat", "checkpoint", "grad", "value_and_grad",
                         "named_call", "checkify"}

#: attributes that concretise statically under tracing — branching on
#: ``x.shape`` / ``x.ndim`` is fine, so those loads don't taint a test
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}

_AT_METHODS = {"set", "add", "subtract", "multiply", "divide", "power",
               "min", "max", "get", "apply", "mul", "div"}

#: constructor last-names classified for the concurrency pass
#: (analysis/threads.py); matched on the final attribute so both
#: ``threading.Lock()`` and a bare imported ``Lock()`` register
_SYNC_MAKERS = {
    "Lock": "lock", "RLock": "lock",
    "Event": "event", "Condition": "event", "Semaphore": "event",
    "BoundedSemaphore": "event", "Barrier": "event",
    "Queue": "queue", "LifoQueue": "queue", "PriorityQueue": "queue",
    "SimpleQueue": "queue",
    "Thread": "thread",
    "ThreadPoolExecutor": "pool", "ProcessPoolExecutor": "pool",
}

#: canonical dotted calls that read wall-clock or OS entropy (JG117);
#: call heads are resolved through the module's import aliases first, so
#: ``from time import time`` and ``import time`` both land on
#: ``time.time``
_ENTROPY_CALLS = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "time.gmtime", "time.localtime",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
    "os.urandom", "os.getrandom",
    "uuid.uuid1", "uuid.uuid3", "uuid.uuid4", "uuid.uuid5",
    "secrets.token_bytes", "secrets.token_hex", "secrets.token_urlsafe",
    "secrets.randbits", "secrets.randbelow", "secrets.choice",
}

#: modules whose bare draws consume the process-global — effectively
#: unseeded — generator: ``random.random()``, ``np.random.rand()``
_GLOBAL_RNG_MODULES = {"random", "numpy.random"}

#: attribute calls on those modules that are NOT entropy draws —
#: constructors (JG121's ``rng_ctors`` fact instead) and state plumbing
_RNG_NEUTRAL = {"Random", "RandomState", "default_rng", "Generator",
                "seed", "getstate", "setstate", "PRNGKey"}

#: canonical seeded-generator constructors (JG121 lineage roots)
_RNG_CTOR_CALLS = {"jax.random.PRNGKey", "jax.random.key",
                   "numpy.random.default_rng", "numpy.random.RandomState",
                   "random.Random"}

#: recorder methods whose argument is a record's field payload; values
#: are the schema record kind each method emits
_RECORDER_METHODS = {"round": "round", "span": "span", "alert": "alert",
                     "control_event": "control", "client_event": "client",
                     "campaign_event": "campaign", "serve_event": "serve",
                     "compile_event": "compile"}

#: module-level literal tables the contract rules (JG117-JG121) consume;
#: extracted with ``ast.literal_eval`` so the rules never import linted
#: code — the tables must therefore stay pure literals at their source
CONTRACT_TABLE_NAMES = (
    "ADVISORY_FIELDS", "ENVELOPE_FIELDS", "VERSION_LADDER",
    "RESERVED_META_NAMESPACES", "DIAGNOSTIC_KINDS",
    "REPLAY_CHECKERS", "REPLAY_EXEMPT_KINDS",
    "SCHEMA_VERSION", "EVENTS", "REQUIRED")


def _canon_call(d: str, import_mods: Dict[str, str],
                import_syms: Dict[str, List[str]]) -> str:
    """Canonical dotted name of a call through the module's imports."""
    head, _, rest = d.partition(".")
    sym = import_syms.get(head)
    if sym is not None:
        full = (sym[0] + "." + sym[1]) if sym[0] else sym[1]
    else:
        full = import_mods.get(head, head)
    return full + ("." + rest) if rest else full


def _entropy_label(canon: str) -> Optional[str]:
    """The canonical name if ``canon`` is an entropy source, else None."""
    if canon in _ENTROPY_CALLS:
        return canon
    head, _, tail = canon.rpartition(".")
    if head in _GLOBAL_RNG_MODULES and tail not in _RNG_NEUTRAL:
        return canon
    return None


def _entropy_in(node: ast.AST, import_mods, import_syms) -> List[str]:
    """Canonical names of every entropy call anywhere under ``node``."""
    out: List[str] = []
    for cur in ast.walk(node):
        if isinstance(cur, ast.Call):
            d = _dotted(cur.func)
            if d:
                label = _entropy_label(
                    _canon_call(d, import_mods, import_syms))
                if label is not None:
                    out.append(label)
    return out


def _unordered_src(node: ast.AST, known_dicts: Set[str]) -> Optional[str]:
    """Human label when iterating ``node`` has no deterministic order."""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return "a set literal"
    if isinstance(node, ast.Name) and node.id in known_dicts:
        return "dict %r" % node.id
    if isinstance(node, ast.Call):
        d = _dotted(node.func)
        if d == "set":
            return "set(...)"
        if d and "." in d:
            last = d.rsplit(".", 1)[-1]
            if last in ("keys", "values", "items"):
                return d + "()"
            if last in ("listdir", "scandir", "iterdir", "glob", "iglob"):
                return d + "()"
    return None


def _assign_names(target: ast.AST) -> List[str]:
    """Plain names bound by an assignment target (incl. tuple unpack)."""
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for el in target.elts:
            if isinstance(el, ast.Starred):
                el = el.value
            if isinstance(el, ast.Name):
                out.append(el.id)
        return out
    return []


def _extract_contracts(fn_node: ast.AST, import_mods: Dict[str, str],
                       import_syms: Dict[str, List[str]]) -> dict:
    """Determinism-contract facts for one scope (summary v3).

    Everything here is a *local* observation — which names were assigned
    entropy, which const-string dict keys were written/read, where the
    recorder methods were called — stitched into whole-program taint by
    :mod:`.contracts` (JG117-JG121).  Like the rest of the extractor the
    pass is purely syntactic: no linted code is ever imported.
    """
    entropy: List[list] = []      # [name, canonical source, line]
    dstores: List[dict] = []      # const-string-key dict writes
    dloads: List[dict] = []       # const-string-key dict reads
    dkinds: Dict[str, str] = {}   # dict var -> const "event" value
    rec_calls: List[dict] = []    # recorder-method emit sites
    rng_ctors: List[dict] = []    # seeded-generator constructions
    key_derives: List[dict] = []  # split/fold_in rebindings
    unordered: List[dict] = []    # iteration with no deterministic order
    usums: List[dict] = []        # sum()/min()/max() over unordered src
    ret_esrc: List[str] = []      # entropy calls inside return values
    ret_loads: List[str] = []     # names loaded by any return value

    known_dicts: Set[str] = set()
    for node in _walk_scope(fn_node):
        if (isinstance(node, (ast.Assign, ast.AnnAssign))
                and isinstance(node.value, ast.Dict)):
            tgt = (node.targets[0] if isinstance(node, ast.Assign)
                   and len(node.targets) == 1 else
                   node.target if isinstance(node, ast.AnnAssign) else None)
            if isinstance(tgt, ast.Name):
                known_dicts.add(tgt.id)

    def ent(value: Optional[ast.AST]) -> List[str]:
        if value is None:
            return []
        return _entropy_in(value, import_mods, import_syms)

    def calls_in(value: Optional[ast.AST]) -> List[str]:
        if value is None:
            return []
        out = []
        for cur in ast.walk(value):
            if isinstance(cur, ast.Call):
                d = _dotted(cur.func)
                if d:
                    out.append(d)
        return out

    def store(var: Optional[str], key_node: ast.AST,
              value: Optional[ast.AST], line: int, col: int) -> None:
        if not (isinstance(key_node, ast.Constant)
                and isinstance(key_node.value, str)):
            return
        key = key_node.value
        dstores.append({"var": var, "key": key, "line": line, "col": col,
                        "loads": _loads_in(value) if value is not None
                        else [],
                        "esrc": ent(value), "calls": calls_in(value)})
        if (var is not None and key == "event"
                and isinstance(value, ast.Constant)
                and isinstance(value.value, str)):
            dkinds[var] = value.value

    def dict_entries(d: ast.Dict, var: Optional[str],
                     line: int, col: int) -> None:
        for k, v in zip(d.keys, d.values):
            if k is not None:
                store(var, k, v, getattr(v, "lineno", line),
                      getattr(v, "col_offset", col))

    def comp_unordered(value: ast.AST) -> Optional[str]:
        if isinstance(value, (ast.ListComp, ast.SetComp,
                              ast.GeneratorExp, ast.DictComp)):
            for gen in value.generators:
                src = _unordered_src(gen.iter, known_dicts)
                if src:
                    return src
        return None

    def call_feeds(call: ast.Call) -> Tuple[List[str], List[str]]:
        feeds: List[str] = []
        esrc: List[str] = []
        for a in list(call.args) + [kw.value for kw in call.keywords]:
            feeds.extend(_loads_in(a))
            esrc.extend(ent(a))
        return feeds, esrc

    def handle_binding(names: List[str], value: ast.AST,
                       line: int, col: int) -> None:
        """Classify one ``names = value`` binding."""
        if isinstance(value, ast.Call):
            d = _dotted(value.func)
            if d:
                canon = _canon_call(d, import_mods, import_syms)
                parts = canon.split(".")
                if canon in _RNG_CTOR_CALLS:
                    feeds, esrc = call_feeds(value)
                    for n in names:
                        rng_ctors.append({
                            "name": n, "ctor": canon, "feeds": feeds,
                            "esrc": esrc, "line": line, "col": col,
                            "unseeded": not (value.args or value.keywords)})
                    return
                if parts[-1] in ("split", "fold_in") and "random" in parts:
                    feeds, esrc = call_feeds(value)
                    for n in names:
                        key_derives.append({"name": n, "feeds": feeds,
                                            "esrc": esrc, "line": line})
                    return
        # a dict literal does not taint its own name — each entry's
        # esrc is recorded field-by-field via dict_entries instead, so
        # an exempt time_unix entry cannot smear siblings
        if not isinstance(value, ast.Dict):
            es = ent(value)
            if es and names:
                for n in names:
                    entropy.append([n, es[0], line])
        src = comp_unordered(value)
        if src and names:
            unordered.append({"targets": names, "src": src,
                              "line": line, "col": col})

    for node in _walk_scope(fn_node):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Name)):
                    store(t.value.id, t.slice, node.value,
                          node.lineno, node.col_offset)
            if (len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Dict)):
                dict_entries(node.value, node.targets[0].id,
                             node.lineno, node.col_offset)
            names: List[str] = []
            for t in node.targets:
                names.extend(_assign_names(t))
            handle_binding(names, node.value, node.lineno,
                           node.col_offset)
        elif isinstance(node, ast.AnnAssign):
            if node.value is None:
                continue
            if isinstance(node.target, ast.Name):
                if isinstance(node.value, ast.Dict):
                    dict_entries(node.value, node.target.id,
                                 node.lineno, node.col_offset)
                handle_binding([node.target.id], node.value,
                               node.lineno, node.col_offset)
        elif isinstance(node, ast.AugAssign):
            if isinstance(node.target, ast.Name):
                es = ent(node.value)
                if es:
                    entropy.append([node.target.id, es[0], node.lineno])
            elif (isinstance(node.target, ast.Subscript)
                  and isinstance(node.target.value, ast.Name)):
                store(node.target.value.id, node.target.slice,
                      node.value, node.lineno, node.col_offset)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            src = _unordered_src(node.iter, known_dicts)
            if src:
                names = _assign_names(node.target)
                if names:
                    unordered.append({"targets": names, "src": src,
                                      "line": node.lineno,
                                      "col": node.col_offset})
        elif isinstance(node, ast.Return):
            if node.value is not None:
                ret_esrc.extend(ent(node.value))
                ret_loads.extend(_loads_in(node.value))
        elif isinstance(node, ast.Subscript):
            if (isinstance(node.ctx, ast.Load)
                    and isinstance(node.value, ast.Name)
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)):
                dloads.append({"var": node.value.id,
                               "key": node.slice.value,
                               "line": node.lineno,
                               "col": node.col_offset, "hard": True})
        elif isinstance(node, ast.Compare):
            if (len(node.ops) == 1
                    and isinstance(node.ops[0], (ast.In, ast.NotIn))
                    and isinstance(node.left, ast.Constant)
                    and isinstance(node.left.value, str)
                    and isinstance(node.comparators[0], ast.Name)):
                dloads.append({"var": node.comparators[0].id,
                               "key": node.left.value,
                               "line": node.lineno,
                               "col": node.col_offset, "hard": False})
        elif isinstance(node, ast.Call):
            d = _dotted(node.func)
            if not d:
                continue
            parts = d.split(".")
            last = parts[-1]
            base = ".".join(parts[:-1])
            simple_base = base if base and "." not in base else None
            if d in ("sum", "min", "max") and node.args:
                arg = node.args[0]
                src = comp_unordered(arg) or _unordered_src(arg,
                                                            known_dicts)
                if src:
                    usums.append({"fn": d, "src": src, "line": node.lineno,
                                  "col": node.col_offset})
            elif last == "setdefault" and simple_base and node.args:
                store(simple_base, node.args[0],
                      node.args[1] if len(node.args) > 1 else None,
                      node.lineno, node.col_offset)
            elif (last == "update" and simple_base and node.args
                  and isinstance(node.args[0], ast.Dict)):
                dict_entries(node.args[0], simple_base,
                             node.lineno, node.col_offset)
            elif (last in ("get", "pop") and simple_base and node.args
                  and isinstance(node.args[0], ast.Constant)
                  and isinstance(node.args[0].value, str)):
                dloads.append({"var": simple_base,
                               "key": node.args[0].value,
                               "line": node.lineno,
                               "col": node.col_offset, "hard": False})
            elif last in _RECORDER_METHODS and base and node.args:
                arg = node.args[0]
                rc = {"m": last, "kind": _RECORDER_METHODS[last],
                      "line": node.lineno, "col": node.col_offset,
                      "var": arg.id if isinstance(arg, ast.Name) else None,
                      "entries": []}
                if isinstance(arg, ast.Dict):
                    for k, v in zip(arg.keys, arg.values):
                        if (k is not None and isinstance(k, ast.Constant)
                                and isinstance(k.value, str)):
                            rc["entries"].append(
                                {"key": k.value,
                                 "line": getattr(v, "lineno", node.lineno),
                                 "col": getattr(v, "col_offset", 0),
                                 "loads": _loads_in(v), "esrc": ent(v),
                                 "calls": calls_in(v)})
                rec_calls.append(rc)

    return {"entropy": entropy, "dstores": dstores, "dloads": dloads,
            "dkinds": dkinds, "rec_calls": rec_calls,
            "rng_ctors": rng_ctors, "key_derives": key_derives,
            "unordered": unordered, "usums": usums,
            "ret_esrc": ret_esrc, "ret_loads": ret_loads}


def file_sha1(text: str) -> str:
    return hashlib.sha1(text.encode("utf-8")).hexdigest()


def strip_summary(summary: dict) -> dict:
    """A JSON-safe copy for the ``--changed`` cache: :class:`Program`
    linkage adds ``_path``/``_mod`` backrefs into the per-function
    dicts, and ``_mod`` is circular (it points at the summary)."""
    out = dict(summary)
    out["functions"] = {
        q: {k: v for k, v in fn.items() if not k.startswith("_")}
        for q, fn in summary["functions"].items()}
    return out


# ============================================================ extraction

def _ref_of(expr: ast.AST) -> dict:
    """Describe a callable expression as a serializable CalleeRef."""
    d = _dotted(expr)
    if d:
        return {"k": "dotted", "v": d}
    if isinstance(expr, ast.Subscript):
        base = expr.value
        bd = _dotted(base)
        if bd:
            return {"k": "sub", "v": bd}
        if isinstance(base, ast.Call):
            return {"k": "subcall", "v": _ref_of(base.func),
                    "args": _arg_descs(base)}
    if isinstance(expr, ast.Call) and expr.args:
        wrap = _last_name(expr.func)
        if wrap and (wrap in _TRANSPARENT_WRAPPERS or wrap == "partial"
                     or wrap.endswith("_jit")):
            inner = _ref_of(expr.args[0])
            ref = {"k": "wrap", "w": wrap, "v": inner}
            if wrap == "partial":
                ref["shift"] = len(expr.args) - 1
                ref["kw"] = [k.arg for k in expr.keywords if k.arg]
            donate = ()
            for kw in expr.keywords:
                if kw.arg == "donate_argnums":
                    donate = _donate_ints(kw.value)
            if donate:
                ref["donate"] = list(donate)
            return ref
    return {"k": "opaque"}


def _loads_in(node: ast.AST) -> List[str]:
    """Bare Name loads inside an expression, skipping lambda bodies and
    skipping names only used as the base of a static attribute
    (``x.shape`` does not taint)."""
    out: List[str] = []
    stack: List[ast.AST] = [node]
    while stack:
        cur = stack.pop()
        if isinstance(cur, FunctionNode + (ast.Lambda,)):
            continue
        if (isinstance(cur, ast.Attribute) and cur.attr in _STATIC_ATTRS
                and isinstance(cur.value, ast.Name)):
            continue
        if isinstance(cur, ast.Name) and isinstance(cur.ctx, ast.Load):
            out.append(cur.id)
        stack.extend(ast.iter_child_nodes(cur))
    return out


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X``, ``self.X.y``, ``cls.X`` -> ``X`` — the attribute that
    names the shared slot on the instance/class.  Anything not rooted at
    ``self``/``cls`` returns None."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name) and cur.id in ("self", "cls") and parts:
        return parts[-1]
    return None


def _self_attrs_in(node: ast.AST) -> Set[str]:
    """Every ``self.X`` slot read inside an expression (outermost
    attribute per chain; lambda bodies skipped like :func:`_loads_in`)."""
    out: Set[str] = set()
    stack: List[ast.AST] = [node]
    while stack:
        cur = stack.pop()
        if isinstance(cur, FunctionNode + (ast.Lambda,)):
            continue
        if isinstance(cur, ast.Attribute):
            attr = _self_attr(cur)
            if attr is not None:
                out.add(attr)
                continue
        stack.extend(ast.iter_child_nodes(cur))
    return out


def _arg_descs(call: ast.Call) -> List[dict]:
    out = []
    for a in call.args:
        if isinstance(a, ast.Starred):
            out.append({"n": None, "loads": _loads_in(a)})
        else:
            out.append({"n": a.id if isinstance(a, ast.Name) else None,
                        "loads": _loads_in(a)})
    return out


def _elt_desc(node: ast.AST) -> dict:
    if isinstance(node, ast.Name):
        return {"k": "name", "v": node.id}
    return {"k": "opaque"}


class _FnWalker(ast.NodeVisitor):
    """Linearises one function body into events + call records.

    Nested defs are skipped (they get their own summaries); branches are
    flattened in source order, so a store in any branch counts as a
    rebind; loops are bracketed with ``ls``/``le`` marker events."""

    def __init__(self):
        self.events: List[dict] = []
        self.calls: List[dict] = []
        self.aliases: Dict[str, dict] = {}
        self.dict_donates: Dict[str, List[int]] = {}
        self.tuple_binds: Dict[str, List[dict]] = {}
        self.returns: List[List[dict]] = []
        self.derives: List[Tuple[str, List[str]]] = []
        # --- concurrency effect facts (analysis/threads.py) ---
        self.spawns: List[dict] = []        # Thread(target=)/pool.submit
        self.sync_makes: List[dict] = []    # lock/queue/pool/thread ctors
        self.joins: List[dict] = []         # .join()/.shutdown() sites
        self.globals: List[str] = []        # `global X` declarations
        self._loop = 0
        self._held: List[str] = []          # lock tokens held lexically
        self._checks: List[List[str]] = []  # self-attrs checked by if/while
        self._call_idx_by_node: Dict[int, int] = {}

    # ------------------------------------------------------ expressions

    def expr(self, node: Optional[ast.AST]) -> None:
        if node is None:
            return
        if isinstance(node, FunctionNode + (ast.Lambda,)):
            return                      # deferred execution: not events
        if isinstance(node, ast.Call):
            self.expr(node.func)
            for a in node.args:
                self.expr(a)
            for k in node.keywords:
                self.expr(k.value)
            self._record_call(node)
            return
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load):
                self.events.append({"t": "load", "n": node.id,
                                    "line": node.lineno,
                                    "col": node.col_offset,
                                    "loop": self._loop})
            return
        if isinstance(node, ast.Attribute):
            attr = _self_attr(node)
            if attr is not None:
                if isinstance(node.ctx, ast.Load) \
                        and attr not in _STATIC_ATTRS:
                    self._attr_event("aload", attr, node)
                base = node.value
                while isinstance(base, ast.Attribute):
                    base = base.value
                self.expr(base)      # keep the bare `self` load event
                return
            for child in ast.iter_child_nodes(node):
                self.expr(child)
            return
        if isinstance(node, ast.NamedExpr):
            self.expr(node.value)
            self._store_target(node.target)
            return
        for child in ast.iter_child_nodes(node):
            self.expr(child)

    def _attr_event(self, t: str, attr: str, node: ast.AST,
                    rmw: bool = False) -> None:
        ev: dict = {"t": t, "n": attr, "line": node.lineno,
                    "col": node.col_offset, "loop": self._loop}
        if self._held:
            ev["h"] = sorted(set(self._held))
        if t == "astore":
            chk = sorted({a for frame in self._checks for a in frame})
            if chk:
                ev["chk"] = chk
            if rmw:
                ev["rmw"] = True
        self.events.append(ev)

    def _record_call(self, node: ast.Call) -> None:
        kw = {}
        for k in node.keywords:
            if k.arg:
                kw[k.arg] = {"n": (k.value.id
                                   if isinstance(k.value, ast.Name)
                                   else None),
                             "loads": _loads_in(k.value)}
        idx = len(self.calls)
        rec = {
            "line": node.lineno, "col": node.col_offset,
            "callee": _ref_of(node.func),
            "args": _arg_descs(node),
            "kw": kw,
            "assigned": None,
        }
        if self._held:
            rec["held"] = sorted(set(self._held))
        self.calls.append(rec)
        self._call_idx_by_node[id(node)] = idx
        self.events.append({"t": "call", "i": idx, "loop": self._loop})
        self._concurrency_call(node)

    def _concurrency_call(self, node: ast.Call) -> None:
        """Spawn edges, lock acquire/release, join/shutdown records."""
        d = _dotted(node.func)
        if not d:
            return
        base, _, last = d.rpartition(".")
        if last == "acquire" and base:
            self._held.append(base)       # recorded call is pre-acquire
        elif last == "release" and base and base in self._held:
            self._held.remove(base)
        elif last in ("join", "shutdown") and base:
            self.joins.append({"token": base, "op": last,
                               "line": node.lineno})
        elif last == "submit" and base and node.args:
            self.spawns.append({"via": "submit", "pool": base,
                                "target": _ref_of(node.args[0]),
                                "name": None,
                                "line": node.lineno,
                                "col": node.col_offset})
        elif last == "Thread":
            tgt = name = None
            for k in node.keywords:
                if k.arg == "target":
                    tgt = _ref_of(k.value)
                elif k.arg == "name" and isinstance(k.value, ast.Constant):
                    name = str(k.value.value)
            if tgt is not None:
                self.spawns.append({"via": "thread", "pool": None,
                                    "target": tgt, "name": name,
                                    "line": node.lineno,
                                    "col": node.col_offset})

    # ------------------------------------------------------- statements

    def _store_target(self, target: ast.AST,
                      value_attrs: Optional[Set[str]] = None) -> None:
        for n in ast.walk(target):
            if isinstance(n, ast.Name) and isinstance(
                    n.ctx, (ast.Store, ast.Del)):
                self.events.append({"t": "store", "n": n.id,
                                    "loop": self._loop})
            elif isinstance(n, ast.Attribute) and isinstance(
                    n.ctx, (ast.Store, ast.Del)):
                attr = _self_attr(n)
                if attr is not None:
                    self._attr_event(
                        "astore", attr, n,
                        rmw=bool(value_attrs and attr in value_attrs))
            elif isinstance(n, ast.Subscript) and isinstance(
                    n.ctx, (ast.Store, ast.Del)):
                attr = _self_attr(n.value)
                if attr is not None:
                    self._attr_event(
                        "astore", attr, n,
                        rmw=bool(value_attrs and attr in value_attrs))

    def _target_names(self, target: ast.AST) -> List[str]:
        if isinstance(target, ast.Name):
            return [target.id]
        if isinstance(target, (ast.Tuple, ast.List)):
            out = []
            for el in target.elts:
                if isinstance(el, ast.Starred):
                    el = el.value
                if isinstance(el, ast.Name):
                    out.append(el.id)
            return out
        return []

    def stmt(self, node: ast.stmt) -> None:
        if isinstance(node, FunctionNode + (ast.ClassDef,)):
            self.events.append({"t": "store", "n": node.name,
                                "loop": self._loop})
            return
        if isinstance(node, ast.Assign):
            self.expr(node.value)
            loads = _loads_in(node.value)
            for target in node.targets:
                for name in self._target_names(target):
                    if loads:
                        self.derives.append((name, loads))
            if len(node.targets) == 1:
                self._extract_binding(node.targets[0], node.value)
            if isinstance(node.value, ast.Call):
                ci = self._call_idx_by_node.get(id(node.value))
                if ci is not None and len(node.targets) == 1:
                    names = self._target_names(node.targets[0])
                    if names:
                        self.calls[ci]["assigned"] = names
            value_attrs = _self_attrs_in(node.value)
            for target in node.targets:
                self._store_target(target, value_attrs)
            return
        if isinstance(node, ast.AugAssign):
            if isinstance(node.target, ast.Name):
                self.events.append({"t": "load", "n": node.target.id,
                                    "line": node.lineno,
                                    "col": node.col_offset,
                                    "loop": self._loop})
                self.derives.append((node.target.id, _loads_in(node.value)))
            else:
                tbase = (node.target.value
                         if isinstance(node.target, ast.Subscript)
                         else node.target)
                attr = _self_attr(tbase)
                if attr is not None:    # self.x += 1: read-modify-write
                    self._attr_event("aload", attr, node.target)
            self.expr(node.value)
            self._store_target(node.target, _self_attrs_in(node.target))
            return
        if isinstance(node, ast.AnnAssign):
            self.expr(node.value)
            if node.value is not None:
                for name in self._target_names(node.target):
                    loads = _loads_in(node.value)
                    if loads:
                        self.derives.append((name, loads))
                if isinstance(node.value, ast.Call):
                    ci = self._call_idx_by_node.get(id(node.value))
                    names = self._target_names(node.target)
                    if ci is not None and names:
                        self.calls[ci]["assigned"] = names
                self._extract_binding(node.target, node.value)
            self._store_target(node.target,
                               _self_attrs_in(node.value)
                               if node.value is not None else None)
            return
        if isinstance(node, ast.Return):
            self.expr(node.value)
            if node.value is not None:
                if isinstance(node.value, ast.Tuple):
                    self.returns.append(
                        [_elt_desc(e) for e in node.value.elts])
                elif (isinstance(node.value, ast.Name)
                        and node.value.id in self.tuple_binds):
                    self.returns.append(self.tuple_binds[node.value.id])
                else:
                    self.returns.append([_elt_desc(node.value)])
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            self.expr(node.iter)
            loads = _loads_in(node.iter)
            for name in self._target_names(node.target):
                if loads:
                    self.derives.append((name, loads))
            self._store_target(node.target)
            self.events.append({"t": "ls"})
            self._loop += 1
            for s in node.body:
                self.stmt(s)
            self._loop -= 1
            self.events.append({"t": "le"})
            for s in node.orelse:
                self.stmt(s)
            return
        if isinstance(node, ast.While):
            self.events.append({"t": "ls"})
            self._loop += 1
            self.expr(node.test)
            checked = sorted(_self_attrs_in(node.test))
            if checked:
                self._checks.append(checked)
            for s in node.body:
                self.stmt(s)
            if checked:
                self._checks.pop()
            self._loop -= 1
            self.events.append({"t": "le"})
            for s in node.orelse:
                self.stmt(s)
            return
        if isinstance(node, ast.If):
            self.expr(node.test)
            # a store to a self-attr the test just read is a
            # check-then-act candidate; the orelse runs when the check
            # failed, so only the body is bracketed
            checked = sorted(_self_attrs_in(node.test))
            if checked:
                self._checks.append(checked)
            for s in node.body:
                self.stmt(s)
            if checked:
                self._checks.pop()
            for s in node.orelse:
                self.stmt(s)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in node.items:
                self.expr(item.context_expr)
                if item.optional_vars is not None:
                    loads = _loads_in(item.context_expr)
                    for name in self._target_names(item.optional_vars):
                        if loads:
                            self.derives.append((name, loads))
                    self._store_target(item.optional_vars)
                else:
                    d = _dotted(item.context_expr)
                    if d:               # `with self._lock:` holds a token
                        self._held.append(d)
                        pushed += 1
            for s in node.body:
                self.stmt(s)
            if pushed:
                del self._held[-pushed:]
            return
        if isinstance(node, ast.Try):
            for s in node.body:
                self.stmt(s)
            for h in node.handlers:
                for s in h.body:
                    self.stmt(s)
            for s in node.orelse + node.finalbody:
                self.stmt(s)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                self._store_target(t)
            return
        if isinstance(node, ast.Global):
            for n in node.names:
                if n not in self.globals:
                    self.globals.append(n)
            return
        # Expr / Assert / Raise / Global / Import / Pass / ...
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self.expr(child)

    def _sync_make(self, token: str, kind: str, value: ast.Call) -> None:
        rec: dict = {"token": token, "kind": kind, "line": value.lineno,
                     "col": value.col_offset}
        if kind == "queue":
            bounded = False
            if value.args and isinstance(value.args[0], ast.Constant) \
                    and value.args[0].value:
                bounded = True
            for k in value.keywords:
                if k.arg == "maxsize" and isinstance(k.value, ast.Constant) \
                        and k.value.value:
                    bounded = True
            rec["bounded"] = bounded
        elif kind == "pool":
            for k in value.keywords:
                if k.arg == "thread_name_prefix" \
                        and isinstance(k.value, ast.Constant):
                    rec["prefix"] = str(k.value.value)
        self.sync_makes.append(rec)

    def _extract_binding(self, target: ast.AST, value: ast.AST) -> None:
        """Callable aliases, donating dict entries, tuple binds, and
        sync-primitive constructions (lock/queue/pool/thread)."""
        if isinstance(value, ast.Call):
            mk = _last_name(value.func)
            kind = _SYNC_MAKERS.get(mk) if mk else None
            if kind is not None:
                if isinstance(target, ast.Name):
                    self._sync_make(target.id, kind, value)
                elif isinstance(target, ast.Attribute):
                    attr = _self_attr(target)
                    if attr is not None:
                        self._sync_make("self." + attr, kind, value)
        if isinstance(target, ast.Name):
            if isinstance(value, ast.Tuple):
                self.tuple_binds[target.id] = [
                    _elt_desc(e) for e in value.elts]
            elif isinstance(value, ast.Call) and value.args:
                if _is_jit_call(value):
                    donate: Tuple[int, ...] = ()
                    for kw in value.keywords:
                        if kw.arg == "donate_argnums":
                            donate = _donate_ints(kw.value)
                    self.aliases[target.id] = {
                        "target": _ref_of(value.args[0]),
                        "shift": 0, "kw": [],
                        "donate": list(donate) if donate else None}
                elif _is_partial_call(value):
                    self.aliases[target.id] = {
                        "target": _ref_of(value.args[0]),
                        "shift": len(value.args) - 1,
                        "kw": [k.arg for k in value.keywords if k.arg],
                        "donate": None}
            elif isinstance(value, (ast.Name, ast.Attribute)):
                d = _dotted(value)
                if d:
                    self.aliases[target.id] = {
                        "target": {"k": "dotted", "v": d},
                        "shift": 0, "kw": [], "donate": None}
        elif (isinstance(target, ast.Subscript)
                and isinstance(target.value, ast.Name)
                and isinstance(value, ast.Call) and value.args
                and _is_jit_call(value)):
            for kw in value.keywords:
                if kw.arg == "donate_argnums":
                    donate = _donate_ints(kw.value)
                    if donate:
                        cur = set(self.dict_donates.get(
                            target.value.id, []))
                        self.dict_donates[target.value.id] = sorted(
                            cur | set(donate))


def _extract_hazards(fn_node: ast.AST, numpy_aliases: Set[str],
                     lines: List[str]) -> List[dict]:
    def text(lineno: int) -> str:
        return (lines[lineno - 1].strip()
                if 1 <= lineno <= len(lines) else "")

    out: List[dict] = []
    for node in _walk_scope(fn_node):
        if isinstance(node, ast.Call):
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("item", "tolist")
                    and not node.args):
                out.append({"kind": "sync", "line": node.lineno,
                            "col": node.col_offset,
                            "names": _loads_in(node.func.value),
                            "msg": f".{node.func.attr}() host sync",
                            "text": text(node.lineno)})
                continue
            d = _dotted(node.func)
            if d:
                head, _, tail = d.rpartition(".")
                if head in numpy_aliases and tail in ("asarray", "array"):
                    names: List[str] = []
                    for a in node.args:
                        names.extend(_loads_in(a))
                    out.append({"kind": "sync", "line": node.lineno,
                                "col": node.col_offset, "names": names,
                                "msg": f"{d}() host materialisation",
                                "text": text(node.lineno)})
                    continue
                if d in ("jax.device_get", "device_get"):
                    names = []
                    for a in node.args:
                        names.extend(_loads_in(a))
                    out.append({"kind": "sync", "line": node.lineno,
                                "col": node.col_offset, "names": names,
                                "msg": f"{d}() host round-trip",
                                "text": text(node.lineno)})
                    continue
            if (isinstance(node.func, ast.Name)
                    and node.func.id in ("float", "int") and node.args
                    and not isinstance(node.args[0], ast.Constant)):
                out.append({"kind": "sync", "line": node.lineno,
                            "col": node.col_offset,
                            "names": _loads_in(node.args[0]),
                            "msg": f"{node.func.id}() concretisation",
                            "text": text(node.lineno)})
        elif isinstance(node, (ast.If, ast.While)):
            test = node.test
            if (isinstance(test, ast.Compare)
                    and all(isinstance(op, (ast.Is, ast.IsNot))
                            for op in test.ops)):
                continue
            names = _loads_in(test)
            if names:
                kind = "if" if isinstance(node, ast.If) else "while"
                out.append({"kind": "branch", "line": node.lineno,
                            "col": node.col_offset, "names": names,
                            "msg": f"Python `{kind}` branch",
                            "text": text(node.lineno)})
    return out


def _extract_prng(fn_node: ast.AST) -> Tuple[List, List, List[str]]:
    key_assigns: List[List] = []
    sampler_uses: List[List] = []
    sanitized: Set[str] = set()
    for node in _walk_scope(fn_node):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and _last_name(node.value.func) == "PRNGKey"):
            key_assigns.append([node.targets[0].id, node.lineno,
                                node.col_offset])
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if not d or "random" not in d.split("."):
            continue
        tail = d.rsplit(".", 1)[-1]
        argnames = [a.id for a in node.args if isinstance(a, ast.Name)]
        if tail in ("split", "fold_in"):
            sanitized.update(argnames)
        elif tail not in _SAMPLER_EXEMPT and node.args \
                and isinstance(node.args[0], ast.Name):
            sampler_uses.append([node.args[0].id, node.lineno,
                                 node.col_offset, tail])
    return key_assigns, sampler_uses, sorted(sanitized)


def _qualname(node: ast.AST, parents: Dict[ast.AST, ast.AST]) -> str:
    parts = [node.name]
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, FunctionNode + (ast.ClassDef,)):
            parts.append(cur.name)
        cur = parents.get(cur)
    return ".".join(reversed(parts))


def _module_name_of(path: str) -> str:
    p = Path(path)
    return ".".join([*(x for x in p.parts[:-1] if x not in ("/", "\\")),
                     p.stem]).lstrip(".")


def extract_module_summary(module: ModuleContext) -> dict:
    """Reduce a parsed module to the serializable program summary."""
    cached = getattr(module, "_graft_flow_summary", None)
    if cached is not None:
        return cached
    index = build_index(module)
    tree = module.tree
    parents = index.parents
    lines = module.lines

    import_mods: Dict[str, str] = {}
    import_syms: Dict[str, List[str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for al in node.names:
                import_mods[al.asname or al.name.split(".")[0]] = al.name
        elif isinstance(node, ast.ImportFrom):
            mod = (node.module or "")
            for al in node.names:
                if al.name == "*":
                    continue
                if node.module is None:
                    import_mods[al.asname or al.name] = al.name
                else:
                    import_syms[al.asname or al.name] = [mod, al.name]

    classes: Dict[str, dict] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            info = {"bases": [b for b in
                              (_last_name(x) for x in node.bases) if b],
                    "methods": {}}
            for child in node.body:
                if isinstance(child, FunctionNode):
                    info["methods"][child.name] = _qualname(child, parents)
            classes[node.name] = info

    donate_root: Dict[ast.AST, Set[int]] = {}
    for site in index.sites:
        if site.fn is not None and site.donates:
            donate_root.setdefault(site.fn, set()).update(
                site.donate_argnums_vals)

    functions: Dict[str, dict] = {}

    def _summarise_fn(fn_node, qual: str, cls: Optional[str],
                      body: List[ast.stmt], params: List[str],
                      ndefaults: int, vararg: bool, method: bool,
                      line: int) -> None:
        walker = _FnWalker()
        for s in body:
            walker.stmt(s)
        key_assigns, sampler_uses, sanitized = (
            _extract_prng(fn_node) if fn_node is not None else ([], [], []))
        functions[qual] = {
            "name": qual.rsplit(".", 1)[-1], "qual": qual, "cls": cls,
            "line": line, "method": method, "params": params,
            "ndefaults": ndefaults, "vararg": vararg,
            "in_jit": fn_node in index.contexts if fn_node else False,
            "jit_root": fn_node in index.static_by_fn if fn_node else False,
            "static": sorted(index.static_by_fn.get(fn_node, set()))
            if fn_node is not None else [],
            "donate_root": sorted(donate_root.get(fn_node, set()))
            if fn_node is not None else [],
            "hazards": (_extract_hazards(fn_node, index.numpy_aliases,
                                         lines)
                        if fn_node is not None else []),
            "derives": [[t, srcs] for t, srcs in walker.derives],
            "calls": walker.calls,
            "events": walker.events,
            "aliases": walker.aliases,
            "dict_donates": walker.dict_donates,
            "tuple_binds": walker.tuple_binds,
            "returns": walker.returns,
            "key_assigns": key_assigns,
            "sampler_uses": sampler_uses,
            "sanitized": sanitized,
            "spawns": walker.spawns,
            "sync_makes": walker.sync_makes,
            "joins": walker.joins,
            "globals": walker.globals,
        }
        if fn_node is not None:
            functions[qual].update(
                _extract_contracts(fn_node, import_mods, import_syms))

    for node in ast.walk(tree):
        if not isinstance(node, FunctionNode):
            continue
        qual = _qualname(node, parents)
        parent = parents.get(node)
        cls = parent.name if isinstance(parent, ast.ClassDef) else None
        decs = {(_last_name(d) or "") for d in node.decorator_list}
        a = node.args
        _summarise_fn(node, qual, cls, node.body, _fn_param_names(node),
                      len(a.defaults), a.vararg is not None,
                      method=cls is not None and "staticmethod" not in decs,
                      line=node.lineno)

    # the module body is a pseudo-function: module-level jitted bindings,
    # donating calls in driver code, and top-level PRNG use all live here
    mod_walker = _FnWalker()
    for s in tree.body:
        mod_walker.stmt(s)
    mk, ms, msan = _extract_prng(tree)
    functions["<module>"] = {
        "name": "<module>", "qual": "<module>", "cls": None, "line": 1,
        "method": False, "params": [], "ndefaults": 0, "vararg": False,
        "in_jit": False, "jit_root": False, "static": [],
        "donate_root": [],
        "hazards": [],
        "derives": [[t, srcs] for t, srcs in mod_walker.derives],
        "calls": mod_walker.calls,
        "events": mod_walker.events,
        "aliases": mod_walker.aliases,
        "dict_donates": mod_walker.dict_donates,
        "tuple_binds": mod_walker.tuple_binds,
        "returns": mod_walker.returns,
        "key_assigns": mk,
        "sampler_uses": ms,
        "sanitized": msan,
        "spawns": mod_walker.spawns,
        "sync_makes": mod_walker.sync_makes,
        "joins": mod_walker.joins,
        "globals": mod_walker.globals,
    }
    functions["<module>"].update(
        _extract_contracts(tree, import_mods, import_syms))

    # machine-readable contract tables (ADVISORY_FIELDS, VERSION_LADDER,
    # REPLAY_CHECKERS, ...): module-level pure-literal assignments only,
    # so the contract pass reads the declared contract without importing
    # the code that declares it
    tables: Dict[str, list] = {}
    for node in tree.body:
        tgt = None
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            tgt = node.targets[0].id
        elif (isinstance(node, ast.AnnAssign)
              and isinstance(node.target, ast.Name)
              and node.value is not None):
            tgt = node.target.id
        if tgt in CONTRACT_TABLE_NAMES:
            try:
                tables[tgt] = [ast.literal_eval(node.value), node.lineno]
            except (ValueError, SyntaxError, TypeError):
                pass

    summary = {
        "version": SUMMARY_VERSION,
        "path": module.path,
        "module_name": _module_name_of(module.path),
        "import_mods": import_mods,
        "import_syms": import_syms,
        "jnp_aliases": sorted(index.jnp_aliases),
        "classes": classes,
        "tables": tables,
        "functions": functions,
        "suppress": [[ln, sorted(ids)] for ln, ids in
                     sorted(suppressed_rules_by_line(module.source).items())],
    }
    module._graft_flow_summary = summary
    return summary


# ============================================================= resolution

class Target:
    """One resolved callee: the fn summary plus the positional mapping
    (partial shift, partial-bound kwargs, implicit self)."""

    __slots__ = ("fn", "shift", "bound_kw", "skip_self")

    def __init__(self, fn: dict, shift: int = 0,
                 bound_kw: Sequence[str] = (), skip_self: bool = False):
        self.fn = fn
        self.shift = shift
        self.bound_kw = frozenset(bound_kw)
        self.skip_self = skip_self

    def param_for_pos(self, pos: int) -> Optional[str]:
        idx = pos + self.shift + (1 if self.skip_self else 0)
        params = self.fn["params"]
        if 0 <= idx < len(params):
            name = params[idx]
            if name not in self.bound_kw:
                return name
        return None


class Program:
    """Linked view over every module summary of one lint run."""

    def __init__(self, summaries: Sequence[dict]):
        self.summaries = list(summaries)
        self.by_path: Dict[str, dict] = {}
        self.by_module_name: List[Tuple[str, dict]] = []
        self.fns: Dict[Tuple[str, str], dict] = {}
        self.methods: Dict[str, List[dict]] = {}
        self.classes: Dict[str, List[Tuple[dict, dict]]] = {}
        for s in self.summaries:
            self.by_path[s["path"]] = s
            self.by_module_name.append((s["module_name"], s))
            for qual, fn in s["functions"].items():
                fn["_path"] = s["path"]
                fn["_mod"] = s
                self.fns[(s["path"], qual)] = fn
            for cls, info in s["classes"].items():
                self.classes.setdefault(cls, []).append((s, info))
                for m, q in info["methods"].items():
                    fn = self.fns.get((s["path"], q))
                    if fn is not None:
                        self.methods.setdefault(m, []).append(fn)
        self.by_module_name.sort(key=lambda t: t[0])

    def all_fns(self) -> Iterator[dict]:
        for s in self.summaries:
            yield from s["functions"].values()

    def module_by_suffix(self, dotted: str) -> Optional[dict]:
        dotted = dotted.lstrip(".")
        if not dotted:
            return None
        for name, s in self.by_module_name:
            if name == dotted or name.endswith("." + dotted):
                return s
        return None

    # ------------------------------------------------------ scope chain

    def scope_chain(self, fn: dict) -> List[dict]:
        """fn, then enclosing function scopes, then the module body."""
        mod = fn["_mod"]
        out = [fn]
        parts = fn["qual"].split(".")
        for cut in range(len(parts) - 1, 0, -1):
            prefix = ".".join(parts[:cut])
            enclosing = mod["functions"].get(prefix)
            if enclosing is not None and enclosing is not fn:
                out.append(enclosing)
        module_fn = mod["functions"].get("<module>")
        if module_fn is not None and module_fn is not fn:
            out.append(module_fn)
        return out

    def lookup_alias(self, fn: dict, name: str) -> Optional[dict]:
        for scope in self.scope_chain(fn):
            alias = scope["aliases"].get(name)
            if alias is not None:
                return alias
        return None

    # ------------------------------------------------------- resolution

    def _class_method(self, cls_name: str, attr: str,
                      seen: Optional[Set[str]] = None) -> List[dict]:
        seen = seen if seen is not None else set()
        if cls_name in seen:
            return []
        seen.add(cls_name)
        out: List[dict] = []
        for s, info in self.classes.get(cls_name, []):
            q = info["methods"].get(attr)
            if q is not None:
                fn = self.fns.get((s["path"], q))
                if fn is not None:
                    out.append(fn)
            else:
                for base in info["bases"]:
                    out.extend(self._class_method(base, attr, seen))
        return out

    def _function_in_module(self, mod: dict, dotted: str) -> List[dict]:
        parts = dotted.split(".")
        if len(parts) == 1:
            fn = mod["functions"].get(parts[0])
            return [fn] if fn is not None else []
        if len(parts) == 2 and parts[0] in mod["classes"]:
            q = mod["classes"][parts[0]]["methods"].get(parts[1])
            if q is not None:
                fn = mod["functions"].get(q)
                return [fn] if fn is not None else []
        return []

    def resolve(self, fn: dict, ref: dict, shift: int = 0,
                bound_kw: Sequence[str] = (), depth: int = 0
                ) -> List[Target]:
        """All program functions a CalleeRef may call, with positional
        mapping.  Unresolvable (external, dynamic) refs return []."""
        if depth > 6 or not isinstance(ref, dict):
            return []
        kind = ref.get("k")
        if kind == "wrap":
            extra_shift = ref.get("shift", 0)
            extra_kw = ref.get("kw", [])
            return self.resolve(fn, ref["v"], shift + extra_shift,
                                list(bound_kw) + list(extra_kw), depth + 1)
        if kind != "dotted":
            return []                     # sub/subcall/opaque: no mapping
        dotted = ref["v"]
        parts = dotted.split(".")
        mod = fn["_mod"]

        if len(parts) == 1:
            name = parts[0]
            alias = self.lookup_alias(fn, name)
            if alias is not None:
                return self.resolve(fn, alias["target"],
                                    shift + alias.get("shift", 0),
                                    list(bound_kw) + list(alias.get("kw",
                                                                    [])),
                                    depth + 1)
            # nested def / sibling in enclosing scopes / module level
            quals = [fn["qual"] + "." + name]
            qparts = fn["qual"].split(".")
            for cut in range(len(qparts) - 1, 0, -1):
                prefix = ".".join(qparts[:cut])
                if prefix in mod["functions"]:
                    quals.append(prefix + "." + name)
            quals.append(name)
            for q in quals:
                got = mod["functions"].get(q)
                if got is not None:
                    return [Target(got, shift, bound_kw)]
            sym = mod["import_syms"].get(name)
            if sym is not None:
                origin = self.module_by_suffix(sym[0])
                if origin is not None:
                    got = self._function_in_module(origin, sym[1])
                    if got:
                        return [Target(g, shift, bound_kw) for g in got]
            return []

        head, attr = parts[0], parts[-1]
        if head in ("self", "cls"):
            if len(parts) == 2 and fn["cls"]:
                found = self._class_method(fn["cls"], attr)
                if found:
                    return [Target(g, shift, bound_kw,
                                   skip_self=g["method"]) for g in found]
            return [Target(g, shift, bound_kw, skip_self=g["method"])
                    for g in self.methods.get(attr, [])]
        # imported module alias: codec.get_trainable_values(...)
        origin_name = mod["import_mods"].get(head)
        if origin_name is None and head in mod["import_syms"]:
            sym = mod["import_syms"][head]
            # `from x import y` where y is a module (or a class)
            if len(parts) == 2 and sym[1] in self.classes:
                found = self._class_method(sym[1], attr)
                return [Target(g, shift, bound_kw,
                               skip_self=g["method"]) for g in found]
            origin_name = sym[0] + "." + sym[1]
        if origin_name is not None:
            origin = self.module_by_suffix(origin_name)
            if origin is not None:
                got = self._function_in_module(origin,
                                               ".".join(parts[1:]))
                return [Target(g, shift, bound_kw) for g in got]
            return []                    # external library: unresolved
        if head in mod["classes"]:
            found = self._class_method(head, attr)
            return [Target(g, shift, bound_kw, skip_self=g["method"])
                    for g in found]
        # method call on an untyped local object: every program class
        # defining the method is a candidate (union)
        if len(parts) >= 2:
            return [Target(g, shift, bound_kw, skip_self=g["method"])
                    for g in self.methods.get(attr, [])]
        return []

    # --------------------------------------------------- donation facts

    def return_facts(self, callee: dict) -> List[Optional[dict]]:
        """Per tuple position of ``callee``'s return value: a donation
        fact ``{"kind": "callable"|"dict", "argnums": [...]}`` or
        None."""
        width = max((len(r) for r in callee["returns"]), default=0)
        facts: List[Optional[dict]] = [None] * width
        for ret in callee["returns"]:
            for pos, elt in enumerate(ret):
                if elt.get("k") != "name":
                    continue
                name = elt["v"]
                alias = callee["aliases"].get(name)
                if alias is not None and alias.get("donate"):
                    facts[pos] = {"kind": "callable",
                                  "argnums": alias["donate"],
                                  "shift": alias.get("shift", 0)}
                elif name in callee["dict_donates"]:
                    facts[pos] = {"kind": "dict",
                                  "argnums": callee["dict_donates"][name]}
        return facts


def _label(fn: dict) -> str:
    return f"{Path(fn['_path']).name}:{fn['qual']}"


def _closure(fn: dict, seed: Set[str]) -> Set[str]:
    """Close a traced-name set over the function's local derives."""
    traced = set(seed)
    for _ in range(len(fn["derives"]) + 1):
        changed = False
        for target, srcs in fn["derives"]:
            if target not in traced and traced.intersection(srcs):
                traced.add(target)
                changed = True
        if not changed:
            break
    return traced


def _program_of(modules: Sequence[ModuleContext],
                extra_summaries: Sequence[dict],
                state: dict) -> Tuple[Program, Dict[str, ModuleContext]]:
    if "flow_program" not in state:
        live = {m.path: m for m in modules}
        sums = [extract_module_summary(m) for m in modules]
        seen = set(live)
        for s in extra_summaries:
            if s.get("version") == SUMMARY_VERSION \
                    and s.get("path") not in seen:
                sums.append(s)
                seen.add(s.get("path"))
        state["flow_program"] = Program(sums)
        state["flow_live"] = live
    return state["flow_program"], state["flow_live"]


def _mk_finding(rule: Rule, live: Dict[str, ModuleContext], path: str,
                line: int, col: int, message: str,
                chain: Sequence[str]) -> Finding:
    module = live.get(path)
    text = module.line_text(line) if module is not None else ""
    return Finding(path=path, line=line, col=col, rule_id=rule.id,
                   severity=rule.severity, message=message,
                   source_line=text, call_chain=tuple(chain))


# ================================================================ JG108

class CrossFunctionHazard(ProgramRule):
    """Traced values chased through resolved call edges from every jit
    root; hazards *lexically* inside a jit context stay with JG101/JG102
    (this rule would otherwise double-report every lexical finding)."""

    id = "JG108"
    severity = Severity.WARNING
    summary = "host sync / traced branch reached via calls from a jit root"

    _MAX_DEPTH = 10

    def check_program(self, modules, extra_summaries, state
                      ) -> Iterator[Finding]:
        prog, live = _program_of(modules, extra_summaries, state)
        reported: Set[Tuple] = set()
        for root in prog.all_fns():
            if not root["jit_root"] or root["_path"] not in live:
                continue
            traced = set(root["params"]) - set(root["static"])
            if not traced:
                continue
            yield from self._walk(prog, live, root, traced, reported)

    def _walk(self, prog: Program, live, root: dict, traced: Set[str],
              reported: Set[Tuple]) -> Iterator[Finding]:
        stack = [(root, frozenset(traced), (root,), None)]
        visited: Set[Tuple[str, str, frozenset]] = set()
        while stack:
            fn, fn_traced, chain, anchor = stack.pop()
            key = (fn["_path"], fn["qual"], fn_traced)
            if key in visited:
                continue
            visited.add(key)
            closed = _closure(fn, set(fn_traced))
            if len(chain) > 1 and not fn["in_jit"]:
                for haz in fn["hazards"]:
                    hit = sorted(closed.intersection(haz["names"]))
                    if not hit:
                        continue
                    rep_key = (anchor, fn["_path"], haz["line"],
                               haz["kind"])
                    if rep_key in reported:
                        continue
                    reported.add(rep_key)
                    what = ("host sync" if haz["kind"] == "sync"
                            else "traced-value branch")
                    yield _mk_finding(
                        self, live, anchor[0], anchor[1], anchor[2],
                        f"call into {_label(fn)!r} reaches a {what} "
                        f"({haz['msg']}) on traced value(s) "
                        f"{', '.join(repr(h) for h in hit)} at "
                        f"{Path(fn['_path']).name}:{haz['line']} "
                        f"(`{haz['text']}`); hoist it out of the jitted "
                        "call path or bind the argument statically",
                        chain=[_label(f) for f in chain])
            if len(chain) > self._MAX_DEPTH:
                continue
            for call in fn["calls"]:
                for target in prog.resolve(fn, call["callee"]):
                    callee = target.fn
                    callee_traced: Set[str] = set()
                    for pos, arg in enumerate(call["args"]):
                        if closed.intersection(arg["loads"]):
                            p = target.param_for_pos(pos)
                            if p is not None:
                                callee_traced.add(p)
                    for kw_name, arg in call["kw"].items():
                        if kw_name in callee["params"] \
                                and kw_name not in target.bound_kw \
                                and closed.intersection(arg["loads"]):
                            callee_traced.add(kw_name)
                    callee_traced -= set(callee["static"])
                    if not callee_traced:
                        continue
                    next_anchor = anchor if anchor is not None else (
                        fn["_path"], call["line"], call["col"])
                    stack.append((callee, frozenset(callee_traced),
                                  chain + (callee,), next_anchor))


# ================================================================ JG109

class UseAfterDonate(ProgramRule):
    """Caller-side scan: after a bare name is passed at a donated
    position, any read before a rebind — or a loop iteration that never
    rebinds it — touches a buffer jax may already have aliased away."""

    id = "JG109"
    severity = Severity.ERROR
    summary = "buffer read after being passed at a donate_argnums position"

    def check_program(self, modules, extra_summaries, state
                      ) -> Iterator[Finding]:
        prog, live = _program_of(modules, extra_summaries, state)
        for fn in prog.all_fns():
            if fn["_path"] in live:
                yield from self._check_fn(prog, live, fn)

    # ---------------------------------------------------------- facts

    def _call_donation(self, prog: Program, fn: dict, facts: Dict[str, dict],
                       call: dict) -> Tuple[List[int], int, Optional[str]]:
        """(donated argnums, positional shift, provenance label)."""
        ref = call["callee"]
        kind = ref.get("k")
        if kind == "wrap" and ref.get("donate"):
            return list(ref["donate"]), ref.get("shift", 0), None
        if kind == "dotted":
            parts = ref["v"].split(".")
            if len(parts) == 1:
                name = parts[0]
                fact = facts.get(name)
                if fact is not None and fact["kind"] == "callable":
                    return (list(fact["argnums"]), fact.get("shift", 0),
                            fact.get("from"))
                alias = prog.lookup_alias(fn, name)
                if alias is not None and alias.get("donate"):
                    return (list(alias["donate"]),
                            alias.get("shift", 0), None)
            for target in prog.resolve(fn, ref):
                if target.fn["donate_root"]:
                    return (list(target.fn["donate_root"]), target.shift
                            - (1 if target.skip_self else 0), None)
        elif kind == "sub":
            base = ref["v"].split(".")[0]
            fact = facts.get(base)
            if fact is not None and fact["kind"] == "dict":
                return list(fact["argnums"]), 0, fact.get("from")
            for scope in prog.scope_chain(fn):
                if base in scope["dict_donates"]:
                    return list(scope["dict_donates"][base]), 0, None
        elif kind == "subcall":
            for target in prog.resolve(fn, ref["v"]):
                rf = prog.return_facts(target.fn)
                if len(rf) == 1 and rf[0] is not None \
                        and rf[0]["kind"] == "dict":
                    return (list(rf[0]["argnums"]), 0, _label(target.fn))
        return [], 0, None

    def _build_facts(self, prog: Program, fn: dict) -> Dict[str, dict]:
        """Local name -> donation fact, from factory-call assignments
        (``a, b, c = trainer._build_fns(ci)``)."""
        facts: Dict[str, dict] = {}
        for call in fn["calls"]:
            assigned = call.get("assigned")
            if not assigned:
                continue
            for target in prog.resolve(fn, call["callee"]):
                rf = prog.return_facts(target.fn)
                if not any(rf):
                    continue
                label = _label(target.fn)
                if len(assigned) == 1 and len(rf) == 1:
                    if rf[0] is not None:
                        facts[assigned[0]] = dict(rf[0], **{"from": label})
                elif len(assigned) == len(rf):
                    for name, fact in zip(assigned, rf):
                        if fact is not None:
                            facts[name] = dict(fact, **{"from": label})
        return facts

    # ----------------------------------------------------------- scan

    def _check_fn(self, prog: Program, live, fn: dict
                  ) -> Iterator[Finding]:
        facts = self._build_facts(prog, fn)
        donated_at: Dict[int, Tuple[List[str], Optional[str], dict]] = {}
        for i, call in enumerate(fn["calls"]):
            argnums, shift, provenance = self._call_donation(
                prog, fn, facts, call)
            if not argnums:
                continue
            names: List[str] = []
            for p in argnums:
                pos = p - shift
                if 0 <= pos < len(call["args"]):
                    n = call["args"][pos]["n"]
                    if n is not None:
                        names.append(n)
            if names:
                donated_at[i] = (names, provenance, call)

        if not donated_at:
            return
        events = fn["events"]
        dead: Dict[str, Tuple[dict, Optional[str]]] = {}
        emitted: Set[Tuple] = set()
        for ev in events:
            t = ev["t"]
            if t == "store":
                dead.pop(ev["n"], None)
            elif t == "load":
                hit = dead.pop(ev["n"], None)
                if hit is not None:
                    call, provenance = hit
                    key = ("read", ev["n"], ev["line"])
                    if key in emitted:
                        continue
                    emitted.add(key)
                    chain = [_label(fn)] + (
                        [provenance] if provenance else [])
                    yield _mk_finding(
                        self, live, fn["_path"], ev["line"], ev["col"],
                        f"{ev['n']!r} is read after being passed at a "
                        f"donate_argnums position on line {call['line']} "
                        "— the buffer may already be donated and its "
                        "contents invalid; rebind the call's result or "
                        "pass a copy",
                        chain=chain)
            elif t == "call" and ev["i"] in donated_at:
                names, provenance, call = donated_at[ev["i"]]
                for n in names:
                    dead[n] = (call, provenance)

        # loop-carried: a donating call inside a loop whose donated name
        # is never re-stored in that loop body is reused (donated) on
        # the next iteration even if no later read appears lexically
        yield from self._loop_carried(live, fn, donated_at, emitted)

    def _loop_carried(self, live, fn: dict, donated_at, emitted
                      ) -> Iterator[Finding]:
        events = fn["events"]
        spans: List[Tuple[int, int]] = []
        stack: List[int] = []
        for idx, ev in enumerate(events):
            if ev["t"] == "ls":
                stack.append(idx)
            elif ev["t"] == "le" and stack:
                spans.append((stack.pop(), idx))
        for start, end in spans:
            span = events[start:end + 1]
            stored = {e["n"] for e in span if e["t"] == "store"}
            for e in span:
                if e["t"] != "call" or e["i"] not in donated_at:
                    continue
                names, provenance, call = donated_at[e["i"]]
                for n in names:
                    if n in stored:
                        continue
                    key = ("loop", n, call["line"])
                    if key in emitted:
                        continue
                    emitted.add(key)
                    chain = [_label(fn)] + (
                        [provenance] if provenance else [])
                    yield _mk_finding(
                        self, live, fn["_path"], call["line"],
                        call["col"],
                        f"{n!r} is passed at a donate_argnums position "
                        "inside a loop but never rebound in the loop "
                        "body — the next iteration reuses a donated "
                        "buffer; thread it through the loop like the "
                        "other carried state",
                        chain=chain)


# ================================================================ JG110

class KeyLineage(ProgramRule):
    """The same PRNG key consumed at two sites where at least one is a
    call edge into a transitively-consuming function.  Purely-local
    double consumption is JG103's finding; purely-unresolvable callees
    (flax ``Module.init``) never count as consumers."""

    id = "JG110"
    severity = Severity.WARNING
    summary = "PRNG key reaches multiple consumers across function calls"

    _MAX_ROUNDS = 20

    def _consuming_params(self, prog: Program) -> Set[Tuple[str, str, str]]:
        consuming: Set[Tuple[str, str, str]] = set()
        for fn in prog.all_fns():
            params = set(fn["params"])
            sanitized = set(fn["sanitized"])
            for name, _ln, _c, _tail in fn["sampler_uses"]:
                if name in params and name not in sanitized:
                    consuming.add((fn["_path"], fn["qual"], name))
        for _ in range(self._MAX_ROUNDS):
            changed = False
            for fn in prog.all_fns():
                params = set(fn["params"])
                sanitized = set(fn["sanitized"])
                for call in fn["calls"]:
                    for pos, arg in enumerate(call["args"]):
                        n = arg["n"]
                        if n is None or n not in params or n in sanitized:
                            continue
                        key = (fn["_path"], fn["qual"], n)
                        if key in consuming:
                            continue
                        for target in prog.resolve(fn, call["callee"]):
                            p = target.param_for_pos(pos)
                            if p is not None and (
                                    target.fn["_path"],
                                    target.fn["qual"], p) in consuming:
                                consuming.add(key)
                                changed = True
                                break
            if not changed:
                break
        return consuming

    def check_program(self, modules, extra_summaries, state
                      ) -> Iterator[Finding]:
        prog, live = _program_of(modules, extra_summaries, state)
        consuming = self._consuming_params(prog)
        for fn in prog.all_fns():
            if fn["_path"] not in live:
                continue
            sanitized = set(fn["sanitized"])
            for kname, kline, _kcol in fn["key_assigns"]:
                if kname in sanitized:
                    continue
                consumers: List[Tuple[int, int, str, Optional[str]]] = []
                for name, line, col, tail in fn["sampler_uses"]:
                    if name == kname:
                        consumers.append((line, col, "local", tail))
                for call in fn["calls"]:
                    for pos, arg in enumerate(call["args"]):
                        if arg["n"] != kname:
                            continue
                        for target in prog.resolve(fn, call["callee"]):
                            p = target.param_for_pos(pos)
                            if p is not None and (
                                    target.fn["_path"],
                                    target.fn["qual"], p) in consuming:
                                consumers.append((call["line"],
                                                  call["col"], "call",
                                                  _label(target.fn)))
                                break
                        else:
                            continue
                        break
                consumers.sort(key=lambda c: (c[0], c[1]))
                if len(consumers) < 2 or not any(
                        c[2] == "call" for c in consumers):
                    continue
                first = consumers[0]
                for line, col, kind, label in consumers[1:]:
                    via = (f"the call into {label!r}" if kind == "call"
                           else f"jax.random.{label}")
                    chain = [_label(fn)] + (
                        [label] if kind == "call" else [])
                    yield _mk_finding(
                        self, live, fn["_path"], line, col,
                        f"PRNG key {kname!r} (created line {kline}) is "
                        f"consumed again here via {via} after already "
                        f"feeding a consumer on line {first[0]} — the "
                        "streams are correlated; derive per-consumer "
                        "keys with jax.random.split/fold_in",
                        chain=chain)


# ================================================================ JG111

class DiscardedPureResult(Rule):
    """jax arrays are immutable: a statement-level ``x.at[0].set(v)`` or
    ``jnp.foo(...)`` computes a new array and drops it — a silent no-op
    that usually means the author expected in-place mutation."""

    id = "JG111"
    severity = Severity.WARNING
    summary = "result of a pure jax op is discarded (silent no-op)"

    def check(self, module: ModuleContext) -> Iterator[Finding]:
        index = build_index(module)
        jnp_aliases = index.jnp_aliases
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Expr)
                    and isinstance(node.value, ast.Call)):
                continue
            call = node.value
            func = call.func
            if (isinstance(func, ast.Attribute)
                    and func.attr in _AT_METHODS
                    and isinstance(func.value, ast.Subscript)
                    and isinstance(func.value.value, ast.Attribute)
                    and func.value.value.attr == "at"):
                yield self.finding(
                    module, node,
                    f".at[...].{func.attr}() returns a NEW array — the "
                    "result is discarded here, so the statement is a "
                    "silent no-op; assign it (`x = x.at[...]."
                    f"{func.attr}(...)`)")
                continue
            d = _dotted(func)
            if not d or "." not in d:
                continue
            head = d.split(".")[0]
            if head in jnp_aliases or d.startswith("jax.numpy."):
                yield self.finding(
                    module, node,
                    f"result of {d}(...) is discarded — jax.numpy ops "
                    "are pure, so this statement is a silent no-op; "
                    "assign or return the result (host-fetch idioms "
                    "belong to numpy: np.asarray)")


FLOW_RULES: Tuple[Rule, ...] = (
    CrossFunctionHazard(),
    UseAfterDonate(),
    KeyLineage(),
    DiscardedPureResult(),
)

#: the full shipped rule set: lexical JG101-JG107, flow JG108-JG111,
#: concurrency JG112-JG116, determinism contracts JG117-JG121.
#: threads.py and contracts.py import Program/summaries from this
#: module, so their rules are pulled in at the bottom — every name they
#: need is already bound by the time these imports run.
from .threads import THREAD_RULES  # noqa: E402  (deliberate late import)
from .contracts import CONTRACT_RULES  # noqa: E402  (deliberate late)

ALL_RULES: Tuple[Rule, ...] = (tuple(MODULE_RULES) + FLOW_RULES
                               + THREAD_RULES + CONTRACT_RULES)
