"""graftcheck CLI.

Usage::

    python -m federated_pytorch_test_tpu.analysis.lint \
        federated_pytorch_test_tpu bench.py [--json] \
        [--baseline analysis/baseline.json] [--write-baseline PATH] \
        [--fail-on {error,warning,advice}]

Exit code 0 when no non-suppressed, non-baselined finding is at or
above ``--fail-on`` (default: warning — ADVICE findings report but do
not fail); 1 otherwise; 2 on usage errors.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import Optional, Sequence

from .core import (LintEngine, Severity, load_baseline, render_json,
                   render_text, save_baseline)
from .rules import ALL_RULES


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m federated_pytorch_test_tpu.analysis.lint",
        description="JAX-aware static analysis for the federated stack")
    p.add_argument("paths", nargs="+",
                   help="files or directories (directories recurse to *.py)")
    p.add_argument("--json", action="store_true",
                   help="emit findings as JSON instead of text")
    p.add_argument("--baseline", type=Path, default=None,
                   help="JSON baseline of grandfathered finding "
                        "fingerprints to ignore")
    p.add_argument("--write-baseline", type=Path, default=None,
                   help="write current findings as the new baseline "
                        "and exit 0")
    p.add_argument("--fail-on", default="warning",
                   choices=["error", "warning", "advice"],
                   help="minimum severity that fails the run "
                        "(default: warning)")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    fail_on = Severity.parse(args.fail_on)
    baseline = None
    if args.baseline is not None:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError) as exc:
            print(f"graftcheck: cannot read baseline: {exc}",
                  file=sys.stderr)
            return 2
    missing = [p for p in args.paths if not Path(p).exists()]
    if missing:
        print(f"graftcheck: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2
    engine = LintEngine(ALL_RULES, baseline=baseline)
    result = engine.lint_paths(args.paths)
    if args.write_baseline is not None:
        save_baseline(args.write_baseline, result.findings)
        print(f"graftcheck: wrote {len(result.findings)} fingerprint(s) "
              f"to {args.write_baseline}")
        return 0
    out = (render_json(result, fail_on) if args.json
           else render_text(result, fail_on))
    print(out)
    return 1 if result.failing(fail_on) else 0


if __name__ == "__main__":
    sys.exit(main())
