"""graftcheck CLI.

Usage::

    python -m federated_pytorch_test_tpu.analysis.lint \
        federated_pytorch_test_tpu bench.py [--json | --sarif] \
        [--baseline analysis/baseline.json] [--write-baseline PATH] \
        [--fail-on {error,warning,advice}] \
        [--changed [GIT_REF]] [--cache PATH]

``--changed`` scopes *reporting* to files that differ from a git ref
(default ``HEAD``) plus untracked files, while the interprocedural
rules (JG108-JG111) still see the whole program: unchanged files
contribute their per-function summaries — from the ``--cache`` file
when the content sha1 still matches, re-extracted otherwise — so a
pre-commit hook pays parse+extract only for what the diff touched.

Exit code 0 when no non-suppressed, non-baselined finding is at or
above ``--fail-on`` (default: warning — ADVICE findings report but do
not fail); 1 otherwise; 2 on usage errors.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set

from .core import (Finding, LintEngine, LintResult, ModuleContext, Severity,
                   expand_paths, load_baseline, norm_path, render_json,
                   render_sarif, render_text, save_baseline)
from .flow import (ALL_RULES, ANALYSIS_VERSION, SUMMARY_VERSION,
                   extract_module_summary, file_sha1, strip_summary)

CACHE_VERSION = 1


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m federated_pytorch_test_tpu.analysis.lint",
        description="JAX-aware static analysis for the federated stack")
    p.add_argument("paths", nargs="*",
                   help="files or directories (directories recurse to *.py)")
    p.add_argument("--selftest", action="store_true",
                   help="run the built-in self-check (each determinism-"
                        "contract rule fires on its canary snippet and "
                        "the DEFAULT_TABLES mirror matches the declaring "
                        "modules) and exit")
    p.add_argument("--json", action="store_true",
                   help="emit findings as JSON instead of text")
    p.add_argument("--sarif", action="store_true",
                   help="emit findings as SARIF 2.1.0 instead of text")
    p.add_argument("--baseline", type=Path, default=None,
                   help="JSON baseline of grandfathered finding "
                        "fingerprints to ignore")
    p.add_argument("--write-baseline", type=Path, default=None,
                   help="write current findings as the new baseline "
                        "and exit 0")
    p.add_argument("--fail-on", default="warning",
                   choices=["error", "warning", "advice"],
                   help="minimum severity that fails the run "
                        "(default: warning)")
    p.add_argument("--changed", nargs="?", const="HEAD", default=None,
                   metavar="GIT_REF",
                   help="report only on files that differ from GIT_REF "
                        "(default HEAD) or are untracked; unchanged files "
                        "still feed the whole-program rules as summaries")
    p.add_argument("--cache", type=Path, default=None,
                   help="summary-cache file: read sha1-matched summaries "
                        "for unchanged files, write back fresh ones")
    return p


def _git_changed(anchor: Path, ref: str) -> Optional[Set[Path]]:
    """Absolute resolved paths changed vs ``ref`` plus untracked files,
    or None when ``anchor`` is not inside a git work tree."""
    anchor_dir = anchor if anchor.is_dir() else anchor.parent
    try:
        top = subprocess.run(
            ["git", "-C", str(anchor_dir), "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, check=True).stdout.strip()
        diff = subprocess.run(
            ["git", "-C", top, "diff", "--name-only", ref],
            capture_output=True, text=True, check=True).stdout
        untracked = subprocess.run(
            ["git", "-C", top, "ls-files", "--others",
             "--exclude-standard"],
            capture_output=True, text=True, check=True).stdout
    except (OSError, subprocess.CalledProcessError):
        return None
    out: Set[Path] = set()
    for line in (diff + untracked).splitlines():
        line = line.strip()
        if line:
            out.add((Path(top) / line).resolve())
    return out


def _load_cache(path: Optional[Path]) -> Dict[str, dict]:
    if path is None or not path.exists():
        return {}
    try:
        data = json.loads(path.read_text())
    except (OSError, ValueError):
        return {}
    if data.get("version") != CACHE_VERSION:
        return {}
    # a sha1 match alone is not enough: editing extraction or rule
    # logic changes what a summary *means* without changing the file it
    # came from, so entries written by a different analysis generation
    # are discarded wholesale (the staleness hole fixed in PR 9)
    if data.get("analysis_version") != ANALYSIS_VERSION:
        return {}
    entries = data.get("summaries")
    return entries if isinstance(entries, dict) else {}


def _save_cache(path: Path, entries: Dict[str, dict]) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(
        {"version": CACHE_VERSION, "analysis_version": ANALYSIS_VERSION,
         "summaries": entries},
        sort_keys=True) + "\n")


def _changed_run(engine: LintEngine, paths: Sequence[str], ref: str,
                 cache_path: Optional[Path]) -> Optional[LintResult]:
    changed = _git_changed(Path(paths[0]), ref)
    if changed is None:
        return None
    cache = _load_cache(cache_path)
    new_cache: Dict[str, dict] = {}
    live_modules: List[ModuleContext] = []
    syntax: List[Finding] = []
    extra: List[dict] = []
    for p in sorted(expand_paths(paths)):
        source = Path(p).read_text()
        sha = file_sha1(source)
        key = norm_path(str(p))
        if Path(p).resolve() in changed:
            module, err = engine._parse(source, str(p))
            if module is None:
                syntax.append(err)
                continue
            live_modules.append(module)
            new_cache[key] = {
                "sha1": sha,
                "summary": strip_summary(extract_module_summary(module))}
            continue
        hit = cache.get(key)
        if (hit and hit.get("sha1") == sha
                and hit.get("summary", {}).get("version")
                == SUMMARY_VERSION):
            summary = dict(hit["summary"])
            summary["path"] = str(p)   # rebind to this run's spelling
        else:
            module, err = engine._parse(source, str(p))
            if module is None:
                continue               # unchanged + unparseable: skip
            summary = extract_module_summary(module)
        extra.append(summary)
        new_cache[key] = {"sha1": sha, "summary": strip_summary(summary)}
    result = engine.lint_modules(live_modules, extra_summaries=extra)
    result.findings.extend(syntax)
    result.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule_id))
    if cache_path is not None:
        _save_cache(cache_path, new_cache)
    return result


#: one canary snippet per determinism-contract rule: the smallest
#: program that must trip exactly that rule.  ``--selftest`` lints each
#: in-memory — a sub-second end-to-end check that the whole pipeline
#: (extraction -> taint -> rules) still catches the contract breaks it
#: exists for, cheap enough to ride in the tier-1 report step.
_SELFTEST_SNIPPETS = {
    "JG117": ("import time\n"
              "def emit(sink, r):\n"
              "    t = time.time()\n"
              "    rec = {'event': 'control', 'observed': t}\n"
              "    sink.control_event(rec)\n"),
    "JG118": ("SCHEMA_VERSION = 2\n"
              "EVENTS = ('round',)\n"
              "REQUIRED = {'round': ('event',)}\n"
              "VERSION_LADDER = (\n"
              "    {'version': 1, 'added_kinds': ('round',),\n"
              "     'added_fields': ()},\n"
              "    {'version': 2, 'added_kinds': (), 'added_fields': (),\n"
              "     'removed_fields': ('loss',)},\n"
              ")\n"),
    "JG119": ("def emit(sink, xs):\n"
              "    ids = [x for x in set(xs)]\n"
              "    rec = {'event': 'client', 'clients': ids}\n"
              "    sink.client_event(rec)\n"),
    "JG120": ("def save_meta(n):\n"
              "    meta = {'sx_orphan': n, 'sx_ok': 1}\n"
              "    return meta\n"
              "def restore_meta(meta):\n"
              "    return meta['sx_ok']\n"),
    "JG121": ("import numpy as np\n"
              "def emit(sink, r):\n"
              "    rng = np.random.default_rng()\n"
              "    v = float(rng.normal())\n"
              "    rec = {'event': 'serve', 'requests': v}\n"
              "    sink.serve_event(rec)\n"),
}

_SELFTEST_CLEAN = (
    "def emit(sink, seed, r):\n"
    "    rec = {'event': 'control', 'round_index': r,\n"
    "           'observed': seed + r}\n"
    "    sink.control_event(rec)\n")


def selftest() -> int:
    """Exit 0 when the contract rules and tables are healthy."""
    from .contracts import DEFAULT_TABLES

    failures: List[str] = []
    engine = LintEngine(ALL_RULES)
    for rule_id, source in sorted(_SELFTEST_SNIPPETS.items()):
        module, err = engine._parse(source, f"<selftest:{rule_id}>")
        if module is None:
            failures.append(f"{rule_id}: canary failed to parse ({err})")
            continue
        got = {f.rule_id for f in engine.lint_modules([module]).findings}
        if got != {rule_id}:
            fired = sorted(got) if got else "nothing"
            failures.append(f"{rule_id}: canary fired {fired} instead")
    module, _ = engine._parse(_SELFTEST_CLEAN, "<selftest:clean>")
    got = {f.rule_id for f in engine.lint_modules([module]).findings}
    if got:
        failures.append(f"clean canary fired {sorted(got)}")

    # the DEFAULT_TABLES mirror (used when the declaring modules are
    # not in the lint run) must match what the declaring modules say
    here = Path(__file__).resolve().parent.parent
    declared: Dict[str, object] = {}
    for rel in ("obs/schema.py", "control/replay.py"):
        src = (here / rel).read_text()
        module, _ = engine._parse(src, str(here / rel))
        if module is None:
            failures.append(f"{rel}: failed to parse for table check")
            continue
        for name, (value, _line) in \
                extract_module_summary(module)["tables"].items():
            declared[name] = value
    for name, mirror in sorted(DEFAULT_TABLES.items()):
        if name not in declared:
            failures.append(f"table {name}: not declared in "
                            "obs/schema.py or control/replay.py")
        elif declared[name] != mirror:
            failures.append(f"table {name}: DEFAULT_TABLES mirror is out "
                            "of sync with the declaring module")

    if failures:
        for f in failures:
            print(f"graftcheck selftest: FAIL: {f}", file=sys.stderr)
        return 1
    print(f"graftcheck selftest: ok ({len(_SELFTEST_SNIPPETS)} contract "
          f"canaries, clean canary, {len(DEFAULT_TABLES)} tables in sync)")
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.selftest:
        return selftest()
    if not args.paths:
        print("graftcheck: no paths given", file=sys.stderr)
        return 2
    if args.json and args.sarif:
        print("graftcheck: --json and --sarif are mutually exclusive",
              file=sys.stderr)
        return 2
    fail_on = Severity.parse(args.fail_on)
    baseline = None
    if args.baseline is not None:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError) as exc:
            print(f"graftcheck: cannot read baseline: {exc}",
                  file=sys.stderr)
            return 2
    missing = [p for p in args.paths if not Path(p).exists()]
    if missing:
        print(f"graftcheck: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2
    engine = LintEngine(ALL_RULES, baseline=baseline)
    if args.changed is not None:
        result = _changed_run(engine, args.paths, args.changed, args.cache)
        if result is None:
            print(f"graftcheck: --changed {args.changed}: not inside a "
                  "git work tree (or the ref is unknown)", file=sys.stderr)
            return 2
    else:
        result = engine.lint_paths(args.paths)
        if args.cache is not None:
            entries: Dict[str, dict] = {}
            for p in sorted(expand_paths(args.paths)):
                source = Path(p).read_text()
                module, _err = engine._parse(source, str(p))
                if module is not None:
                    entries[norm_path(str(p))] = {
                        "sha1": file_sha1(source),
                        "summary": strip_summary(
                            extract_module_summary(module))}
            _save_cache(args.cache, entries)
    if args.write_baseline is not None:
        save_baseline(args.write_baseline, result.findings)
        print(f"graftcheck: wrote {len(result.findings)} fingerprint(s) "
              f"to {args.write_baseline}")
        return 0
    if args.sarif:
        out = render_sarif(result, ALL_RULES)
    elif args.json:
        out = render_json(result, fail_on)
    else:
        out = render_text(result, fail_on)
    print(out)
    return 1 if result.failing(fail_on) else 0


if __name__ == "__main__":
    sys.exit(main())
