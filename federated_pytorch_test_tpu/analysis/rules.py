"""graftcheck rule set (JG101-JG107).

All rules share one per-module :class:`JitIndex` that answers "which
functions execute under a jit trace, and which of their parameters are
static there".  Jit contexts are found syntactically:

- ``jax.jit(fn, ...)`` call sites, resolving ``fn`` through
  ``shard_map(fn, ...)`` wrappers and ``functools.partial(fn, kw=...)``
  (partial-bound kwargs become *static* parameters — they are baked
  into the traced callable, not traced);
- ``@jax.jit`` / ``@partial(jax.jit, ...)`` decorators;
- functions lexically nested inside either of the above.

The rules in this module are *lexical*: each looks at one jit context
at a time.  Cross-function flow — a traced array passed into a helper
defined elsewhere, a donated buffer read after the donating call, a
PRNG key consumed on both sides of a function boundary — lives in
:mod:`.flow` (JG108-JG111), which reuses this module's
:class:`JitIndex` and callable-resolution helpers.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .core import Finding, ModuleContext, Rule, Severity

FunctionNode = (ast.FunctionDef, ast.AsyncFunctionDef)
ScopeNode = FunctionNode + (ast.Module,)
_BRANCHY = (ast.If, ast.While, ast.For, ast.AsyncFor, ast.Try)

_TIMER_FUNCS = {"perf_counter", "monotonic", "time", "process_time"}
_SYNC_NAMES = {"block_until_ready", "device_get", "item", "tolist",
               "asarray"}
_ARRAY_CTORS = {"array", "asarray", "zeros", "ones", "full", "arange",
                "linspace", "eye"}
_STATE_PARAMS = {"state", "opt_state", "params", "carry"}
_SAMPLER_EXEMPT = {"split", "fold_in", "PRNGKey", "key", "key_data",
                   "wrap_key_data", "clone"}


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _last_name(node: ast.AST) -> Optional[str]:
    d = _dotted(node)
    return d.rsplit(".", 1)[-1] if d else None


def _is_jit_call(call: ast.Call) -> bool:
    """jax.jit / pjit call sites, plus local wrappers that follow the
    ``*_jit(fn, ...)`` naming convention (e.g. the engines'
    ``_instrument_jit``) — otherwise instrumentation helpers would hide
    the step functions from every jit-context rule."""
    d = _dotted(call.func)
    if not d:
        return False
    last = d.rsplit(".", 1)[-1]
    return d in ("jit", "jax.jit") or last == "pjit" \
        or last.endswith("_jit")


def _is_partial_call(call: ast.Call) -> bool:
    return _last_name(call.func) == "partial"


def _is_timer_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = _dotted(node.func)
    if not d:
        return False
    last = d.rsplit(".", 1)[-1]
    if last not in _TIMER_FUNCS:
        return False
    # bare time() must come from the time module to count
    return last != "time" or d in ("time", "time.time")


@dataclass
class JitSite:
    """One jax.jit(...) call or @jit decorator."""

    call: Optional[ast.Call]          # None for bare @jax.jit decorators
    node: ast.AST                     # node to anchor findings on
    fn: Optional[ast.AST]             # resolved wrapped FunctionDef
    static_params: Set[str] = field(default_factory=set)
    donates: bool = False
    static_argnums: Tuple[int, ...] = ()
    donate_argnums_vals: Tuple[int, ...] = ()  # literal ints when spelled
    bound_name: Optional[str] = None  # `f = jax.jit(...)` binding, if any


@dataclass
class JitIndex:
    parents: Dict[ast.AST, ast.AST]
    sites: List[JitSite]
    contexts: Set[ast.AST]                       # FunctionDefs under jit
    static_by_fn: Dict[ast.AST, Set[str]]        # root fn -> static params
    numpy_aliases: Set[str]
    jnp_aliases: Set[str]
    jitted_bindings: Dict[str, JitSite]
    fn_by_scope: Dict[Tuple[ast.AST, str], ast.AST]

    def enclosing_fn(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None and not isinstance(cur, FunctionNode):
            cur = self.parents.get(cur)
        return cur

    def in_jit_context(self, node: ast.AST) -> bool:
        fn = self.enclosing_fn(node)
        while fn is not None:
            if fn in self.contexts:
                return True
            fn = self.enclosing_fn(fn)
        return False


def _build_parents(tree: ast.Module) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _enclosing_scope(parents, node) -> ast.AST:
    cur = parents.get(node)
    while cur is not None and not isinstance(cur, ScopeNode):
        cur = parents.get(cur)
    return cur


def _resolve_callable(expr: ast.AST, scope: ast.AST, parents,
                      fn_by_scope) -> Tuple[Optional[ast.AST], Set[str], int]:
    """Resolve the callable passed to jit to a local FunctionDef.

    Returns (fn_node_or_None, partial-bound kwarg names, count of
    partial-bound positionals).  Sees through shard_map(...) and
    functools.partial(...).
    """
    if isinstance(expr, ast.Name):
        cur = scope
        while cur is not None:
            fn = fn_by_scope.get((cur, expr.id))
            if fn is not None:
                return fn, set(), 0
            cur = _enclosing_scope(parents, cur)
        return None, set(), 0
    if isinstance(expr, ast.Call) and expr.args:
        last = _last_name(expr.func)
        if last == "shard_map":
            return _resolve_callable(expr.args[0], scope, parents,
                                     fn_by_scope)
        if _is_partial_call(expr):
            fn, kws, pos = _resolve_callable(expr.args[0], scope, parents,
                                             fn_by_scope)
            kws = kws | {k.arg for k in expr.keywords if k.arg}
            return fn, kws, pos + len(expr.args) - 1
    return None, set(), 0


def _const_tuple_ints(node: ast.AST) -> Tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                out.append(el.value)
        return tuple(out)
    return ()


def _donate_ints(node: ast.AST) -> Tuple[int, ...]:
    """Literal donate_argnums, seeing through the engines' conditional
    wrapper ``donate_argnums=self._donate_argnums((0, 1))`` (donation
    still *happens* at those positions whenever the knob is on, so the
    flow rules must treat the site as donating)."""
    vals = _const_tuple_ints(node)
    if vals:
        return vals
    if isinstance(node, ast.Call) and len(node.args) == 1:
        return _const_tuple_ints(node.args[0])
    if isinstance(node, ast.IfExp):
        return _donate_ints(node.body) or _donate_ints(node.orelse)
    return ()


def _const_strs(node: ast.AST) -> Tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(el.value for el in node.elts
                     if isinstance(el, ast.Constant)
                     and isinstance(el.value, str))
    return ()


def _fn_param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args]
    return names


def build_index(module: ModuleContext) -> JitIndex:
    cached = getattr(module, "_graft_index", None)
    if cached is not None:
        return cached
    tree = module.tree
    parents = _build_parents(tree)

    numpy_aliases: Set[str] = set()
    jnp_aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for al in node.names:
                if al.name == "numpy":
                    numpy_aliases.add(al.asname or "numpy")
                if al.name == "jax.numpy":
                    jnp_aliases.add(al.asname or "jax.numpy")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax" and any(a.name == "numpy"
                                            for a in node.names):
                for a in node.names:
                    if a.name == "numpy":
                        jnp_aliases.add(a.asname or "numpy")

    # (scope node, name) -> FunctionDef defined directly in that scope
    fn_by_scope: Dict[Tuple[ast.AST, str], ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, FunctionNode):
            fn_by_scope[(_enclosing_scope(parents, node), node.name)] = node

    sites: List[JitSite] = []
    jitted_bindings: Dict[str, JitSite] = {}

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_call(node) and node.args:
            scope = _enclosing_scope(parents, node)
            fn, static_kw, _ = _resolve_callable(node.args[0], scope,
                                                 parents, fn_by_scope)
            static = set(static_kw)
            argnums: Tuple[int, ...] = ()
            donates = False
            donate_vals: Tuple[int, ...] = ()
            for kw in node.keywords:
                if kw.arg in ("static_argnums", "static_argnposnums"):
                    argnums = _const_tuple_ints(kw.value)
                elif kw.arg == "static_argnames":
                    static |= set(_const_strs(kw.value))
                elif kw.arg in ("donate_argnums", "donate_argnames"):
                    donates = True
                    if kw.arg == "donate_argnums":
                        donate_vals = _donate_ints(kw.value)
            if fn is not None:
                names = _fn_param_names(fn)
                for i in argnums:
                    if 0 <= i < len(names):
                        static.add(names[i])
            site = JitSite(call=node, node=node, fn=fn,
                           static_params=static, donates=donates,
                           static_argnums=argnums,
                           donate_argnums_vals=donate_vals)
            sites.append(site)
            parent = parents.get(node)
            if (isinstance(parent, ast.Assign) and len(parent.targets) == 1
                    and isinstance(parent.targets[0], ast.Name)):
                site.bound_name = parent.targets[0].id
                jitted_bindings[site.bound_name] = site

    # decorator forms: @jax.jit / @jit / @partial(jax.jit, ...)
    for node in ast.walk(tree):
        if not isinstance(node, FunctionNode):
            continue
        for dec in node.decorator_list:
            static: Set[str] = set()
            argnums = ()
            donates = False
            donate_vals: Tuple[int, ...] = ()
            is_jit = False
            if _dotted(dec) in ("jit", "jax.jit"):
                is_jit = True
            elif isinstance(dec, ast.Call):
                if _is_jit_call(dec):
                    is_jit, call = True, dec
                elif (_is_partial_call(dec) and dec.args
                      and _dotted(dec.args[0]) in ("jit", "jax.jit")):
                    is_jit, call = True, dec
                if is_jit:
                    for kw in dec.keywords:
                        if kw.arg == "static_argnums":
                            argnums = _const_tuple_ints(kw.value)
                        elif kw.arg == "static_argnames":
                            static |= set(_const_strs(kw.value))
                        elif kw.arg in ("donate_argnums", "donate_argnames"):
                            donates = True
                            if kw.arg == "donate_argnums":
                                donate_vals = _donate_ints(kw.value)
            if is_jit:
                names = _fn_param_names(node)
                for i in argnums:
                    if 0 <= i < len(names):
                        static.add(names[i])
                sites.append(JitSite(
                    call=dec if isinstance(dec, ast.Call) else None,
                    node=dec, fn=node, static_params=static,
                    donates=donates, static_argnums=argnums,
                    donate_argnums_vals=donate_vals))

    roots: Dict[ast.AST, Set[str]] = {}
    for site in sites:
        if site.fn is not None:
            roots.setdefault(site.fn, set()).update(site.static_params)

    contexts: Set[ast.AST] = set()
    for root in roots:
        contexts.add(root)
        for sub in ast.walk(root):
            if isinstance(sub, FunctionNode):
                contexts.add(sub)

    index = JitIndex(parents=parents, sites=sites, contexts=contexts,
                     static_by_fn=roots, numpy_aliases=numpy_aliases or
                     {"numpy", "np", "onp"},
                     jnp_aliases=jnp_aliases or {"jnp"},
                     jitted_bindings=jitted_bindings,
                     fn_by_scope=fn_by_scope)
    module._graft_index = index
    return index


def _walk_scope(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body without descending into nested defs
    (comprehensions and lambdas are treated as part of the scope)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, FunctionNode):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# ------------------------------------------------------------------- JG101

class HostSyncInJit(Rule):
    id = "JG101"
    severity = Severity.ERROR
    summary = "host sync / numpy materialisation inside a jitted function"

    def check(self, module: ModuleContext) -> Iterator[Finding]:
        index = build_index(module)
        if not index.contexts:
            return
        np_prefixes = index.numpy_aliases
        for fn in index.contexts:
            for node in _walk_scope(fn):
                if not isinstance(node, ast.Call):
                    continue
                # x.item() / x.tolist()
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("item", "tolist")
                        and not node.args):
                    yield self.finding(
                        module, node,
                        f".{node.func.attr}() forces a device->host sync "
                        "inside a jitted function; return the value and "
                        "read it outside the trace")
                    continue
                d = _dotted(node.func)
                if d:
                    head, _, tail = d.rpartition(".")
                    if head in np_prefixes and tail in ("asarray", "array"):
                        yield self.finding(
                            module, node,
                            f"{d}() materialises a traced value on the host "
                            "inside a jitted function; use jax.numpy or "
                            "move the conversion outside jit")
                        continue
                    if d in ("jax.device_get", "device_get"):
                        yield self.finding(
                            module, node,
                            f"{d}() inside a jitted function is a host "
                            "round-trip; fetch outside the trace")
                        continue
                if (isinstance(node.func, ast.Name)
                        and node.func.id in ("float", "int")
                        and node.args
                        and not isinstance(node.args[0], ast.Constant)):
                    yield self.finding(
                        module, node,
                        f"{node.func.id}() on a non-literal inside a jitted "
                        "function concretises a traced value (host sync / "
                        "TracerConversionError); keep it as an array")


# ------------------------------------------------------------------- JG102

class TracedBranch(Rule):
    id = "JG102"
    severity = Severity.ERROR
    summary = "Python control flow on a traced value inside jit"

    def check(self, module: ModuleContext) -> Iterator[Finding]:
        index = build_index(module)
        if not index.contexts:
            return
        for fn in index.contexts:
            traced = set(_fn_param_names(fn))
            traced -= index.static_by_fn.get(fn, set())
            # parameters of enclosing jit contexts are traced here too
            outer = index.enclosing_fn(fn)
            while outer is not None:
                if outer in index.contexts:
                    traced |= (set(_fn_param_names(outer))
                               - index.static_by_fn.get(outer, set()))
                outer = index.enclosing_fn(outer)
            if not traced:
                continue
            for node in _walk_scope(fn):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                test = node.test
                # `x is None` checks are resolved statically at trace time
                if (isinstance(test, ast.Compare)
                        and all(isinstance(op, (ast.Is, ast.IsNot))
                                for op in test.ops)):
                    continue
                hit = next(
                    (n for n in ast.walk(test)
                     if isinstance(n, ast.Name)
                     and isinstance(n.ctx, ast.Load)
                     and n.id in traced), None)
                if hit is not None:
                    kind = "if" if isinstance(node, ast.If) else "while"
                    yield self.finding(
                        module, node,
                        f"Python `{kind}` on traced value {hit.id!r} inside "
                        "a jitted function; use lax.cond/lax.while_loop or "
                        "jnp.where (or bind the argument statically)")


# ------------------------------------------------------------------- JG103

class KeyReuse(Rule):
    id = "JG103"
    severity = Severity.WARNING
    summary = "PRNG key constructed or consumed more than once"

    def check(self, module: ModuleContext) -> Iterator[Finding]:
        index = build_index(module)
        scopes: List[ast.AST] = [module.tree]
        for node in ast.walk(module.tree):
            if isinstance(node, FunctionNode):
                scopes.append(node)
        for scope in scopes:
            yield from self._check_scope(module, scope)

    def _prngkey_calls(self, scope) -> List[ast.Call]:
        out = []
        for node in _walk_scope(scope):
            if (isinstance(node, ast.Call)
                    and _last_name(node.func) == "PRNGKey"):
                out.append(node)
        return out

    def _check_scope(self, module, scope) -> Iterator[Finding]:
        # (a) the same PRNGKey(<expr>) built twice in one scope
        by_arg: Dict[str, List[ast.Call]] = {}
        for call in self._prngkey_calls(scope):
            key = ast.dump(ast.Module(
                body=[ast.Expr(a) for a in call.args], type_ignores=[]))
            by_arg.setdefault(key, []).append(call)
        for calls in by_arg.values():
            calls.sort(key=lambda c: (c.lineno, c.col_offset))
            for dup in calls[1:]:
                yield self.finding(
                    module, dup,
                    "PRNGKey(...) constructed twice from the same seed "
                    "expression in this scope — both consumers draw the "
                    "SAME stream; derive the second key via "
                    "jax.random.fold_in/split")
        # (b) one key name feeding >= 2 jax.random samplers, never split
        assigned: Dict[str, ast.AST] = {}
        for node in _walk_scope(scope):
            if (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                    and _last_name(node.value.func) == "PRNGKey"):
                assigned[node.targets[0].id] = node
        if not assigned:
            return
        uses: Dict[str, List[ast.Call]] = {k: [] for k in assigned}
        split_names: Set[str] = set()
        for node in _walk_scope(scope):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if not d or "random" not in d.split("."):
                continue
            tail = d.rsplit(".", 1)[-1]
            argnames = {a.id for a in node.args if isinstance(a, ast.Name)}
            for name in argnames & set(assigned):
                if tail in ("split", "fold_in"):
                    split_names.add(name)
                elif tail not in _SAMPLER_EXEMPT:
                    uses[name].append(node)
        for name, calls in uses.items():
            if name in split_names or len(calls) < 2:
                continue
            calls.sort(key=lambda c: (c.lineno, c.col_offset))
            for dup in calls[1:]:
                yield self.finding(
                    module, dup,
                    f"PRNG key {name!r} feeds multiple jax.random "
                    "consumers without an intervening split/fold_in — "
                    "the draws are correlated")


# ------------------------------------------------------------------- JG104

class TimerNoSync(Rule):
    id = "JG104"
    severity = Severity.WARNING
    summary = "wall-clock timer around dispatched work without a host sync"

    def check(self, module: ModuleContext) -> Iterator[Finding]:
        bodies: List[List[ast.stmt]] = []
        for node in ast.walk(module.tree):
            for attr in ("body", "orelse", "finalbody"):
                blk = getattr(node, attr, None)
                if isinstance(blk, list) and blk \
                        and isinstance(blk[0], ast.stmt):
                    bodies.append(blk)
        for body in bodies:
            yield from self._check_block(module, body)

    def _timer_assign(self, stmt) -> Optional[str]:
        if (isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and _is_timer_call(stmt.value)):
            return stmt.targets[0].id
        return None

    def _elapsed_pairs(self, stmt, timers: Dict[str, int]
                       ) -> List[Tuple[str, Optional[str]]]:
        """(timer name, minuend-name-or-None) for `X - t` in stmt."""
        out = []
        for node in ast.walk(stmt):
            if (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub)
                    and isinstance(node.right, ast.Name)
                    and node.right.id in timers):
                minuend = None
                if isinstance(node.left, ast.Name):
                    minuend = node.left.id
                elif _is_timer_call(node.left):
                    minuend = None        # inline perf_counter() read
                else:
                    continue              # unrecognised minuend: skip pair
                out.append((node.right.id, minuend))
        return out

    def _check_block(self, module, body) -> Iterator[Finding]:
        timers: Dict[str, int] = {}          # name -> stmt index of assign
        for i, stmt in enumerate(body):
            name = self._timer_assign(stmt)
            if name is not None:
                timers[name] = i
                continue
            if not timers:
                continue
            for tname, minuend in self._elapsed_pairs(stmt, timers):
                start = timers.pop(tname, None)
                if start is None:
                    continue
                if minuend is not None and minuend in timers:
                    end = timers[minuend]        # region ends at 2nd stamp
                elif minuend is not None:
                    continue                     # `x - t` with unknown x
                else:
                    end = i
                region = body[start + 1:end + 1]
                if not region:
                    continue
                has_call = any(isinstance(n, ast.Call)
                               for s in region for n in ast.walk(s))
                if not has_call:
                    continue
                # a yield in the region means this is a context-manager /
                # generator timer: it measures the CALLER's code, and the
                # sync responsibility lives at the call site
                if any(isinstance(n, (ast.Yield, ast.YieldFrom))
                       for s in region for n in ast.walk(s)):
                    continue
                if not self._region_synced(region):
                    yield self.finding(
                        module, body[start],
                        f"timer {tname!r} measures a region that dispatches "
                        "work but never syncs the host unconditionally "
                        "(block_until_ready/fetch/float) before the elapsed "
                        "read — this times dispatch, not execution")

    def _region_synced(self, region: Sequence[ast.stmt]) -> bool:
        for stmt in region:
            if self._stmt_syncs(stmt):
                return True
        return False

    def _stmt_syncs(self, stmt: ast.stmt) -> bool:
        """True if stmt unconditionally reaches a sync marker (markers
        nested under if/while/for/try don't count; conditional
        *expressions* do)."""
        if isinstance(stmt, _BRANCHY):
            return False
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            if any(self._expr_syncs(it.context_expr)
                   for it in stmt.items):
                return True
            return any(self._stmt_syncs(s) for s in stmt.body)
        if isinstance(stmt, FunctionNode + (ast.ClassDef,)):
            return False
        return self._expr_syncs(stmt)

    def _expr_syncs(self, root: ast.AST) -> bool:
        stack = [root]
        while stack:
            node = stack.pop()
            if isinstance(node, FunctionNode + (ast.Lambda,)):
                continue
            if isinstance(node, ast.Call) and self._is_sync_call(node):
                return True
            stack.extend(ast.iter_child_nodes(node))
        return False

    def _is_sync_call(self, call: ast.Call) -> bool:
        last = _last_name(call.func)
        if last is None:
            return False
        if last in _SYNC_NAMES or "sync" in last.lower() or last == "fetch":
            return True
        if last in ("float", "int") and isinstance(call.func, ast.Name):
            return bool(call.args) and not isinstance(call.args[0],
                                                      ast.Constant)
        # jax.tree.map(np.asarray, x): mapping a fetching function over a
        # tree is this repo's "force a host fetch" idiom
        if last in ("map", "tree_map"):
            return any(_last_name(a) in _SYNC_NAMES for a in call.args)
        return False


# ------------------------------------------------------------------- JG105

class RecompileHazard(Rule):
    id = "JG105"
    severity = Severity.WARNING
    summary = "recompilation hazard: closure array / non-hashable static"

    def check(self, module: ModuleContext) -> Iterator[Finding]:
        index = build_index(module)
        yield from self._closure_arrays(module, index)
        yield from self._nonhashable_statics(module, index)

    def _closure_arrays(self, module, index) -> Iterator[Finding]:
        np_like = index.numpy_aliases | {"jnp", "jax"}
        for fn in index.contexts:
            local: Set[str] = set(_fn_param_names(fn))
            array_outer: Dict[str, int] = {}
            for node in _walk_scope(fn):
                if isinstance(node, ast.Name) and isinstance(
                        node.ctx, (ast.Store, ast.Del)):
                    local.add(node.id)
            outer = index.enclosing_fn(fn)
            while outer is not None:
                for node in _walk_scope(outer):
                    if (isinstance(node, ast.Assign)
                            and len(node.targets) == 1
                            and isinstance(node.targets[0], ast.Name)
                            and isinstance(node.value, ast.Call)):
                        d = _dotted(node.value.func)
                        if d and "." in d:
                            head, _, tail = d.rpartition(".")
                            if head in np_like and tail in _ARRAY_CTORS:
                                array_outer.setdefault(
                                    node.targets[0].id, node.lineno)
                outer = index.enclosing_fn(outer)
            if not array_outer:
                continue
            seen: Set[str] = set()
            for node in _walk_scope(fn):
                if (isinstance(node, ast.Name)
                        and isinstance(node.ctx, ast.Load)
                        and node.id in array_outer
                        and node.id not in local
                        and node.id not in seen):
                    seen.add(node.id)
                    yield self.finding(
                        module, node,
                        f"jitted function closes over concrete array "
                        f"{node.id!r} (built at line "
                        f"{array_outer[node.id]}); a rebuilt closure "
                        "retraces — pass it as an argument instead")

    def _nonhashable_statics(self, module, index) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = node.func.id if isinstance(node.func, ast.Name) else None
            site = index.jitted_bindings.get(name) if name else None
            if site is None or not site.static_argnums:
                continue
            for pos in site.static_argnums:
                if pos < len(node.args) and isinstance(
                        node.args[pos], (ast.List, ast.Dict, ast.Set)):
                    yield self.finding(
                        module, node.args[pos],
                        f"non-hashable literal at static_argnums position "
                        f"{pos} of jitted {name!r} — every call retraces "
                        "(and jax raises on unhashable statics); pass a "
                        "tuple or hashable config object")


# ------------------------------------------------------------------- JG106

class MissingDonation(Rule):
    """Warning severity: with the engine donation-safe end to end (every
    state-carrying jit site either donates or carries an explicit
    suppression explaining why the caller must keep the input alive), an
    undeclared site is a real perf bug — the round allocates a second copy
    of the model state on TPU — not a style nit."""

    id = "JG106"
    severity = Severity.WARNING
    summary = "jitted update fn carries large state but donates no buffers"

    def check(self, module: ModuleContext) -> Iterator[Finding]:
        index = build_index(module)
        for site in index.sites:
            if site.donates or site.fn is None:
                continue
            params = set(_fn_param_names(site.fn))
            hit = sorted(params & _STATE_PARAMS)
            if not hit:
                continue
            fn_name = getattr(site.fn, "name", "<fn>")
            yield self.finding(
                module, site.node,
                f"jit of {fn_name!r} updates large state "
                f"({', '.join(hit)}) without donate_argnums; donate (or "
                "spell donate_argnums=() / suppress with a why-comment "
                "when the caller must keep the input buffers alive)")


# ------------------------------------------------------------------- JG107

def _axes_from_mesh_call(call: ast.Call) -> Optional[Set[str]]:
    """Axis names of a ``Mesh(devices, axis_names)`` construction, or None
    when the call is not a Mesh / the names are not string literals
    (``client_mesh()`` and friends stay opaque on purpose)."""
    if _last_name(call.func) != "Mesh":
        return None
    names: Tuple[str, ...] = ()
    for kw in call.keywords:
        if kw.arg == "axis_names":
            names = _const_strs(kw.value)
    if not names and len(call.args) > 1:
        names = _const_strs(call.args[1])
    return set(names) or None


def _mesh_axes(mesh_expr: Optional[ast.AST],
               tree: ast.Module) -> Optional[Set[str]]:
    """Statically-known axis names of the mesh expression, else None."""
    if isinstance(mesh_expr, ast.Call):
        return _axes_from_mesh_call(mesh_expr)
    if not isinstance(mesh_expr, ast.Name):
        return None                       # self.mesh etc: unknown
    axes: Optional[Set[str]] = None
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == mesh_expr.id):
            if not isinstance(node.value, ast.Call):
                return None
            got = _axes_from_mesh_call(node.value)
            if got is None:
                return None               # one opaque rebinding: unknown
            axes = (axes or set()) | got
    return axes


def _module_str_constant(tree: ast.Module, name: str) -> Optional[str]:
    """Value of a module-level ``NAME = "literal"`` binding, if unique."""
    val = None
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            val = node.value.value
    return val


def _iter_p_calls(expr: ast.AST, tree: ast.Module,
                  _resolve: bool = True) -> Iterator[ast.Call]:
    """P(...) / PartitionSpec(...) calls inside a specs expression.

    A Name element (``spec_c`` built earlier) is resolved one level deep
    through ``name = P(...)`` assignments anywhere in the module — the
    engines build their specs once per builder function.
    """
    stack = [expr]
    while stack:
        node = stack.pop()
        if (isinstance(node, ast.Call)
                and _last_name(node.func) in ("P", "PartitionSpec")):
            yield node
            continue
        if isinstance(node, ast.Name) and _resolve:
            for asg in ast.walk(tree):
                if (isinstance(asg, ast.Assign) and len(asg.targets) == 1
                        and isinstance(asg.targets[0], ast.Name)
                        and asg.targets[0].id == node.id):
                    yield from _iter_p_calls(asg.value, tree, _resolve=False)
            continue
        stack.extend(ast.iter_child_nodes(node))


def _spec_axis_names(expr: ast.AST, tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for call in _iter_p_calls(expr, tree):
        for a in call.args:
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                out.add(a.value)
            elif isinstance(a, ast.Name):
                v = _module_str_constant(tree, a.id)
                if v is not None:
                    out.add(v)
    return out


class ShardingAnnotation(Rule):
    """Error severity: both defects are guaranteed runtime failures — a
    wrong ``in_specs`` arity raises inside shard_map's argument zip, and
    an axis name the mesh doesn't define raises at lowering — but only
    when that code path finally executes, which for the engines' cached
    per-block builders can be minutes into a TPU run."""

    id = "JG107"
    severity = Severity.ERROR
    summary = "shard_map in_specs/out_specs disagree with callable or mesh"

    def check(self, module: ModuleContext) -> Iterator[Finding]:
        index = build_index(module)
        tree = module.tree
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and _last_name(node.func) == "shard_map" and node.args):
                continue
            kw = {k.arg: k.value for k in node.keywords if k.arg}
            in_specs = kw.get("in_specs") or (
                node.args[2] if len(node.args) > 2 else None)
            out_specs = kw.get("out_specs") or (
                node.args[3] if len(node.args) > 3 else None)
            mesh_expr = kw.get("mesh") or (
                node.args[1] if len(node.args) > 1 else None)
            yield from self._check_arity(module, index, node, in_specs)
            yield from self._check_axes(module, tree, node, mesh_expr,
                                        in_specs, out_specs)

    def _check_arity(self, module, index, node,
                     in_specs) -> Iterator[Finding]:
        # only a literal tuple/list pins the arity; a single spec is a
        # pytree-prefix broadcast and a Name is opaque
        if not isinstance(in_specs, (ast.Tuple, ast.List)):
            return
        scope = _enclosing_scope(index.parents, node)
        fn, bound_kw, bound_pos = _resolve_callable(
            node.args[0], scope, index.parents, index.fn_by_scope)
        if fn is None or fn.args.vararg is not None:
            return                        # lambda / foreign fn / *args
        names = _fn_param_names(fn)
        n_max = len(names) - bound_pos - len(bound_kw & set(names))
        n_defaults = len(fn.args.defaults)
        n_specs = len(in_specs.elts)
        if not (n_max - n_defaults <= n_specs <= n_max):
            want = (str(n_max) if n_defaults == 0
                    else f"{n_max - n_defaults}..{n_max}")
            yield self.finding(
                module, in_specs,
                f"in_specs has {n_specs} entries but "
                f"{getattr(fn, 'name', '<fn>')!r} takes {want} positional "
                "argument(s) after partial binding — shard_map will raise "
                "when this call site finally executes")

    def _check_axes(self, module, tree, node, mesh_expr, in_specs,
                    out_specs) -> Iterator[Finding]:
        axes = _mesh_axes(mesh_expr, tree)
        if not axes:
            return                        # mesh not statically known
        for label, expr in (("in_specs", in_specs),
                            ("out_specs", out_specs)):
            if expr is None:
                continue
            unknown = sorted(_spec_axis_names(expr, tree) - axes)
            if unknown:
                yield self.finding(
                    module, expr,
                    f"{label} names mesh axis "
                    f"{', '.join(repr(u) for u in unknown)} but the mesh "
                    f"defines only {sorted(axes)} — lowering raises on the "
                    "undefined axis")


#: the lexical (single-module) rule set; :mod:`.flow` appends the
#: interprocedural JG108-JG111 rules and exposes the combined ALL_RULES
MODULE_RULES: Sequence[Rule] = (
    HostSyncInJit(),
    TracedBranch(),
    KeyReuse(),
    TimerNoSync(),
    RecompileHazard(),
    MissingDonation(),
    ShardingAnnotation(),
)
