"""Runtime sanitizers: checkify wiring + retrace sentinel.

Both are default-off and wrap the engines' jitted callables at build
time, so the default path constructs the *literal* pre-existing
``jax.jit(shard_map(fn))`` chain — bit-identical by construction (the
same contract as compress/faults/obs).

- ``--sanitize``: every instrumented step runs under
  ``jax.experimental.checkify`` with NaN/inf (``float_checks``) and
  out-of-bounds index (``index_checks``) assertions; the error payload
  is thrown on the host after each call (which forces a sync — this is
  a debugging mode, not a perf mode).
- ``--retrace-sentinel``: counts executions of the traced Python body
  of each instrumented callable.  The body only runs when jit traces
  (compiled dispatch never re-enters Python), so ``count - 1`` per
  callable is its retrace count; regressions (a leaked weak type, an
  unhashable static, a rebuilt closure) show up as a nonzero
  ``jit_retraces`` in the obs round records and the bench artifact.
  Zero runtime cost: the wrapper is never called after compilation.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
from jax.experimental import checkify

_errors_cache: "frozenset | None" = None


def index_checks_supported(version: str) -> bool:
    """Whether this jax version's ``index_checks`` are trustworthy.

    Every 0.4.x ``checkify.scatter_oob`` crashes (internal IndexError,
    not a check failure) on the scatter in a gather VJP — the exact op
    the cross-entropy ``take_along_axis`` backward pass emits — so the
    whole 0.4 line is gated off without probing.  0.5+ carries the fix;
    an unparseable version string returns True so the runtime probe in
    :func:`sanitize_errors` gets the final word.
    """
    try:
        major, minor = (int(x) for x in version.split(".")[:2])
    except (ValueError, TypeError):
        return True
    return (major, minor) >= (0, 5)


def sanitize_errors():
    """NaN/inf checks always; index checks when this jax supports them.

    The version gate (:func:`index_checks_supported`) rejects the 0.4.x
    line outright; newer jax is still probed once on a tiny gather-grad
    and index_checks dropped if the instrumentation itself is broken.
    Cached after the first call, so a jax bump flips index checks on
    with no code change here.
    """
    global _errors_cache
    if _errors_cache is None:
        errs = checkify.float_checks
        if index_checks_supported(jax.__version__):
            try:
                def _probe(x, i):
                    sel = jnp.take_along_axis(x, i[..., None], axis=-1)
                    return sel[..., 0].sum()

                checkify.checkify(jax.grad(_probe),
                                  errors=checkify.index_checks)(
                    jnp.ones((2, 3)), jnp.arange(2))
                errs = errs | checkify.index_checks
            except Exception:
                pass
        _errors_cache = errs
    return _errors_cache


class TraceSentinel:
    """Counts traces of jit-wrapped callables by name.

    ``wrap(fn, name)`` returns a callable that bumps ``counts[name]``
    and delegates; wrap it *inside* ``jax.jit`` so the bump happens
    exactly once per trace (first compile included).
    """

    def __init__(self):
        self.counts: Dict[str, int] = {}

    def wrap(self, fn: Callable, name: str) -> Callable:
        self.counts.setdefault(name, 0)
        counts = self.counts

        @functools.wraps(fn)
        def counted(*args, **kwargs):
            counts[name] += 1
            return fn(*args, **kwargs)

        return counted

    @property
    def traces(self) -> int:
        return sum(sorted(self.counts.values()))

    @property
    def retraces(self) -> int:
        """Traces beyond the first per callable — the regressions."""
        return sum(sorted(v - 1 for v in self.counts.values() if v > 0))


def checkify_callable(fn: Callable) -> Callable:
    """Transform ``fn`` so its outputs become ``(error, outputs)``.

    Apply to the *pre-jit* callable (shard_map output included — the
    checks thread through the mesh axes), then jit the result: the
    checkified jaxpr is traced once and cached like any jitted fn.
    """
    return checkify.checkify(fn, errors=sanitize_errors())


def throwing(jitted_fn: Callable) -> Callable:
    """Unwrap a checkified jitted fn: throw the error, return outputs.

    ``err.throw()`` raises :class:`jax.experimental.checkify.JaxRuntimeError`
    on the first failed check (with the failing primitive named) and
    forces a host sync on the error payload.
    """

    @functools.wraps(jitted_fn)
    def wrapper(*args: Any, **kwargs: Any):
        err, out = jitted_fn(*args, **kwargs)
        err.throw()
        return out

    return wrapper


def instrument_jit(fn: Callable, name: str, *, sanitize: bool,
                   sentinel: "TraceSentinel | None",
                   ledger=None, **jit_kwargs) -> Callable:
    """The one assembly point: conditionally checkify + count, then jit.

    With all knobs off this is exactly ``jax.jit(fn, **jit_kwargs)``.
    ``ledger`` is an ``obs.costs.CostLedger``: its trace counter wraps
    the pre-jit callable (innermost, like the sentinel) and its dispatch
    timer wraps the jitted fn directly — under ``throwing`` so the timed
    window never includes the checkify host sync.
    """
    if sanitize:
        fn = checkify_callable(fn)
    if sentinel is not None:
        fn = sentinel.wrap(fn, name)
    if ledger is not None:
        fn = ledger.mark(fn, name)
    jfn = jax.jit(fn, **jit_kwargs)
    if ledger is not None:
        jfn = ledger.instrument(jfn, name)
    if sanitize:
        jfn = throwing(jfn)
    return jfn
