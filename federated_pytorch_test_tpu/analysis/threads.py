"""Concurrency graftcheck: thread-role inference + host-race rules.

PRs 5-8 made the host side genuinely concurrent — the async checkpoint
writer runs a one-worker pool, the LOFAR pipeline runs a bounded-queue
prefetch thread, the engine stages epochs on a worker — so this module
polices host-concurrency bugs the way flow.py polices donation bugs:
statically, whole-program, zero findings baselined.

**Role inference.**  Every ``threading.Thread(target=...)`` constructor
and every ``<pool>.submit(fn, ...)`` on a known ``ThreadPoolExecutor``
is a *spawn edge*; its target function is seeded with a role named
after the thread ``name=``, the pool's ``thread_name_prefix``, or the
target function itself (``_produce`` -> ``produce``).  Spawned roles
propagate over resolved call edges, but only through *unambiguous*
resolutions — an untyped ``obj.meth(...)`` that unions into several
classes would smear a worker role across unrelated code, so multi-
candidate edges stop spawned-role flow.  The ``main`` role starts at
every module body and every function with no incoming call or spawn
edge (public API, drivers) and propagates through every edge including
unions: over-approximating *main* is harmless (it is the safe role),
over-approximating a *worker* role would manufacture races.

A function reachable both ways (``save_checkpoint_swapped``: called
synchronously by the engine and submitted to the ckpt-writer pool)
carries both roles.  Construction-time writes (``__init__`` and
friends) are excluded from the race rules: publish-before-spawn is the
idiom the whole tree uses.

**Rules.**

- **JG112** (WARNING) — a shared mutable attribute (``self.x`` /
  ``global x``) written under >= 2 thread roles with no common lock
  held across all write sites.  Attributes that *are* synchronisation
  objects (locks, queues, events, pools, thread handles) are exempt:
  they synchronise themselves.
- **JG113** (WARNING) — a blocking call (queue get/put, ``join``,
  ``result``, ``wait``, file I/O, ``time.sleep``,
  ``block_until_ready``, cross-host barrier) or a JAX dispatch issued
  while holding a lock: the lock's critical section inherits the full
  latency and every other thread convoys behind it.
- **JG114** (WARNING) — non-atomic check-then-act (``if k in
  self._d: ... self._d[k] = ...``) or read-modify-write
  (``self._round += 1``) on state accessed under >= 2 roles, with no
  lock held at the mutating site.
- **JG115** (ERROR) — JAX device computation (``jnp.*`` /
  ``jax.lax.*`` / ``jax.random.*`` samplers / ``device_put`` / a call
  resolving into a jitted function) reachable under a non-main thread
  role — the bug class ``snapshot_to_host`` exists to prevent: the
  runtime's dispatch path is not thread-safe against the main round
  loop.  Host-only jax calls (``jax.process_index``, ``jax.tree.*``,
  ``device_get``) are deliberately not dispatch.
- **JG116** (WARNING) — lifecycle: a thread/pool stored on an
  attribute with no reachable ``join``/``shutdown`` anywhere in the
  program, a local thread neither joined nor returned, a thread
  spawned without keeping a handle at all, and an unbounded
  ``queue.Queue`` that receives puts (the producer can outrun the
  consumer without backpressure).
"""

from __future__ import annotations

from collections import deque
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .core import Finding, ProgramRule, Severity
from .flow import Program, _label, _mk_finding, _program_of

MAIN_ROLE = "main"

#: construction-time functions whose attribute writes are
#: publish-before-spawn, not races
_INIT_NAMES = {"__init__", "__new__", "__post_init__"}

#: blocking call tails (resolved against the callee's dotted name)
_BLOCKING_TAILS = {
    "join": "blocks on a thread/process join",
    "result": "blocks on a future result",
    "wait": "blocks on an event/condition/future wait",
    "block_until_ready": "blocks on a device computation",
    "sync_global_devices": "blocks on a cross-host barrier",
}


def _short_name(fn: dict) -> str:
    return fn["qual"].rsplit(".", 1)[-1]


def _fn_key(fn: dict) -> Tuple[str, str]:
    return (fn["_path"], fn["qual"])


def _token_attr(token: str) -> str:
    """``self._lock`` -> ``_lock``; bare locals pass through."""
    return token.rsplit(".", 1)[-1]


class ThreadModel:
    """Program-wide concurrency facts: sync-object attributes, spawn
    edges, and the role set of every function."""

    def __init__(self, prog: Program):
        self.prog = prog
        #: attr/local name -> sync kind ("lock"/"queue"/"pool"/...)
        self.sync_attr_kinds: Dict[str, str] = {}
        #: (owner class or "", attr) -> make record
        self.sync_makes: Dict[Tuple[str, str], dict] = {}
        #: names known to be Lock/RLock objects
        self.lock_names: Set[str] = set()
        #: (path, qual) -> set of role names
        self.roles: Dict[Tuple[str, str], Set[str]] = {}
        #: role name -> human label of the spawn site that created it
        self.role_sources: Dict[str, str] = {}
        #: spawn edges as (spawning fn, spawn record, role, targets)
        self.spawn_edges: List[Tuple[dict, dict, str, List[dict]]] = []
        self._succ_cache: Dict[Tuple[str, str], List[List[dict]]] = {}
        self._collect_sync()
        self._collect_spawns()
        self._propagate()

    # ------------------------------------------------------------ build

    def _collect_sync(self) -> None:
        for fn in self.prog.all_fns():
            for m in fn["sync_makes"]:
                token = m["token"]
                attr = _token_attr(token)
                owner = fn["cls"] or ""
                self.sync_makes[(owner, attr)] = m
                self.sync_attr_kinds[attr] = m["kind"]
                if m["kind"] == "lock":
                    self.lock_names.add(attr)

    def _role_name(self, fn: dict, spawn: dict) -> str:
        if spawn.get("name"):
            return spawn["name"]
        if spawn["via"] == "submit" and spawn.get("pool"):
            attr = _token_attr(spawn["pool"])
            make = (self.sync_makes.get((fn["cls"] or "", attr))
                    or self.sync_makes.get(("", attr)))
            if make is None:        # any class owning a pool by this name
                for (_owner, a), m in self.sync_makes.items():
                    if a == attr and m["kind"] == "pool":
                        make = m
                        break
            if make is not None and make.get("prefix"):
                return make["prefix"]
        ref = spawn["target"]
        while isinstance(ref, dict) and ref.get("k") == "wrap":
            ref = ref["v"]
        if isinstance(ref, dict) and ref.get("k") == "dotted":
            return _token_attr(ref["v"]).strip("_") or "worker"
        return f"worker@{spawn['line']}"

    def _is_known_pool(self, fn: dict, base: Optional[str]) -> bool:
        if not base:
            return False
        return self.sync_attr_kinds.get(_token_attr(base)) == "pool"

    def _collect_spawns(self) -> None:
        for fn in self.prog.all_fns():
            for spawn in fn["spawns"]:
                if spawn["via"] == "submit" \
                        and not self._is_known_pool(fn, spawn.get("pool")):
                    continue        # .submit on something that is no pool
                role = self._role_name(fn, spawn)
                targets = [t.fn for t in
                           self.prog.resolve(fn, spawn["target"])]
                self.role_sources.setdefault(
                    role, f"{_label(fn)}:{spawn['line']}")
                self.spawn_edges.append((fn, spawn, role, targets))

    def _successors(self, fn: dict) -> List[List[dict]]:
        """Resolved callees per call site (each inner list is the
        candidate set of one call)."""
        key = _fn_key(fn)
        got = self._succ_cache.get(key)
        if got is None:
            got = [[t.fn for t in self.prog.resolve(fn, call["callee"])]
                   for call in fn["calls"]]
            self._succ_cache[key] = got
        return got

    def _propagate(self) -> None:
        work: deque = deque()

        def add(fn: dict, role: str) -> None:
            have = self.roles.setdefault(_fn_key(fn), set())
            if role not in have:
                have.add(role)
                work.append((fn, role))

        spawn_targets: Set[Tuple[str, str]] = set()
        for _fn, _spawn, role, targets in self.spawn_edges:
            for t in targets:
                spawn_targets.add(_fn_key(t))
                add(t, role)
        # spawned roles flow only through unambiguous call edges
        while work:
            fn, role = work.popleft()
            for candidates in self._successors(fn):
                if len(candidates) == 1:
                    add(candidates[0], role)

        has_in: Set[Tuple[str, str]] = set(spawn_targets)
        for fn in self.prog.all_fns():
            for candidates in self._successors(fn):
                for callee in candidates:
                    if callee is not fn:
                        has_in.add(_fn_key(callee))
        for fn in self.prog.all_fns():
            if fn["qual"] == "<module>" or _fn_key(fn) not in has_in:
                add(fn, MAIN_ROLE)
        # main propagates through every edge, unions included
        while work:
            fn, role = work.popleft()
            for candidates in self._successors(fn):
                for callee in candidates:
                    add(callee, role)

    # ---------------------------------------------------------- queries

    def roles_of(self, fn: dict) -> Set[str]:
        return self.roles.get(_fn_key(fn), set())

    def worker_roles_of(self, fn: dict) -> Set[str]:
        return self.roles_of(fn) - {MAIN_ROLE}

    def held_locks(self, tokens: Sequence[str]) -> Set[str]:
        """The subset of held ``with``/``acquire`` tokens that are known
        Lock/RLock objects."""
        return {t for t in tokens if _token_attr(t) in self.lock_names}

    def is_sync_attr(self, attr: str) -> bool:
        return attr in self.sync_attr_kinds

    def shared_accesses(self) -> Dict[Tuple[str, str],
                                      List[Tuple[dict, dict]]]:
        """(owner, attr) -> [(fn, event)] over every ``self.X`` access
        and every ``global``-declared name (owner = ``<module name>``)."""
        out: Dict[Tuple[str, str], List[Tuple[dict, dict]]] = {}
        for fn in self.prog.all_fns():
            if fn["cls"]:
                for ev in fn["events"]:
                    if ev["t"] in ("aload", "astore"):
                        out.setdefault((fn["cls"], ev["n"]),
                                       []).append((fn, ev))
            g = set(fn["globals"])
            if g:
                owner = fn["_mod"]["module_name"]
                for ev in fn["events"]:
                    if ev["t"] == "store" and ev["n"] in g:
                        sev = {"t": "astore", "n": ev["n"],
                               "line": fn["line"], "col": 0}
                        out.setdefault((owner, ev["n"]),
                                       []).append((fn, sev))
                    elif ev["t"] == "load" and ev["n"] in g:
                        out.setdefault((owner, ev["n"]),
                                       []).append((fn, ev))
        return out

    # ------------------------------------------------- dispatch classing

    def dispatch_desc(self, fn: dict, call: dict) -> Optional[str]:
        """Why this call is a JAX device dispatch, or None."""
        ref = call["callee"]
        while isinstance(ref, dict) and ref.get("k") == "wrap":
            ref = ref["v"]
        if isinstance(ref, dict) and ref.get("k") == "dotted":
            d = ref["v"]
            head = d.split(".")[0]
            if head in fn["_mod"].get("jnp_aliases", []) \
                    or d.startswith("jax.numpy."):
                return f"{d}() device op"
            if d.startswith(("jax.lax.", "lax.")):
                return f"{d}() lax op"
            if d.startswith("jax.random."):
                return f"{d}() sampler"
            if d in ("jax.device_put", "device_put"):
                return f"{d}() transfer"
        for target in self.prog.resolve(fn, call["callee"]):
            if target.fn["jit_root"] or target.fn["in_jit"]:
                return f"call into jitted {_label(target.fn)!r}"
        return None

    def blocking_desc(self, fn: dict, call: dict) -> Optional[str]:
        """Why this call blocks the current thread, or None."""
        ref = call["callee"]
        if not (isinstance(ref, dict) and ref.get("k") == "dotted"):
            return None
        d = ref["v"]
        base, _, last = d.rpartition(".")
        if last in _BLOCKING_TAILS and (base or last in (
                "block_until_ready", "sync_global_devices")):
            return _BLOCKING_TAILS[last]
        if d == "open":
            return "performs file I/O (open)"
        if d == "time.sleep" or d.endswith(".sleep"):
            return "sleeps"
        if last in ("get", "put") and base \
                and self.sync_attr_kinds.get(_token_attr(base)) == "queue":
            return f"blocks on queue .{last}()"
        return None


def _model_of(prog: Program, state: dict) -> ThreadModel:
    model = state.get("thread_model")
    if model is None or model.prog is not prog:
        model = ThreadModel(prog)
        state["thread_model"] = model
    return model


def build_thread_model(prog: Program) -> ThreadModel:
    """Public entry for tests: infer roles over an existing Program."""
    return ThreadModel(prog)


def _roles_str(roles: Set[str]) -> str:
    return "{" + ", ".join(sorted(roles)) + "}"


# ================================================================ JG112

class SharedWriteNoLock(ProgramRule):
    """A slot written under two different thread roles is a data race
    unless every write site holds one common lock."""

    id = "JG112"
    severity = Severity.WARNING
    summary = "shared attribute written under >=2 thread roles, no lock"

    def check_program(self, modules, extra_summaries, state
                      ) -> Iterator[Finding]:
        prog, live = _program_of(modules, extra_summaries, state)
        model = _model_of(prog, state)
        for (owner, attr), sites in sorted(model.shared_accesses().items()):
            if model.is_sync_attr(attr):
                continue
            writes = [(fn, ev) for fn, ev in sites
                      if ev["t"] == "astore"
                      and _short_name(fn) not in _INIT_NAMES]
            if not writes:
                continue
            role_union: Set[str] = set()
            for fn, _ev in writes:
                role_union |= model.roles_of(fn)
            if len(role_union) < 2:
                continue
            guards = [model.held_locks(ev.get("h", ()))
                      for _fn, ev in writes]
            if set.intersection(*guards):
                continue
            writers = sorted({_label(fn) for fn, _ev in writes})
            for fn, ev in writes:
                if fn["_path"] not in live:
                    continue
                yield _mk_finding(
                    self, live, fn["_path"], ev["line"], ev["col"],
                    f"{owner}.{attr!s} is written under thread roles "
                    f"{_roles_str(role_union)} with no common lock held "
                    "across the write sites — concurrent writers race; "
                    "guard every access with one threading.Lock (or "
                    "confine the slot to a single role)",
                    chain=writers)
                break               # one finding per slot


# ================================================================ JG113

class BlockingUnderLock(ProgramRule):
    """Blocking (or dispatching to the device) while holding a lock
    serialises every thread that wants the lock behind the slow call."""

    id = "JG113"
    severity = Severity.WARNING
    summary = "blocking call or JAX dispatch while holding a lock"

    def check_program(self, modules, extra_summaries, state
                      ) -> Iterator[Finding]:
        prog, live = _program_of(modules, extra_summaries, state)
        model = _model_of(prog, state)
        for fn in prog.all_fns():
            if fn["_path"] not in live:
                continue
            for call in fn["calls"]:
                held = sorted(model.held_locks(call.get("held", ())))
                if not held:
                    continue
                why = model.blocking_desc(fn, call)
                if why is None:
                    why = model.dispatch_desc(fn, call)
                    if why is not None:
                        why = f"dispatches to the device ({why})"
                if why is None:
                    continue
                yield _mk_finding(
                    self, live, fn["_path"], call["line"], call["col"],
                    f"this call {why} while holding "
                    f"{', '.join(held)} — the critical section inherits "
                    "the full wait and other threads convoy on the "
                    "lock; move the slow call outside the lock and "
                    "only publish the result under it",
                    chain=[_label(fn)])


# ================================================================ JG114

class CheckThenAct(ProgramRule):
    """``if <reads self.x>: self.x = ...`` and ``self.x += 1`` are
    atomic only single-threaded; under two roles the interleaving
    between check/read and act/write loses updates."""

    id = "JG114"
    severity = Severity.WARNING
    summary = "non-atomic check-then-act / read-modify-write across roles"

    def check_program(self, modules, extra_summaries, state
                      ) -> Iterator[Finding]:
        prog, live = _program_of(modules, extra_summaries, state)
        model = _model_of(prog, state)
        for (owner, attr), sites in sorted(model.shared_accesses().items()):
            if model.is_sync_attr(attr):
                continue
            active = [(fn, ev) for fn, ev in sites
                      if _short_name(fn) not in _INIT_NAMES]
            if not any(ev["t"] == "astore" for _fn, ev in active):
                continue
            role_union: Set[str] = set()
            for fn, _ev in active:
                role_union |= model.roles_of(fn)
            if len(role_union) < 2:
                continue
            for fn, ev in active:
                if ev["t"] != "astore" or fn["_path"] not in live:
                    continue
                rmw = bool(ev.get("rmw"))
                checked = attr in ev.get("chk", ())
                if not (rmw or checked):
                    continue
                if model.held_locks(ev.get("h", ())):
                    continue
                shape = ("read-modify-write" if rmw
                         else "check-then-act (tested by the enclosing "
                              "if/while)")
                yield _mk_finding(
                    self, live, fn["_path"], ev["line"], ev["col"],
                    f"non-atomic {shape} on {owner}.{attr!s}, which is "
                    f"accessed under thread roles "
                    f"{_roles_str(role_union)} — another role can "
                    "interleave between the read/test and this write; "
                    "hold a lock across the whole sequence",
                    chain=[_label(fn)])


# ================================================================ JG115

class ThreadedJaxDispatch(ProgramRule):
    """JAX dispatch is only safe from the thread that owns the runtime
    (the main round loop); a worker role that traces/launches device
    work races the engine's own dispatch — snapshot on the main thread
    (``snapshot_to_host``) and hand workers plain host arrays."""

    id = "JG115"
    severity = Severity.ERROR
    summary = "JAX device dispatch reachable from a non-main thread role"

    def check_program(self, modules, extra_summaries, state
                      ) -> Iterator[Finding]:
        prog, live = _program_of(modules, extra_summaries, state)
        model = _model_of(prog, state)
        for fn in prog.all_fns():
            if fn["_path"] not in live:
                continue
            workers = model.worker_roles_of(fn)
            if not workers:
                continue
            for call in fn["calls"]:
                desc = model.dispatch_desc(fn, call)
                if desc is None:
                    continue
                chain = [model.role_sources.get(r, r)
                         for r in sorted(workers)]
                yield _mk_finding(
                    self, live, fn["_path"], call["line"], call["col"],
                    f"{desc} runs under worker thread role(s) "
                    f"{_roles_str(workers)} — device dispatch off the "
                    "main thread races the round loop's own launches; "
                    "materialise on the main thread (snapshot_to_host) "
                    "and pass host arrays to the worker",
                    chain=chain)


# ================================================================ JG116

class ThreadLifecycle(ProgramRule):
    """Threads/pools must have a reachable join/shutdown (otherwise
    exit and abort paths leak workers mid-write), and producer queues
    must be bounded (otherwise a fast producer buffers without limit)."""

    id = "JG116"
    severity = Severity.WARNING
    summary = "thread/pool without join/shutdown, or unbounded queue puts"

    def check_program(self, modules, extra_summaries, state
                      ) -> Iterator[Finding]:
        prog, live = _program_of(modules, extra_summaries, state)
        model = _model_of(prog, state)
        join_tokens: Set[str] = set()
        put_bases: Set[str] = set()
        for fn in prog.all_fns():
            for j in fn["joins"]:
                join_tokens.add(j["token"])
                join_tokens.add(_token_attr(j["token"]))
            for call in fn["calls"]:
                ref = call["callee"]
                if isinstance(ref, dict) and ref.get("k") == "dotted":
                    base, _, last = ref["v"].rpartition(".")
                    if last in ("put", "put_nowait") and base:
                        put_bases.add(_token_attr(base))
        for fn in prog.all_fns():
            if fn["_path"] not in live:
                continue
            returned = {elt.get("v") for ret in fn["returns"]
                        for elt in ret if elt.get("k") == "name"}
            for m in fn["sync_makes"]:
                token, kind = m["token"], m["kind"]
                if kind in ("thread", "pool"):
                    what = ("thread" if kind == "thread" else
                            "executor pool")
                    verb = "join()" if kind == "thread" else "shutdown()"
                    if token.startswith("self."):
                        if token in join_tokens \
                                or _token_attr(token) in join_tokens:
                            continue
                    else:
                        if any(j["token"] == token for j in fn["joins"]) \
                                or token in returned:
                            continue
                    yield _mk_finding(
                        self, live, fn["_path"], m["line"], m["col"],
                        f"{token} holds a {what} with no reachable "
                        f"{verb} anywhere in the program — exit and "
                        "abort paths leak the worker mid-write; retire "
                        f"it with {verb} on every path (a close()/"
                        "finally block)",
                        chain=[_label(fn)])
                elif kind == "queue" and not m.get("bounded", True):
                    attr = _token_attr(token)
                    if attr in put_bases:
                        yield _mk_finding(
                            self, live, fn["_path"], m["line"], m["col"],
                            f"{token} is an unbounded queue that "
                            "receives puts — a producer that outruns "
                            "its consumer buffers without limit; "
                            "construct it with maxsize= to get "
                            "backpressure",
                            chain=[_label(fn)])
            # fire-and-forget: a Thread(...) spawned without binding
            # any handle cannot be joined at all
            make_lines = {m["line"] for m in fn["sync_makes"]
                          if m["kind"] == "thread"}
            for spawn in fn["spawns"]:
                if spawn["via"] == "thread" \
                        and spawn["line"] not in make_lines:
                    yield _mk_finding(
                        self, live, fn["_path"], spawn["line"],
                        spawn["col"],
                        "thread spawned without keeping a handle — it "
                        "can never be joined, so program exit races "
                        "whatever it is doing; bind it and join on the "
                        "shutdown path",
                        chain=[_label(fn)])


THREAD_RULES: Tuple[ProgramRule, ...] = (
    SharedWriteNoLock(),
    BlockingUnderLock(),
    CheckThenAct(),
    ThreadedJaxDispatch(),
    ThreadLifecycle(),
)
