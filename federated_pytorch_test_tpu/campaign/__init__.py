"""Soak campaigns: trace-driven heavy-traffic schedules (campaign/).

The resilience stack (faults, churn, control plane, restart supervisor,
elastic reshape, population cohorts) is exercised by the tests for
seconds at a time; this package is the "operate unattended for weeks"
story.  A declarative schedule spec (:mod:`.schedule`) compiles diurnal
arrival curves, churn waves, straggler storms, correlated corruption
bursts and deterministic preemption events into the existing seeded
fault families; a deterministic virtual clock (:mod:`.clock`) scales a
simulated week into CI minutes without touching any recorded value; and
the soak harness (:mod:`.harness`) drives supervisor-managed
multi-restart campaigns whose every segment lands in ONE obs stream
that ``control.replay`` re-derives bit-exactly.
"""

from federated_pytorch_test_tpu.campaign.clock import VirtualClock
from federated_pytorch_test_tpu.campaign.harness import run_soak
from federated_pytorch_test_tpu.campaign.schedule import (
    CampaignSchedule, CampaignWindow)

__all__ = ["CampaignSchedule", "CampaignWindow", "VirtualClock",
           "run_soak"]
