"""Deterministic virtual clock: accelerated time for soak campaigns.

The determinism contract (PARITY.md v0.13): the virtual clock NEVER
feeds math or recorded values — it only scales how long the process
actually waits.  Every recorded duration (the supervisor's
``backoff_seconds``, the health monitor's round-count windows) keeps
its unscaled deterministic value, so ``control.replay``'s pure-function
re-derivation is untouched; ``accel`` merely divides the wall-clock
spent sleeping, which was never recorded in a replay-checked field to
begin with.  A simulated week of diurnal load therefore runs in CI
minutes with a bit-identical stream.
"""

from __future__ import annotations

import time
from typing import Callable


class VirtualClock:
    """Scales sleeps by ``accel`` virtual seconds per wall second.

    ``sleep(virtual_seconds)`` waits ``virtual_seconds / accel`` wall
    seconds (``accel >= 1`` compresses, ``accel = 1`` is real time) and
    advances the virtual-time ledger either way.  Inject it wherever a
    component accepts a ``sleep=`` callable — the restart supervisor's
    backoff is the canonical site — and the component's recorded values
    stay byte-identical to the unaccelerated run.
    """

    def __init__(self, accel: float = 1.0,
                 sleep: Callable[[float], None] = time.sleep):
        if accel <= 0:
            raise ValueError(f"virtual-clock accel={accel} must be > 0")
        self.accel = float(accel)
        self._sleep = sleep
        self.virtual_slept = 0.0
        self.wall_slept = 0.0

    def sleep(self, seconds: float) -> None:
        """Wait ``seconds`` VIRTUAL seconds (``seconds/accel`` wall)."""
        if seconds <= 0:
            return
        wall = seconds / self.accel
        self._sleep(wall)
        self.virtual_slept += float(seconds)
        self.wall_slept += wall

    def __repr__(self) -> str:
        return (f"VirtualClock(accel={self.accel:g}, "
                f"virtual_slept={self.virtual_slept:.3f}s, "
                f"wall_slept={self.wall_slept:.3f}s)")


def selftest() -> str:
    """No real waiting: a recording fake stands in for time.sleep."""
    waits: list = []
    c = VirtualClock(accel=120.0, sleep=waits.append)
    c.sleep(60.0)
    c.sleep(0.0)
    c.sleep(6.0)
    assert waits == [0.5, 0.05], waits
    assert c.virtual_slept == 66.0 and abs(c.wall_slept - 0.55) < 1e-12
    try:
        VirtualClock(accel=0.0)
    except ValueError:
        pass
    else:
        raise AssertionError("accel=0 accepted")
    return "virtual clock selftest OK: 66.0 virtual s in 0.55 wall s"


if __name__ == "__main__":
    print(selftest())
