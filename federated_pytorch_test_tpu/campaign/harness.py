"""Soak harness: supervised multi-restart campaign runs on one stream.

:func:`run_soak` is the campaign twin of
:func:`~federated_pytorch_test_tpu.control.supervisor.supervise_classifier`:
it compiles the config's ``campaign_spec``, builds the
:class:`~federated_pytorch_test_tpu.campaign.clock.VirtualClock` from the
resolved acceleration factor, and threads the clock's ``sleep`` through
the supervisor so restart backoffs wait ``backoff / accel`` wall seconds
while the RECORDED ``backoff_seconds`` stay the unscaled seeded values —
``control.replay`` verifies the same numbers at any acceleration
(PARITY.md v0.13).

Every attempt's trainer is pinned to one ``obs_run_name`` so all
segments append to a single campaign JSONL: run headers delimit
segments, supervisor restart/reshape/ladder records land in the dying
segment, and ``obs.report`` aggregates the whole file into availability
% and rounds lost (see README "Soak campaigns").

The harness also maps the spec's ``health_window_hours`` (virtual time)
onto the engine's round-count ``health_window`` knob, so health
escalation windows track the campaign's virtual clock rather than a
round count tuned for short runs.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

from federated_pytorch_test_tpu.campaign.clock import VirtualClock
from federated_pytorch_test_tpu.campaign.schedule import CampaignSchedule

__all__ = ["resolve_accel", "soak_config", "run_soak", "selftest"]


def resolve_accel(cfg, sched: CampaignSchedule) -> float:
    """Acceleration factor: CLI knob wins, then spec, then real time.

    Acceleration is scheduling-inert by construction — it only divides
    wall-clock waits, never the virtual times or probabilities recorded
    in the stream — so any value replays identically.
    """
    accel = float(getattr(cfg, "campaign_accel", 0.0) or 0.0)
    if accel <= 0:
        accel = float(sched.accel or 0.0)
    return accel if accel > 0 else 1.0


def soak_config(cfg, sched: CampaignSchedule):
    """Config with campaign-derived knobs applied (pure, returns a copy).

    ``health_window_hours`` (virtual) becomes the engine's round-count
    ``health_window``: ``max(2, round(H * 3600 / round_seconds))``.
    Zero (the default) leaves the engine knob untouched.
    """
    if sched.health_window_hours > 0:
        rounds = max(2, round(sched.health_window_hours * 3600.0
                              / sched.round_seconds))
        cfg = dataclasses.replace(cfg, health_window=rounds)
    return cfg


def run_soak(build_trainer, cfg, checkpoint_path: str, *,
             state=None, resume: bool = False,
             run_kwargs: Optional[Dict[str, Any]] = None,
             retry_on: Tuple = (),
             log: Callable[[str], None] = print,
             engine: str = "classifier",
             run_name: str = "soak"):
    """Supervised campaign run; returns ``(result, clock)``.

    ``build_trainer(cfg, attempt)`` is the same factory
    :func:`supervise_classifier` takes; the harness pins each trainer's
    ``obs_run_name`` to ``run_name`` (unless the factory already set
    one) so every segment appends to the same campaign stream.  The
    returned :class:`VirtualClock` reports how much virtual/wall time
    the supervisor spent in backoff.
    """
    from federated_pytorch_test_tpu.control.supervisor import (
        supervise_classifier)

    sched = CampaignSchedule.parse(getattr(cfg, "campaign_spec", "none"))
    if sched is None:
        raise ValueError(
            "run_soak requires a campaign: cfg.campaign_spec is "
            f"{getattr(cfg, 'campaign_spec', 'none')!r} (use "
            "supervise_classifier directly for plain supervised runs)")
    clock = VirtualClock(accel=resolve_accel(cfg, sched))
    cfg = soak_config(cfg, sched)

    def build(c, attempt):
        trainer = build_trainer(c, attempt)
        if getattr(trainer, "obs_run_name", None) is None:
            trainer.obs_run_name = run_name
        return trainer

    result = supervise_classifier(
        build, cfg, checkpoint_path, state=state, resume=resume,
        run_kwargs=run_kwargs, retry_on=retry_on, log=log,
        sleep=clock.sleep, engine=engine)
    return result, clock


def selftest() -> str:
    """Pure checks of accel resolution and health-window derivation."""
    sched = CampaignSchedule.parse(
        "hours=48,round_minutes=30,diurnal=0.5,accel=120,"
        "health_window_hours=4")

    class _Cfg:
        campaign_accel = 0.0
        health_window = 8

    assert resolve_accel(_Cfg(), sched) == 120.0
    cfg = _Cfg()
    cfg.campaign_accel = 600.0
    assert resolve_accel(cfg, sched) == 600.0       # CLI wins
    plain = CampaignSchedule.parse("hours=2,round_minutes=30,diurnal=0.5")
    assert resolve_accel(_Cfg(), plain) == 1.0      # real time default

    # 4 virtual hours at 30-minute rounds -> 8-round health window
    @dataclasses.dataclass
    class _DCfg:
        health_window: int = 2

    assert soak_config(_DCfg(), sched).health_window == 8
    assert soak_config(_DCfg(), plain).health_window == 2  # untouched
    try:
        run_soak(None, _DCfg(), "/tmp/nope")
    except (ValueError, AttributeError):
        pass
    else:                                            # pragma: no cover
        raise AssertionError("run_soak must reject campaign-off configs")
    return ("campaign harness selftest OK: accel resolution and "
            "health-window mapping are pure")


if __name__ == "__main__":                           # pragma: no cover
    print(selftest())
