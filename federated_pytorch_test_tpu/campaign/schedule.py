"""Trace-driven campaign schedules: declarative heavy-traffic load.

A campaign spec describes a WEEK of production traffic — the diurnal
arrival curve, churn waves that follow it, straggler storms and
correlated corruption bursts that strike at seeded virtual hours, and
deterministic preemption events — and this module compiles it into the
existing seeded fault/churn families (train/faults.py).  FL_PyTorch
(arXiv:2202.03099) frames federated experiments as managed, replayable
campaigns; FedJAX (arXiv:2108.02117) shows seeded client-population
simulation is what makes that CI-feasible.  This is both, on top of the
fault machinery the chaos tests already trust.

Spec grammar (``--campaign-spec``)::

    none
    hours=H,round_minutes=M,diurnal=A,drop=P,straggle=P,corrupt=P,
    mode=M,scale=X,join=P,leave=P,storm=P,storm_len=N,storm_straggle=P,
    burst=P,burst_len=N,burst_corrupt=P,preempt_at=h1+h2,seed=N,
    accel=X,health_window_hours=H

- ``hours`` is the declared campaign length (virtual hours; default 48)
  and ``round_minutes`` maps one communication round to that many
  virtual minutes (default 30) — virtual time is ``round_index *
  round_minutes * 60`` seconds, a pure function of the round index, so
  every derived quantity survives kill/resume and mesh reshape.
- ``diurnal=A`` (amplitude in [0, 1]) shapes the arrival fraction
  ``1 - A*(0.5 + 0.5*cos(2*pi*h/24))`` — trough at virtual midnight,
  peak at noon.  Arrival feeds the DROP family: the effective per-round
  drop probability is ``1 - arrival*(1 - drop)`` (absent clients are
  non-participants, exactly the established semantics).
- ``join=/leave=`` are churn waves riding the same curve: effective
  ``join*arrival`` and ``leave*(2 - arrival)`` — departures surge in
  the trough, rejoins in the ramp.
- ``storm=P`` starts a straggler storm at each virtual hour with seeded
  probability ``P`` (tag ``73``); a storm lasts ``storm_len`` hours and
  raises the straggle probability to ``storm_straggle``.  ``burst=P``
  is the correlated-corruption twin (tag ``79``, ``burst_len``,
  ``burst_corrupt``).
- ``preempt_at=h1+h2`` schedules deterministic slice preemptions: the
  first round at or past each virtual hour raises
  :class:`~..parallel.mesh.CollectiveTimeoutError` (after the newest
  checkpoint is durable), so the restart supervisor's reshape rung
  exercises mid-campaign.
- ``accel=X`` is the virtual-clock scale (virtual seconds per wall
  second) the harness hands to :class:`~.clock.VirtualClock`.
  Scheduling-inert: nothing derived from it is recorded.
- ``health_window_hours=H`` sizes the health monitor's rolling window
  in VIRTUAL time; the harness converts it to the equivalent round
  count before the run (recorded in the header config like any knob).

Everything the schedule derives is hour-quantized (probabilities are
constant within a virtual hour) and a pure function of ``(seed,
round_index)`` — the same statelessness contract as every fault family
— so ``control.replay`` re-derives the entire campaign from the stream
header, and a resumed segment replays the identical trajectory.  Tags
``73``/``79`` keep the storm/burst draws disjoint from participation
(11), compressor (23), population (31/37/41), faults (47), delay
(53/61), churn (67), preempt (71) and backoff (0xC791) streams.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Tuple

import numpy as np

from federated_pytorch_test_tpu.train.faults import CORRUPT_MODES, FaultSpec

#: seeded-draw tags for the correlated-event families (see module
#: docstring for the full allocation table)
STORM_TAG = 73
BURST_TAG = 79

#: campaign-record field names, in emission order — shared by the
#: recorder path (rounds._emit_round_obs) and the replay verifier
#: (control/replay.check_campaign_records) so both compare the same set
CAMPAIGN_FIELDS = ("round_index", "virtual_seconds", "arrival_frac",
                   "drop_p", "straggle_p", "corrupt_p", "join_p",
                   "leave_p", "storm", "burst", "preempt_now", "phase")


@dataclasses.dataclass(frozen=True)
class CampaignWindow:
    """One round's hour-quantized slice of the campaign schedule.

    A pure function of ``(schedule, round_index)`` — every probability
    is what the derived :class:`FaultSpec` for that round carries, and
    every field lands verbatim in the stream's ``campaign`` record
    (schema v12) when the window transitions.
    """

    round_index: int
    virtual_seconds: float
    hour: int                 # virtual-hour index (quantization unit)
    arrival_frac: float
    drop_p: float
    straggle_p: float
    corrupt_p: float
    join_p: float
    leave_p: float
    storm: bool
    burst: bool
    preempt_now: bool
    phase: str                # trough|shoulder|peak, storm/burst override


@dataclasses.dataclass(frozen=True)
class CampaignSchedule:
    """Parsed ``--campaign-spec`` (see module docstring for the grammar)."""

    hours: float = 48.0
    round_minutes: float = 30.0
    diurnal: float = 0.0
    drop: float = 0.0
    straggle: float = 0.0
    corrupt: float = 0.0
    mode: str = "scale"
    scale: float = 100.0
    join: float = 0.0
    leave: float = 0.0
    storm: float = 0.0
    storm_len: int = 2
    storm_straggle: float = 0.5
    burst: float = 0.0
    burst_len: int = 1
    burst_corrupt: float = 0.5
    preempt_at: Tuple[float, ...] = ()
    seed: int = 0
    accel: float = 0.0        # 0 = harness/default decides (1.0)
    health_window_hours: float = 0.0

    @property
    def has_churn(self) -> bool:
        """Does ANY window of this campaign move the membership ledger?

        Sticky by design: the engine's churn gates (ledger meta, rejoin
        resets, v9 round fields) must not flap per-window, or a resumed
        segment checkpointed during a join=leave=0 window would lose the
        ledger.
        """
        return self.join > 0 or self.leave > 0

    @property
    def round_seconds(self) -> float:
        return self.round_minutes * 60.0

    @property
    def total_rounds(self) -> int:
        """Rounds needed to cover the declared campaign length."""
        return int(math.ceil(self.hours * 3600.0 / self.round_seconds))

    @classmethod
    def parse(cls, spec: Optional[str]) -> Optional["CampaignSchedule"]:
        """``"none"``/empty/None -> None (campaign off — the literal
        seed path); else key=value CSV, same grammar style as
        ``--fault-spec``."""
        if spec is None or spec.strip() in ("", "none"):
            return None
        kw: dict = {}
        for item in spec.split(","):
            item = item.strip()
            if not item:
                continue
            if "=" not in item:
                raise ValueError(
                    f"campaign-spec item {item!r} is not key=value "
                    "(grammar: hours=H,round_minutes=M,diurnal=A,"
                    "drop=P,...,preempt_at=h1+h2,seed=N,accel=X)")
            key, val = (s.strip() for s in item.split("=", 1))
            if key in ("drop", "straggle", "corrupt", "join", "leave",
                       "storm", "burst", "storm_straggle",
                       "burst_corrupt", "diurnal"):
                p = float(val)
                if not 0.0 <= p <= 1.0:
                    raise ValueError(
                        f"campaign-spec {key}={p} outside [0, 1]")
                kw[key] = p
            elif key in ("hours", "round_minutes", "accel",
                         "health_window_hours"):
                x = float(val)
                if x < 0 or (x <= 0 and key in ("hours", "round_minutes")):
                    raise ValueError(
                        f"campaign-spec {key}={x} must be positive")
                kw[key] = x
            elif key in ("storm_len", "burst_len"):
                n = int(val)
                if n < 1:
                    raise ValueError(
                        f"campaign-spec {key}={n} must be >= 1 hour")
                kw[key] = n
            elif key == "mode":
                if val not in CORRUPT_MODES:
                    raise ValueError(
                        f"campaign-spec mode={val!r}; expected one of "
                        f"{CORRUPT_MODES}")
                kw[key] = val
            elif key == "scale":
                kw[key] = float(val)
            elif key == "seed":
                kw[key] = int(val)
            elif key == "preempt_at":
                hs = tuple(float(s) for s in val.split("+") if s != "")
                if not hs or any(h < 0 for h in hs):
                    raise ValueError(
                        f"campaign-spec preempt_at={val!r}: need "
                        "non-negative virtual hours joined by '+'")
                kw[key] = tuple(sorted(hs))
            else:
                raise ValueError(f"unknown campaign-spec key {key!r}")
        out = cls(**kw)
        if not (out.diurnal > 0 or out.drop > 0 or out.straggle > 0
                or out.corrupt > 0 or out.has_churn or out.storm > 0
                or out.burst > 0 or out.preempt_at):
            raise ValueError(
                f"campaign-spec {spec!r} schedules no load (set diurnal/"
                "drop/straggle/corrupt/join/leave/storm/burst/preempt_at,"
                " or pass 'none')")
        return out

    # -- the pure schedule functions -----------------------------------

    def virtual_seconds(self, round_index: int) -> float:
        """Virtual time at the START of ``round_index`` — a pure
        function of the index, so resume/reshape cannot skew it."""
        return float(round_index) * self.round_seconds

    def hour_index(self, round_index: int) -> int:
        return int(self.virtual_seconds(round_index) // 3600.0)

    def arrival(self, hour: int) -> float:
        """Diurnal arrival fraction for virtual hour ``hour`` (constant
        within the hour; trough at virtual midnight, peak at noon)."""
        if self.diurnal <= 0:
            return 1.0
        frac = 0.5 + 0.5 * math.cos(2.0 * math.pi * (hour % 24) / 24.0)
        return round(1.0 - self.diurnal * frac, 6)

    def _event_active(self, hour: int, tag: int, prob: float,
                      length: int) -> bool:
        """Is a seeded correlated event (storm/burst) covering ``hour``?

        An event starts at virtual hour ``h`` iff ``rng([seed, tag, h])
        < prob`` and covers hours ``h .. h+length-1``; checking every
        candidate start keeps the answer a pure function of the hour."""
        if prob <= 0.0:
            return False
        for start in range(max(0, hour - length + 1), hour + 1):
            u = np.random.default_rng(
                [self.seed, tag, start]).random()
            if u < prob:
                return True
        return False

    def _preempt_round(self, at_hour: float) -> int:
        """First round index whose start time is >= the event hour
        (floored at 1 — a round-0 preemption would have no checkpoint
        to recover from)."""
        return max(1, int(math.ceil(at_hour * 3600.0 / self.round_seconds)))

    def preempt_rounds(self) -> Tuple[int, ...]:
        return tuple(sorted({self._preempt_round(h)
                             for h in self.preempt_at}))

    def window(self, round_index: int) -> CampaignWindow:
        """Compile the schedule at ``round_index`` — THE pure function
        everything else (engine tick, record emission, replay
        verification, tests) shares."""
        hour = self.hour_index(round_index)
        arrival = self.arrival(hour)
        storm = self._event_active(hour, STORM_TAG, self.storm,
                                   self.storm_len)
        burst = self._event_active(hour, BURST_TAG, self.burst,
                                   self.burst_len)
        drop_p = round(1.0 - arrival * (1.0 - self.drop), 6)
        straggle_p = round(max(self.straggle,
                               self.storm_straggle if storm else 0.0), 6)
        corrupt_p = round(max(self.corrupt,
                              self.burst_corrupt if burst else 0.0), 6)
        join_p = round(self.join * arrival, 6)
        leave_p = round(min(1.0, self.leave * (2.0 - arrival)), 6)
        if storm and burst:
            phase = "storm+burst"
        elif storm:
            phase = "storm"
        elif burst:
            phase = "burst"
        elif arrival >= 0.75:
            phase = "peak"
        elif arrival >= 0.4:
            phase = "shoulder"
        else:
            phase = "trough"
        return CampaignWindow(
            round_index=int(round_index),
            virtual_seconds=self.virtual_seconds(round_index),
            hour=hour, arrival_frac=arrival, drop_p=drop_p,
            straggle_p=straggle_p, corrupt_p=corrupt_p, join_p=join_p,
            leave_p=leave_p, storm=storm, burst=burst,
            preempt_now=round_index in self.preempt_rounds(),
            phase=phase)

    def spec_for(self, w: CampaignWindow,
                 base: Optional[FaultSpec] = None) -> FaultSpec:
        """The derived per-round :class:`FaultSpec` for window ``w``.

        Every probability flows into the EXISTING seeded families (tags
        47/67), so the per-client draws are the same machinery the
        chaos tests trust; ``preempt`` stays 0 — campaign preemption is
        the deterministic ``preempt_at`` event, not the Bernoulli tag-71
        family.
        """
        return dataclasses.replace(
            base if base is not None else FaultSpec(),
            drop=w.drop_p, straggle=w.straggle_p, corrupt=w.corrupt_p,
            join=w.join_p, leave=w.leave_p, mode=self.mode,
            scale=self.scale, seed=self.seed, preempt=0.0)

    def record_fields(self, w: CampaignWindow) -> dict:
        """The ``campaign`` record body (schema v12) for window ``w`` —
        deliberately NO wall-clock field: every value is a pure function
        of (spec, round_index), the replay contract."""
        return {
            "round_index": w.round_index,
            "virtual_seconds": w.virtual_seconds,
            "arrival_frac": w.arrival_frac,
            "drop_p": w.drop_p, "straggle_p": w.straggle_p,
            "corrupt_p": w.corrupt_p, "join_p": w.join_p,
            "leave_p": w.leave_p, "storm": w.storm, "burst": w.burst,
            "preempt_now": w.preempt_now, "phase": w.phase,
        }

    def expected_emissions(self, round_indices) -> list:
        """Which of a SEGMENT's round indices emit a ``campaign`` record,
        and with what fields: ``[(round_index, fields), ...]``.

        The emission rule (shared verbatim with the engine's
        ``_emit_round_obs``): the segment's first completed round, every
        virtual-hour transition, and any round whose window carries
        ``preempt_now`` (the post-resume re-run of a preempted round is
        worth a line in the timeline).  Pure function of (spec, the
        segment's round indices) — exactly what ``control.replay``
        recomputes from the stream.
        """
        out, last_hour = [], None
        for r in round_indices:
            w = self.window(int(r))
            if last_hour is None or w.hour != last_hour or w.preempt_now:
                out.append((int(r), self.record_fields(w)))
            last_hour = w.hour
        return out


def selftest() -> str:
    """Deterministic self-check of the schedule compiler (chained into
    ``report --selftest``): purity across independent parses, the
    diurnal/storm/burst/preempt algebra, and the grammar's rejections."""
    spec = ("hours=48,round_minutes=30,diurnal=0.6,leave=0.2,join=0.5,"
            "storm=0.3,storm_len=2,burst=0.25,burst_len=1,"
            "preempt_at=12+36,seed=9")
    a = CampaignSchedule.parse(spec)
    b = CampaignSchedule.parse(spec)
    assert a == b, "parse is not pure"
    rounds = range(a.total_rounds)
    wa = [a.window(r) for r in rounds]
    wb = [b.window(r) for r in reversed(rounds)]
    assert wa == list(reversed(wb)), "window() is stateful"
    assert {w.hour for w in wa} == set(range(48)), "hour coverage"
    arr = [w.arrival_frac for w in wa]
    assert min(arr) == round(1.0 - 0.6, 6) and max(arr) == 1.0, arr
    assert a.preempt_rounds() == (24, 72), a.preempt_rounds()
    assert sum(w.preempt_now for w in wa) == 2
    # derived FaultSpec: seeded families see the window probabilities
    w12 = a.window(25)
    fs = a.spec_for(w12)
    assert fs.drop == w12.drop_p and fs.seed == 9 and fs.preempt == 0.0
    # emission rule: 1 per hour + the preempt re-run rounds; resuming
    # mid-campaign replays the identical tail
    em = a.expected_emissions(list(rounds))
    tail = a.expected_emissions(list(rounds)[51:])
    assert em[26:] == tail[1:], "resume tail diverges"
    for bad in ("hours=0,diurnal=1", "diurnal=2", "storm_len=0,storm=1",
                "nonsense", "what=1", "hours=48"):
        try:
            CampaignSchedule.parse(bad)
        except ValueError:
            pass
        else:
            raise AssertionError(f"{bad!r} parsed")
    assert CampaignSchedule.parse("none") is None
    assert CampaignSchedule.parse(None) is None
    return (f"campaign schedule selftest OK: {len(wa)} windows, "
            f"{len(em)} emissions, preempts at rounds "
            f"{a.preempt_rounds()}")


if __name__ == "__main__":
    print(selftest())
