"""Lossy update-compression subsystem (see base.py for the contract)."""

from federated_pytorch_test_tpu.compress.base import (
    COMPRESS_CHOICES,
    Compressor,
    make_compressor,
    stacked_init,
)
from federated_pytorch_test_tpu.compress.error_feedback import ErrorFeedback
from federated_pytorch_test_tpu.compress.quantize import StochasticQuantizer
from federated_pytorch_test_tpu.compress.topk import TopK

__all__ = [
    "COMPRESS_CHOICES",
    "Compressor",
    "ErrorFeedback",
    "StochasticQuantizer",
    "TopK",
    "make_compressor",
    "stacked_init",
]
