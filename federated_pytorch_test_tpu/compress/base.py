"""Compressor interface for lossy federated-update communication.

The block codec (utils/codec.py) already shrinks each comm round to the
active block's flat vector — the reference's core bandwidth claim
(README.md:2).  This subsystem stacks lossy compression of the client
*update deltas* ``d_k = x_k - z`` on top: the server reconstructs
``x̂_k = z + decode(encode(d_k))`` and runs the unchanged algorithm
global update on the reconstructions, so every strategy (FedAvg /
FedProx / ADMM) is compression-agnostic.  This is the pluggable
``compressor`` stage FedJAX ships (PAPERS.md: arXiv:2108.02117).

Contract (all implementations):

- ``encode(vec, state) -> (payload, state)`` — jit/vmap-safe; ``vec`` is
  the f32 flat block vector [n]; ``payload`` is a pytree of fixed-shape
  arrays (XLA-friendly: shapes depend only on ``n``), ``state`` a
  per-client pytree (PRNG keys, residuals) threaded round to round.
- ``decode(payload, n) -> vec`` — the dense f32 [n] reconstruction.
  ``n`` is the STATIC dense size: fixed-shape payloads cannot carry it
  (a deliberate deviation from a payload-borne size; k/chunk counts are
  static for XLA anyway).
- ``init_state(n, key) -> pytree | None`` — fresh per-client state
  (``key`` is raw uint32[2] key data, the engine's convention).
- ``bytes_on_wire(n) -> int`` — exact payload bytes one client ships per
  round (matches the sum of payload leaf nbytes).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import numpy as np

#: CLI surface — drivers/common.py derives --compress choices from this
#: so the flag and the factory cannot drift.
COMPRESS_CHOICES = ("none", "q8", "q4", "topk")


class Compressor:
    """Identity compressor — the dense path.  Base class for the rest.

    Note the engine never routes ``--compress none`` through encode/decode
    at all (the dense comm round stays the literal pre-compression code,
    bit-identical); Identity exists so benches and tests can treat the
    settings uniformly.
    """

    name: str = "none"
    #: sparse payloads ({"idx","val"}) take the gather-then-scatter
    #: reduction in parallel/comm.py instead of dense decode-and-sum
    sparse: bool = False

    def init_state(self, n: int, key) -> Optional[Any]:
        return None

    def encode(self, vec, state) -> Tuple[Any, Any]:
        return vec, state

    def decode(self, payload, n: int):
        return payload

    def decode_into(self, payload, scratch):
        """Dense reconstruction accumulated into a caller-provided ZEROED
        [n] buffer.  The fused/roofline comm path (ops/packed_reduce.py,
        train/engine.py) threads a donated scratch through the comm step
        so sparse decodes reuse one HBM accumulator round after round;
        the base is zeros either way, so the result is bitwise
        ``decode(payload, n)``.  Dense compressors ignore the buffer."""
        return self.decode(payload, scratch.shape[0])

    def transport_params(self):
        """``(bits, chunk)`` when the payload is fixed-grid chunk-scaled
        integers the fused collective can re-quantize hop to hop
        (ops/packed_reduce.py pack_chunks), else ``None`` — the wire
        contract a transport needs, declared by the compressor itself so
        the fused path and the codec cannot drift."""
        return None

    def reset_state(self, state):
        """Drop any carried update memory (error-feedback residual) while
        keeping stream state (PRNG keys).  Called by the engine's update
        guards when a client is quarantined: the residual was computed
        from a rejected (possibly non-finite) delta and must not be
        applied when the client rejoins.  Stateless/memoryless
        compressors return ``state`` unchanged."""
        return state

    def bytes_on_wire(self, n: int) -> int:
        return 4 * n                       # dense f32


def make_compressor(name: str, *, topk_frac: float = 0.01,
                    quant_chunk: int = 256,
                    error_feedback: bool = False) -> Compressor:
    """Factory behind ``--compress {none,q8,q4,topk}``."""
    from federated_pytorch_test_tpu.compress.error_feedback import (
        ErrorFeedback,
    )
    from federated_pytorch_test_tpu.compress.quantize import (
        StochasticQuantizer,
    )
    from federated_pytorch_test_tpu.compress.topk import TopK

    if name not in COMPRESS_CHOICES:
        raise ValueError(
            f"unknown compressor {name!r}; expected one of {COMPRESS_CHOICES}")
    if name == "none":
        if error_feedback:
            raise ValueError(
                "error_feedback requires a lossy compressor "
                "(--compress q8/q4/topk); the dense path has no residual")
        return Compressor()
    inner = {"q8": lambda: StochasticQuantizer(bits=8, chunk=quant_chunk),
             "q4": lambda: StochasticQuantizer(bits=4, chunk=quant_chunk),
             "topk": lambda: TopK(frac=topk_frac)}[name]()
    return ErrorFeedback(inner) if error_feedback else inner


def stacked_init(comp: Compressor, K: int, n: int, seed: int):
    """Host-side [K, ...]-stacked fresh state for all clients (or None).

    Per-client PRNG streams come from splitting one seeded base key —
    deterministic, so a resumed run that re-inits (fresh block) draws the
    same stream the original did.
    """
    base = jax.random.PRNGKey(seed)
    keys = np.asarray(jax.random.key_data(jax.random.split(base, K)))
    per = [comp.init_state(n, keys[k]) for k in range(K)]
    if per[0] is None:
        return None
    return jax.tree.map(lambda *xs: np.stack([np.asarray(x) for x in xs]),
                        *per)
