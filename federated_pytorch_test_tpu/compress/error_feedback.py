"""Error-feedback wrapper: dropped mass re-enters the next round.

EF-SGD / EF21-style memory: compress ``u = vec + residual`` instead of
``vec`` and carry ``residual' = u - decode(encode(u))`` in the per-client
state (it rides in ``ClientState.comp`` next to the inner compressor's
PRNG key).  For biased compressors (top-k) this is the difference between
tracking the dense trajectory and drifting — asserted by the convergence
tests.  The wrapper IS a Compressor, so the engine and the collectives
treat ``q8`` and ``topk+ef`` identically.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax.numpy as jnp

from federated_pytorch_test_tpu.compress.base import Compressor


class ErrorFeedback(Compressor):
    def __init__(self, inner: Compressor):
        if inner.name == "none":
            raise ValueError("error feedback around the identity "
                             "compressor is a no-op; refuse loudly")
        self.inner = inner
        self.name = inner.name + "+ef"
        self.sparse = inner.sparse

    def init_state(self, n: int, key):
        return {"inner": self.inner.init_state(n, key),
                "resid": jnp.zeros((n,), jnp.float32)}

    def encode(self, vec, state) -> Tuple[Any, Any]:
        u = vec + state["resid"]
        payload, inner2 = self.inner.encode(u, state["inner"])
        resid = u - self.inner.decode(payload, u.shape[0])
        return payload, {"inner": inner2, "resid": resid}

    def decode(self, payload, n: int):
        return self.inner.decode(payload, n)

    def decode_into(self, payload, scratch):
        return self.inner.decode_into(payload, scratch)

    def transport_params(self):
        return self.inner.transport_params()

    def reset_state(self, state):
        """Quarantine policy (train/engine.py update guards): RESET the
        residual, carry the inner stream state.  The residual of a
        guard-rejected round was computed from the rejected delta — for a
        NaN/Inf corruption it IS non-finite — so applying it when the
        client rejoins would re-inject the poisoned mass the guard just
        stopped.  The inner state (quantizer PRNG position) carries no
        update mass and is kept."""
        return {"inner": self.inner.reset_state(state["inner"]),
                "resid": jnp.zeros_like(state["resid"])}

    def bytes_on_wire(self, n: int) -> int:
        return self.inner.bytes_on_wire(n)
