"""Stochastic uniform quantization to int8 / int4 with per-chunk scales.

The flat block vector is cut into ``chunk``-sized pieces; each piece is
scaled by its own max-abs so one outlier cannot wash out the resolution of
the whole block, then rounded STOCHASTICALLY — ``floor(u + uniform)`` —
which makes the quantizer unbiased: ``E[decode(encode(v))] = v``, so the
federated mean over many clients concentrates on the dense mean (QSGD-style;
the per-client PRNG key lives in the compressor state and is split every
round).  int4 payloads are nibble-packed two-per-byte.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from federated_pytorch_test_tpu.compress.base import Compressor


class StochasticQuantizer(Compressor):
    def __init__(self, bits: int = 8, chunk: int = 256):
        if bits not in (4, 8):
            raise ValueError(f"bits={bits}; int8 and int4 only")
        if chunk < 2 or chunk % 2:
            raise ValueError(f"quant chunk={chunk} must be even and >= 2 "
                             "(int4 packs value pairs)")
        self.bits = bits
        self.chunk = chunk
        self.qmax = 2 ** (bits - 1) - 1          # 127 / 7, symmetric grid
        self.name = f"q{bits}"

    # -- helpers -----------------------------------------------------------
    def _chunks(self, n: int) -> int:
        return -(-n // self.chunk)

    def init_state(self, n: int, key):
        return {"key": jnp.asarray(key, jnp.uint32)}

    def encode(self, vec, state) -> Tuple[Any, Any]:
        n = vec.shape[0]
        c = self._chunks(n)
        v = jnp.pad(vec, (0, c * self.chunk - n)).reshape(c, self.chunk)
        scale = jnp.max(jnp.abs(v), axis=1) / self.qmax
        safe = jnp.where(scale > 0, scale, 1.0)   # all-zero chunk -> q = 0
        key, sub = jax.random.split(state["key"])
        u = v / safe[:, None] + jax.random.uniform(sub, v.shape)
        q = jnp.clip(jnp.floor(u), -self.qmax, self.qmax).astype(jnp.int8)
        if self.bits == 4:
            nib = (q + 8).astype(jnp.uint8)       # [1, 15]
            q = (nib[:, 0::2] << 4) | nib[:, 1::2]
        return ({"q": q, "scale": safe.astype(jnp.float32)},
                {"key": key})

    def decode(self, payload, n: int):
        q = payload["q"]
        if self.bits == 4:
            hi = (q >> 4).astype(jnp.int8) - 8
            lo = (q & 0xF).astype(jnp.int8) - 8
            q = jnp.stack([hi, lo], axis=-1).reshape(q.shape[0], -1)
        v = q.astype(jnp.float32) * payload["scale"][:, None]
        return v.reshape(-1)[:n]

    def transport_params(self):
        # the payload grid (per-chunk max-abs scale, symmetric +/-qmax
        # integers) is exactly what the fused collective's hop codec
        # speaks — declare it (ops/packed_reduce.py pack_chunks)
        return self.bits, self.chunk

    def bytes_on_wire(self, n: int) -> int:
        c = self._chunks(n)
        return c * self.chunk * self.bits // 8 + 4 * c
