"""Top-k magnitude sparsification with fixed-shape payloads.

Keeps only the k largest-|v| coordinates of the flat block vector.  k is a
STATIC function of (frac, n), so the {"idx": i32[k], "val": f32[k]} payload
has fixed shapes and the whole round stays one compiled program — the
XLA-friendly formulation of sparse federated updates (cf. the
reduced-representation exchange of arXiv:2004.13336).

Biased (drops mass every round) — pair with the ErrorFeedback wrapper,
which re-injects the dropped residual next round; tests demonstrate plain
top-k tracking the dense trajectory measurably worse.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax.numpy as jnp

from federated_pytorch_test_tpu.compress.base import Compressor
from federated_pytorch_test_tpu.ops.topk_select import top_k_abs_indices


class TopK(Compressor):
    sparse = True
    name = "topk"

    def __init__(self, frac: float = 0.01):
        if not 0.0 < frac <= 1.0:
            raise ValueError(f"topk frac={frac} must be in (0, 1]")
        self.frac = frac

    def k_for(self, n: int) -> int:
        return max(1, min(n, int(round(self.frac * n))))

    def encode(self, vec, state) -> Tuple[Any, Any]:
        # selection dispatches through ops/topk_select: single-shot
        # lax.top_k on CPU, chunked two-stage on TPU — bitwise-identical
        # index sets by the tie-break argument documented there
        k = self.k_for(vec.shape[0])
        idx = top_k_abs_indices(vec, k)
        return {"idx": idx, "val": vec[idx]}, state

    def decode(self, payload, n: int):
        return jnp.zeros((n,), payload["val"].dtype).at[
            payload["idx"]].add(payload["val"])

    def decode_into(self, payload, scratch):
        # scatter-add into the caller's zeroed (donated) buffer: same
        # math as decode, no fresh [n] zeros materialized per round
        return scratch.at[payload["idx"]].add(payload["val"])

    def bytes_on_wire(self, n: int) -> int:
        return 8 * self.k_for(n)                 # i32 index + f32 value
