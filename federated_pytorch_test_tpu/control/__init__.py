"""Closed-loop federation control plane (default OFF).

Three cooperating parts (README "Control plane"):

- :mod:`.policy` — pure deterministic rules mapping the recorded
  telemetry stream (round records + health alerts) to typed
  interventions, emitted as ``control`` records (obs schema v8);
- :mod:`.supervisor` — bounded-retry restart with seeded exponential
  backoff and a cumulative degradation ladder;
- :mod:`.replay` — ``python -m federated_pytorch_test_tpu.control.replay``
  re-derives decisions from a recorded stream and diffs them against
  the recorded records (the determinism contract, PARITY.md).

The train/ engines import this package lazily and only when
``--control`` is not ``off`` / ``--max-restarts`` is nonzero, so the
default path never touches it.
"""

from federated_pytorch_test_tpu.control.policy import (  # noqa: F401
    COMPRESS_LADDER,
    CONTROL_MODES,
    CONTROL_POLICIES,
    Controller,
    ControlPolicy,
    ControlRestart,
    Decision,
    controller_from_config,
)
from federated_pytorch_test_tpu.control.supervisor import (  # noqa: F401
    DEGRADATION_LADDER,
    RestartBudgetExhausted,
    ladder_overrides,
    restart_backoff_seconds,
    supervise,
    supervise_classifier,
)
