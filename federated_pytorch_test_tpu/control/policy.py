"""Deterministic control policy: telemetry in, typed interventions out.

The policy engine is the decision half of the closed-loop control plane
(README "Control plane").  A :class:`ControlPolicy` consumes the SAME
record stream the obs layer writes — round records plus the
:class:`~..obs.health.HealthMonitor`'s alert records — and maps them to
typed :class:`Decision` objects:

- ``escalate_compression`` / ``deescalate_compression`` — walk the
  ``none → q8 → q4 → topk`` ladder when the comm fraction of the round
  stays above/below its thresholds (block scope: the compressor is
  baked into the compiled round fns, so the engine applies it at the
  next block boundary).
- ``relax_staleness`` / ``tighten_staleness`` — widen ``max_staleness``
  on sustained admission blowups, walk it back toward the configured
  value once admissions go quiet (round scope: the engine reads the
  knob on the host every round, so it applies live).
- ``tighten_trim`` / ``relax_trim`` — grow/shrink ``trim_frac`` under
  guard-spike pressure when the robust aggregator uses it (restart
  scope: the mean fn is baked at construction; the restart supervisor
  applies it on the next segment).
- ``shrink_cohort`` / ``grow_cohort`` — under population federation
  (``--population K``) throughput collapse first halves ``cohort_frac``
  (the fraction of sampled cohort slots active per round) down to a
  floor of 0.25, and sustained healthy throughput doubles it back
  toward the configured value (round scope: the round kernel reads the
  knob on the host every round).  Tried BEFORE ``shrink_batch`` — a
  smaller cohort is cheaper to undo than a pipeline rebuild.
- ``shrink_batch`` / ``grow_batch`` — halve/double ``default_batch``
  within declared bounds on throughput collapse/recovery vs the rolling
  median (restart scope: the data pipeline is built at construction).
- ``checkpoint_restart`` — a non-fatal non-finite-loss alert under
  ``--control act`` triggers checkpoint-then-restart through the
  supervisor (fatal alerts are ignored here: the engine aborts and the
  supervisor owns recovery).

DETERMINISM CONTRACT (PARITY.md): every decision is a pure function of
the recorded telemetry and the round index — no wall clock, no
randomness, no device values beyond what the round records already
carry.  Each intervention is hysteresis-gated (per-rule streaks + a
per-param cooldown) so decisions don't flap, and the policy advances
its *internal* view of each knob when it decides (in ``observe`` and
``act`` mode alike), so the decision sequence is identical in both
modes and ``python -m federated_pytorch_test_tpu.control.replay`` can
re-derive it bit-exactly from the JSONL stream.
"""

from __future__ import annotations

import dataclasses
import math
from collections import deque
from typing import Any, Dict, List, Optional

CONTROL_MODES = ("off", "observe", "act")

#: escalation ladder for the wire format (compress/)
COMPRESS_LADDER = ("none", "q8", "q4", "topk")

#: hysteresis presets selectable via --control-policy
CONTROL_POLICIES = ("default", "eager", "patient")
_PRESETS = {
    "default": dict(streak=3, cooldown=6),
    "eager": dict(streak=2, cooldown=3),
    "patient": dict(streak=5, cooldown=12),
}

#: intervention scopes — who can apply the decision, and when
SCOPE_ROUND = "round"      # engine, next round (host-read knob)
SCOPE_BLOCK = "block"      # engine, next block boundary (recompile)
SCOPE_RESTART = "restart"  # supervisor, next run segment (reconstruct)
SCOPE_ADVISORY = "advisory"  # nobody: recorded evidence only — by
#                              construction Controller._register never
#                              queues this scope, so client-health
#                              signals can extend the replay contract
#                              without adding interventions


class ControlRestart(RuntimeError):
    """The policy decided checkpoint-then-restart under ``--control
    act``.  Raised by the ENGINE at the round boundary (after the
    round's mid-run checkpoint is flushed and verified); the restart
    supervisor catches it and resumes.  Carries the decision record."""

    def __init__(self, decision: Dict[str, Any]):
        self.decision = dict(decision)
        super().__init__(
            f"control restart requested at round "
            f"{decision.get('round_index')}: {decision.get('reason', '')}")


def _finite(v) -> bool:
    return (isinstance(v, (int, float)) and not isinstance(v, bool)
            and math.isfinite(v))


def _cfg_get(cfg, name: str, default):
    """Read a knob off a FederatedConfig OR a run_header config dict —
    the same policy must be constructible from a live config and from
    the snapshot a recorded stream carries (control/replay.py)."""
    if isinstance(cfg, dict):
        v = cfg.get(name, default)
    else:
        v = getattr(cfg, name, default)
    return default if v is None else v


@dataclasses.dataclass(frozen=True)
class Decision:
    """One typed intervention; maps 1:1 onto a ``control`` record."""

    round_index: int
    intervention: str
    param: str
    from_value: Any
    to_value: Any
    scope: str
    reason: str
    observed: Optional[float] = None
    threshold: Optional[float] = None
    streak: Optional[int] = None

    def fields(self, *, source: str, mode: Optional[str] = None,
               applied: Optional[bool] = None) -> Dict[str, Any]:
        """The control-record body (obs/schema.py v8) for this decision."""
        f: Dict[str, Any] = {
            "round_index": int(self.round_index),
            "source": source,
            "intervention": self.intervention,
            "param": self.param,
            "from_value": self.from_value,
            "to_value": self.to_value,
            "scope": self.scope,
            "reason": self.reason,
        }
        if self.observed is not None:
            f["observed"] = float(self.observed)
        if self.threshold is not None:
            f["threshold"] = float(self.threshold)
        if self.streak is not None:
            f["streak"] = int(self.streak)
        if mode is not None:
            f["mode"] = mode
        if applied is not None:
            f["applied"] = bool(applied)
        return f

    #: the content replay compares — everything except who/how it was
    #: applied (mode/applied are engine-side facts, not decisions)
    def key(self) -> tuple:
        return (self.round_index, self.intervention, self.param,
                repr(self.from_value), repr(self.to_value), self.scope,
                self.reason, self.observed, self.threshold, self.streak)


class ControlPolicy:
    """Pure decision rules over the record stream; see module docstring.

    Thresholds derive ONLY from constructor arguments, all of which are
    recorded in the run-header config snapshot — so
    :meth:`from_config` rebuilds the identical policy from a stream.
    """

    COMM_FRAC_HI = 0.5        # comm/round fraction that forces escalation
    COMM_FRAC_LO = 0.05       # fraction that allows de-escalation
    TRIM_STEP = 0.05
    TRIM_MAX = 0.45
    STALENESS_RELAX_LIMIT = 4  # max rounds above the configured cutoff
    TPUT_OK_FRAC = 0.75       # healthy-throughput floor vs rolling median
    COHORT_FRAC_MIN = 0.25    # floor the cohort rung shrinks toward

    def __init__(self, *, preset: str = "default", compress: str = "none",
                 max_staleness: int = 4, trim_frac: float = 0.1,
                 default_batch: int = 128, robust_agg: str = "none",
                 fused_collective: bool = False, async_rounds: bool = False,
                 window: int = 8, population: int = 0,
                 cohort_frac: float = 1.0):
        if preset not in _PRESETS:
            raise ValueError(f"control policy {preset!r} not in "
                             f"{CONTROL_POLICIES}")
        if compress not in COMPRESS_LADDER:
            raise ValueError(f"compress {compress!r} not in "
                             f"{COMPRESS_LADDER}")
        self.preset = preset
        self.streak = int(_PRESETS[preset]["streak"])
        self.cooldown = int(_PRESETS[preset]["cooldown"])
        self.window = max(2, int(window))
        # starting knob values (the configured baseline the policy
        # de-escalates back toward) and declared bounds
        self._start_compress = COMPRESS_LADDER.index(compress)
        # under fused collectives the sparse rung is off the table (the
        # dense dual aggregate can't ride a sparse wire) and "none"
        # violates the fused path's packed-wire requirement
        self._max_compress = (COMPRESS_LADDER.index("q4")
                              if fused_collective
                              else len(COMPRESS_LADDER) - 1)
        self._start_staleness = int(max_staleness)
        self._start_trim = float(trim_frac)
        self._start_batch = int(default_batch)
        self._batch_min = max(8, self._start_batch // 4)
        self._trim_capable = robust_agg in ("trim", "krum")
        self._async = bool(async_rounds)
        self._pop = int(population) > 0
        self._start_frac = float(cohort_frac)
        # internal knob view: advances when a decision fires (BOTH
        # modes — see module docstring determinism note)
        self.cur_compress = self._start_compress
        self.cur_staleness = self._start_staleness
        self.cur_trim = self._start_trim
        self.cur_batch = self._start_batch
        self.cur_frac = self._start_frac
        # hysteresis state: per-rule consecutive-round counters and a
        # per-param cooldown horizon (round index the param re-arms at)
        self._streaks: Dict[str, int] = {}
        self._cooldown_until: Dict[str, int] = {}
        self._ips: deque = deque(maxlen=self.window)

    @classmethod
    def from_config(cls, cfg) -> "ControlPolicy":
        """Build from a FederatedConfig or a run_header ``config`` dict."""
        return cls(
            preset=str(_cfg_get(cfg, "control_policy", "default")),
            compress=str(_cfg_get(cfg, "compress", "none")),
            max_staleness=int(_cfg_get(cfg, "max_staleness", 4)),
            trim_frac=float(_cfg_get(cfg, "trim_frac", 0.1)),
            default_batch=int(_cfg_get(cfg, "default_batch", 128)),
            robust_agg=str(_cfg_get(cfg, "robust_agg", "none")),
            fused_collective=bool(_cfg_get(cfg, "fused_collective", False)),
            async_rounds=bool(_cfg_get(cfg, "async_rounds", False)),
            window=int(_cfg_get(cfg, "health_window", 8)),
            population=int(_cfg_get(cfg, "population", 0)),
            cohort_frac=float(_cfg_get(cfg, "cohort_frac", 1.0)),
        )

    # -- hysteresis plumbing -------------------------------------------

    def _bump(self, rule: str, bad: bool) -> int:
        n = self._streaks.get(rule, 0) + 1 if bad else 0
        self._streaks[rule] = n
        return n

    def _armed(self, param: str, ridx: int) -> bool:
        return ridx >= self._cooldown_until.get(param, -(1 << 30))

    def _decide(self, ridx: int, intervention: str, param: str,
                from_value, to_value, scope: str, reason: str, *,
                observed=None, threshold=None, streak=None
                ) -> Optional[Decision]:
        if not self._armed(param, ridx):
            return None
        self._cooldown_until[param] = ridx + self.cooldown
        return Decision(
            round_index=int(ridx), intervention=intervention, param=param,
            from_value=from_value, to_value=to_value, scope=scope,
            reason=reason,
            observed=float(observed) if _finite(observed) else None,
            threshold=float(threshold) if _finite(threshold) else None,
            streak=int(streak) if isinstance(streak, int) else None)

    # -- the rules ------------------------------------------------------

    def observe(self, rec: Dict[str, Any]) -> List[Decision]:
        """Feed one record (round or alert); returns fired decisions.

        Records MUST be fed in stream (file) order — the recorder feeds
        the controller round N before round N's alerts for exactly this
        reason (obs/recorder.py attach_control).
        """
        ev = rec.get("event", "round")
        if ev == "alert":
            return self._observe_alert(rec)
        if ev == "round":
            return self._observe_round(rec)
        if ev == "client":
            return self._observe_client(rec)
        return []

    def _observe_alert(self, alert: Dict[str, Any]) -> List[Decision]:
        # fatal alerts mean the engine is about to abort: recovery
        # belongs to the restart supervisor, not an in-run decision
        if alert.get("severity") == "fatal":
            return []
        rule = alert.get("rule")
        ridx = int(alert.get("round_index", -1))
        obs, thr = alert.get("observed"), alert.get("threshold")
        stk = alert.get("streak")
        out: List[Decision] = []
        if rule == "nonfinite_loss":
            d = self._decide(
                ridx, "checkpoint_restart", "run", None, None,
                SCOPE_RESTART,
                "non-finite loss streak: restart from the last verified "
                "checkpoint", observed=obs, threshold=thr, streak=stk)
            if d:
                out.append(d)
        elif (rule == "admission_blowup" and self._async
              and self.cur_staleness
              < self._start_staleness + self.STALENESS_RELAX_LIMIT):
            d = self._decide(
                ridx, "relax_staleness", "max_staleness",
                self.cur_staleness, self.cur_staleness + 1, SCOPE_ROUND,
                "admission controller rejecting every arrival: widen the "
                "staleness cutoff", observed=obs, threshold=thr,
                streak=stk)
            if d:
                self.cur_staleness += 1
                out.append(d)
        elif (rule == "guard_spike" and self._trim_capable
              and self.cur_trim + self.TRIM_STEP <= self.TRIM_MAX + 1e-9):
            new = round(self.cur_trim + self.TRIM_STEP, 4)
            d = self._decide(
                ridx, "tighten_trim", "trim_frac", self.cur_trim, new,
                SCOPE_RESTART,
                "guard spike: raise the trimmed-mean rejection fraction",
                observed=obs, threshold=thr, streak=stk)
            if d:
                self.cur_trim = new
                out.append(d)
        elif rule == "throughput_collapse":
            if (self._pop
                    and self.cur_frac > self.COHORT_FRAC_MIN + 1e-9):
                # population mode: the cohort rung goes first — a
                # host-read knob the kernel applies next round, far
                # cheaper to undo than a restart-scope pipeline rebuild
                new = round(max(self.COHORT_FRAC_MIN,
                                self.cur_frac / 2), 4)
                d = self._decide(
                    ridx, "shrink_cohort", "cohort_frac", self.cur_frac,
                    new, SCOPE_ROUND,
                    "throughput collapse vs rolling median: shrink the "
                    "sampled cohort before touching the minibatch",
                    observed=obs, threshold=thr, streak=stk)
                if d:
                    self.cur_frac = new
                    out.append(d)
            elif self.cur_batch > self._batch_min:
                new = max(self._batch_min, self.cur_batch // 2)
                d = self._decide(
                    ridx, "shrink_batch", "default_batch", self.cur_batch,
                    new, SCOPE_RESTART,
                    "throughput collapse vs rolling median: shrink the "
                    "minibatch", observed=obs, threshold=thr, streak=stk)
                if d:
                    self.cur_batch = new
                    out.append(d)
        elif rule == "serve_drift":
            # serving rung (serve/): live served accuracy collapsed vs
            # its EMA baseline — arm a forced weight refresh at the next
            # serve tick.  A host-read flag like cohort_frac: the kernel
            # republishes the CURRENT consensus without bumping the pure
            # weights_version sequence, so replay is untouched; a
            # serving-off run logs a skip (rounds._apply_round_control).
            d = self._decide(
                ridx, "refresh_serving", "serve_swap", None, "resync",
                SCOPE_ROUND,
                "served accuracy drifted below the EMA envelope: "
                "republish the consensus weights to the serving plane",
                observed=obs, threshold=thr, streak=stk)
            if d:
                out.append(d)
        return out

    def _observe_client(self, rec: Dict[str, Any]) -> List[Decision]:
        """Client-health evidence from a schema-v10 ``client`` record
        (obs/clients.py) — observe-only: the one rule here fires an
        SCOPE_ADVISORY decision, which ``Controller._register`` never
        queues, so client records extend the replay contract without
        adding interventions.  Same hysteresis plumbing as every other
        rule, so replay from a recorded stream reproduces the exact
        decision sequence."""
        ridx = rec.get("round_index")
        if not isinstance(ridx, int):
            return []
        norms = rec.get("update_norm")
        guard = rec.get("guard_ok")
        active = rec.get("active")
        k = rec.get("clients")
        if not isinstance(norms, list) or not isinstance(k, int):
            return []
        offenders = set()
        for i, v in enumerate(norms[:k]):
            if isinstance(v, (int, float)) and not math.isfinite(v):
                offenders.add(i)
        if isinstance(guard, list) and isinstance(active, list):
            for i, (g, a) in enumerate(zip(guard[:k], active[:k])):
                if _finite(g) and _finite(a) and a > 0 and g < 0.5:
                    offenders.add(i)
        n = self._bump("client_sick", bool(offenders))
        out: List[Decision] = []
        if n >= self.streak and offenders:
            d = self._decide(
                ridx, "flag_clients", "client_health", None,
                sorted(offenders), SCOPE_ADVISORY,
                f"per-client evidence: {len(offenders)} client(s) with "
                f"non-finite update norms or guard rejections for {n} "
                "consecutive rounds",
                observed=float(len(offenders)), threshold=0.0, streak=n)
            if d:
                out.append(d)
        return out

    def _observe_round(self, rec: Dict[str, Any]) -> List[Decision]:
        ridx = rec.get("round_index")
        if not isinstance(ridx, int):
            return []
        out: List[Decision] = []
        secs = rec.get("round_seconds")
        comm = rec.get("comm_seconds")

        # compression ladder: comm fraction of the round vs thresholds
        if _finite(secs) and secs > 0 and _finite(comm):
            frac = comm / secs
            n = self._bump("comm_hi", frac > self.COMM_FRAC_HI)
            if n >= self.streak and self.cur_compress < self._max_compress:
                d = self._decide(
                    ridx, "escalate_compression", "compress",
                    COMPRESS_LADDER[self.cur_compress],
                    COMPRESS_LADDER[self.cur_compress + 1], SCOPE_BLOCK,
                    f"comm fraction above {self.COMM_FRAC_HI} for "
                    f"{n} rounds: escalate the wire format",
                    observed=frac, threshold=self.COMM_FRAC_HI, streak=n)
                if d:
                    self.cur_compress += 1
                    out.append(d)
            m = self._bump("comm_lo", frac < self.COMM_FRAC_LO)
            if (m >= 2 * self.streak
                    and self.cur_compress > self._start_compress):
                d = self._decide(
                    ridx, "deescalate_compression", "compress",
                    COMPRESS_LADDER[self.cur_compress],
                    COMPRESS_LADDER[self.cur_compress - 1], SCOPE_BLOCK,
                    f"comm fraction below {self.COMM_FRAC_LO} for "
                    f"{m} rounds: step the wire format back toward the "
                    "configured baseline",
                    observed=frac, threshold=self.COMM_FRAC_LO, streak=m)
                if d:
                    self.cur_compress -= 1
                    out.append(d)

        # staleness walk-back: once admissions go quiet, step a relaxed
        # cutoff back toward the configured value
        if self._async and self.cur_staleness > self._start_staleness:
            rej = rec.get("admission_rejected")
            n = self._bump("staleness_quiet", _finite(rej) and rej == 0)
            if n >= 2 * self.streak:
                d = self._decide(
                    ridx, "tighten_staleness", "max_staleness",
                    self.cur_staleness, self.cur_staleness - 1,
                    SCOPE_ROUND,
                    f"no admission rejections for {n} rounds: walk the "
                    "staleness cutoff back",
                    observed=0.0, threshold=0.0, streak=n)
                if d:
                    self.cur_staleness -= 1
                    out.append(d)

        # batch walk-back: sustained healthy throughput after a shrink
        images = rec.get("images")
        ips = (images / secs if _finite(images) and _finite(secs)
               and secs > 0 and images > 0 else None)
        if ips is not None:
            if (self.cur_batch < self._start_batch
                    and len(self._ips) >= self.window):
                med = sorted(self._ips)[len(self._ips) // 2]
                n = self._bump("tput_ok",
                               ips >= self.TPUT_OK_FRAC * med)
                if n >= 2 * self.streak:
                    new = min(self._start_batch, self.cur_batch * 2)
                    d = self._decide(
                        ridx, "grow_batch", "default_batch",
                        self.cur_batch, new, SCOPE_RESTART,
                        f"throughput healthy vs rolling median for {n} "
                        "rounds: grow the minibatch back",
                        observed=ips, threshold=self.TPUT_OK_FRAC * med,
                        streak=n)
                    if d:
                        self.cur_batch = new
                        out.append(d)
            # cohort walk-back: sustained healthy throughput after a
            # shrink_cohort regrows the sampled fraction (round scope)
            if (self._pop and self.cur_frac < self._start_frac - 1e-9
                    and len(self._ips) >= self.window):
                med = sorted(self._ips)[len(self._ips) // 2]
                n = self._bump("cohort_ok",
                               ips >= self.TPUT_OK_FRAC * med)
                if n >= 2 * self.streak:
                    new = round(min(self._start_frac,
                                    self.cur_frac * 2), 4)
                    d = self._decide(
                        ridx, "grow_cohort", "cohort_frac",
                        self.cur_frac, new, SCOPE_ROUND,
                        f"throughput healthy vs rolling median for {n} "
                        "rounds: regrow the sampled cohort",
                        observed=ips, threshold=self.TPUT_OK_FRAC * med,
                        streak=n)
                    if d:
                        self.cur_frac = new
                        out.append(d)
            self._ips.append(ips)

        # trim walk-back: guards quiet after a tighten
        if self._trim_capable and self.cur_trim > self._start_trim + 1e-9:
            trips = rec.get("guard_trips")
            n = self._bump("guard_quiet", _finite(trips) and trips == 0)
            if n >= 2 * self.streak:
                new = round(max(self._start_trim,
                                self.cur_trim - self.TRIM_STEP), 4)
                d = self._decide(
                    ridx, "relax_trim", "trim_frac", self.cur_trim, new,
                    SCOPE_RESTART,
                    f"no guard trips for {n} rounds: relax the "
                    "trimmed-mean rejection fraction",
                    observed=0.0, threshold=0.0, streak=n)
                if d:
                    self.cur_trim = new
                    out.append(d)
        return out


class Controller:
    """Mode + recorder glue around a :class:`ControlPolicy`.

    Attached to a :class:`~..obs.recorder.RunRecorder` via
    ``attach_control``; the recorder feeds it every round and alert
    record in stream order.  Each fired decision is emitted as a
    ``control`` record; in ``act`` mode the applicable ones are queued
    for the engine to pick up at the round/block boundary
    (``take_round`` / ``take_block`` / ``take_restart``).

    ``observe()`` never raises — a policy failure degrades to "no
    decision" (mirroring the health monitor's contract), so the control
    plane can never kill a run it was meant to protect.
    """

    def __init__(self, policy: ControlPolicy, *, mode: str = "observe",
                 can_restart: bool = False):
        if mode not in ("observe", "act"):
            raise ValueError(f"controller mode {mode!r} must be "
                             "'observe' or 'act'")
        self.policy = policy
        self.mode = mode
        self.can_restart = bool(can_restart)
        self.recorder = None          # set by RunRecorder.attach_control
        self.decisions: List[Decision] = []
        self.records: List[Dict[str, Any]] = []
        self._pending_round: List[Decision] = []
        self._pending_block: List[Decision] = []
        self._restart: Optional[Decision] = None

    def observe(self, rec: Dict[str, Any]) -> None:
        try:
            fired = self.policy.observe(rec)
        except Exception:
            return                    # never kill the run
        for d in fired:
            self._register(d)

    def _register(self, d: Decision) -> None:
        applied = False
        if self.mode == "act":
            if d.scope == SCOPE_ROUND:
                self._pending_round.append(d)
                applied = True
            elif d.scope == SCOPE_BLOCK:
                self._pending_block.append(d)
                applied = True
            elif d.intervention == "checkpoint_restart":
                if self.can_restart and self._restart is None:
                    self._restart = d
                    applied = True
            # other restart-scope decisions are recorded for the
            # supervisor / operator; nothing to apply in-run
        self.decisions.append(d)
        body = d.fields(source="policy", mode=self.mode, applied=applied)
        self.records.append(body)
        if self.recorder is not None:
            try:
                self.recorder.control_event(body)
            except Exception:
                pass                  # a sink failure must not kill the run

    def take_round(self) -> List[Decision]:
        """Drain act-mode round-scope decisions (apply before next round)."""
        out, self._pending_round = self._pending_round, []
        return out

    def take_block(self) -> List[Decision]:
        """Drain act-mode block-scope decisions (apply at block boundary)."""
        out, self._pending_block = self._pending_block, []
        return out

    def take_restart(self) -> Optional[Decision]:
        """Pop the act-mode checkpoint-then-restart decision, if any."""
        d, self._restart = self._restart, None
        return d


def controller_from_config(cfg, recorder=None) -> Optional[Controller]:
    """Build a Controller from a FederatedConfig-like object.

    Returns None when ``control == "off"`` (nothing is attached — the
    obs stream and the training math stay exactly as before, the same
    contract as ``monitor_from_config``).
    """
    mode = _cfg_get(cfg, "control", "off")
    if mode not in CONTROL_MODES:
        raise ValueError(f"control={mode!r} must be one of {CONTROL_MODES}")
    if mode == "off":
        return None
    ctl = Controller(ControlPolicy.from_config(cfg), mode=mode)
    if recorder is not None:
        recorder.attach_control(ctl)
    return ctl
