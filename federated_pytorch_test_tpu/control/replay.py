"""Replay CLI: re-derive control decisions from a recorded stream.

``python -m federated_pytorch_test_tpu.control.replay run.jsonl`` reads
an obs JSONL artifact, rebuilds the :class:`~.policy.ControlPolicy`
from each segment's run-header ``config`` snapshot, feeds the segment's
round and alert records through it IN FILE ORDER, and diffs the derived
decision sequence against the recorded ``control`` records.  Supervisor
records are checked too: the seeded backoff of every ``restart`` record
is recomputed from (``restart_backoff``, ``seed``, ``attempt``) and the
attempt numbers must count up from 1.  Under population federation the
recorded cohorts are part of the contract: every ``client`` record's
``registry_ids`` must re-derive from the seeded sampler given only the
header config and the round's loop coordinates
(:func:`check_cohort_records`).

Exit 0 when every recorded decision is reproduced bit-exactly; exit 1
(with a diff) on any divergence — the determinism contract of the
control plane (PARITY.md).  This works because decisions are pure
functions of the recorded telemetry + round index: no wall clock, no
randomness, no device state outside the stream.
"""

from __future__ import annotations

import argparse
import sys
from typing import Any, Dict, List, Optional, Tuple

from federated_pytorch_test_tpu.control.policy import (
    Controller, ControlPolicy)

#: decision-content fields replay compares (mode/applied are engine-side
#: facts — whether the knob was actually turned — not decision content)
_COMPARE_FIELDS = ("round_index", "intervention", "param", "from_value",
                   "to_value", "scope", "reason", "observed", "threshold",
                   "streak")

# ------------------------------------------------------------------- #
# Machine-readable replay-coverage contract (graftcheck JG118).
#
# The contract pass reads these tables via ast.literal_eval — pure
# literals only.  Every record kind the recorder can emit must either
# map to its check_* functions here (re-derived bit-exactly by replay)
# or be declared exempt below.  JG118 flags an emitted kind covered by
# neither, and flags a listed checker name with no matching function in
# this module (the "deleted check_*" regression).

#: replay-checked record kind -> the check_* functions that re-derive it
REPLAY_CHECKERS = {
    "control": ("check_policy_records", "check_supervisor_records",
                "check_reshape_records"),
    "client": ("check_cohort_records",),
    "campaign": ("check_campaign_records",),
    "serve": ("check_serve_records",),
}

#: kinds deliberately outside the bit-exact replay contract: envelope /
#: timing streams (run_header, round, summary, span, compile) and the
#: watchdog's threshold verdicts (alert) — their pure subsets are
#: covered indirectly by the golden-digest and health tests instead
REPLAY_EXEMPT_KINDS = ("run_header", "round", "summary", "span", "alert",
                       "compile")


def _decision_key(rec: Dict[str, Any]) -> Tuple:
    return tuple(rec.get(k) for k in _COMPARE_FIELDS)


def _fmt(rec: Dict[str, Any]) -> str:
    return ", ".join(f"{k}={rec.get(k)!r}" for k in _COMPARE_FIELDS
                     if rec.get(k) is not None)


def segment_stream(records: List[Dict[str, Any]]
                   ) -> List[List[Dict[str, Any]]]:
    """Split a (possibly multi-segment) stream at run_header records.

    Supervisor records appended after a dead segment's summary belong to
    that segment (they are written between the summary and the next
    header), which this split preserves.  Records before the first
    header (none in practice) form a headerless leading segment.
    """
    segments: List[List[Dict[str, Any]]] = []
    cur: List[Dict[str, Any]] = []
    for rec in records:
        if rec.get("event") == "run_header" and cur:
            segments.append(cur)
            cur = []
        cur.append(rec)
    if cur:
        segments.append(cur)
    return segments


def derive_segment_decisions(segment: List[Dict[str, Any]]
                             ) -> Optional[List[Dict[str, Any]]]:
    """Policy decisions this segment's telemetry implies, in order.

    Returns None when the segment ran with ``control == "off"`` (or
    predates the control plane): no policy existed, so no decisions can
    be derived — any recorded policy record in such a segment is a
    divergence the caller reports.
    """
    header = next((r for r in segment
                   if r.get("event") == "run_header"), None)
    config = (header or {}).get("config")
    if not isinstance(config, dict):
        return None
    mode = config.get("control", "off")
    if mode not in ("observe", "act"):
        return None
    # Controller (not bare policy) so exception-swallowing matches the
    # in-run path exactly; no recorder attached — we only want .records
    ctl = Controller(ControlPolicy.from_config(config), mode=mode,
                     can_restart=True)
    for rec in segment:
        # client records are policy input too (schema v10 advisory
        # client-health rule) — file order IS the in-process feed order
        if rec.get("event") in ("round", "alert", "client"):
            ctl.observe(rec)
    return ctl.records


def check_policy_records(segments: List[List[Dict[str, Any]]],
                         errors: List[str]) -> int:
    """Diff derived vs recorded policy decisions per segment."""
    checked = 0
    for si, segment in enumerate(segments):
        recorded = [r for r in segment if r.get("event") == "control"
                    and r.get("source") == "policy"]
        derived = derive_segment_decisions(segment)
        if derived is None:
            if recorded:
                errors.append(
                    f"segment {si}: {len(recorded)} policy control "
                    "record(s) but the header config has control off "
                    "(or no config snapshot) — cannot have been "
                    "produced by this configuration")
            continue
        checked += len(recorded)
        for i in range(max(len(derived), len(recorded))):
            if i >= len(derived):
                errors.append(
                    f"segment {si} decision {i}: recorded but NOT "
                    f"derivable from telemetry: {_fmt(recorded[i])}")
                continue
            if i >= len(recorded):
                errors.append(
                    f"segment {si} decision {i}: derived from telemetry "
                    f"but missing from the stream: {_fmt(derived[i])}")
                continue
            if _decision_key(derived[i]) != _decision_key(recorded[i]):
                errors.append(
                    f"segment {si} decision {i} diverges:\n"
                    f"    recorded: {_fmt(recorded[i])}\n"
                    f"    derived:  {_fmt(derived[i])}")
    return checked


def check_supervisor_records(records: List[Dict[str, Any]],
                             errors: List[str]) -> int:
    """Verify restart attempt numbering and recomputed seeded backoff."""
    header = next((r for r in records
                   if r.get("event") == "run_header"), None)
    config = (header or {}).get("config")
    sup = [r for r in records if r.get("event") == "control"
           and r.get("source") == "supervisor"]
    restarts = [r for r in sup if r.get("intervention") == "restart"]
    for i, rec in enumerate(restarts):
        if rec.get("attempt") != i + 1:
            errors.append(
                f"supervisor restart {i}: attempt={rec.get('attempt')!r}"
                f" but restarts must count up from 1 (expected {i + 1})")
    if isinstance(config, dict):
        # ladder never overrides restart_backoff/seed, so the FIRST
        # header's values govern every segment's backoff
        from federated_pytorch_test_tpu.control.supervisor import (
            restart_backoff_seconds)
        base = config.get("restart_backoff")
        seed = config.get("seed")
        if isinstance(base, (int, float)) and isinstance(seed, int):
            for rec in restarts:
                attempt = rec.get("attempt")
                got = rec.get("backoff_seconds")
                if not isinstance(attempt, int):
                    continue
                want = restart_backoff_seconds(float(base), seed, attempt)
                if got != want:
                    errors.append(
                        f"supervisor restart attempt {attempt}: recorded "
                        f"backoff_seconds={got!r} but the seeded formula "
                        f"gives {want!r} (base={base}, seed={seed})")
    return len(sup)


def _segment_mesh(segment: List[Dict[str, Any]]) -> Optional[int]:
    header = next((r for r in segment
                   if r.get("event") == "run_header"), None)
    mesh = (header or {}).get("mesh_shape")
    if isinstance(mesh, dict) and isinstance(mesh.get("clients"), int):
        return mesh["clients"]
    return None


def check_reshape_records(segments: List[List[Dict[str, Any]]],
                          errors: List[str]) -> int:
    """Verify supervisor ``reshape`` records against the mesh headers.

    The elastic-federation contract: every mesh-size change between
    consecutive segments must be announced by EXACTLY ONE ``reshape``
    control record in the dying segment, whose ``from_value`` is that
    segment's header mesh and ``to_value`` the next segment's — a
    dropped or tampered record is a replay divergence (exit 1), like
    any other decision.  A reshape record in the final segment (no
    successor header to check against) is left unverified: the
    resumed process may simply have been killed before its header.
    """
    checked = 0
    for si, segment in enumerate(segments):
        reshapes = [r for r in segment if r.get("event") == "control"
                    and r.get("source") == "supervisor"
                    and r.get("intervention") == "reshape"]
        checked += len(reshapes)
        d_here = _segment_mesh(segment)
        d_next = (_segment_mesh(segments[si + 1])
                  if si + 1 < len(segments) else None)
        if d_here is None or d_next is None:
            continue
        if d_here != d_next:
            if not reshapes:
                errors.append(
                    f"segment {si}: mesh reshaped {d_here} -> {d_next} "
                    "devices with NO reshape control record in the dying "
                    "segment (record dropped?)")
                continue
            if len(reshapes) > 1:
                errors.append(
                    f"segment {si}: {len(reshapes)} reshape records for "
                    "one mesh change (expected exactly one)")
            rec = reshapes[0]
            if (rec.get("from_value") != d_here
                    or rec.get("to_value") != d_next):
                errors.append(
                    f"segment {si}: reshape record says "
                    f"{rec.get('from_value')!r} -> {rec.get('to_value')!r}"
                    f" but the run headers say {d_here} -> {d_next} "
                    "(record tampered?)")
        elif reshapes:
            errors.append(
                f"segment {si}: reshape record(s) present but the next "
                f"segment resumed on the SAME {d_here}-device mesh "
                "(record forged?)")
    return checked


def check_cohort_records(segments: List[List[Dict[str, Any]]],
                         errors: List[str]) -> int:
    """Verify recorded population cohorts against the seeded sampler.

    Population mode: every ``client`` record's ``registry_ids`` must
    equal ``population.sampler.sample_cohort`` recomputed from the
    header config (``seed``/``population``/``K``/``cohort_sampling``)
    and the matching round record's loop coordinates.  The cohort draw
    is stateless and frac-free (the control plane's cohort rung masks
    slots, it never perturbs WHICH ids were drawn), so the whole
    sequence re-derives from the header alone — across kill/resume and
    mesh-reshape segment boundaries exactly like policy decisions.
    """
    from federated_pytorch_test_tpu.population.sampler import sample_cohort

    checked = 0
    for si, segment in enumerate(segments):
        header = next((r for r in segment
                       if r.get("event") == "run_header"), None)
        config = (header or {}).get("config")
        crecs = [r for r in segment if r.get("event") == "client"
                 and isinstance(r.get("registry_ids"), list)]
        if not crecs:
            continue
        pop = (config or {}).get("population") if isinstance(config, dict) \
            else None
        if not isinstance(pop, int) or pop <= 0:
            errors.append(
                f"segment {si}: client record(s) carry registry_ids but "
                "the header config has population off (or no config "
                "snapshot) — cannot have been produced by this "
                "configuration")
            continue
        K = int(config.get("K", 0))
        seed = int(config.get("seed", 0))
        method = str(config.get("cohort_sampling", "uniform"))
        coords: Dict[int, Tuple] = {}
        for r in segment:
            if (r.get("event") == "round"
                    and isinstance(r.get("round_index"), int)):
                coords.setdefault(
                    r["round_index"],
                    (r.get("nloop"), r.get("block"), r.get("nadmm")))
        for rec in crecs:
            ridx = rec.get("round_index")
            c = coords.get(ridx)
            if c is None or not all(isinstance(v, int) for v in c):
                errors.append(
                    f"segment {si} round {ridx}: client record carries "
                    "registry_ids but no round record supplies the loop "
                    "coordinates to recompute the draw")
                continue
            checked += 1
            want = sample_cohort(pop, K, seed=seed, nloop=c[0], ci=c[1],
                                 nadmm=c[2], method=method).tolist()
            got = [int(v) for v in rec["registry_ids"]]
            if got != want:
                errors.append(
                    f"segment {si} round {ridx}: recorded cohort "
                    f"{got[:8]}{'...' if len(got) > 8 else ''} diverges "
                    f"from the seeded draw "
                    f"{want[:8]}{'...' if len(want) > 8 else ''} "
                    f"(seed={seed}, population={pop}, method={method})")
    return checked


def check_campaign_records(segments: List[List[Dict[str, Any]]],
                           errors: List[str]) -> int:
    """Verify recorded campaign windows against the compiled schedule.

    Soak campaigns (PARITY.md v0.13): every ``campaign`` record is a
    pure function of (header ``campaign_spec``, the round indices this
    segment completed) — the schedule compiler is stateless, so the
    exact emission sequence (first round of the segment, every
    virtual-hour boundary, every deterministic-preemption window)
    re-derives from the header alone and must match the stream
    field-by-field, bit-exactly.  A campaign record in a segment whose
    header has no campaign is a forgery, exactly like cohorts.
    """
    from federated_pytorch_test_tpu.campaign.schedule import (
        CAMPAIGN_FIELDS, CampaignSchedule)

    checked = 0
    for si, segment in enumerate(segments):
        header = next((r for r in segment
                       if r.get("event") == "run_header"), None)
        config = (header or {}).get("config")
        crecs = [r for r in segment if r.get("event") == "campaign"]
        spec = (config or {}).get("campaign_spec") \
            if isinstance(config, dict) else None
        try:
            sched = CampaignSchedule.parse(spec)
        except ValueError as e:
            errors.append(f"segment {si}: unparseable campaign_spec "
                          f"{spec!r} in the header config: {e}")
            continue
        if sched is None:
            if crecs:
                errors.append(
                    f"segment {si}: {len(crecs)} campaign record(s) but "
                    "the header config has no campaign (or no config "
                    "snapshot) — cannot have been produced by this "
                    "configuration")
            continue
        rounds = [r["round_index"] for r in segment
                  if r.get("event") == "round"
                  and isinstance(r.get("round_index"), int)]
        expected = sched.expected_emissions(rounds)
        checked += len(crecs)
        for i in range(max(len(expected), len(crecs))):
            if i >= len(expected):
                errors.append(
                    f"segment {si} campaign record {i}: recorded but NOT "
                    "derivable from the schedule (round_index="
                    f"{crecs[i].get('round_index')!r})")
                continue
            ridx, fields = expected[i]
            if i >= len(crecs):
                errors.append(
                    f"segment {si} campaign record {i}: derived from the "
                    f"schedule (round {ridx}) but missing from the stream")
                continue
            got = {k: crecs[i].get(k) for k in CAMPAIGN_FIELDS}
            if got != fields:
                diff = ", ".join(
                    f"{k}: recorded {got[k]!r} != derived {fields[k]!r}"
                    for k in CAMPAIGN_FIELDS if got[k] != fields[k])
                errors.append(
                    f"segment {si} campaign record {i} (round {ridx}) "
                    f"diverges: {diff}")
    return checked


def check_serve_records(segments: List[List[Dict[str, Any]]],
                        errors: List[str]) -> int:
    """Verify recorded serving rounds against the serve schedule.

    Serving plane (PARITY.md v0.14): every round a serving segment
    completes emits exactly one ``serve`` record whose PURE fields —
    ``weights_version`` (= 1 + round // swap_every), the tag-83
    ``requests`` draw, the batch plan (``batches``/``padded_slots``/
    ``padding_waste_frac``), ``swap`` and ``drift_injected`` — are
    functions of (header ``serve_spec``, round_index) alone, so the
    whole sequence re-derives from the header and must match the stream
    field-by-field, bit-exactly.  Latency/QPS/swap-gap/accuracy fields
    are advisory wall-clock telemetry and are NOT compared.  A serve
    record in a serving-off segment is a forgery, exactly like cohorts
    and campaign windows.
    """
    from federated_pytorch_test_tpu.serve.batcher import (
        SERVE_FIELDS, ServeSchedule)

    checked = 0
    for si, segment in enumerate(segments):
        header = next((r for r in segment
                       if r.get("event") == "run_header"), None)
        config = (header or {}).get("config")
        srecs = [r for r in segment if r.get("event") == "serve"]
        spec = (config or {}).get("serve_spec") \
            if isinstance(config, dict) else None
        try:
            sched = ServeSchedule.parse(spec)
        except ValueError as e:
            errors.append(f"segment {si}: unparseable serve_spec "
                          f"{spec!r} in the header config: {e}")
            continue
        if sched is None:
            if srecs:
                errors.append(
                    f"segment {si}: {len(srecs)} serve record(s) but "
                    "the header config has serving off (or no config "
                    "snapshot) — cannot have been produced by this "
                    "configuration")
            continue
        rounds = [r["round_index"] for r in segment
                  if r.get("event") == "round"
                  and isinstance(r.get("round_index"), int)]
        expected = sched.expected_records(rounds)
        checked += len(srecs)
        for i in range(max(len(expected), len(srecs))):
            if i >= len(expected):
                errors.append(
                    f"segment {si} serve record {i}: recorded but NOT "
                    "derivable from the schedule (round_index="
                    f"{srecs[i].get('round_index')!r})")
                continue
            ridx, fields = expected[i]
            if i >= len(srecs):
                errors.append(
                    f"segment {si} serve record {i}: derived from the "
                    f"schedule (round {ridx}) but missing from the stream")
                continue
            got = {k: srecs[i].get(k) for k in SERVE_FIELDS}
            if got != fields:
                diff = ", ".join(
                    f"{k}: recorded {got[k]!r} != derived {fields[k]!r}"
                    for k in SERVE_FIELDS if got[k] != fields[k])
                errors.append(
                    f"segment {si} serve record {i} (round {ridx}) "
                    f"diverges: {diff}")
    return checked


def replay(records: List[Dict[str, Any]]) -> Tuple[List[str], Dict[str, int]]:
    """Full replay check; returns (errors, stats)."""
    errors: List[str] = []
    segments = segment_stream(records)
    n_policy = check_policy_records(segments, errors)
    n_sup = check_supervisor_records(records, errors)
    n_reshape = check_reshape_records(segments, errors)
    n_cohort = check_cohort_records(segments, errors)
    n_campaign = check_campaign_records(segments, errors)
    n_serve = check_serve_records(segments, errors)
    return errors, {"segments": len(segments), "policy_records": n_policy,
                    "supervisor_records": n_sup,
                    "reshape_records": n_reshape,
                    "cohort_records": n_cohort,
                    "campaign_records": n_campaign,
                    "serve_records": n_serve}


def selftest() -> str:
    """Synthesize a stream through the REAL recorder+controller pipeline,
    then assert replay reproduces it (exit 0) and detects tampering
    (exit 1) — chained into the tier-1 ``report --selftest`` flow."""
    import json
    import os
    import tempfile

    from federated_pytorch_test_tpu.control.policy import (
        controller_from_config)
    from federated_pytorch_test_tpu.obs.recorder import make_recorder
    from federated_pytorch_test_tpu.obs.report import read_records

    config = {"K": 2, "control": "observe", "control_policy": "eager",
              "compress": "none", "max_staleness": 4, "trim_frac": 0.1,
              "default_batch": 128, "robust_agg": "none",
              "fused_collective": False, "async_rounds": False,
              "health_window": 8, "seed": 0, "restart_backoff": 1.0}

    def synth(d: str, rounds, mesh: Optional[int] = None,
              name: str = "ctl-selftest") -> str:
        rec = make_recorder("jsonl", d, run_name=name,
                            engine="selftest", algorithm="fedavg")
        controller_from_config(config, recorder=rec)
        rec.open(config=config,
                 mesh_shape=None if mesh is None else {"clients": mesh})
        for i, comm in enumerate(rounds):
            rec.round({"round_index": i, "nloop": 0, "block": 0,
                       "nadmm": i, "N": 10, "loss": 1.0, "rho": 1.0,
                       "round_seconds": 1.0, "comm_seconds": comm,
                       "images": 256})
        rec.close()
        return os.path.join(d, f"{name}.jsonl")

    with tempfile.TemporaryDirectory() as d:
        # comm fraction 0.8 for 2 rounds trips the eager preset's
        # escalation streak — exactly one decision fires
        path = synth(d, [0.8, 0.8, 0.1, 0.1])
        records = read_records(path)
        ctl_recs = [r for r in records if r.get("event") == "control"]
        assert len(ctl_recs) == 1, ctl_recs
        assert ctl_recs[0]["intervention"] == "escalate_compression", \
            ctl_recs
        assert ctl_recs[0]["to_value"] == "q8", ctl_recs
        assert "time_unix" not in ctl_recs[0], \
            "control records must not carry wall-clock time"
        errors, stats = replay(records)
        assert not errors, errors
        assert stats["policy_records"] == 1, stats

        # healthy stream: zero decisions, replay still passes
        d2 = os.path.join(d, "healthy")
        os.makedirs(d2, exist_ok=True)
        errors2, _ = replay(read_records(synth(d2, [0.1, 0.1, 0.1])))
        assert not errors2, errors2

        # tampering: flip the decision's to_value -> divergence
        tampered = []
        for r in records:
            r = dict(r)
            if r.get("event") == "control":
                r["to_value"] = "topk"
            tampered.append(r)
        errors3, _ = replay(tampered)
        assert errors3 and "diverges" in errors3[0], errors3

        # tampering: drop the record entirely -> "missing from stream"
        dropped = [r for r in records if r.get("event") != "control"]
        errors4, _ = replay(dropped)
        assert errors4 and "missing from the stream" in errors4[0], \
            errors4

        # supervisor backoff verification catches a forged value
        from federated_pytorch_test_tpu.control.supervisor import (
            restart_backoff_seconds)
        from federated_pytorch_test_tpu.obs.schema import SCHEMA_VERSION
        good = restart_backoff_seconds(1.0, 0, 1)
        sup = {"event": "control", "schema": SCHEMA_VERSION,
               "run_id": "x", "round_index": 3, "source": "supervisor",
               "mode": "act", "applied": True, "intervention": "restart",
               "param": "run", "attempt": 1, "backoff_seconds": good,
               "reason": "selftest"}
        errors5, _ = replay(records + [sup])
        assert not errors5, errors5
        errors6, _ = replay(records
                            + [dict(sup, backoff_seconds=good + 1.0)])
        assert errors6 and "seeded formula" in errors6[0], errors6

        # elastic reshape verification: a two-segment stream whose mesh
        # shrinks 8 -> 4 with the matching reshape record replays clean;
        # tampering the record or dropping it is a divergence
        d3 = os.path.join(d, "reshape")
        os.makedirs(d3, exist_ok=True)
        seg_a = read_records(synth(d3, [0.1, 0.1], mesh=8, name="seg-a"))
        seg_b = read_records(synth(d3, [0.1], mesh=4, name="seg-b"))
        reshape = {"event": "control", "schema": SCHEMA_VERSION,
                   "run_id": "x", "round_index": 1,
                   "source": "supervisor", "mode": "act", "applied": True,
                   "intervention": "reshape", "param": "num_devices",
                   "from_value": 8, "to_value": 4, "scope": "restart",
                   "attempt": 1, "reason": "selftest preemption"}
        elastic = seg_a + [sup, reshape] + seg_b
        errors7, stats7 = replay(elastic)
        assert not errors7, errors7
        assert stats7["reshape_records"] == 1, stats7
        errors8, _ = replay(
            [dict(r, to_value=3) if r.get("intervention") == "reshape"
             else r for r in elastic])
        assert errors8 and "tampered" in errors8[0], errors8
        errors9, _ = replay(
            [r for r in elastic if r.get("intervention") != "reshape"])
        assert errors9 and "dropped" in errors9[0], errors9

        # population cohorts: registry_ids re-derive from the seeded
        # sampler; a tampered id list is a divergence
        from federated_pytorch_test_tpu.population.sampler import (
            sample_cohort)
        d5 = os.path.join(d, "pop")
        os.makedirs(d5, exist_ok=True)
        base = read_records(synth(d5, [0.1, 0.1], name="pop"))
        popped = [dict(r, config=dict(config, population=16))
                  if r.get("event") == "run_header" else r for r in base]
        clients = []
        for r in base:
            if r.get("event") == "round":
                ids = sample_cohort(16, 2, seed=0, nloop=r["nloop"],
                                    ci=r["block"], nadmm=r["nadmm"],
                                    method="uniform")
                clients.append({"event": "client",
                                "schema": SCHEMA_VERSION, "run_id": "x",
                                "round_index": r["round_index"],
                                "clients": 2,
                                "registry_ids": ids.tolist()})
        errors10, stats10 = replay(popped + clients)
        assert not errors10, errors10
        assert stats10["cohort_records"] == 2, stats10
        bad = [dict(c) for c in clients]
        bad[0]["registry_ids"] = [(v + 1) % 16
                                  for v in bad[0]["registry_ids"]]
        errors11, _ = replay(popped + bad)
        assert errors11 and "seeded draw" in errors11[0], errors11
        # registry_ids on a population-off stream is itself a divergence
        errors12, _ = replay(base + clients)
        assert errors12 and "population off" in errors12[0], errors12

        # campaign windows: records re-derive from the header's
        # campaign_spec + completed round indices; tampering a window
        # field, dropping an emission, or forging a record on a
        # campaign-off stream all diverge
        from federated_pytorch_test_tpu.campaign.schedule import (
            CampaignSchedule)
        spec = "hours=3,round_minutes=30,diurnal=0.5,drop=0.2,seed=9"
        sched = CampaignSchedule.parse(spec)
        d6 = os.path.join(d, "campaign")
        os.makedirs(d6, exist_ok=True)
        camp_base = read_records(
            synth(d6, [0.1] * sched.total_rounds, name="campaign"))
        camped = [dict(r, config=dict(config, campaign_spec=spec))
                  if r.get("event") == "run_header" else r
                  for r in camp_base]
        camp_recs = [dict({"event": "campaign",
                           "schema": SCHEMA_VERSION, "run_id": "x"},
                          **fields)
                     for _, fields in sched.expected_emissions(
                         range(sched.total_rounds))]
        errors13, stats13 = replay(camped + camp_recs)
        assert not errors13, errors13
        assert stats13["campaign_records"] == len(camp_recs) >= 3, stats13
        bad_camp = [dict(c) for c in camp_recs]
        bad_camp[1]["drop_p"] = round(bad_camp[1]["drop_p"] + 0.01, 6)
        errors14, _ = replay(camped + bad_camp)
        assert errors14 and "diverges" in errors14[0], errors14
        errors15, _ = replay(camped + camp_recs[:-1])
        assert errors15 and "missing from the stream" in errors15[0], \
            errors15
        # campaign record on a campaign-off stream is a forgery
        errors16, _ = replay(camp_base + camp_recs[:1])
        assert errors16 and "no campaign" in errors16[0], errors16

        # serve records: the pure fields re-derive from the header's
        # serve_spec + completed rounds; tampering the version, dropping
        # a round, or forging a record on a serving-off stream diverge
        from federated_pytorch_test_tpu.serve.batcher import ServeSchedule
        sspec = "qps=16,round_minutes=0.5,swap_every=2,seed=5"
        ssched = ServeSchedule.parse(sspec)
        d7 = os.path.join(d, "serve")
        os.makedirs(d7, exist_ok=True)
        serve_base = read_records(synth(d7, [0.1] * 4, name="serve"))
        served = [dict(r, config=dict(config, serve_spec=sspec))
                  if r.get("event") == "run_header" else r
                  for r in serve_base]
        serve_recs = [dict({"event": "serve", "schema": SCHEMA_VERSION,
                            "run_id": "x", "serve_qps": 123.4}, **fields)
                      for _, fields in ssched.expected_records(range(4))]
        errors17, stats17 = replay(served + serve_recs)
        assert not errors17, errors17
        assert stats17["serve_records"] == 4, stats17
        bad_serve = [dict(c) for c in serve_recs]
        bad_serve[2]["weights_version"] += 1
        errors18, _ = replay(served + bad_serve)
        assert errors18 and "diverges" in errors18[0], errors18
        errors19, _ = replay(served + serve_recs[:-1])
        assert errors19 and "missing from the stream" in errors19[0], \
            errors19
        errors20, _ = replay(serve_base + serve_recs[:1])
        assert errors20 and "serving off" in errors20[0], errors20
        json.dumps(stats)  # stats stay JSON-representable
    return "control replay selftest: OK (decisions reproduce; tampering detected)"


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m federated_pytorch_test_tpu.control.replay",
        description="Re-derive control decisions from a recorded obs "
                    "JSONL and diff against the recorded control "
                    "records (see README 'Control plane')")
    p.add_argument("path", nargs="?", help="run JSONL file")
    p.add_argument("--selftest", action="store_true",
                   help="run the built-in replay selftest and exit")
    args = p.parse_args(argv)
    if args.selftest:
        print(selftest())
        return 0
    if not args.path:
        p.error("a run JSONL path is required (or --selftest)")
    from federated_pytorch_test_tpu.obs.report import read_records
    from federated_pytorch_test_tpu.obs.schema import SchemaError
    try:
        records = read_records(args.path)
    except (OSError, SchemaError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    errors, stats = replay(records)
    if errors:
        print(f"REPLAY DIVERGED ({len(errors)} problem(s)) over "
              f"{stats['segments']} segment(s):")
        for e in errors:
            print(f"  - {e}")
        return 1
    print(f"replay OK: {stats['policy_records']} policy decision(s), "
          f"{stats['supervisor_records']} supervisor record(s), "
          f"{stats['reshape_records']} reshape record(s), "
          f"{stats['cohort_records']} cohort record(s), "
          f"{stats['campaign_records']} campaign record(s) and "
          f"{stats['serve_records']} serve record(s) reproduce "
          f"across {stats['segments']} segment(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
