"""Restart supervisor: bounded retry, seeded backoff, degradation ladder.

The supervisor is the recovery half of the control plane.  It wraps an
engine run so a :class:`~..obs.health.RunHealthAbort` (or a policy
:class:`~.policy.ControlRestart`, or an injected crash the caller opts
into via ``retry_on``) triggers resume-from-verified-checkpoint instead
of killing the job:

- **Bounded budget**: at most ``--max-restarts`` restarts; when the
  budget is spent the supervisor appends a structured ``give_up``
  control record to the run's JSONL stream and raises
  :class:`RestartBudgetExhausted` chained onto the original failure.
- **Seeded backoff**: attempt ``k`` sleeps
  ``restart_backoff * 2**(k-1) * jitter`` where the jitter in
  ``[0.5, 1.5)`` comes from ``np.random.default_rng([seed, tag, k])``
  — deterministic per (seed, attempt), recomputable by
  ``control.replay`` from the run-header config alone.
- **Degradation ladder**: attempt 1 resumes with NO config changes, so
  a supervised restart with no interventions is bitwise identical to a
  manual kill/resume (PARITY.md).  Attempt ``k >= 2`` applies ladder
  stages ``0..k-2`` cumulatively:

  1. ``shield`` — turn on update guards + quarantine and escalate the
     compression ladder one rung (cheaper wire while unstable);
  2. ``robust_agg`` — upgrade the aggregator to coordinate-wise median
     (skipped when fused_collective/sharded_update own the chokepoint);
  3. ``reduced_cohort`` — halve client participation (floor 0.25).

  A stage override that would violate an engine construction rule
  (e.g. ``update_guard`` under ``bb_update``, or a compress escalation
  on the CPC engine, which has no compression path) is skipped, not
  forced — degradation must never introduce a new failure mode.  Engine
  incompatibilities are declared in :data:`ENGINE_LADDER_EXCLUSIONS`
  and every suppressed rung field is logged as a ``ladder_override``
  control record with ``applied: false`` and the skip reason.  Every
  override and every restart is appended to the stream as a ``control``
  record with ``source="supervisor"``.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from federated_pytorch_test_tpu.control.policy import (
    COMPRESS_LADDER, ControlRestart)
from federated_pytorch_test_tpu.obs.health import RunHealthAbort
from federated_pytorch_test_tpu.obs.schema import (
    SCHEMA_VERSION, validate_record)
from federated_pytorch_test_tpu.parallel.mesh import CollectiveTimeoutError
from federated_pytorch_test_tpu.utils.checkpoint import (
    CheckpointCorruptError, NoUsableCheckpointError)

#: distinguishes the supervisor's backoff stream from any other consumer
#: of the run seed (stateless-seed idiom, see utils/serialization notes)
_BACKOFF_TAG = 0xC791

#: exceptions the supervisor always converts into a restart attempt.
#: CollectiveTimeoutError is the preemption signal (a peer lost mid-
#: collective, or the simulated preempt= fault family) — under
#: cfg.elastic_resume the classifier supervisor additionally reshapes
#: the mesh before resuming (see supervise_classifier's reshape rung).
RETRYABLE = (RunHealthAbort, ControlRestart, CheckpointCorruptError,
             CollectiveTimeoutError)


class RestartBudgetExhausted(RuntimeError):
    """Every restart attempt failed; carries the attempt count and the
    terminal record that was appended to the stream."""

    def __init__(self, attempts: int, record: Dict[str, Any]):
        self.attempts = int(attempts)
        self.record = dict(record)
        super().__init__(
            f"run still failing after {attempts} supervised restart(s); "
            "giving up with a structured terminal record")


def restart_backoff_seconds(base: float, seed: int, attempt: int) -> float:
    """Deterministic exponential backoff with seeded jitter.

    Pure function of (base, seed, attempt) — ``control.replay`` recomputes
    it from the run-header config to verify recorded restart records.
    """
    if base <= 0:
        return 0.0
    rng = np.random.default_rng([int(seed), _BACKOFF_TAG, int(attempt)])
    jitter = 0.5 + float(rng.random())
    return float(base * (2.0 ** (attempt - 1)) * jitter)


# -- degradation ladder -----------------------------------------------

#: ladder fields an engine's constructor rejects outright.  The ladder
#: must never degrade a run into a config the engine cannot build:
#: classifier and VAE share the full blockwise feature set, while the
#: CPC chain has no compression path (the residual/error-feedback
#: machinery assumes the classifier's blockwise layout), so the shield
#: rung's compress escalation is skipped there — with a logged reason —
#: rather than forced into a constructor ValueError.
ENGINE_LADDER_EXCLUSIONS: Dict[str, Tuple[str, ...]] = {
    "classifier": (),
    "vae": (),
    "cpc": ("compress",),
}


def _stage_shield(cfg, engine: str = "classifier") -> Dict[str, Any]:
    excluded = ENGINE_LADDER_EXCLUSIONS.get(engine, ())
    ov: Dict[str, Any] = {}
    # guards mask poisoned updates pre-aggregation; forbidden under
    # bb_update (engine constructor rule), so skip rather than crash
    if not getattr(cfg, "bb_update", False):
        if not cfg.update_guard:
            ov["update_guard"] = True
        if cfg.quarantine_rounds < 2:
            ov["quarantine_rounds"] = 2
    if "compress" not in excluded and cfg.compress in COMPRESS_LADDER:
        idx = COMPRESS_LADDER.index(cfg.compress)
        cap = (COMPRESS_LADDER.index("q4") if cfg.fused_collective
               else len(COMPRESS_LADDER) - 1)
        if idx < cap:
            ov["compress"] = COMPRESS_LADDER[idx + 1]
    return ov


def _stage_robust_agg(cfg, engine: str = "classifier") -> Dict[str, Any]:
    # fused_collective/sharded_update replace the aggregation chokepoint
    # the robust estimators need (engine constructor rule)
    if (cfg.robust_agg == "none" and not cfg.fused_collective
            and not cfg.sharded_update
            and "robust_agg" not in ENGINE_LADDER_EXCLUSIONS.get(engine, ())):
        return {"robust_agg": "median"}
    return {}


def _stage_reduced_cohort(cfg, engine: str = "classifier") -> Dict[str, Any]:
    # population mode: the cohort is the scheduling unit, so degrade the
    # sampled-cohort fraction (the knob the round kernel reads per
    # round) instead of the per-slot participation coin
    if int(getattr(cfg, "population", 0) or 0) > 0:
        f = float(getattr(cfg, "cohort_frac", 1.0) or 1.0)
        if f > 0.5:
            return {"cohort_frac": 0.5}
        if f > 0.25:
            return {"cohort_frac": round(f / 2.0, 4)}
        return {}
    # partial participation is forbidden under bb_update
    if (getattr(cfg, "bb_update", False)
            or "participation" in ENGINE_LADDER_EXCLUSIONS.get(engine, ())):
        return {}
    p = float(cfg.participation)
    if p > 0.5:
        return {"participation": 0.5}
    if p > 0.25:
        return {"participation": round(p / 2.0, 4)}
    return {}


#: (name, override builder) — applied cumulatively from attempt 2 on
DEGRADATION_LADDER: Tuple[Tuple[str, Callable], ...] = (
    ("shield", _stage_shield),
    ("robust_agg", _stage_robust_agg),
    ("reduced_cohort", _stage_reduced_cohort),
)


def surviving_device_count(devices: int, K: int) -> int:
    """Largest device count ``d < devices`` with ``K % d == 0``.

    The reshape rung's target mesh after a preemption: losing any slice
    of a ``devices``-chip mesh leaves at most ``devices - 1`` usable,
    and the client axis needs ``K`` divisible by the mesh size.  Returns
    ``devices`` unchanged when no smaller divisor exists (a 1-device
    mesh has nothing to shrink to — the restart resumes in place).
    """
    for d in range(min(devices - 1, K), 0, -1):
        if K % d == 0:
            return d
    return devices


def ladder_overrides(cfg, attempt: int, engine: str = "classifier"):
    """Config after the ladder for restart ``attempt`` (1-based).

    Attempt 1 is a PLAIN resume — bitwise the manual kill/resume path.
    Attempt ``k >= 2`` applies stages ``0..k-2`` cumulatively (capped at
    the ladder length).  Returns ``(stage_index, new_cfg, changes)``
    where ``changes`` is ``[(stage_name, field, old, new), ...]`` and
    ``stage_index`` is the highest rung reached (0 = none).  ``engine``
    suppresses rung fields the target engine cannot build (see
    :data:`ENGINE_LADDER_EXCLUSIONS`); :func:`ladder_skips` reports
    what was suppressed so it can be logged.
    """
    changes: List[Tuple[str, str, Any, Any]] = []
    cur = cfg
    stage_index = min(max(0, attempt - 1), len(DEGRADATION_LADDER))
    for name, build in DEGRADATION_LADDER[:stage_index]:
        ov = build(cur, engine=engine)
        if not ov:
            continue
        for field, new in sorted(ov.items()):
            changes.append((name, field, getattr(cur, field), new))
        cur = dataclasses.replace(cur, **ov)
    return stage_index, cur, changes


def ladder_skips(cfg, attempt: int, engine: str):
    """Rung fields suppressed for ``engine`` at restart ``attempt``.

    Returns ``[(stage_name, field, reason), ...]`` — the overrides the
    classifier ladder WOULD have applied but this engine's constructor
    rejects.  The supervisor logs each as a ``ladder_override`` control
    record with ``applied: false`` so a degraded CPC/VAE run's stream
    still explains why a rung did nothing.
    """
    if not ENGINE_LADDER_EXCLUSIONS.get(engine, ()):
        return []
    skips: List[Tuple[str, str, str]] = []
    cur = cfg          # evolves with the engine-filtered overrides that run
    stage_index = min(max(0, attempt - 1), len(DEGRADATION_LADDER))
    for name, build in DEGRADATION_LADDER[:stage_index]:
        full = build(cur, engine="classifier")
        kept = build(cur, engine=engine)
        for field in sorted(set(full) - set(kept)):
            skips.append((name, field,
                          f"engine '{engine}' cannot build "
                          f"{field}={full[field]!r}; rung field skipped"))
        if kept:
            cur = dataclasses.replace(cur, **kept)
    return skips


def ladder_records(cfg, attempt: int, *, run_id: str, ridx: int,
                   engine: str = "classifier") -> List[Dict[str, Any]]:
    """``ladder_override`` control records for restart ``attempt``.

    Applied overrides carry from/to values; engine-suppressed rung
    fields carry ``applied: false`` and the skip reason.  Shared by
    :func:`supervise_classifier` and the bare-``supervise`` CPC/VAE
    driver path so both streams explain their degradation identically.
    """
    stage, _, changes = ladder_overrides(cfg, attempt, engine=engine)
    recs: List[Dict[str, Any]] = []
    for stage_name, field, old, new in changes:
        recs.append(dict(
            _base_record(run_id or "unknown", ridx),
            intervention="ladder_override", param=field,
            from_value=old, to_value=new, scope="restart",
            attempt=attempt, ladder_stage=stage,
            reason=f"degradation ladder stage {stage} ({stage_name})"))
    for stage_name, field, why in ladder_skips(cfg, attempt, engine):
        recs.append(dict(
            _base_record(run_id or "unknown", ridx),
            intervention="ladder_override", param=field,
            scope="restart", attempt=attempt, ladder_stage=stage,
            applied=False,
            reason=f"degradation ladder stage ({stage_name}) "
                   f"skipped: {why}"))
    return recs


# -- record plumbing ---------------------------------------------------


def _append_control_records(jsonl_path: Optional[str],
                            records: List[Dict[str, Any]]) -> None:
    """Append supervisor control records to the segment's JSONL stream.

    The segment's recorder already closed (the run aborted), so the
    supervisor appends validated lines directly; they land between the
    dead segment's summary and the next segment's run_header, which is
    where ``control.replay`` expects them.  Best-effort: a sink failure
    must not stop the restart.
    """
    if not jsonl_path:
        return
    try:
        with open(jsonl_path, "a") as f:
            for rec in records:
                f.write(json.dumps(validate_record(rec)) + "\n")
    except OSError:
        pass


def _failure_round(exc: BaseException) -> int:
    alert = getattr(exc, "alert", None)
    if isinstance(alert, dict) and isinstance(
            alert.get("round_index"), int):
        return alert["round_index"]
    decision = getattr(exc, "decision", None)
    if isinstance(decision, dict) and isinstance(
            decision.get("round_index"), int):
        return decision["round_index"]
    # CollectiveTimeoutError carries the round directly (no alert dict:
    # a hung collective never reached the telemetry layer)
    ridx = getattr(exc, "round_index", None)
    if isinstance(ridx, int):
        return ridx
    return -1


def _base_record(run_id: str, ridx: int) -> Dict[str, Any]:
    # control records deliberately carry no time_unix: the determinism
    # contract (PARITY.md) makes them a pure function of the stream
    return {"event": "control", "schema": SCHEMA_VERSION,
            "run_id": run_id, "round_index": ridx,
            "source": "supervisor", "mode": "act", "applied": True}


# -- the supervisor ----------------------------------------------------


def supervise(run_attempt: Callable[[int, bool], Any], *,
              max_restarts: int, backoff_base: float, seed: int,
              retry_on: Tuple = (), log: Callable[[str], None] = print,
              sleep: Callable[[float], None] = time.sleep,
              describe: Callable[[int], Tuple[Optional[str], int, List[Dict[str, Any]]]] = None):
    """Generic retry/backoff loop around ``run_attempt(attempt, resume)``.

    ``run_attempt`` is called with the 1-based attempt number and a
    resume flag (False only for attempt 1 when the caller starts fresh —
    the caller decides; here it is simply ``attempt > 1`` or what the
    caller closed over).  A retryable failure (``RETRYABLE`` plus any
    ``retry_on`` extras) consumes one unit of restart budget; anything
    else propagates untouched.

    ``describe(attempt, exc)`` (optional) returns
    ``(jsonl_path, run_id_hint, extra_records)`` for the segment that
    just failed so restart/terminal records land in its stream —
    classifier runs use :func:`supervise_classifier` which wires this to
    the trainer's recorder (``exc`` lets its reshape rung react to the
    failure TYPE, not just the count); bare callers may pass None and
    get log-only supervision (CPC/VAE path).  A one-argument
    ``describe(attempt)`` keeps working (pre-reshape callers).
    """
    retryable = RETRYABLE + tuple(retry_on)
    attempt = 0
    while True:
        try:
            return run_attempt(attempt + 1, attempt > 0)
        except NoUsableCheckpointError as e:
            # no recovery point exists: retrying cannot help
            log(f"supervisor: no usable checkpoint to resume from "
                f"({e}); giving up")
            raise
        except retryable as e:
            attempt += 1
            ridx = _failure_round(e)
            jsonl_path, run_id, extra = (None, "", [])
            if describe is not None:
                try:
                    try:
                        jsonl_path, run_id, extra = describe(attempt, e)
                    except TypeError:       # legacy one-arg describe
                        jsonl_path, run_id, extra = describe(attempt)
                except Exception:
                    jsonl_path, run_id, extra = (None, "", [])
            if attempt > max_restarts:
                rec = dict(_base_record(run_id or "unknown", ridx),
                           intervention="give_up", param="run",
                           attempt=attempt,
                           reason=f"{type(e).__name__}: restart budget "
                                  f"({max_restarts}) exhausted")
                _append_control_records(jsonl_path, [rec])
                raise RestartBudgetExhausted(attempt - 1, rec) from e
            backoff = restart_backoff_seconds(backoff_base, seed, attempt)
            rec = dict(_base_record(run_id or "unknown", ridx),
                       intervention="restart", param="run",
                       attempt=attempt, backoff_seconds=backoff,
                       reason=f"{type(e).__name__}: resume from the "
                              "last verified checkpoint")
            recs = [rec] + list(extra)
            _append_control_records(jsonl_path, recs)
            log(f"supervisor: attempt {attempt}/{max_restarts} after "
                f"{type(e).__name__} at round {ridx}; backoff "
                f"{backoff:.2f}s")
            if backoff > 0:
                sleep(backoff)


def supervise_classifier(build_trainer, cfg, checkpoint_path: str, *,
                         state=None, resume: bool = False,
                         run_kwargs: Optional[Dict[str, Any]] = None,
                         retry_on: Tuple = (),
                         log: Callable[[str], None] = print,
                         sleep: Callable[[float], None] = time.sleep,
                         engine: str = "classifier"):
    """Supervised blockwise-engine run with the full degradation ladder.

    ``build_trainer(cfg, attempt)`` constructs the trainer for each
    attempt's (possibly degraded) config — it MUST return a fresh
    trainer for ``attempt > 1`` (an aborted trainer's staging pool is
    closed); the supervisor threads the ladder through
    ``dataclasses.replace`` and records every override as a
    ``ladder_override`` control record in the failed segment's stream.
    ``engine`` makes the ladder constraint-aware: rung fields the
    target engine cannot build are suppressed and logged with
    ``applied: false`` instead of forced (the VAE driver passes
    ``engine="vae"``; CPC, whose ``run`` takes no state, goes through
    bare :func:`supervise` + :func:`ladder_records` instead).
    Returns whatever ``trainer.run`` returns.
    """
    kwargs = dict(run_kwargs or {})
    box: Dict[str, Any] = {"trainer": None, "cfg": cfg, "stage": 0}

    def run_attempt(attempt: int, resume_now: bool):
        if attempt > 1:
            # attempt is the 1-based RUN number; the restart number is
            # attempt - 1.  Restart 1 resumes plain (ladder stage 0 —
            # bitwise the manual kill/resume path); the ladder engages
            # from restart 2 on.
            stage, degraded, changes = ladder_overrides(
                cfg, attempt - 1, engine=engine)
            box["stage"], box["cfg"] = stage, degraded
            box["changes"] = changes
        if box.get("reshape_to"):
            # reshape rung (elastic federation): a CollectiveTimeoutError
            # marked the mesh as having lost a slice — rebuild the
            # trainer over the surviving device count recorded by
            # describe(); sticky across later attempts (the lost slice
            # does not come back mid-run)
            box["cfg"] = dataclasses.replace(
                box["cfg"], num_devices=box["reshape_to"])
        trainer = build_trainer(box["cfg"], attempt)
        box["trainer"] = trainer
        st = (state if attempt == 1 and state is not None
              else trainer.init_state())
        return trainer.run(st, checkpoint_path=checkpoint_path,
                           resume=resume or resume_now, **kwargs)

    def describe(attempt: int, exc: Optional[BaseException] = None):
        trainer = box["trainer"]
        rec = getattr(trainer, "obs_recorder", None)
        jsonl_path = getattr(rec, "jsonl_path", None)
        run_id = getattr(rec, "run_id", "") or ""
        ridx = getattr(rec, "_last_index", -1)
        if not isinstance(ridx, int):
            ridx = -1
        if ridx < 0:
            ridx = max(-1, _failure_round(exc) if exc is not None else -1)
        extra: List[Dict[str, Any]] = []
        if (isinstance(exc, CollectiveTimeoutError)
                and getattr(box["cfg"], "elastic_resume", False)
                and trainer is not None):
            # reshape rung: the timeout says a slice is gone — resume
            # the newest checkpoint onto the largest surviving mesh that
            # still divides the client axis, and append the typed
            # `reshape` decision to the dying segment's stream so
            # control.replay can verify it against the next segment's
            # run_header mesh_shape
            d_here = int(box.get("reshape_to") or trainer.D)
            d_next = surviving_device_count(d_here, cfg.K)
            if d_next != d_here:
                box["reshape_to"] = d_next
                extra.append(dict(
                    _base_record(run_id or "unknown", ridx),
                    intervention="reshape", param="num_devices",
                    from_value=d_here, to_value=d_next, scope="restart",
                    attempt=attempt,
                    reason=f"CollectiveTimeoutError: resume from the "
                           f"newest checkpoint on the surviving "
                           f"{d_next}-device mesh"))
        if attempt <= max(0, cfg.max_restarts):
            # `attempt` here is the restart number about to run; its
            # ladder stage is recorded against the segment that just
            # died so replay sees cause before effect
            extra.extend(ladder_records(
                cfg, attempt, run_id=run_id, ridx=ridx, engine=engine))
        return jsonl_path, run_id, extra

    return supervise(
        run_attempt, max_restarts=cfg.max_restarts,
        backoff_base=cfg.restart_backoff, seed=cfg.seed,
        retry_on=retry_on, log=log, sleep=sleep, describe=describe)
