"""Host-side data pipelines feeding the client mesh.

TPU-native re-design of the reference's L3 data layer (SURVEY.md section 1):
the per-client ``DataLoader`` dicts (reference: federated_multi.py:52-85)
become dense ``[K, steps, batch, ...]`` numpy arrays built once on the host and
``jax.device_put`` along the ``clients`` mesh axis — no Python iterator in the
hot loop, no host round-trips between minibatches.
"""

from federated_pytorch_test_tpu.data.cifar10 import (  # noqa: F401
    FederatedCifar10,
    load_cifar10_arrays,
)
from federated_pytorch_test_tpu.data.lofar import (  # noqa: F401
    CPCDataSource,
    RoundPrefetcher,
    get_data_minibatch,
)
