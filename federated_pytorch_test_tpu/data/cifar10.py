"""CIFAR10 federated data pipeline.

Re-design of the reference's per-client loader block (duplicated ~35 lines in
6 drivers; canonical copy federated_multi.py:52-85):

  * 50000 train images split into K contiguous index ranges with
    ``K_perslave = floor((50000 + K - 1) / K)`` (federated_multi.py:54);
    the reference's off-by-one (each shard's range ends at
    ``K_perslave*(ck+1)-1`` *exclusive*, dropping one sample per shard,
    no_consensus_multi.py:43-46) is reproduced behind ``drop_last_sample``
    (default True for parity);
  * normalisation to [-1, 1] (``Normalize((0.5,0.5,0.5),(0.5,0.5,0.5))``),
    with optional per-client biased means AND stds ``(0.5 + k/100,
    0.5 - k/100, 0.5)`` simulating non-IID inputs (the reference biases
    both arguments of Normalize, federated_multi.py:66);
  * every client evaluates on the full 10000-image test set
    (federated_multi.py:84-85);
  * partial final minibatches are kept (torch DataLoader drop_last=False,
    federated_multi.py:74-83): the last batch is padded to the static batch
    size by wrapping around the shuffled permutation, and a per-sample
    weight array marks the pad rows with 0 so losses/metrics exclude them
    (``include_remainder``, default True).

TPU-first: instead of K torch ``DataLoader`` objects iterated sequentially,
the pipeline materialises dense ``[K, steps, batch, 32, 32, 3]`` NHWC arrays
(one leading client axis to shard over the mesh) and reshuffles per epoch with
a numpy ``Generator`` — all device work is one ``device_put`` per epoch.

Data source: real CIFAR-10 python-pickle batches (``data_batch_1..5``,
``test_batch``) if a directory is found/given; otherwise a deterministic
synthetic CIFAR-10 lookalike (class-structured images, same shapes/counts) so
the framework trains and benchmarks end-to-end in a zero-egress environment.
"""

from __future__ import annotations

import functools
import os
import pickle
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

TRAIN_SIZE = 50000
TEST_SIZE = 10000
NUM_CLASSES = 10
IMAGE_SHAPE = (32, 32, 3)

_SEARCH_DIRS = (
    "./data/cifar-10-batches-py",
    "./cifar-10-batches-py",
    "/root/data/cifar-10-batches-py",
    os.path.expanduser("~/.cache/cifar-10-batches-py"),
)


def _load_pickle_batches(dirname: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Read the standard CIFAR-10 python pickle batches into NHWC uint8."""

    def read(name):
        with open(os.path.join(dirname, name), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        y = np.asarray(d[b"labels"], dtype=np.int32)
        return x, y

    xs, ys = zip(*(read(f"data_batch_{i}") for i in range(1, 6)))
    xte, yte = read("test_batch")
    return np.concatenate(xs), np.concatenate(ys), xte, yte


@functools.lru_cache(maxsize=4)
def _synthetic_cifar10(seed: int = 0, noise: float = 48.0,
                       prototypes: int = 1
                       ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Deterministic CIFAR-10 stand-in with learnable class structure.

    Each class c gets ``prototypes`` fixed low-frequency template images;
    a sample is a randomly chosen class prototype plus pixel noise (std
    ``noise``), clipped to uint8.  With the default single prototype a
    linear probe separates the classes and accuracy curves behave
    qualitatively like the real dataset (rise well above 10% chance),
    which is what the reference's only benchmark artifact measures
    (README.md:28-30).

    With many prototypes the prototypes are mutually unpredictable, so
    test accuracy scales with how many of them the training data covered —
    i.e. with sample count.  The accuracy-parity comparison uses this to
    make the published K=1 >= federated >= standalone-1/K ordering
    non-degenerate on synthetic data (a 1/K shard covers ~1/K of the
    prototype clusters).
    """
    rng = np.random.default_rng(seed)
    # low-frequency templates: upsampled 4x4 random patterns per
    # class/prototype/channel
    coarse = rng.uniform(40.0, 215.0, size=(NUM_CLASSES, prototypes, 4, 4, 3))
    templates = np.repeat(np.repeat(coarse, 8, axis=2), 8, axis=3)

    def make(n, rng):
        y = rng.integers(0, NUM_CLASSES, size=n).astype(np.int32)
        proto = rng.integers(0, prototypes, size=n)
        nz = rng.normal(0.0, noise, size=(n,) + IMAGE_SHAPE)
        x = np.clip(templates[y, proto] + nz, 0, 255).astype(np.uint8)
        return x, y

    xtr, ytr = make(TRAIN_SIZE, rng)
    xte, yte = make(TEST_SIZE, rng)
    # lru_cached (generating 60k images costs seconds per call; tests and
    # the comparison driver construct many pipelines) — freeze so shared
    # arrays cannot be mutated through one consumer
    for a in (xtr, ytr, xte, yte):
        a.setflags(write=False)
    return xtr, ytr, xte, yte


def load_cifar10_arrays(data_dir: Optional[str] = None, synthetic_seed: int = 0,
                        synthetic_noise: float = 48.0,
                        synthetic_prototypes: int = 1):
    """(train_x, train_y, test_x, test_y) as (uint8 NHWC, int32) arrays.

    Tries ``data_dir``, then $CIFAR10_DIR, then the standard search paths;
    falls back to the synthetic dataset.  Returns a 5th element: the source
    tag ('disk' or 'synthetic').
    """
    candidates: List[str] = []
    if data_dir:
        candidates.append(data_dir)
    if os.environ.get("CIFAR10_DIR"):
        candidates.append(os.environ["CIFAR10_DIR"])
    candidates.extend(_SEARCH_DIRS)
    for d in candidates:
        if os.path.isfile(os.path.join(d, "data_batch_1")):
            return (*_load_pickle_batches(d), "disk")
    return (*_synthetic_cifar10(synthetic_seed, synthetic_noise,
                                synthetic_prototypes), "synthetic")


def normalize(x_uint8: np.ndarray, mean: Tuple[float, float, float],
              std: Optional[Tuple[float, float, float]] = None) -> np.ndarray:
    """ToTensor + Normalize(mean, std) — federated_multi.py:62-71.

    The reference passes the SAME triple for mean and std (both the plain
    ``(0.5,0.5,0.5)`` and the biased ``(0.5+k/100, 0.5-k/100, 0.5)`` cases,
    federated_multi.py:66), so ``std`` defaults to ``mean``.
    """
    x = x_uint8.astype(np.float32) / 255.0
    m = np.asarray(mean, dtype=np.float32)
    s = m if std is None else np.asarray(std, dtype=np.float32)
    return (x - m) / s


def client_means(K: int, biased_input: bool) -> np.ndarray:
    """Per-client normalisation means — federated_multi.py:60-71."""
    if not biased_input:
        return np.tile(np.float32([0.5, 0.5, 0.5]), (K, 1))
    ks = np.arange(K, dtype=np.float32)
    return np.stack([0.5 + ks / 100.0, 0.5 - ks / 100.0, np.full(K, 0.5, np.float32)], axis=1)


def client_norm_stats(K: int, biased_input: bool) -> np.ndarray:
    """Per-client (mean, std) pairs [K, 2, 3] — federated_multi.py:66.

    The reference's Normalize biases mean and std with the SAME per-client
    triple; the plain case uses 0.5 for both.
    """
    m = client_means(K, biased_input)
    return np.stack([m, m], axis=1)


def shard_indices(K: int, n: int = TRAIN_SIZE, drop_last_sample: bool = True) -> List[np.ndarray]:
    """Contiguous 1/K index ranges — federated_multi.py:52-58.

    ``drop_last_sample=True`` reproduces the reference's exclusive upper bound
    ``K_perslave*(ck+1)-1`` which silently drops one sample per shard
    (SURVEY.md section 7 quirks list).
    """
    per = (n + K - 1) // K
    out = []
    for ck in range(K):
        hi = min(per * (ck + 1), n)
        if drop_last_sample:
            hi = min(per * (ck + 1) - 1, n)
        out.append(np.arange(per * ck, hi))
    return out


@dataclass
class FederatedCifar10:
    """K-client CIFAR10 with dense per-epoch batch tensors.

    Usage (the production uint8 + sample-weight API)::

        data = FederatedCifar10(K=8, batch=128, biased_input=False)
        xb, yb, wb = data.epoch_batches_raw(seed)  # [K, steps, B, 32,32,3] u8,
                                                   # [K, steps, B] i32/f32
        xt, yt, wt = data.test_batches_raw()       # [tsteps, B, ...]

    ``steps`` counts the wrap-padded remainder batch when
    ``include_remainder`` (pad rows weighted 0); the host-float convenience
    methods ``epoch_batches``/``test_batches`` return FULL batches only
    (``samples_per_client // batch`` steps), which is fewer than ``.steps``
    whenever a remainder exists.

    The leading axis is the client mesh axis.  Every client gets the same
    number of steps (shards are equal-sized by construction); the per-epoch
    shuffle matches the reference's ``SubsetRandomSampler`` semantics
    (federated_multi.py:74-83) with an explicit numpy Generator.
    """

    K: int = 10
    batch: int = 128
    biased_input: bool = False
    drop_last_sample: bool = True
    include_remainder: bool = True  # torch drop_last=False parity (:74-83)
    data_dir: Optional[str] = None
    synthetic_seed: int = 0
    synthetic_noise: float = 48.0           # pixel-noise std of the fallback
    synthetic_prototypes: int = 1           # templates per class (fallback)
    limit_per_client: Optional[int] = None  # cap shard size (tests/benchmarks)
    limit_test: Optional[int] = None        # cap test-set size (tests)
    # filled in __post_init__
    source: str = field(init=False, default="")

    def __post_init__(self):
        xtr, ytr, xte, yte, src = load_cifar10_arrays(
            self.data_dir, self.synthetic_seed, self.synthetic_noise,
            self.synthetic_prototypes)
        self.source = src
        self._norm = client_norm_stats(self.K, self.biased_input)
        idx = shard_indices(self.K, len(xtr), self.drop_last_sample)
        n_min = min(len(i) for i in idx)
        if self.limit_per_client:
            n_min = min(n_min, self.limit_per_client)
        if self.limit_test:
            xte, yte = xte[: self.limit_test], yte[: self.limit_test]
        full = n_min // self.batch
        self.remainder = n_min - full * self.batch if self.include_remainder else 0
        self.steps = full + (1 if self.remainder else 0)
        # store raw uint8 shards; normalisation is applied per epoch (cheap,
        # and biased means are per-client so can't be pre-folded globally)
        self._train_x = np.stack([xtr[i[:n_min]] for i in idx])  # [K, n, 32,32,3] u8
        self._train_y = np.stack([ytr[i[:n_min]] for i in idx]).astype(np.int32)
        self._test_x = xte
        self._test_y = yte.astype(np.int32)

    @property
    def samples_per_client(self) -> int:
        return self._train_x.shape[1]

    def train_shards_raw(self) -> Tuple[np.ndarray, np.ndarray]:
        """Raw per-client shards ([K, n, 32, 32, 3] u8, [K, n] i32).

        The engine's device-resident staging path puts these in HBM once
        and builds every epoch's shuffled batches with an on-device
        permutation gather (train/engine.py `_stage_epoch`)."""
        return self._train_x, self._train_y

    @property
    def means(self) -> np.ndarray:
        """Per-client normalisation means [K, 3] (federated_multi.py:60-71)."""
        return self._norm[:, 0]

    @property
    def norm_stats(self) -> np.ndarray:
        """Per-client (mean, std) [K, 2, 3] (federated_multi.py:66)."""
        return self._norm

    def epoch_batches_raw(self, seed: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """One shuffled epoch as raw uint8: ([K, steps, B, 32,32,3],
        [K, steps, B] labels, [K, steps, B] f32 sample weights).

        Normalisation happens on-device inside the jitted step (the engine
        folds in the per-client biased means), so the host only permutes
        uint8 — 4x less host->device traffic than staging float32.

        The final partial minibatch (DataLoader drop_last=False,
        federated_multi.py:74-83) is padded to the static batch size by
        wrapping around the permutation; pad rows carry weight 0.
        """
        rng = np.random.default_rng(seed)
        n = self.steps * self.batch
        w_flat = np.ones(n, np.float32)
        if self.remainder:
            w_flat[self.steps * self.batch - self.batch + self.remainder:] = 0.0
        xs, ys = [], []
        for ck in range(self.K):
            perm = rng.permutation(self.samples_per_client)
            if n > len(perm):                 # wrap-pad the remainder batch
                perm = np.concatenate([perm, perm[: n - len(perm)]])
            perm = perm[:n]
            xs.append(self._train_x[ck, perm].reshape(
                self.steps, self.batch, *IMAGE_SHAPE))
            ys.append(self._train_y[ck, perm].reshape(self.steps, self.batch))
        w = np.tile(w_flat.reshape(1, self.steps, self.batch), (self.K, 1, 1))
        return np.stack(xs), np.stack(ys), w

    def test_batches_raw(self, batch: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Full test set ONCE (not per client) as uint8 [tsteps, B, ...] plus
        labels [tsteps, B] and f32 weights [tsteps, B]; clients differ only
        in their normalisation stats, which the engine applies on-device.

        With ``include_remainder`` (default) the test set is wrap-padded so
        ALL samples are evaluated (reference parity: the 10k set is not a
        batch multiple of 128; pad rows carry weight 0)."""
        b = batch or self.batch
        n_test = len(self._test_x)
        if self.include_remainder:
            tsteps = -(-n_test // b)
            n = tsteps * b
            pad = np.arange(n) % n_test       # wrap-pad
            w = np.ones(n, np.float32)
            w[n_test:] = 0.0
            return (self._test_x[pad].reshape(tsteps, b, *IMAGE_SHAPE),
                    self._test_y[pad].reshape(tsteps, b),
                    w.reshape(tsteps, b))
        tsteps = n_test // b
        n = tsteps * b
        return (self._test_x[:n].reshape(tsteps, b, *IMAGE_SHAPE),
                self._test_y[:n].reshape(tsteps, b),
                np.ones((tsteps, b), np.float32))

    def epoch_batches(self, seed: int) -> Tuple[np.ndarray, np.ndarray]:
        """One epoch of FULL shuffled minibatches as host float32 (convenience
        for tests/notebooks): [K, full, B, 32,32,3] f32, [K, full, B] i32.
        The production path is ``epoch_batches_raw`` (uint8 + weights)."""
        rng = np.random.default_rng(seed)
        full = self.samples_per_client // self.batch
        n = full * self.batch
        xs, ys = [], []
        for ck in range(self.K):
            perm = rng.permutation(self.samples_per_client)[:n]
            x = normalize(self._train_x[ck, perm], tuple(self._norm[ck, 0]),
                          tuple(self._norm[ck, 1]))
            xs.append(x.reshape(full, self.batch, *IMAGE_SHAPE))
            ys.append(self._train_y[ck, perm].reshape(full, self.batch))
        return np.stack(xs), np.stack(ys)

    def test_batches(self, batch: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
        """Full test set, replicated per client with that client's transform.

        Reference parity: every client evaluates on the complete 10k test set
        under its own (possibly biased) normalisation (federated_multi.py:84-85,
        :108-121).  Returns [K, tsteps, B, ...] arrays (remainder dropped —
        host-float convenience; the engine's eval path covers the remainder
        via ``test_batches_raw`` weights).
        """
        b = batch or self.batch
        tsteps = len(self._test_x) // b
        n = tsteps * b
        xs = []
        for ck in range(self.K):
            x = normalize(self._test_x[:n], tuple(self._norm[ck, 0]),
                          tuple(self._norm[ck, 1]))
            xs.append(x.reshape(tsteps, b, *IMAGE_SHAPE))
        y = np.tile(self._test_y[:n].reshape(1, tsteps, b), (self.K, 1, 1))
        return np.stack(xs), y
