"""CIFAR10 federated data pipeline.

Re-design of the reference's per-client loader block (duplicated ~35 lines in
6 drivers; canonical copy federated_multi.py:52-85):

  * 50000 train images split into K contiguous index ranges with
    ``K_perslave = floor((50000 + K - 1) / K)`` (federated_multi.py:54);
    the reference's off-by-one (each shard's range ends at
    ``K_perslave*(ck+1)-1`` *exclusive*, dropping one sample per shard,
    no_consensus_multi.py:43-46) is reproduced behind ``drop_last_sample``
    (default True for parity);
  * normalisation to [-1, 1] (``Normalize((0.5,0.5,0.5),(0.5,0.5,0.5))``),
    with optional per-client biased means ``(0.5 + k/100, 0.5 - k/100, 0.5)``
    simulating non-IID inputs (``biased_input``, federated_multi.py:60-71);
  * every client evaluates on the full 10000-image test set
    (federated_multi.py:84-85).

TPU-first: instead of K torch ``DataLoader`` objects iterated sequentially,
the pipeline materialises dense ``[K, steps, batch, 32, 32, 3]`` NHWC arrays
(one leading client axis to shard over the mesh) and reshuffles per epoch with
a numpy ``Generator`` — all device work is one ``device_put`` per epoch.

Data source: real CIFAR-10 python-pickle batches (``data_batch_1..5``,
``test_batch``) if a directory is found/given; otherwise a deterministic
synthetic CIFAR-10 lookalike (class-structured images, same shapes/counts) so
the framework trains and benchmarks end-to-end in a zero-egress environment.
"""

from __future__ import annotations

import os
import pickle
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

TRAIN_SIZE = 50000
TEST_SIZE = 10000
NUM_CLASSES = 10
IMAGE_SHAPE = (32, 32, 3)

_SEARCH_DIRS = (
    "./data/cifar-10-batches-py",
    "./cifar-10-batches-py",
    "/root/data/cifar-10-batches-py",
    os.path.expanduser("~/.cache/cifar-10-batches-py"),
)


def _load_pickle_batches(dirname: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Read the standard CIFAR-10 python pickle batches into NHWC uint8."""

    def read(name):
        with open(os.path.join(dirname, name), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        y = np.asarray(d[b"labels"], dtype=np.int32)
        return x, y

    xs, ys = zip(*(read(f"data_batch_{i}") for i in range(1, 6)))
    xte, yte = read("test_batch")
    return np.concatenate(xs), np.concatenate(ys), xte, yte


def _synthetic_cifar10(seed: int = 0) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Deterministic CIFAR-10 stand-in with learnable class structure.

    Each class c gets a fixed low-frequency template image; samples are the
    template plus moderate pixel noise, clipped to uint8.  A linear probe
    separates the classes, and accuracy curves behave qualitatively like the
    real dataset (rises well above 10% chance), which is what the reference's
    only benchmark artifact measures (README.md:28-30).
    """
    rng = np.random.default_rng(seed)
    # low-frequency templates: upsampled 4x4 random patterns per class/channel
    coarse = rng.uniform(40.0, 215.0, size=(NUM_CLASSES, 4, 4, 3))
    templates = np.repeat(np.repeat(coarse, 8, axis=1), 8, axis=2)  # [10,32,32,3]

    def make(n, rng):
        y = rng.integers(0, NUM_CLASSES, size=n).astype(np.int32)
        noise = rng.normal(0.0, 48.0, size=(n,) + IMAGE_SHAPE)
        x = np.clip(templates[y] + noise, 0, 255).astype(np.uint8)
        return x, y

    xtr, ytr = make(TRAIN_SIZE, rng)
    xte, yte = make(TEST_SIZE, rng)
    return xtr, ytr, xte, yte


def load_cifar10_arrays(data_dir: Optional[str] = None, synthetic_seed: int = 0):
    """(train_x, train_y, test_x, test_y) as (uint8 NHWC, int32) arrays.

    Tries ``data_dir``, then $CIFAR10_DIR, then the standard search paths;
    falls back to the synthetic dataset.  Returns a 5th element: the source
    tag ('disk' or 'synthetic').
    """
    candidates: List[str] = []
    if data_dir:
        candidates.append(data_dir)
    if os.environ.get("CIFAR10_DIR"):
        candidates.append(os.environ["CIFAR10_DIR"])
    candidates.extend(_SEARCH_DIRS)
    for d in candidates:
        if os.path.isfile(os.path.join(d, "data_batch_1")):
            return (*_load_pickle_batches(d), "disk")
    return (*_synthetic_cifar10(synthetic_seed), "synthetic")


def normalize(x_uint8: np.ndarray, mean: Tuple[float, float, float]) -> np.ndarray:
    """ToTensor + Normalize(mean, (0.5, 0.5, 0.5)) — federated_multi.py:62-71."""
    x = x_uint8.astype(np.float32) / 255.0
    m = np.asarray(mean, dtype=np.float32)
    return (x - m) / 0.5


def client_means(K: int, biased_input: bool) -> np.ndarray:
    """Per-client normalisation means — federated_multi.py:60-71."""
    if not biased_input:
        return np.tile(np.float32([0.5, 0.5, 0.5]), (K, 1))
    ks = np.arange(K, dtype=np.float32)
    return np.stack([0.5 + ks / 100.0, 0.5 - ks / 100.0, np.full(K, 0.5, np.float32)], axis=1)


def shard_indices(K: int, n: int = TRAIN_SIZE, drop_last_sample: bool = True) -> List[np.ndarray]:
    """Contiguous 1/K index ranges — federated_multi.py:52-58.

    ``drop_last_sample=True`` reproduces the reference's exclusive upper bound
    ``K_perslave*(ck+1)-1`` which silently drops one sample per shard
    (SURVEY.md section 7 quirks list).
    """
    per = (n + K - 1) // K
    out = []
    for ck in range(K):
        hi = min(per * (ck + 1), n)
        if drop_last_sample:
            hi = min(per * (ck + 1) - 1, n)
        out.append(np.arange(per * ck, hi))
    return out


@dataclass
class FederatedCifar10:
    """K-client CIFAR10 with dense per-epoch batch tensors.

    Usage::

        data = FederatedCifar10(K=8, batch=128, biased_input=False)
        xb, yb = data.epoch_batches(rng_seed)   # [K, steps, B, 32, 32, 3], [K, steps, B]
        xt, yt = data.test_batches()            # [K, tsteps, B, 32, 32, 3], ...

    The leading axis is the client mesh axis.  Every client gets the same
    number of steps (shards are equal-sized by construction); the per-epoch
    shuffle matches the reference's ``SubsetRandomSampler`` semantics
    (federated_multi.py:74-83) with an explicit numpy Generator.
    """

    K: int = 10
    batch: int = 128
    biased_input: bool = False
    drop_last_sample: bool = True
    data_dir: Optional[str] = None
    synthetic_seed: int = 0
    limit_per_client: Optional[int] = None  # cap shard size (tests/benchmarks)
    limit_test: Optional[int] = None        # cap test-set size (tests)
    # filled in __post_init__
    source: str = field(init=False, default="")

    def __post_init__(self):
        xtr, ytr, xte, yte, src = load_cifar10_arrays(self.data_dir, self.synthetic_seed)
        self.source = src
        self._means = client_means(self.K, self.biased_input)
        idx = shard_indices(self.K, len(xtr), self.drop_last_sample)
        n_min = min(len(i) for i in idx)
        if self.limit_per_client:
            n_min = min(n_min, self.limit_per_client)
        if self.limit_test:
            xte, yte = xte[: self.limit_test], yte[: self.limit_test]
        self.steps = n_min // self.batch
        # store raw uint8 shards; normalisation is applied per epoch (cheap,
        # and biased means are per-client so can't be pre-folded globally)
        self._train_x = np.stack([xtr[i[:n_min]] for i in idx])  # [K, n, 32,32,3] u8
        self._train_y = np.stack([ytr[i[:n_min]] for i in idx]).astype(np.int32)
        self._test_x = xte
        self._test_y = yte.astype(np.int32)

    @property
    def samples_per_client(self) -> int:
        return self._train_x.shape[1]

    @property
    def means(self) -> np.ndarray:
        """Per-client normalisation means [K, 3] (federated_multi.py:60-71)."""
        return self._means

    def epoch_batches_raw(self, seed: int) -> Tuple[np.ndarray, np.ndarray]:
        """One shuffled epoch as raw uint8: [K, steps, B, 32,32,3], [K, steps, B].

        Normalisation happens on-device inside the jitted step (the engine
        folds in the per-client biased means), so the host only permutes
        uint8 — 4x less host->device traffic than staging float32.
        """
        rng = np.random.default_rng(seed)
        n = self.steps * self.batch
        xs, ys = [], []
        for ck in range(self.K):
            perm = rng.permutation(self.samples_per_client)[:n]
            xs.append(self._train_x[ck, perm].reshape(
                self.steps, self.batch, *IMAGE_SHAPE))
            ys.append(self._train_y[ck, perm].reshape(self.steps, self.batch))
        return np.stack(xs), np.stack(ys)

    def test_batches_raw(self, batch: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
        """Full test set ONCE (not per client) as uint8 [tsteps, B, ...] plus
        labels [tsteps, B]; clients differ only in their normalisation means,
        which the engine applies on-device."""
        b = batch or self.batch
        tsteps = len(self._test_x) // b
        n = tsteps * b
        return (self._test_x[:n].reshape(tsteps, b, *IMAGE_SHAPE),
                self._test_y[:n].reshape(tsteps, b))

    def epoch_batches(self, seed: int) -> Tuple[np.ndarray, np.ndarray]:
        """One epoch of shuffled minibatches: [K, steps, B, 32,32,3] f32, [K, steps, B] i32."""
        rng = np.random.default_rng(seed)
        n = self.steps * self.batch
        xs, ys = [], []
        for ck in range(self.K):
            perm = rng.permutation(self.samples_per_client)[:n]
            x = normalize(self._train_x[ck, perm], tuple(self._means[ck]))
            xs.append(x.reshape(self.steps, self.batch, *IMAGE_SHAPE))
            ys.append(self._train_y[ck, perm].reshape(self.steps, self.batch))
        return np.stack(xs), np.stack(ys)

    def test_batches(self, batch: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
        """Full test set, replicated per client with that client's transform.

        Reference parity: every client evaluates on the complete 10k test set
        under its own (possibly biased) normalisation (federated_multi.py:84-85,
        :108-121).  Returns [K, tsteps, B, ...] arrays (remainder dropped).
        """
        b = batch or self.batch
        tsteps = len(self._test_x) // b
        n = tsteps * b
        xs = []
        for ck in range(self.K):
            x = normalize(self._test_x[:n], tuple(self._means[ck]))
            xs.append(x.reshape(tsteps, b, *IMAGE_SHAPE))
        y = np.tile(self._test_y[:n].reshape(1, tsteps, b), (self.K, 1, 1))
        return np.stack(xs), y
