"""LOFAR visibility data pipeline for CPC (reference federated_cpc.py:52-108).

Reads LOFAR ``.h5`` extracts: ``measurement/saps/<SAP>/visibilities`` with
shape (nbase, ntime, nfreq, npol=4, ncomplex=2) plus per-baseline
``visibility_scale_factors`` (nbase, nfreq, npol).  A minibatch is a random
baseline subset mapped to an 8-channel image (4 pol x re/im, scale factors
applied), unfolded into patch_size x patch_size patches with 50% overlap and
clamped to +-1e6.  Returns ``(patchx, patchy, y)`` where y is
``[batch*patchx*patchy, patch, patch, 8]`` (NHWC — the reference is NCHW).

Zero-egress fallback: when a file is missing, a deterministic synthetic
visibility cube keyed on (filename, SAP) is generated with structured
fringes + RFI-like spikes + noise, so the CPC driver trains end-to-end
without the (non-redistributable) LOFAR observations.
"""

from __future__ import annotations

import hashlib
import os
import queue
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

try:
    import h5py
    HAVE_H5PY = True
except ImportError:                    # pragma: no cover - h5py is baked in
    HAVE_H5PY = False


def _synthetic_cube(filename: str, sap: str, nbase: int = 64, ntime: int = 64,
                    nfreq: int = 64):
    """Deterministic synthetic (visibilities, scale_factors) for one SAP."""
    seed = int.from_bytes(
        hashlib.sha256(f"{os.path.basename(filename)}:{sap}".encode())
        .digest()[:4], "little")
    rng = np.random.default_rng(seed)
    t = np.arange(ntime)[:, None]
    f = np.arange(nfreq)[None, :]
    vis = np.zeros((nbase, ntime, nfreq, 4, 2), np.float32)
    for b in range(nbase):
        # per-baseline fringe rates/delays; per-pol amplitude
        rate = rng.uniform(0.02, 0.3)
        delay = rng.uniform(0.02, 0.3)
        amp = rng.uniform(0.5, 2.0, size=4)
        phase = 2 * np.pi * (rate * t + delay * f) + rng.uniform(0, 2 * np.pi)
        for p in range(4):
            vis[b, :, :, p, 0] = amp[p] * np.cos(phase)
            vis[b, :, :, p, 1] = amp[p] * np.sin(phase)
        # RFI-like narrowband spikes in a few channels
        for _ in range(rng.integers(1, 4)):
            ch = rng.integers(0, nfreq)
            vis[b, :, ch, :, :] += rng.normal(0, 10.0, size=(ntime, 4, 2))
    vis += rng.normal(0, 0.3, size=vis.shape).astype(np.float32)
    scale = rng.uniform(0.5, 1.5, size=(nbase, nfreq, 4)).astype(np.float32)
    return vis.astype(np.float32), scale




def extract_patches(x: np.ndarray, patch_size: int, stride: int) -> Tuple[int, int, np.ndarray]:
    """Unfold [B, C, T, F] into [B*px*py, C, patch, patch], baseline-major:
    row r = b*px*py + ci*py + cj.

    DOCUMENTED DEVIATION: the reference builds the rows PATCH-major
    (federated_cpc.py:93-99: block k=ci*py+cj holds all baselines) but later
    reinterprets them with ``output.view(batch_size, patchx, patchy, -1)``
    (federated_cpc.py:259-261), which assumes baseline-major order — so its
    latents grid mixes unrelated baselines/patches.  We use the consistent
    baseline-major order end-to-end, giving the contextgen a true patch grid
    (the InfoNCE objective is still positives-on-the-diagonal either way).
    """
    B, C, T, F = x.shape
    px = (T - patch_size) // stride + 1
    py = (F - patch_size) // stride + 1
    s = np.lib.stride_tricks.sliding_window_view(
        x, (patch_size, patch_size), axis=(2, 3))[:, :, ::stride, ::stride]
    # s: [B, C, px, py, patch, patch] -> [B, px, py, C, patch, patch]
    out = s.transpose(0, 2, 3, 1, 4, 5).reshape(
        B * px * py, C, patch_size, patch_size)
    return px, py, out


def get_data_minibatch(filename: str, SAP: str = "0", batch_size: int = 2,
                       patch_size: int = 32,
                       rng: np.random.Generator | None = None
                       ) -> Tuple[int, int, np.ndarray]:
    """One CPC minibatch — reference get_data_minibatch (federated_cpc.py:52-108).

    Returns (patchx, patchy, y) with y [batch*px*py, patch, patch, 8] float32
    NHWC, scale factors applied, clipped to +-1e6.
    """
    rng = rng or np.random.default_rng()
    use_disk = HAVE_H5PY and os.path.isfile(filename)

    def fill(x, g, h):
        baselines = rng.integers(0, g.shape[0], batch_size)
        for ck, mybase in enumerate(baselines):
            for ci in range(4):
                sf = np.asarray(h[mybase, :, ci])[None, :]   # [1, nfreq]
                x[ck, 2 * ci] = np.asarray(g[mybase, :, :, ci, 0]) * sf
                x[ck, 2 * ci + 1] = np.asarray(g[mybase, :, :, ci, 1]) * sf

    if use_disk:
        with h5py.File(filename, "r") as f:
            g = f["measurement"]["saps"][SAP]["visibilities"]
            h = f["measurement"]["saps"][SAP]["visibility_scale_factors"]
            nbase, ntime, nfreq, npol, _ = g.shape
            x = np.zeros((batch_size, 8, ntime, nfreq), np.float32)
            fill(x, g, h)
    else:
        vis, scale = _synthetic_cube(filename, SAP)
        nbase, ntime, nfreq, npol, _ = vis.shape
        x = np.zeros((batch_size, 8, ntime, nfreq), np.float32)
        fill(x, vis, scale)

    px, py, y = extract_patches(x, patch_size, patch_size // 2)
    np.clip(y, -1e6, 1e6, out=y)
    return px, py, np.ascontiguousarray(y.transpose(0, 2, 3, 1))  # NHWC


class CPCDataSource:
    """Per-client (file, SAP) assignment — reference federated_cpc.py:137-145."""

    def __init__(self, file_list: List[str], sap_list: List[str],
                 batch_size: int = 128, patch_size: int = 32, seed: int = 0):
        assert len(file_list) == len(sap_list)
        self.file_list = file_list
        self.sap_list = sap_list
        self.batch_size = batch_size
        self.patch_size = patch_size
        self.seed = seed
        self._rng = np.random.default_rng(seed)
        # guards the round counter: round_batches runs both on the
        # caller's thread (direct path) and on a RoundPrefetcher
        # producer.  The lock only sequences counter bumps — every draw
        # is keyed on (seed, round, client), so locking cannot change
        # any sampled value (PARITY.md: bit-identical math path).
        self._lock = threading.Lock()
        self._round = 0

    @property
    def K(self) -> int:
        return len(self.file_list)

    def minibatch(self, ck: int) -> Tuple[int, int, np.ndarray]:
        return get_data_minibatch(
            self.file_list[ck], self.sap_list[ck], self.batch_size,
            self.patch_size, self._rng)

    def round_batches(self, niter: int,
                      clients: Optional[Sequence[int]] = None
                      ) -> Tuple[int, int, np.ndarray]:
        """[len(clients), niter, batch*px*py, patch, patch, 8] for one comm
        round (``clients`` defaults to all K).

        Random draws are keyed on ``(seed, round_counter, client)`` rather
        than one shared sequential generator, so (a) the prefetching and
        direct call paths see identical data, and (b) on multi-host, where
        each process builds only ITS client subset (federated_cpc.py:137-145
        assigns clients to hosts via the file list), the per-client streams
        stay uncorrelated — a shared generator would hand every process the
        same draw sequence starting at its first client.
        """
        clients = range(self.K) if clients is None else clients
        with self._lock:
            rnd = self._round
            self._round += 1
        out = []
        px = py = None
        for ck in clients:
            rng = np.random.default_rng([self.seed, rnd, ck])
            its = []
            for _ in range(niter):
                px, py, y = get_data_minibatch(
                    self.file_list[ck], self.sap_list[ck], self.batch_size,
                    self.patch_size, rng)
                its.append(y)
            out.append(np.stack(its))
        return px, py, np.stack(out)


class RoundPrefetcher:
    """Double-buffered background producer over
    :meth:`CPCDataSource.round_batches` (SURVEY.md section 7 hard part 6:
    the reference re-draws fresh minibatches per round on the host,
    federated_cpc.py:252-253, which serialises host work against device
    compute).  The producer thread builds round n+1's host tensor while
    round n computes; ``Queue(maxsize=1)`` bounds host memory at ~2 rounds
    in flight.
    """

    def __init__(self, source: CPCDataSource, niter: int, total_rounds: int,
                 clients: Optional[Sequence[int]] = None):
        self._q: queue.Queue = queue.Queue(maxsize=1)
        self._stop = False
        self._exc: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._produce, args=(source, niter, total_rounds, clients),
            daemon=True)
        self._thread.start()

    def _put(self, item) -> bool:
        """Bounded put that gives up when the consumer closed us."""
        while not self._stop:
            try:
                self._q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self, source, niter, total, clients):
        try:
            for _ in range(total):
                if not self._put(source.round_batches(niter, clients)):
                    return
        except BaseException as e:      # noqa: BLE001 — relayed to get()
            self._exc = e
            self._put(None)

    def get(self) -> Tuple[int, int, np.ndarray]:
        item = self._q.get()
        if item is None:
            raise RuntimeError("CPC prefetch producer failed") from self._exc
        return item

    def close(self) -> None:
        """Unblock and retire the producer.

        Joins the thread: it exits within one put-poll (~0.2s) of finishing
        any in-flight ``round_batches`` build, and joining guarantees no
        producer is still advancing the source's round counter (locked,
        but a straggler bump would still skew which rounds the direct
        path sees) when the caller reuses the CPCDataSource."""
        self._stop = True
        self._thread.join()
