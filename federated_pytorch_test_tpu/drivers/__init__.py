"""Entry-point drivers mirroring the reference scripts.

Reference script           -> module here
-------------------------------------------------------------------
no_consensus_multi.py      -> drivers.no_consensus_multi
federated_multi.py         -> drivers.federated_multi
fedprox_multi.py           -> drivers.fedprox_multi
consensus_multi.py         -> drivers.consensus_multi
federated_vae.py           -> drivers.federated_vae
federated_vae_cl.py        -> drivers.federated_vae_cl
federated_cpc.py           -> drivers.federated_cpc

The reference configures by editing module constants in-source
(federated_multi.py:9-48); here the same knobs (same names) are CLI flags
with the reference's defaults, e.g.::

    python -m federated_pytorch_test_tpu.drivers.federated_multi \
        --K 8 --use-resnet --Nloop 12
"""
