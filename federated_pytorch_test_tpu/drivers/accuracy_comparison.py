"""Accuracy-curve comparison — the reference's only published result.

Reference README.md:28-30 + comparison.png: test accuracy of K=10
{standalone, FedAvg, consensus} vs a K=1 upper bound, trained on CIFAR10
with the Net model.  This driver reproduces that comparison and writes the
accuracy-vs-round curves to a JSON artifact; the regression test
(tests/test_accuracy_parity.py) asserts the published qualitative ordering

    K=1 upper bound >= federated (FedAvg/consensus) >= standalone-1/K >> chance

on a scaled-down run.

Usage::

    python -m federated_pytorch_test_tpu.drivers.accuracy_comparison \
        [--K 10] [--Nloop 3] [--Nadmm 3] [--batch 64] [--n-train 1024] \
        [--n-test 2048] [--out artifacts/accuracy_comparison.json]
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict, List

import numpy as np

from federated_pytorch_test_tpu.data.cifar10 import FederatedCifar10
from federated_pytorch_test_tpu.models.simple import Net
from federated_pytorch_test_tpu.train import (
    AdmmConsensus,
    BlockwiseFederatedTrainer,
    FedAvg,
    FederatedConfig,
    NoConsensus,
)

_SILENT = lambda m: None


def _curve(history) -> List[float]:
    """Mean-over-clients test accuracy per evaluated round."""
    return [float(np.mean(h["accuracy"])) for h in history
            if "accuracy" in h]


def run_comparison(K: int = 10, Nloop: int = 3, Nadmm: int = 3,
                   batch: int = 64, n_train: int = 1024,
                   n_test: int = 2048, seed: int = 5,
                   synthetic_noise: float = 48.0,
                   synthetic_prototypes: int = 32,
                   log=_SILENT) -> Dict[str, object]:
    """All four runs of the reference comparison; returns curve dict.

    Budget fairness: the standalone runs get Nloop*Nadmm full-net epochs,
    the federated runs get Nloop sweeps x Nadmm rounds x 1 epoch (the
    reference's published configuration shape, federated_multi.py:13-16);
    the K=1 upper bound sees the union of all clients' data (K*n_train).
    """
    total_epochs = Nloop * Nadmm
    results: Dict[str, object] = {
        "config": dict(K=K, Nloop=Nloop, Nadmm=Nadmm, batch=batch,
                       n_train=n_train, n_test=n_test, seed=seed,
                       synthetic_noise=synthetic_noise,
                       synthetic_prototypes=synthetic_prototypes),
    }

    # with one prototype per class the synthetic stand-in saturates at
    # 100% for every run; many prototypes make test accuracy scale with
    # training-sample coverage so the published ordering is non-degenerate
    # (irrelevant when real CIFAR batches are on disk)
    dataK = FederatedCifar10(K=K, batch=batch, limit_per_client=n_train,
                             limit_test=n_test,
                             synthetic_noise=synthetic_noise,
                             synthetic_prototypes=synthetic_prototypes)
    results["data_source"] = dataK.source

    log(f"standalone K={K} ({total_epochs} epochs)")
    cfg = FederatedConfig(K=K, Nepoch=total_epochs, default_batch=batch,
                          check_results=True, seed=seed)
    t = BlockwiseFederatedTrainer(Net(), cfg, dataK, NoConsensus())
    _, hist = t.run_independent(log=_SILENT)
    results["standalone"] = _curve(hist)

    for name, algo, rho in (("fedavg", FedAvg(), 1.0),
                            ("consensus", AdmmConsensus(), 0.1)):
        log(f"{name} K={K} (Nloop={Nloop} Nadmm={Nadmm})")
        cfg = FederatedConfig(K=K, Nloop=Nloop, Nepoch=1, Nadmm=Nadmm,
                              default_batch=batch, check_results=True,
                              admm_rho0=rho, seed=seed)
        t = BlockwiseFederatedTrainer(Net(), cfg, dataK, algo)
        _, hist = t.run(log=_SILENT)
        results[name] = _curve(hist)

    log(f"upper bound K=1 ({total_epochs} epochs, {K * n_train} samples)")
    data1 = FederatedCifar10(K=1, batch=batch,
                             limit_per_client=K * n_train,
                             limit_test=n_test,
                             synthetic_noise=synthetic_noise,
                             synthetic_prototypes=synthetic_prototypes)
    cfg = FederatedConfig(K=1, Nepoch=total_epochs, default_batch=batch,
                          check_results=True, seed=seed)
    t = BlockwiseFederatedTrainer(Net(), cfg, data1, NoConsensus())
    _, hist = t.run_independent(log=_SILENT)
    results["upper_k1"] = _curve(hist)

    results["final"] = {k: results[k][-1] for k in
                        ("standalone", "fedavg", "consensus", "upper_k1")}
    return results


def main(argv=None):
    p = argparse.ArgumentParser(prog="accuracy_comparison",
                                description=__doc__.splitlines()[0])
    p.add_argument("--K", type=int, default=10)
    p.add_argument("--Nloop", type=int, default=3)
    p.add_argument("--Nadmm", type=int, default=3)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--n-train", type=int, default=1024)
    p.add_argument("--n-test", type=int, default=2048)
    p.add_argument("--seed", type=int, default=5)
    p.add_argument("--noise", type=float, default=48.0,
                   help="synthetic-fallback pixel-noise std")
    p.add_argument("--prototypes", type=int, default=32,
                   help="synthetic-fallback templates per class")
    p.add_argument("--out", default="artifacts/accuracy_comparison.json")
    args = p.parse_args(argv)
    res = run_comparison(K=args.K, Nloop=args.Nloop, Nadmm=args.Nadmm,
                         batch=args.batch, n_train=args.n_train,
                         n_test=args.n_test, seed=args.seed,
                         synthetic_noise=args.noise,
                         synthetic_prototypes=args.prototypes, log=print)
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(res, f, indent=1)
    print(json.dumps(res["final"]))
    print(f"wrote {args.out}")
    return res


if __name__ == "__main__":
    main()
