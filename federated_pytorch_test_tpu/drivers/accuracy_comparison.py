"""Accuracy-curve comparison — the reference's only published result.

Reference README.md:28-30 + comparison.png: test accuracy of K=10
{standalone, FedAvg, consensus} vs a K=1 upper bound, trained on CIFAR10
with the Net model.  This driver reproduces that comparison and writes the
accuracy-vs-round curves to a JSON artifact; the regression test
(tests/test_accuracy_parity.py) asserts the published qualitative ordering

    K=1 upper bound >= federated (FedAvg/consensus) >= standalone-1/K >> chance

on a scaled-down run.

Usage::

    python -m federated_pytorch_test_tpu.drivers.accuracy_comparison \
        [--K 10] [--Nloop 3] [--Nadmm 3] [--batch 64] [--n-train 1024] \
        [--n-test 2048] [--out artifacts/accuracy_comparison.json]
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict, List

import numpy as np

from federated_pytorch_test_tpu.data.cifar10 import FederatedCifar10
from federated_pytorch_test_tpu.models.simple import Net
from federated_pytorch_test_tpu.train import (
    AdmmConsensus,
    BlockwiseFederatedTrainer,
    FedAvg,
    FederatedConfig,
    NoConsensus,
)

_SILENT = lambda m: None


def _curve(history) -> List[float]:
    """Mean-over-clients test accuracy per evaluated round."""
    return [float(np.mean(h["accuracy"])) for h in history
            if "accuracy" in h]


def run_comparison(K: int = 10, Nloop: int = 3, Nadmm: int = 3,
                   batch: int = 64, n_train: int = 1024,
                   n_test: int = 2048, seed: int = 5,
                   synthetic_noise: float = 48.0,
                   synthetic_prototypes: int = 32,
                   log=_SILENT) -> Dict[str, object]:
    """All four runs of the reference comparison; returns curve dict.

    Budget fairness: the standalone runs get Nloop*Nadmm full-net epochs,
    the federated runs get Nloop sweeps x Nadmm rounds x 1 epoch (the
    reference's published configuration shape, federated_multi.py:13-16);
    the K=1 upper bound sees the union of all clients' data (K*n_train).
    """
    total_epochs = Nloop * Nadmm
    results: Dict[str, object] = {
        "config": dict(K=K, Nloop=Nloop, Nadmm=Nadmm, batch=batch,
                       n_train=n_train, n_test=n_test, seed=seed,
                       synthetic_noise=synthetic_noise,
                       synthetic_prototypes=synthetic_prototypes),
    }

    # with one prototype per class the synthetic stand-in saturates at
    # 100% for every run; many prototypes make test accuracy scale with
    # training-sample coverage so the published ordering is non-degenerate
    # (irrelevant when real CIFAR batches are on disk)
    dataK = FederatedCifar10(K=K, batch=batch, limit_per_client=n_train,
                             limit_test=n_test,
                             synthetic_noise=synthetic_noise,
                             synthetic_prototypes=synthetic_prototypes)
    results["data_source"] = dataK.source

    log(f"standalone K={K} ({total_epochs} epochs)")
    cfg = FederatedConfig(K=K, Nepoch=total_epochs, default_batch=batch,
                          check_results=True, seed=seed)
    t = BlockwiseFederatedTrainer(Net(), cfg, dataK, NoConsensus())
    _, hist = t.run_independent(log=_SILENT)
    results["standalone"] = _curve(hist)

    for name, algo, rho in (("fedavg", FedAvg(), 1.0),
                            ("consensus", AdmmConsensus(), 0.1)):
        log(f"{name} K={K} (Nloop={Nloop} Nadmm={Nadmm})")
        cfg = FederatedConfig(K=K, Nloop=Nloop, Nepoch=1, Nadmm=Nadmm,
                              default_batch=batch, check_results=True,
                              admm_rho0=rho, seed=seed)
        t = BlockwiseFederatedTrainer(Net(), cfg, dataK, algo)
        _, hist = t.run(log=_SILENT)
        results[name] = _curve(hist)

    log(f"upper bound K=1 ({total_epochs} epochs, {K * n_train} samples)")
    data1 = FederatedCifar10(K=1, batch=batch,
                             limit_per_client=K * n_train,
                             limit_test=n_test,
                             synthetic_noise=synthetic_noise,
                             synthetic_prototypes=synthetic_prototypes)
    cfg = FederatedConfig(K=1, Nepoch=total_epochs, default_batch=batch,
                          check_results=True, seed=seed)
    t = BlockwiseFederatedTrainer(Net(), cfg, data1, NoConsensus())
    _, hist = t.run_independent(log=_SILENT)
    results["upper_k1"] = _curve(hist)

    results["final"] = {k: results[k][-1] for k in
                        ("standalone", "fedavg", "consensus", "upper_k1")}
    return results


#: fixed color per entity (never re-assigned by rank/order; the palette is
#: a validated 4-slot categorical set — adjacent-pair CVD-safe; the
#: low-contrast yellow slot is relieved by direct end-of-line labels)
_SERIES = (("upper_k1", "#2a78d6", "K=1 upper bound"),
           ("fedavg", "#eb6834", "FedAvg K=10"),
           ("consensus", "#1baf7a", "consensus K=10"),
           ("standalone", "#eda100", "standalone 1/K"))


def write_plot(results: Dict[str, object], path: str) -> None:
    """The repo's analogue of the reference's comparison.png (README.md:28-30):
    test-accuracy curves of the four runs over normalized training budget
    (the runs evaluate at different cadences — standalone per epoch,
    federated per communication round — so the x axis is fraction of run,
    one shared scale, not a dual axis)."""
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(7.2, 4.4), dpi=150)
    fig.patch.set_facecolor("#fcfcfb")
    ax.set_facecolor("#fcfcfb")
    ends = []
    for name, color, label in _SERIES:
        c = results[name]
        x = [100.0 * i / max(len(c) - 1, 1) for i in range(len(c))]
        ax.plot(x, c, color=color, linewidth=2, label=label,
                solid_capstyle="round")
        ends.append([label, float(c[-1])])
    # dodge overlapping end-of-line labels (saturated runs all finish ~100)
    ends.sort(key=lambda e: e[1])
    for prev, cur in zip(ends, ends[1:]):
        cur[1] = max(cur[1], prev[1] + 3.2)
    for label, y in ends:
        ax.annotate(label, (100.0, y), xytext=(6, 0),
                    textcoords="offset points", fontsize=8,
                    color="#52514e", va="center")
    ax.set_xlim(0, 118)                      # headroom for end labels
    ax.set_xlabel("training budget (%)", color="#52514e")
    ax.set_ylabel("test accuracy (%)", color="#52514e")
    ax.set_title("CIFAR10 federated comparison "
                 f"(K={results['config']['K']}, "
                 f"data={results['data_source']})",
                 color="#0b0b0b", fontsize=11)
    ax.grid(True, color="#e4e3df", linewidth=0.6)
    for s in ("top", "right"):
        ax.spines[s].set_visible(False)
    for s in ("left", "bottom"):
        ax.spines[s].set_color("#c3c2b7")
    ax.tick_params(colors="#52514e")
    ax.legend(loc="lower right", fontsize=8, frameon=False,
              labelcolor="#0b0b0b")
    fig.tight_layout()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fig.savefig(path, facecolor=fig.get_facecolor())
    plt.close(fig)


def main(argv=None):
    p = argparse.ArgumentParser(prog="accuracy_comparison",
                                description=__doc__.splitlines()[0])
    p.add_argument("--K", type=int, default=10)
    p.add_argument("--Nloop", type=int, default=3)
    p.add_argument("--Nadmm", type=int, default=3)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--n-train", type=int, default=1024)
    p.add_argument("--n-test", type=int, default=2048)
    p.add_argument("--seed", type=int, default=5)
    p.add_argument("--noise", type=float, default=48.0,
                   help="synthetic-fallback pixel-noise std")
    p.add_argument("--prototypes", type=int, default=32,
                   help="synthetic-fallback templates per class")
    p.add_argument("--out", default="artifacts/accuracy_comparison.json")
    p.add_argument("--plot", nargs="?", const="artifacts/comparison.png",
                   default=None,
                   help="also write the accuracy-curve plot (the reference's "
                        "comparison.png analogue); optional PATH")
    p.add_argument("--replot", metavar="JSON", default=None,
                   help="skip training; plot from an existing results JSON")
    args = p.parse_args(argv)
    if args.replot:
        if args.plot is None:        # --replot's whole point is the plot
            args.plot = "artifacts/comparison.png"
        with open(args.replot) as f:
            res = json.load(f)
    else:
        res = run_comparison(K=args.K, Nloop=args.Nloop, Nadmm=args.Nadmm,
                             batch=args.batch, n_train=args.n_train,
                             n_test=args.n_test, seed=args.seed,
                             synthetic_noise=args.noise,
                             synthetic_prototypes=args.prototypes, log=print)
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(res, f, indent=1)
        print(f"wrote {args.out}")
    if args.plot:
        write_plot(res, args.plot)
        print(f"wrote {args.plot}")
    print(json.dumps(res["final"]))
    return res


if __name__ == "__main__":
    main()
