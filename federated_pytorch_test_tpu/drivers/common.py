"""Shared CLI plumbing for the classifier drivers.

Factors out the ~120-line skeleton the reference duplicates across its six
CIFAR scripts (SURVEY.md "Shared driver skeleton"): flags, data partition,
model choice, common init, engine construction, final checkpoint.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
from typing import Optional

from federated_pytorch_test_tpu.compress import COMPRESS_CHOICES
from federated_pytorch_test_tpu.data.cifar10 import FederatedCifar10
from federated_pytorch_test_tpu.parallel.comm import ROBUST_AGG_CHOICES
from federated_pytorch_test_tpu.models.resnet import ResNet9, ResNet18
from federated_pytorch_test_tpu.models.simple import Net, Net1, Net2
from federated_pytorch_test_tpu.train.algorithms import Algorithm
from federated_pytorch_test_tpu.train.config import FederatedConfig
from federated_pytorch_test_tpu.train.engine import BlockwiseFederatedTrainer
from federated_pytorch_test_tpu.utils.checkpoint import load_checkpoint, save_checkpoint


def build_parser(defaults: FederatedConfig, prog: str) -> argparse.ArgumentParser:
    """Argparse over the FederatedConfig fields, reference knob names kept."""
    p = argparse.ArgumentParser(
        prog=prog,
        description="TPU-native federated CIFAR10 driver "
                    "(reference parity: see module docstring)")
    # converters for Optional[...] fields (default None carries no type)
    _optional_types = {"data_dir": str, "num_devices": int,
                       "profile_dir": str, "obs_dir": str,
                       "compile_cache_dir": str}
    # tri-state booleans: absent -> None (auto), --flag/--no-flag override
    _optional_bools = {"device_data", "donate"}
    for f in dataclasses.fields(FederatedConfig):
        default = getattr(defaults, f.name)
        arg = "--" + f.name.replace("_", "-")
        if f.name in _optional_bools or isinstance(default, bool):
            p.add_argument(arg, action=argparse.BooleanOptionalAction,
                           default=default)
        elif f.name == "optimizer":
            p.add_argument(arg, choices=("adam", "lbfgs"), default=default)
        elif f.name == "norm":
            p.add_argument(arg, choices=("batch", "group"), default=default)
        elif f.name == "compress":
            p.add_argument(arg, choices=COMPRESS_CHOICES, default=default)
        elif f.name == "robust_agg":
            p.add_argument(arg, choices=ROBUST_AGG_CHOICES, default=default)
        elif f.name == "fault_spec":
            p.add_argument(
                arg, type=str, default=default, metavar="SPEC",
                help="fault-injection spec: 'none' or "
                     "drop=P,straggle=P,corrupt=P,mode=nan|inf|signflip|"
                     "scale|innerprod|collude,scale=X,seed=N,clients=i+j,"
                     "delay=P,delay_max=N,join=P,leave=P,preempt=P "
                     "(train/faults.py; delay= drives --async-rounds "
                     "arrival times; join=/leave= drive the membership "
                     "ledger, preempt= simulates mid-run preemption)")
        elif f.name == "campaign_spec":
            p.add_argument(
                arg, type=str, default=default, metavar="SPEC",
                help="soak-campaign schedule (campaign/schedule.py): "
                     "'none' or hours=H,round_minutes=M,diurnal=A,"
                     "drop=P,straggle=P,corrupt=P,mode=...,join=P,"
                     "leave=P,storm=P,storm_len=N,storm_straggle=P,"
                     "burst=P,burst_len=N,burst_corrupt=P,"
                     "preempt_at=H1+H2,seed=N,accel=X,"
                     "health_window_hours=H — compiles diurnal load, "
                     "churn waves, straggler storms, corruption bursts "
                     "and deterministic preemptions onto the seeded "
                     "fault families; mutually exclusive with "
                     "--fault-spec (README 'Soak campaigns')")
        elif f.name == "model":
            p.add_argument(arg, choices=MODEL_CHOICES, default=default)
        elif f.name == "health_action":
            from federated_pytorch_test_tpu.obs.health import HEALTH_ACTIONS
            p.add_argument(
                arg, choices=HEALTH_ACTIONS, default=default,
                help="streaming watchdog response (obs/health.py): warn "
                     "emits alert records, abort raises RunHealthAbort, "
                     "checkpoint-abort saves+verifies a final checkpoint "
                     "first (default: warn)")
        elif f.name == "control":
            from federated_pytorch_test_tpu.control.policy import (
                CONTROL_MODES,
            )
            p.add_argument(
                arg, choices=CONTROL_MODES, default=default,
                help="closed-loop control plane (control/): observe "
                     "records deterministic intervention decisions, act "
                     "applies them; replay with python -m "
                     "federated_pytorch_test_tpu.control.replay "
                     "(default: off — bit-identical to no controller)")
        elif f.name == "control_policy":
            from federated_pytorch_test_tpu.control.policy import (
                CONTROL_POLICIES,
            )
            p.add_argument(
                arg, choices=CONTROL_POLICIES, default=default,
                help="hysteresis preset for --control decisions "
                     "(control/policy.py; default: default)")
        elif f.name == "cohort_sampling":
            from federated_pytorch_test_tpu.population import (
                SAMPLER_CHOICES,
            )
            p.add_argument(
                arg, choices=SAMPLER_CHOICES, default=default,
                help="population cohort sampler (population/sampler.py): "
                     "uniform, weighted (seeded static availability "
                     "weights) or stratified (one id per contiguous "
                     "stratum); only meaningful with --population > 0 "
                     "(default: uniform)")
        elif f.name == "compile_cache_dir":
            p.add_argument(
                arg, type=str, default=default, metavar="DIR",
                help="persistent XLA compile-cache dir "
                     "(utils/compile_cache.py); default: auto "
                     "(FEDTPU_COMPILE_CACHE_DIR env, else tests/.jax_cache)"
                     "; the literal 'none' disables the cache")
        elif default is None:
            conv = _optional_types.get(f.name)
            if conv is None:
                raise TypeError(
                    f"FederatedConfig.{f.name} has default None; add its "
                    "converter to _optional_types in drivers/common.py")
            p.add_argument(arg, type=conv, default=None)
        else:
            p.add_argument(arg, type=type(default), default=default)
    # data-size overrides for smoke runs (not in the reference)
    p.add_argument("--n-train", type=int, default=None,
                   help="cap samples per client (smoke tests)")
    p.add_argument("--n-test", type=int, default=None,
                   help="cap test-set size (smoke tests)")
    return p


def config_from_args(args: argparse.Namespace) -> FederatedConfig:
    kw = {f.name: getattr(args, f.name) for f in dataclasses.fields(FederatedConfig)}
    return FederatedConfig(**kw)


def default_obs_dir(cfg: FederatedConfig) -> FederatedConfig:
    """Driver-entry observability default: file telemetry ON.

    A driver run with no ``--obs-dir`` writes its JSONL under
    ``<checkpoint_dir>/obs`` (``--obs-sinks none`` opts out); bare
    engine-API callers (unit tests) keep the file-free ``auto``+None
    behaviour.  Summarise with
    ``python -m federated_pytorch_test_tpu.obs.report <file>``.
    """
    if cfg.obs_dir is None and cfg.obs_sinks == "auto":
        cfg = dataclasses.replace(
            cfg, obs_dir=os.path.join(cfg.checkpoint_dir, "obs"))
    return cfg


def setup_runtime(cfg: FederatedConfig) -> None:
    """One driver-entry chokepoint, called before the first device query:
    enable the shared persistent compile cache (TPU compiles of the
    per-block epoch dominate cold runs), join the multi-host runtime when
    requested, and honor the ``use_tpu`` platform gate (``apply_platform``).
    Every CLI main routes through here (the CPC main passes its argparse
    namespace — only ``.use_tpu`` is read)."""
    from federated_pytorch_test_tpu.utils.compile_cache import (
        enable_persistent_compile_cache,
    )

    enable_persistent_compile_cache(getattr(cfg, "compile_cache_dir", None))
    apply_platform(cfg)


def apply_platform(cfg: FederatedConfig) -> None:
    """Honor ``use_tpu`` (the reference's ``use_cuda`` gate,
    federated_multi.py:32): when False, run on the host CPU platform.
    Must be called before the first JAX device query; if the backend is
    already initialized on a non-CPU platform, warns instead of failing.

    Also joins the multi-host runtime first when ``FEDTPU_DISTRIBUTED=1``
    (parallel/mesh.py:initialize_multihost).  Drivers reach this via
    ``setup_runtime``.
    """
    from federated_pytorch_test_tpu.parallel.mesh import initialize_multihost

    initialize_multihost()
    if cfg.use_tpu:
        return
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError as e:                     # backend already up
        import warnings
        warnings.warn(f"--no-use-tpu requested but the JAX backend is "
                      f"already initialized ({e}); continuing on the "
                      "existing platform")


# the single model registry: argparse choices and pick_model both derive
# from it, so the two cannot drift
_MODELS = {"net": Net, "net1": Net1, "net2": Net2,
           "resnet9": ResNet9, "resnet18": ResNet18}
MODEL_CHOICES = ("auto",) + tuple(_MODELS)


def pick_model(cfg: FederatedConfig):
    """Classifier model from cfg.model (the reference's source-edit model
    switch, federated_multi.py:92-97, as a flag); "auto" keeps the
    use_resnet semantics."""
    import jax.numpy as jnp

    dtype = jnp.bfloat16 if cfg.bf16 else None
    name = cfg.model
    if name == "auto":
        name = "resnet18" if cfg.use_resnet else "net"
    if name not in _MODELS:
        raise ValueError(f"unknown model {name!r}; "
                         f"expected one of {MODEL_CHOICES}")
    if name.startswith("resnet"):
        return _MODELS[name](dtype=dtype, norm=cfg.norm)
    return _MODELS[name](dtype=dtype)


def make_trainer(cfg: FederatedConfig, algorithm: Algorithm,
                 n_train: Optional[int] = None,
                 n_test: Optional[int] = None) -> BlockwiseFederatedTrainer:
    model = pick_model(cfg)
    data = FederatedCifar10(
        K=cfg.K, batch=cfg.default_batch, biased_input=cfg.biased_input,
        drop_last_sample=cfg.drop_last_sample, data_dir=cfg.data_dir,
        limit_per_client=n_train, limit_test=n_test)
    return BlockwiseFederatedTrainer(model, cfg, data, algorithm)


def checkpoint_path(cfg: FederatedConfig, name: str) -> str:
    return os.path.join(cfg.checkpoint_dir, name)


def finish(trainer: BlockwiseFederatedTrainer, state, name: str, history):
    """Save the end-of-run checkpoint (reference federated_multi.py:226-233).

    Saves the optimizer state of the final block alongside the model, as the
    reference does (:231 stores optimizer.state_dict()); like the reference,
    ``maybe_load`` restores model variables only (:99-103)."""
    cfg = trainer.cfg
    if cfg.save_model:
        meta = {"rounds": len(history)}
        opt_state = state.opt_state if state.opt_state is not None else ()
        save_checkpoint(checkpoint_path(cfg, name),
                        state._asdict() | {"opt_state": opt_state}, meta)
        print(f"saved checkpoint -> {checkpoint_path(cfg, name)}")


def maybe_load(trainer: BlockwiseFederatedTrainer, name: str):
    """Resume model params if --load-model (reference :99-103 restores model
    state only; we restore params + batch_stats)."""
    cfg = trainer.cfg
    state = trainer.init_state()
    path = checkpoint_path(cfg, name)
    if cfg.load_model and os.path.isdir(os.path.abspath(os.path.expanduser(path))):
        restored, meta = load_checkpoint(path, like=None)
        from federated_pytorch_test_tpu.parallel.mesh import (
            client_sharding,
            stage_tree_global,
        )
        csh = client_sharding(trainer.mesh)
        state = state._replace(
            params=stage_tree_global(restored["params"], csh),
            batch_stats=stage_tree_global(restored["batch_stats"], csh))
        rounds_prior = int(meta.get("rounds", 0)) if meta else 0
        print(f"loaded checkpoint <- {path} (rounds={rounds_prior})")
    return state


def print_obs_artifact(trainer) -> None:
    """Point the operator at the run's JSONL telemetry (if any)."""
    rec = getattr(trainer, "obs_recorder", None)
    if rec is not None and rec.jsonl_path:
        print(f"obs artifact -> {rec.jsonl_path} "
              f"(python -m federated_pytorch_test_tpu.obs.report "
              f"{rec.jsonl_path})")


def run_classifier_driver(prog: str, defaults: FederatedConfig,
                          algorithm: Algorithm, independent: bool = False,
                          argv=None):
    args = build_parser(defaults, prog).parse_args(argv)
    cfg = default_obs_dir(config_from_args(args))
    setup_runtime(cfg)
    trainer = make_trainer(cfg, algorithm, args.n_train, args.n_test)
    trainer.obs_run_name = prog
    mname = type(trainer.model).__name__
    if mname == "ResNet":
        mname = f"ResNet{trainer.model.qualifier}"
    print(f"{prog}: K={cfg.K} model={mname} "
          f"devices={trainer.D} clients/device={trainer.K_local} "
          f"data={trainer.data.source}")
    state = maybe_load(trainer, prog)
    if independent:
        state, history = trainer.run_independent(state)
    else:
        supervised = cfg.max_restarts > 0
        campaign = getattr(cfg, "campaign_spec", "none") not in (
            "none", "", None)
        # supervision is resume-from-checkpoint: a restart budget (or a
        # campaign, whose deterministic preemptions need a resume point)
        # forces the mid-run checkpoint on even without
        # --midrun-checkpoint
        ck = (checkpoint_path(cfg, prog + "_midrun")
              if (cfg.midrun_checkpoint or supervised or campaign)
              else None)
        if supervised or campaign:
            def build_trainer(c, attempt):
                nonlocal trainer
                if attempt > 1:
                    # the failed attempt's trainer is closed (staging
                    # pool shut down); rebuild on the (possibly
                    # ladder-degraded) config
                    trainer = make_trainer(c, algorithm,
                                           args.n_train, args.n_test)
                    trainer.obs_run_name = prog
                return trainer

            if campaign:
                from federated_pytorch_test_tpu.campaign.harness import (
                    run_soak,
                )

                (state, history), clock = run_soak(
                    build_trainer, cfg, ck, state=state,
                    resume=cfg.load_model, run_name=prog)
                print(f"soak campaign done: {clock!r}")
            else:
                from federated_pytorch_test_tpu.control.supervisor import (
                    supervise_classifier,
                )

                state, history = supervise_classifier(
                    build_trainer, cfg, ck, state=state,
                    resume=cfg.load_model)
        else:
            state, history = trainer.run(
                state, checkpoint_path=ck,
                resume=cfg.load_model and ck is not None)
    print("Finished Training")
    print_obs_artifact(trainer)
    finish(trainer, state, prog, history)
    return state, history
