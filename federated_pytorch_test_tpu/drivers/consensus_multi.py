"""ADMM consensus with optional Barzilai-Borwein adaptive rho.

Reference: consensus_multi.py (K=10, Nloop=12, Nepoch=1, Nadmm=5,
admm_rho0=0.1, bb_update=False default, biased_input=True).  Clients are
never reset to z — consensus only via the augmented-Lagrangian penalty.
"""

from federated_pytorch_test_tpu.drivers.common import run_classifier_driver
from federated_pytorch_test_tpu.train.algorithms import AdmmConsensus
from federated_pytorch_test_tpu.train.config import FederatedConfig

DEFAULTS = FederatedConfig(K=10, Nloop=12, Nepoch=1, Nadmm=5,
                           admm_rho0=0.1, biased_input=True)


def main(argv=None):
    return run_classifier_driver("consensus_multi", DEFAULTS, AdmmConsensus(),
                                 argv=argv)


if __name__ == "__main__":
    main()
