"""Federated CPC on LOFAR visibilities (arXiv:1905.09272).

Reference: federated_cpc.py (K=4 clients <-> (H5 file, SAP) pairs, Lc=256,
Rc=32, batch_size=128, Nloop=1, Niter=10, Nadmm=1, LBFGSNew(history 7,
max_iter 2, batch_mode)).  Files that are absent (the LOFAR extracts are not
redistributable) fall back to deterministic synthetic visibility cubes keyed
on (file, SAP) — see data/lofar.py.

Checkpoints: one orbax directory holding all three sub-models' stacked
client pytrees (the reference writes encoder<k>.model etc. per client but
LOADS from unsuffixed names — a quirk we fix, federated_cpc.py:126-134 vs
:308-318).
"""

import argparse
import os

from federated_pytorch_test_tpu.data.lofar import CPCDataSource
from federated_pytorch_test_tpu.train.cpc_engine import CPCTrainer
from federated_pytorch_test_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

DEFAULT_FILES = ["L785751.MS_extract.h5", "L785751.MS_extract.h5",
                 "L785747.MS_extract.h5", "L785757.MS_extract.h5"]
DEFAULT_SAPS = ["1", "2", "0", "0"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="federated_cpc",
        description="TPU-native federated CPC on LOFAR visibilities")
    p.add_argument("--file-list", nargs="+", default=DEFAULT_FILES)
    p.add_argument("--sap-list", nargs="+", default=DEFAULT_SAPS)
    p.add_argument("--Lc", type=int, default=256)
    p.add_argument("--Rc", type=int, default=32)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--patch-size", type=int, default=32)
    p.add_argument("--Nloop", type=int, default=1)
    p.add_argument("--Niter", type=int, default=10)
    p.add_argument("--Nadmm", type=int, default=1)
    p.add_argument("--seed", type=int, default=69)
    p.add_argument("--load-model", action=argparse.BooleanOptionalAction,
                   default=False)
    p.add_argument("--save-model", action=argparse.BooleanOptionalAction,
                   default=True)
    p.add_argument("--use-tpu", action=argparse.BooleanOptionalAction,
                   default=True)
    p.add_argument("--checkpoint-dir", default="./checkpoints")
    p.add_argument("--profile-dir", default=None,
                   help="write a jax.profiler (XProf) trace of the run")
    p.add_argument("--obs-dir", default=None,
                   help="directory for observability artifacts (default: "
                        "<checkpoint-dir>/obs)")
    p.add_argument("--obs-sinks", default="auto",
                   help="comma-separated obs sinks "
                        "(auto|none|jsonl|csv|stdout|memory)")
    from federated_pytorch_test_tpu.obs.health import HEALTH_ACTIONS
    p.add_argument("--health-action", choices=HEALTH_ACTIONS,
                   default="warn",
                   help="streaming watchdog response (obs/health.py): "
                        "warn emits alert records, abort raises "
                        "RunHealthAbort, checkpoint-abort verifies a "
                        "final checkpoint first (default: warn)")
    p.add_argument("--num-devices", type=int, default=None,
                   help="mesh size (default: as many devices as divide K)")
    p.add_argument("--midrun-checkpoint",
                   action=argparse.BooleanOptionalAction, default=False,
                   help="save a resumable checkpoint every comm round; "
                        "resume with --load-model")
    p.add_argument("--async-checkpoint",
                   action=argparse.BooleanOptionalAction, default=False,
                   help="write mid-run checkpoints from a background "
                        "thread (host snapshot first, so it is donation-"
                        "safe); same on-disk slot format")
    p.add_argument("--donate", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="donate the round fn's state/z/opt buffers to XLA "
                        "(default: auto — on for TPU/GPU, off on CPU); "
                        "bit-identical either way")
    p.add_argument("--sanitize", action=argparse.BooleanOptionalAction,
                   default=False,
                   help="run the jitted CPC round under "
                        "jax.experimental.checkify (NaN/inf + index "
                        "checks; debugging mode, adds a per-round sync)")
    p.add_argument("--retrace-sentinel",
                   action=argparse.BooleanOptionalAction, default=False,
                   help="count jit retraces of the round step and emit "
                        "jit_retraces in the obs round records")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)

    from federated_pytorch_test_tpu.drivers.common import setup_runtime

    setup_runtime(args)                  # duck-typed: needs .use_tpu only
    if args.use_tpu and args.Lc > 64:
        import sys

        print(
            f"federated_cpc: WARNING — Lc={args.Lc} on the TPU backend can "
            "trigger a pathological XLA compile of the jitted CPC round "
            "(observed >20 min at Lc=256; README 'Known issues'); Lc<=64 "
            "compiles in seconds", file=sys.stderr)
    data = CPCDataSource(args.file_list, args.sap_list,
                         batch_size=args.batch_size,
                         patch_size=args.patch_size, seed=args.seed)
    trainer = CPCTrainer(data, latent_dim=args.Lc, reduced_dim=args.Rc,
                         Niter=args.Niter, num_devices=args.num_devices,
                         sanitize=args.sanitize,
                         retrace_sentinel=args.retrace_sentinel,
                         donate=args.donate)
    print(f"federated_cpc: K={data.K} Lc={args.Lc} Rc={args.Rc} "
          f"devices={trainer.D}")
    state = trainer.state0
    ckpt = os.path.join(args.checkpoint_dir, "federated_cpc")
    if args.load_model and os.path.isdir(os.path.abspath(
            os.path.expanduser(ckpt))):
        restored, _ = load_checkpoint(ckpt)
        from federated_pytorch_test_tpu.parallel.mesh import (
            client_sharding,
            stage_tree_global,
        )
        csh = client_sharding(trainer.mesh)
        state = type(state)(**{k: stage_tree_global(restored[k], csh)
                               for k in restored})
        print(f"loaded checkpoint <- {ckpt}")
    midrun = (os.path.join(args.checkpoint_dir, "federated_cpc_midrun")
              if args.midrun_checkpoint else None)
    # same driver-entry default as the classifier drivers
    # (common.default_obs_dir): file telemetry on unless opted out
    obs_dir = args.obs_dir
    if obs_dir is None and args.obs_sinks == "auto":
        obs_dir = os.path.join(args.checkpoint_dir, "obs")
    state, history = trainer.run(Nloop=args.Nloop, Nadmm=args.Nadmm,
                                 state=state, profile_dir=args.profile_dir,
                                 checkpoint_path=midrun,
                                 resume=args.load_model and midrun is not None,
                                 async_checkpoint=args.async_checkpoint,
                                 obs_dir=obs_dir, obs_sinks=args.obs_sinks,
                                 obs_run_name="federated_cpc",
                                 health_action=args.health_action)
    print("Finished Training")
    from federated_pytorch_test_tpu.drivers.common import print_obs_artifact
    print_obs_artifact(trainer)
    if args.save_model:
        save_checkpoint(ckpt, state._asdict(), meta={"rounds": len(history)})
        print(f"saved checkpoint -> {ckpt}")
    return state, history


if __name__ == "__main__":
    main()
