"""Federated CPC on LOFAR visibilities (arXiv:1905.09272).

Reference: federated_cpc.py (K=4 clients <-> (H5 file, SAP) pairs, Lc=256,
Rc=32, batch_size=128, Nloop=1, Niter=10, Nadmm=1, LBFGSNew(history 7,
max_iter 2, batch_mode)).  Files that are absent (the LOFAR extracts are not
redistributable) fall back to deterministic synthetic visibility cubes keyed
on (file, SAP) — see data/lofar.py.

The CLI is the shared classifier surface (drivers/common.build_parser —
every FederatedConfig field is a flag, so ``--fault-spec``,
``--update-guard``, ``--robust-agg``, ``--async-rounds``,
``--max-restarts`` etc. work here exactly as on the classifier drivers)
plus the CPC-specific data/model knobs below.  Flags the CPC engine
cannot honour (``--compress``, ``--fused-collective``,
``--sharded-update``, ``--bb-update``) fail fast with the constructor's
ValueError rather than being silently ignored.

Checkpoints: one orbax directory holding all three sub-models' stacked
client pytrees (the reference writes encoder<k>.model etc. per client but
LOADS from unsuffixed names — a quirk we fix, federated_cpc.py:126-134 vs
:308-318).
"""

import argparse
import os

from federated_pytorch_test_tpu.data.lofar import CPCDataSource
from federated_pytorch_test_tpu.drivers import common
from federated_pytorch_test_tpu.train.cpc_engine import CPCTrainer
from federated_pytorch_test_tpu.train.config import FederatedConfig
from federated_pytorch_test_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

DEFAULT_FILES = ["L785751.MS_extract.h5", "L785751.MS_extract.h5",
                 "L785747.MS_extract.h5", "L785757.MS_extract.h5"]
DEFAULT_SAPS = ["1", "2", "0", "0"]

#: reference defaults (federated_cpc.py argparse block): K comes from the
#: file list, one outer loop, one ADMM step per block, midrun off.
DEFAULTS = FederatedConfig(K=4, Nloop=1, Nadmm=1, midrun_checkpoint=False,
                           check_results=False)


def build_parser() -> argparse.ArgumentParser:
    p = common.build_parser(DEFAULTS, "federated_cpc")
    p.description = "TPU-native federated CPC on LOFAR visibilities"
    # CPC-specific knobs (none are FederatedConfig fields, so no clash
    # with the generated flag surface)
    p.add_argument("--file-list", nargs="+", default=DEFAULT_FILES)
    p.add_argument("--sap-list", nargs="+", default=DEFAULT_SAPS)
    p.add_argument("--Lc", type=int, default=256,
                   help="CPC latent dimension (reference Lc)")
    p.add_argument("--Rc", type=int, default=32,
                   help="reduced/context dimension (reference Rc)")
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--patch-size", type=int, default=32)
    p.add_argument("--Niter", type=int, default=10,
                   help="LBFGS data batches per client per round")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    cfg = common.default_obs_dir(common.config_from_args(args))
    common.setup_runtime(cfg)
    if cfg.use_tpu and args.Lc > 64:
        import sys

        print(
            f"federated_cpc: WARNING — Lc={args.Lc} on the TPU backend can "
            "trigger a pathological XLA compile of the jitted CPC round "
            "(observed >20 min at Lc=256; README 'Known issues'); Lc<=64 "
            "compiles in seconds", file=sys.stderr)
    data = CPCDataSource(args.file_list, args.sap_list,
                         batch_size=args.batch_size,
                         patch_size=args.patch_size, seed=cfg.seed)

    def make_trainer(c):
        return CPCTrainer(data, latent_dim=args.Lc, reduced_dim=args.Rc,
                          Niter=args.Niter, cfg=c)

    trainer = make_trainer(cfg)
    print(f"federated_cpc: K={data.K} Lc={args.Lc} Rc={args.Rc} "
          f"devices={trainer.D}")
    state = trainer.state0
    ckpt = common.checkpoint_path(cfg, "federated_cpc")
    if cfg.load_model and os.path.isdir(os.path.abspath(
            os.path.expanduser(ckpt))):
        restored, _ = load_checkpoint(ckpt)
        from federated_pytorch_test_tpu.parallel.mesh import (
            client_sharding,
            stage_tree_global,
        )
        csh = client_sharding(trainer.mesh)
        state = type(state)(**{k: stage_tree_global(restored[k], csh)
                               for k in restored})
        print(f"loaded checkpoint <- {ckpt}")
    supervised = cfg.max_restarts > 0
    # supervision is resume-from-checkpoint: a restart budget forces the
    # mid-run checkpoint on even without --midrun-checkpoint
    midrun = (common.checkpoint_path(cfg, "federated_cpc_midrun")
              if (cfg.midrun_checkpoint or supervised) else None)
    run_kwargs = dict(
        Nloop=cfg.Nloop, Nadmm=cfg.Nadmm, profile_dir=cfg.profile_dir,
        checkpoint_path=midrun, async_checkpoint=cfg.async_checkpoint,
        obs_dir=cfg.obs_dir, obs_sinks=cfg.obs_sinks,
        obs_run_name="federated_cpc", health_action=cfg.health_action)
    if supervised:
        from federated_pytorch_test_tpu.control.supervisor import (
            ladder_overrides,
            ladder_records,
            supervise,
        )

        box = {"trainer": trainer}

        def run_attempt(attempt, resume_now):
            if attempt > 1:
                # CPC's run takes no externally-built state, so a fresh
                # attempt rebuilds the trainer on the (possibly
                # ladder-degraded) config and resumes from the midrun
                # slot; engine="cpc" keeps the ladder within what
                # CPCTrainer can construct (no compression path)
                _, degraded, _ = ladder_overrides(cfg, attempt - 1,
                                                  engine="cpc")
                box["trainer"] = make_trainer(degraded)
            t = box["trainer"]
            st = state if attempt == 1 else t.state0
            return t.run(state=st,
                         resume=cfg.load_model or resume_now,
                         **run_kwargs)

        def describe(attempt, exc=None):
            rec = getattr(box["trainer"], "obs_recorder", None)
            jsonl_path = getattr(rec, "jsonl_path", None)
            run_id = getattr(rec, "run_id", "") or ""
            ridx = getattr(rec, "_last_index", -1)
            if not isinstance(ridx, int):
                ridx = -1
            extra = []
            if attempt <= max(0, cfg.max_restarts):
                extra = ladder_records(cfg, attempt, run_id=run_id,
                                       ridx=ridx, engine="cpc")
            return jsonl_path, run_id, extra

        state, history = supervise(
            run_attempt, max_restarts=cfg.max_restarts,
            backoff_base=cfg.restart_backoff, seed=cfg.seed,
            describe=describe)
        trainer = box["trainer"]
    else:
        state, history = trainer.run(
            state=state, resume=cfg.load_model and midrun is not None,
            **run_kwargs)
    print("Finished Training")
    common.print_obs_artifact(trainer)
    if cfg.save_model:
        save_checkpoint(ckpt, state._asdict(), meta={"rounds": len(history)})
        print(f"saved checkpoint -> {ckpt}")
    return state, history


if __name__ == "__main__":
    main()
