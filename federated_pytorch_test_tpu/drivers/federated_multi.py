"""Blockwise FedAvg: average the active block, write z back to every client.

Reference: federated_multi.py (K=10, Nloop=12, Nepoch=1, Nadmm=3,
lambda1=lambda2=1e-4, Adam lr=1e-3, biased_input=True).
"""

from federated_pytorch_test_tpu.drivers.common import run_classifier_driver
from federated_pytorch_test_tpu.train.algorithms import FedAvg
from federated_pytorch_test_tpu.train.config import FederatedConfig

DEFAULTS = FederatedConfig(K=10, Nloop=12, Nepoch=1, Nadmm=3,
                           biased_input=True)


def main(argv=None):
    return run_classifier_driver("federated_multi", DEFAULTS, FedAvg(),
                                 argv=argv)


if __name__ == "__main__":
    main()
