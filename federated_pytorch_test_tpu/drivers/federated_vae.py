"""Federated VAE: layer-wise FedAvg on AutoEncoderCNN.

Reference: federated_vae.py (K=10, Nloop=12, Nepoch=1, Nadmm=3, Adam lr=1e-3,
biased_input=True, z written back every round).
"""

from federated_pytorch_test_tpu.data.cifar10 import FederatedCifar10
from federated_pytorch_test_tpu.drivers import common
from federated_pytorch_test_tpu.models.vae import AutoEncoderCNN
from federated_pytorch_test_tpu.train.algorithms import FedAvg
from federated_pytorch_test_tpu.train.config import FederatedConfig
from federated_pytorch_test_tpu.train.vae_engine import VAETrainer

DEFAULTS = FederatedConfig(K=10, Nloop=12, Nepoch=1, Nadmm=3,
                           biased_input=True, check_results=False)


def main(argv=None):
    args = common.build_parser(DEFAULTS, "federated_vae").parse_args(argv)
    cfg = common.default_obs_dir(common.config_from_args(args))
    common.setup_runtime(cfg)
    data = FederatedCifar10(
        K=cfg.K, batch=cfg.default_batch, biased_input=cfg.biased_input,
        drop_last_sample=cfg.drop_last_sample, data_dir=cfg.data_dir,
        limit_per_client=args.n_train, limit_test=args.n_test)
    trainer = VAETrainer(AutoEncoderCNN(), cfg, data, FedAvg())
    trainer.obs_run_name = "federated_vae"
    print(f"federated_vae: K={cfg.K} devices={trainer.D} data={data.source}")
    state = common.maybe_load(trainer, "federated_vae")
    supervised = cfg.max_restarts > 0
    # supervision is resume-from-checkpoint: a restart budget forces the
    # mid-run checkpoint on even without --midrun-checkpoint
    ck = (common.checkpoint_path(cfg, "federated_vae_midrun")
          if (cfg.midrun_checkpoint or supervised) else None)
    if supervised:
        from federated_pytorch_test_tpu.control.supervisor import (
            supervise_classifier,
        )

        def build_trainer(c, attempt):
            nonlocal trainer
            if attempt > 1:
                # the failed attempt's trainer is closed (staging pool
                # shut down); rebuild on the ladder-degraded config —
                # engine="vae" keeps the ladder within what VAETrainer
                # can construct
                trainer = VAETrainer(AutoEncoderCNN(), c, data, FedAvg())
                trainer.obs_run_name = "federated_vae"
            return trainer

        state, history = supervise_classifier(
            build_trainer, cfg, ck, state=state,
            resume=cfg.load_model, engine="vae")
    else:
        state, history = trainer.run(state, checkpoint_path=ck,
                                     resume=cfg.load_model and ck is not None)
    print("Finished Training")
    common.print_obs_artifact(trainer)
    common.finish(trainer, state, "federated_vae", history)
    return state, history


if __name__ == "__main__":
    main()
