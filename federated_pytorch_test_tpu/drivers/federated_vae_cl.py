"""Federated clustering VAE (arXiv:2005.04613).

Reference: federated_vae_cl.py (K=1 default, Kc=10 clusters, Lc=32 latent,
Nloop=12, Nepoch=1, Nadmm=3, lambda2=1e-3, 3-block sweep with per-block
Adam/LBFGS switching, z written back).
"""

from federated_pytorch_test_tpu.data.cifar10 import FederatedCifar10
from federated_pytorch_test_tpu.drivers import common
from federated_pytorch_test_tpu.models.vae_cl import AutoEncoderCNNCL
from federated_pytorch_test_tpu.train.algorithms import FedAvg
from federated_pytorch_test_tpu.train.config import FederatedConfig
from federated_pytorch_test_tpu.train.vae_engine import VAECLTrainer

DEFAULTS = FederatedConfig(K=1, Nloop=12, Nepoch=1, Nadmm=3,
                           lambda2=1e-3, biased_input=False,
                           check_results=False,
                           lbfgs_history_size=10, lbfgs_max_iter=4)


def main(argv=None):
    p = common.build_parser(DEFAULTS, "federated_vae_cl")
    p.add_argument("--Kc", type=int, default=10,
                   help="number of clusters (federated_vae_cl.py:22)")
    p.add_argument("--Lc", type=int, default=32,
                   help="latent dimension (federated_vae_cl.py:23)")
    args = p.parse_args(argv)
    cfg = common.default_obs_dir(common.config_from_args(args))
    common.setup_runtime(cfg)
    data = FederatedCifar10(
        K=cfg.K, batch=cfg.default_batch, biased_input=cfg.biased_input,
        drop_last_sample=cfg.drop_last_sample, data_dir=cfg.data_dir,
        limit_per_client=args.n_train, limit_test=args.n_test)
    model = AutoEncoderCNNCL(K=args.Kc, L=args.Lc)
    trainer = VAECLTrainer(model, cfg, data, FedAvg())
    trainer.obs_run_name = "federated_vae_cl"
    print(f"federated_vae_cl: K={cfg.K} Kc={args.Kc} Lc={args.Lc} "
          f"devices={trainer.D} data={data.source}")
    state = common.maybe_load(trainer, "federated_vae_cl")
    ck = (common.checkpoint_path(cfg, "federated_vae_cl_midrun")
          if cfg.midrun_checkpoint else None)
    state, history = trainer.run(state, checkpoint_path=ck,
                                 resume=cfg.load_model and ck is not None)
    print("Finished Training")
    common.print_obs_artifact(trainer)
    common.finish(trainer, state, "federated_vae_cl", history)
    return state, history


if __name__ == "__main__":
    main()
