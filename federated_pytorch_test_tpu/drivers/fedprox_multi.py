"""FedProx: proximal term (rho/2)||x-z||^2 in the local loss; z never
written back (the reference's comment "master will send z to all slaves"
is aspirational — no put_trainable_values exists, fedprox_multi.py:227).

Reference: fedprox_multi.py (K=10, Nloop=12, Nepoch=1, Nadmm=5,
admm_rho0=1.0 — the FedProx 'mu', biased_input=True).
"""

from federated_pytorch_test_tpu.drivers.common import run_classifier_driver
from federated_pytorch_test_tpu.train.algorithms import FedProx
from federated_pytorch_test_tpu.train.config import FederatedConfig

DEFAULTS = FederatedConfig(K=10, Nloop=12, Nepoch=1, Nadmm=5,
                           admm_rho0=1.0, biased_input=True)


def main(argv=None):
    return run_classifier_driver("fedprox_multi", DEFAULTS, FedProx(),
                                 argv=argv)


if __name__ == "__main__":
    main()
