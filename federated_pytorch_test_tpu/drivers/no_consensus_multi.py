"""Baseline: K independent models, no parameter exchange ever.

Reference: no_consensus_multi.py (K=10, Nepoch=20, Adam lr=1e-3, Adam
re-created per epoch, full net trainable, biased_input=True).
"""

from federated_pytorch_test_tpu.drivers.common import run_classifier_driver
from federated_pytorch_test_tpu.train.algorithms import NoConsensus
from federated_pytorch_test_tpu.train.config import FederatedConfig

DEFAULTS = FederatedConfig(K=10, Nepoch=20, biased_input=True)


def main(argv=None):
    return run_classifier_driver("no_consensus_multi", DEFAULTS,
                                 NoConsensus(), independent=True, argv=argv)


if __name__ == "__main__":
    main()
