from federated_pytorch_test_tpu.models.base import BlockModule, to_plain_dict  # noqa: F401
from federated_pytorch_test_tpu.models.simple import Net, Net1, Net2  # noqa: F401
from federated_pytorch_test_tpu.models.resnet import (  # noqa: F401
    BasicBlock,
    Bottleneck,
    ResNet,
    ResNet9,
    ResNet18,
)
from federated_pytorch_test_tpu.models.vae import AutoEncoderCNN  # noqa: F401
from federated_pytorch_test_tpu.models.vae_cl import AutoEncoderCNNCL  # noqa: F401
from federated_pytorch_test_tpu.models.cpc import (  # noqa: F401
    ContextgenCNN,
    EncoderCNN,
    PredictorCNN,
)

MODEL_REGISTRY = {
    "net": Net,
    "net1": Net1,
    "net2": Net2,
    "resnet9": ResNet9,
    "resnet18": ResNet18,
    "vae": AutoEncoderCNN,
    "vae_cl": AutoEncoderCNNCL,
    "cpc_encoder": EncoderCNN,
    "cpc_contextgen": ContextgenCNN,
    "cpc_predictor": PredictorCNN,
}


def get_model(name: str, **kwargs):
    return MODEL_REGISTRY[name](**kwargs)
