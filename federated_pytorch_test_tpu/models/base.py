"""Common interface for blockwise-federated models.

Every model publishes:

  * ``param_order()`` — parameter paths in the reference's torch
    ``net.parameters()`` definition order (weight and bias are separate
    entries), the coordinate system for block ids;
  * ``train_order_block_ids()`` — the hand-specified partition of that flat
    enumeration into training blocks, copied semantically from the reference
    (e.g. simple_models.py:38-39 for Net, :222-226 for ResNet);
  * ``linear_layer_ids()`` — parameter-enumeration indices of the fc weight
    entries (simple_models.py:29-30).  NOTE the reference quirk: drivers test
    ``ci in linear_layer_ids()`` where ``ci`` is the *block* index
    (federated_multi.py:183), a unit confusion — e.g. for Net only block 4
    (fc3) ever gets L1+L2 regularisation.  We reproduce that condition
    verbatim for parity.
"""

from __future__ import annotations

from typing import Any, Dict, List

import flax.linen as nn
import jax
import jax.numpy as jnp


class BlockModule(nn.Module):
    """Flax module with blockwise-federation metadata."""

    def param_order(self) -> List[str]:  # pragma: no cover - abstract
        raise NotImplementedError

    def train_order_block_ids(self) -> List[List[int]]:  # pragma: no cover
        raise NotImplementedError

    def linear_layer_ids(self) -> List[int]:
        return []

    # -- convenience -----------------------------------------------------
    def init_variables(self, rng: jax.Array, *sample_args, **call_kwargs):
        """Initialise and split into (params, batch_stats)."""
        variables = self.init(rng, *sample_args, **call_kwargs)
        params = variables.get("params", {})
        batch_stats = variables.get("batch_stats", {})
        return to_plain_dict(params), to_plain_dict(batch_stats)


def to_plain_dict(tree) -> Dict[str, Any]:
    """Unfreeze nested flax collections into plain nested dicts."""
    if hasattr(tree, "items"):
        return {k: to_plain_dict(v) for k, v in tree.items()}
    return tree


def pairs(*names: str) -> List[str]:
    """Expand module names into kernel/bias path pairs (torch w,b order)."""
    out: List[str] = []
    for n in names:
        out.append(f"{n}/kernel")
        out.append(f"{n}/bias")
    return out


elu = jax.nn.elu


def max_pool_2x2(x: jnp.ndarray) -> jnp.ndarray:
    return nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))


def flatten(x: jnp.ndarray) -> jnp.ndarray:
    return x.reshape((x.shape[0], -1))
