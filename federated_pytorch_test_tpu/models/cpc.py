"""CPC models for LOFAR visibility patches (arXiv:1905.09272).

Re-designs of reference simple_models.py:436-514:
  * ``EncoderCNN``    — 8-channel input (4 pol x re/im), 5 parallel dilated
    convs (dilation 1,2,4,8,16) concatenated, then 3 strided convs to
    ``latent_dim``, avg-pool (reference :436-470);
  * ``ContextgenCNN`` — pixelCNN-ish 4-conv latents→context, shape preserving,
    bias-free (reference :474-494);
  * ``PredictorCNN``  — two 1x1 convs projecting latents and context to
    ``reduced_dim`` for InfoNCE (reference :498-514).
"""

from __future__ import annotations

from typing import List

import flax.linen as nn
import jax.numpy as jnp

from federated_pytorch_test_tpu.models.base import BlockModule, elu, pairs
from federated_pytorch_test_tpu.ops.dilated_conv import TapConv


def _pad(p: int):
    return ((p, p), (p, p))


class EncoderCNN(BlockModule):
    latent_dim: int = 1024

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        """x: [B, 32, 32, 8] → [B, latent_dim]."""
        # five dilated views, all 32x32 -> 16x16.  TapConv (im2col) rather
        # than nn.Conv: at dilation 16 the receptive span (49 px) exceeds
        # the 32 px input and XLA:TPU's dilated-conv lowering has been
        # observed to compile pathologically at reference width inside the
        # jitted CPC round (README "Known issues"); the tap-gather matmul
        # is numerically identical (tests/test_dilated_conv.py) with the
        # same param tree.
        xs = []
        for d, p in ((1, 1), (2, 3), (4, 6), (8, 12), (16, 24)):
            xs.append(elu(TapConv(8, (4, 4), strides=(2, 2),
                                  kernel_dilation=(d, d),
                                  padding=_pad(p), name=f"conv1_{d}")(x)))
        x = jnp.concatenate(xs, axis=-1)  # [B,16,16,40]
        x = elu(nn.Conv(self.latent_dim // 4, (4, 4), strides=(2, 2),
                        padding=_pad(1), name="conv2")(x))  # 8x8
        x = elu(nn.Conv(self.latent_dim // 2, (4, 4), strides=(2, 2),
                        padding=_pad(1), name="conv3")(x))  # 4x4
        x = elu(nn.Conv(self.latent_dim, (4, 4), strides=(2, 2),
                        padding=_pad(1), name="conv4")(x))  # 2x2
        x = nn.avg_pool(x, window_shape=(2, 2), strides=(2, 2))  # 1x1
        return x.reshape((x.shape[0], -1))  # [B, latent_dim]

    def param_order(self) -> List[str]:
        return pairs("conv1_1", "conv1_2", "conv1_4", "conv1_8", "conv1_16",
                     "conv2", "conv3", "conv4")

    def train_order_block_ids(self) -> List[List[int]]:
        # reference simple_models.py:468-470
        return [[0, 9], [10, 15]]


class ContextgenCNN(BlockModule):
    latent_dim: int = 1024

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        """x: [B, px, py, latent_dim] → same shape."""
        x = elu(nn.Conv(self.latent_dim // 4, (1, 1), use_bias=False,
                        padding="VALID", name="conv1")(x))
        x = elu(nn.Conv(self.latent_dim // 4, (2, 2), use_bias=False,
                        padding=_pad(1), name="conv2")(x))  # px+1
        x = elu(nn.Conv(self.latent_dim // 2, (2, 2), use_bias=False,
                        padding="VALID", name="conv3")(x))  # px
        x = elu(nn.Conv(self.latent_dim, (1, 1), use_bias=False,
                        padding="VALID", name="conv4")(x))
        return x

    def param_order(self) -> List[str]:
        # bias-free convs: one flat entry per conv (matches torch enumeration)
        return ["conv1/kernel", "conv2/kernel", "conv3/kernel", "conv4/kernel"]

    def train_order_block_ids(self) -> List[List[int]]:
        # reference simple_models.py:492-494 — full net
        return [[0, 3]]


class PredictorCNN(BlockModule):
    latent_dim: int = 1024
    reduced_dim: int = 64

    @nn.compact
    def __call__(self, latents: jnp.ndarray, context: jnp.ndarray,
                 train: bool = True):
        """[B, px, py, latent] x2 → ([B, px, py, reduced] x2)."""
        reduced_latents = nn.Conv(self.reduced_dim, (1, 1), use_bias=False,
                                  padding="VALID", name="conv1")(latents)
        prediction = nn.Conv(self.reduced_dim, (1, 1), use_bias=False,
                             padding="VALID", name="conv2")(context)
        return reduced_latents, prediction

    def param_order(self) -> List[str]:
        return ["conv1/kernel", "conv2/kernel"]

    def train_order_block_ids(self) -> List[List[int]]:
        # reference simple_models.py:512-514 — full net
        return [[0, 1]]
