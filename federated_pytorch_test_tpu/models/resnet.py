"""CIFAR-variant ResNet9/18 with ELU activations.

Re-design of reference simple_models.py:132-237: 3x3 stem (no 7x7/maxpool),
4 stages, ELU everywhere ReLU would be, avg-pool 4, linear head.  BatchNorm
affine params (scale/bias) are ordinary parameters — they participate in
blocks and federation averaging, exactly as torch's ``net.parameters()``
includes BN weight/bias; running stats live in the ``batch_stats`` collection,
stay per-client and are never averaged (matching torch, where buffers are not
in ``parameters()``; see SURVEY.md section 7 "BatchNorm under federation").

``norm="group"`` swaps every BatchNorm for a GroupNorm (32 groups) at the
SAME module name, so the parameter enumeration order, the hand-made block
partitions and all block tooling are unchanged.  This removes the BN caveat
above for pod-scale federation (SURVEY.md section 7 hard part 4 "consider
GroupNorm"): GroupNorm has no running statistics, so ALL normalisation
state is ordinary parameters that federate like any other — clients drift
only through weights, never through unaveraged buffers — and train/eval
behavior is identical (no use_running_average split).  The reference has
no such option; the per-client-stats BatchNorm (default) remains the
parity configuration.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

from federated_pytorch_test_tpu.models.base import BlockModule, elu


def _apply_norm(norm: str, name: str, x, train: bool):
    """BatchNorm (torch defaults: eps=1e-5, momentum=0.1 -> flax 0.9) or
    GroupNorm(32) under the SAME module name.  Normalisation always
    computes in float32 — only the convs/dense run in the compute dtype."""
    if norm == "group":
        return nn.GroupNorm(num_groups=32, epsilon=1e-5, dtype=jnp.float32,
                            name=name)(x)
    return nn.BatchNorm(momentum=0.9, epsilon=1e-5, dtype=jnp.float32,
                        name=name)(x, use_running_average=not train)


class BasicBlock(nn.Module):
    """Two 3x3 convs + identity/projection shortcut (expansion 1).

    Reference simple_models.py:132-154.
    """

    planes: int
    stride: int = 1
    expansion: int = 1
    dtype: Optional[Any] = None   # compute dtype for convs (bf16 on TPU)
    norm: str = "batch"           # "batch" (parity) | "group" (pod-safe)

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        in_planes = x.shape[-1]
        out = nn.Conv(self.planes, (3, 3), strides=(self.stride, self.stride),
                      padding="SAME", use_bias=False, dtype=self.dtype,
                      name="conv1")(x)
        out = elu(_apply_norm(self.norm, "bn1", out, train))
        out = nn.Conv(self.planes, (3, 3), padding="SAME", use_bias=False,
                      dtype=self.dtype, name="conv2")(out)
        out = _apply_norm(self.norm, "bn2", out, train)
        if self.stride != 1 or in_planes != self.expansion * self.planes:
            sc = nn.Conv(self.expansion * self.planes, (1, 1),
                         strides=(self.stride, self.stride), use_bias=False,
                         dtype=self.dtype, name="shortcut_conv")(x)
            sc = _apply_norm(self.norm, "shortcut_bn", sc, train)
        else:
            sc = x
        return elu(out + sc)


class Bottleneck(nn.Module):
    """1x1 → 3x3 → 1x1 bottleneck (expansion 4).

    Reference simple_models.py:157-182 (defined for parity; the reference
    factories never reach it).
    """

    planes: int
    stride: int = 1
    expansion: int = 4
    dtype: Optional[Any] = None
    norm: str = "batch"

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        in_planes = x.shape[-1]
        out = nn.Conv(self.planes, (1, 1), use_bias=False, dtype=self.dtype,
                      name="conv1")(x)
        out = elu(_apply_norm(self.norm, "bn1", out, train))
        out = nn.Conv(self.planes, (3, 3), strides=(self.stride, self.stride),
                      padding="SAME", use_bias=False, dtype=self.dtype,
                      name="conv2")(out)
        out = elu(_apply_norm(self.norm, "bn2", out, train))
        out = nn.Conv(self.expansion * self.planes, (1, 1), use_bias=False,
                      dtype=self.dtype, name="conv3")(out)
        out = _apply_norm(self.norm, "bn3", out, train)
        if self.stride != 1 or in_planes != self.expansion * self.planes:
            sc = nn.Conv(self.expansion * self.planes, (1, 1),
                         strides=(self.stride, self.stride), use_bias=False,
                         dtype=self.dtype, name="shortcut_conv")(x)
            sc = _apply_norm(self.norm, "shortcut_bn", sc, train)
        else:
            sc = x
        return elu(out + sc)


_STAGE_PLANES = (64, 128, 256, 512)
_STAGE_STRIDES = (1, 2, 2, 2)


class ResNet(BlockModule):
    """Reference simple_models.py:185-230 (CIFAR stem, ELU, avgpool 4)."""

    num_blocks: Sequence[int] = (2, 2, 2, 2)
    qualifier: int = 18  # 9 or 18 — selects the hand-made block partition
    num_classes: int = 10
    bottleneck: bool = False
    #: compute dtype for convs/dense (params stay float32; BN and the loss
    #: run in float32).  bfloat16 feeds the MXU at full rate on TPU.
    dtype: Optional[Any] = None
    #: "batch" = reference parity (per-client running stats, see module
    #: docstring); "group" = GroupNorm(32), no stats, pod-scale safe
    norm: str = "batch"

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        out = nn.Conv(64, (3, 3), padding="SAME", use_bias=False,
                      dtype=self.dtype, name="conv1")(x)
        out = elu(_apply_norm(self.norm, "bn1", out, train))
        block_cls = Bottleneck if self.bottleneck else BasicBlock
        for stage, (planes, stride, n) in enumerate(
            zip(_STAGE_PLANES, _STAGE_STRIDES, self.num_blocks), start=1
        ):
            strides = [stride] + [1] * (n - 1)
            for i, s in enumerate(strides):
                out = block_cls(planes=planes, stride=s, dtype=self.dtype,
                                norm=self.norm,
                                name=f"layer{stage}_{i}")(out, train=train)
        out = nn.avg_pool(out, window_shape=(4, 4), strides=(4, 4))
        out = out.reshape((out.shape[0], -1))
        # head in float32 for numerically stable logits/CE
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="linear")(out.astype(jnp.float32))

    # -- federation metadata ------------------------------------------------
    def param_order(self) -> List[str]:
        """Torch ``net.parameters()`` enumeration order of the reference ResNet.

        Per BasicBlock: conv1.w, bn1.{scale,bias}, conv2.w, bn2.{scale,bias},
        then (if projection) shortcut conv.w, shortcut bn.{scale,bias} — the
        registration order of reference simple_models.py:135-147.
        """
        order: List[str] = ["conv1/kernel", "bn1/scale", "bn1/bias"]
        expansion = 4 if self.bottleneck else 1
        in_planes = 64
        for stage, (planes, stride, n) in enumerate(
            zip(_STAGE_PLANES, _STAGE_STRIDES, self.num_blocks), start=1
        ):
            strides = [stride] + [1] * (n - 1)
            for i, s in enumerate(strides):
                p = f"layer{stage}_{i}"
                convs = ["conv1", "conv2"] + (["conv3"] if self.bottleneck else [])
                for j, c in enumerate(convs, start=1):
                    order += [f"{p}/{c}/kernel", f"{p}/bn{j}/scale", f"{p}/bn{j}/bias"]
                if s != 1 or in_planes != expansion * planes:
                    order += [f"{p}/shortcut_conv/kernel",
                              f"{p}/shortcut_bn/scale", f"{p}/shortcut_bn/bias"]
                in_planes = planes * expansion
        order += ["linear/kernel", "linear/bias"]
        return order

    def train_order_block_ids(self) -> List[List[int]]:
        # reference simple_models.py:222-226 — hand-made partitions
        if self.qualifier == 18:
            return [[0, 2], [3, 8], [9, 14], [15, 23], [24, 29], [30, 38],
                    [39, 44], [45, 53], [54, 59], [60, 61]]
        return [[0, 2], [3, 8], [9, 14], [15, 17], [18, 23], [24, 29],
                [30, 32], [33, 37]]

    def linear_layer_ids(self) -> List[int]:
        # reference simple_models.py:229-230 (empty)
        return []


def ResNet18(dtype=None, norm: str = "batch") -> ResNet:
    """Reference simple_models.py:233-234."""
    return ResNet(num_blocks=(2, 2, 2, 2), qualifier=18, dtype=dtype,
                  norm=norm)


def ResNet9(dtype=None, norm: str = "batch") -> ResNet:
    """Reference simple_models.py:236-237."""
    return ResNet(num_blocks=(1, 1, 1, 1), qualifier=9, dtype=dtype,
                  norm=norm)
