"""CIFAR-variant ResNet9/18 with ELU activations.

Re-design of reference simple_models.py:132-237: 3x3 stem (no 7x7/maxpool),
4 stages, ELU everywhere ReLU would be, avg-pool 4, linear head.  BatchNorm
affine params (scale/bias) are ordinary parameters — they participate in
blocks and federation averaging, exactly as torch's ``net.parameters()``
includes BN weight/bias; running stats live in the ``batch_stats`` collection,
stay per-client and are never averaged (matching torch, where buffers are not
in ``parameters()``; see SURVEY.md section 7 "BatchNorm under federation").
BN is :class:`MaskedBatchNorm`: identical to flax BatchNorm on full batches,
and given per-sample pad weights (``sample_weight``) it excludes wrap-pad
rows from the batch statistics, matching torch BN on the true partial batch
(reference drop_last=False, federated_multi.py:74-83).

``norm="group"`` swaps every BatchNorm for a GroupNorm (32 groups) at the
SAME module name, so the parameter enumeration order, the hand-made block
partitions and all block tooling are unchanged.  This removes the BN caveat
above for pod-scale federation (SURVEY.md section 7 hard part 4 "consider
GroupNorm"): GroupNorm has no running statistics, so ALL normalisation
state is ordinary parameters that federate like any other — clients drift
only through weights, never through unaveraged buffers — and train/eval
behavior is identical (no use_running_average split).  The reference has
no such option; the per-client-stats BatchNorm (default) remains the
parity configuration.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from federated_pytorch_test_tpu.models.base import BlockModule, elu


class MaskedBatchNorm(nn.Module):
    """BatchNorm whose batch statistics can exclude pad rows.

    Same parameter/stat tree as ``nn.BatchNorm`` (params ``scale``/``bias``,
    batch_stats ``mean``/``var``) and the same algorithm (biased variance,
    EMA update ``ra = m*ra + (1-m)*batch``) — with ``w`` None this IS flax
    BatchNorm.  With ``w`` given ([B] pad weights, 0 on the wrap-padded rows
    of the final partial minibatch, data/cifar10.py), the train-time
    mean/var are weighted over real rows only, so both the normalisation
    of real rows and the running-stat update reproduce torch BN on the
    TRUE partial batch (reference federated_multi.py:74-83 uses
    drop_last=False, so torch BN never sees pad rows) — closing the one
    known bit-parity hole for the flagship ResNet18 config (PARITY.md C12).
    """

    momentum: float = 0.9
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x, w=None, use_running_average=False):
        x = jnp.asarray(x, jnp.float32)
        C = x.shape[-1]
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((C,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((C,), jnp.float32))
        scale = self.param("scale", nn.initializers.ones, (C,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (C,), jnp.float32)
        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            axes = tuple(range(x.ndim - 1))
            if w is None:
                mean = jnp.mean(x, axes)
                mean2 = jnp.mean(jnp.square(x), axes)
            else:
                wf = w.astype(jnp.float32).reshape(
                    (-1,) + (1,) * (x.ndim - 1))
                # rows-that-count x spatial positions per row
                denom = jnp.sum(wf) * (x[0].size // C)
                mean = jnp.sum(x * wf, axes) / denom
                mean2 = jnp.sum(jnp.square(x) * wf, axes) / denom
            var = mean2 - jnp.square(mean)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * var
        y = (x - mean) * jax.lax.rsqrt(var + self.epsilon)
        return y * scale + bias


def _apply_norm(norm: str, name: str, x, train: bool, w=None):
    """BatchNorm (torch defaults: eps=1e-5, momentum=0.1 -> flax 0.9) or
    GroupNorm(32) under the SAME module name.  Normalisation always
    computes in float32 — only the convs/dense run in the compute dtype.
    ``w`` ([B] pad weights) excludes wrap-pad rows from BN batch stats;
    GroupNorm normalises per-sample, so pad rows can't contaminate it."""
    if norm == "group":
        return nn.GroupNorm(num_groups=32, epsilon=1e-5, dtype=jnp.float32,
                            name=name)(x)
    return MaskedBatchNorm(momentum=0.9, epsilon=1e-5, name=name)(
        x, w=w, use_running_average=not train)


class BasicBlock(nn.Module):
    """Two 3x3 convs + identity/projection shortcut (expansion 1).

    Reference simple_models.py:132-154.
    """

    planes: int
    stride: int = 1
    expansion: int = 1
    dtype: Optional[Any] = None   # compute dtype for convs (bf16 on TPU)
    norm: str = "batch"           # "batch" (parity) | "group" (pod-safe)

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True,
                 sample_weight=None) -> jnp.ndarray:
        w = sample_weight
        in_planes = x.shape[-1]
        out = nn.Conv(self.planes, (3, 3), strides=(self.stride, self.stride),
                      padding=((1, 1), (1, 1)), use_bias=False, dtype=self.dtype,
                      name="conv1")(x)
        out = elu(_apply_norm(self.norm, "bn1", out, train, w))
        out = nn.Conv(self.planes, (3, 3), padding=((1, 1), (1, 1)), use_bias=False,
                      dtype=self.dtype, name="conv2")(out)
        out = _apply_norm(self.norm, "bn2", out, train, w)
        if self.stride != 1 or in_planes != self.expansion * self.planes:
            sc = nn.Conv(self.expansion * self.planes, (1, 1),
                         strides=(self.stride, self.stride), use_bias=False,
                         dtype=self.dtype, name="shortcut_conv")(x)
            sc = _apply_norm(self.norm, "shortcut_bn", sc, train, w)
        else:
            sc = x
        return elu(out + sc)


class Bottleneck(nn.Module):
    """1x1 → 3x3 → 1x1 bottleneck (expansion 4).

    Reference simple_models.py:157-182 (defined for parity; the reference
    factories never reach it).
    """

    planes: int
    stride: int = 1
    expansion: int = 4
    dtype: Optional[Any] = None
    norm: str = "batch"

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True,
                 sample_weight=None) -> jnp.ndarray:
        w = sample_weight
        in_planes = x.shape[-1]
        out = nn.Conv(self.planes, (1, 1), use_bias=False, dtype=self.dtype,
                      name="conv1")(x)
        out = elu(_apply_norm(self.norm, "bn1", out, train, w))
        out = nn.Conv(self.planes, (3, 3), strides=(self.stride, self.stride),
                      padding=((1, 1), (1, 1)), use_bias=False, dtype=self.dtype,
                      name="conv2")(out)
        out = elu(_apply_norm(self.norm, "bn2", out, train, w))
        out = nn.Conv(self.expansion * self.planes, (1, 1), use_bias=False,
                      dtype=self.dtype, name="conv3")(out)
        out = _apply_norm(self.norm, "bn3", out, train, w)
        if self.stride != 1 or in_planes != self.expansion * self.planes:
            sc = nn.Conv(self.expansion * self.planes, (1, 1),
                         strides=(self.stride, self.stride), use_bias=False,
                         dtype=self.dtype, name="shortcut_conv")(x)
            sc = _apply_norm(self.norm, "shortcut_bn", sc, train, w)
        else:
            sc = x
        return elu(out + sc)


_STAGE_PLANES = (64, 128, 256, 512)
_STAGE_STRIDES = (1, 2, 2, 2)


class ResNet(BlockModule):
    """Reference simple_models.py:185-230 (CIFAR stem, ELU, avgpool 4)."""

    num_blocks: Sequence[int] = (2, 2, 2, 2)
    qualifier: int = 18  # 9 or 18 — selects the hand-made block partition
    num_classes: int = 10
    bottleneck: bool = False
    #: compute dtype for convs/dense (params stay float32; BN and the loss
    #: run in float32).  bfloat16 feeds the MXU at full rate on TPU.
    dtype: Optional[Any] = None
    #: "batch" = reference parity (per-client running stats, see module
    #: docstring); "group" = GroupNorm(32), no stats, pod-scale safe
    norm: str = "batch"

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True,
                 sample_weight=None) -> jnp.ndarray:
        out = nn.Conv(64, (3, 3), padding=((1, 1), (1, 1)), use_bias=False,
                      dtype=self.dtype, name="conv1")(x)
        out = elu(_apply_norm(self.norm, "bn1", out, train, sample_weight))
        block_cls = Bottleneck if self.bottleneck else BasicBlock
        for stage, (planes, stride, n) in enumerate(
            zip(_STAGE_PLANES, _STAGE_STRIDES, self.num_blocks), start=1
        ):
            strides = [stride] + [1] * (n - 1)
            for i, s in enumerate(strides):
                out = block_cls(planes=planes, stride=s, dtype=self.dtype,
                                norm=self.norm,
                                name=f"layer{stage}_{i}")(
                                    out, train=train,
                                    sample_weight=sample_weight)
        out = nn.avg_pool(out, window_shape=(4, 4), strides=(4, 4))
        out = out.reshape((out.shape[0], -1))
        # head in float32 for numerically stable logits/CE
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="linear")(out.astype(jnp.float32))

    # -- federation metadata ------------------------------------------------
    def param_order(self) -> List[str]:
        """Torch ``net.parameters()`` enumeration order of the reference ResNet.

        Per BasicBlock: conv1.w, bn1.{scale,bias}, conv2.w, bn2.{scale,bias},
        then (if projection) shortcut conv.w, shortcut bn.{scale,bias} — the
        registration order of reference simple_models.py:135-147.
        """
        order: List[str] = ["conv1/kernel", "bn1/scale", "bn1/bias"]
        expansion = 4 if self.bottleneck else 1
        in_planes = 64
        for stage, (planes, stride, n) in enumerate(
            zip(_STAGE_PLANES, _STAGE_STRIDES, self.num_blocks), start=1
        ):
            strides = [stride] + [1] * (n - 1)
            for i, s in enumerate(strides):
                p = f"layer{stage}_{i}"
                convs = ["conv1", "conv2"] + (["conv3"] if self.bottleneck else [])
                for j, c in enumerate(convs, start=1):
                    order += [f"{p}/{c}/kernel", f"{p}/bn{j}/scale", f"{p}/bn{j}/bias"]
                if s != 1 or in_planes != expansion * planes:
                    order += [f"{p}/shortcut_conv/kernel",
                              f"{p}/shortcut_bn/scale", f"{p}/shortcut_bn/bias"]
                in_planes = planes * expansion
        order += ["linear/kernel", "linear/bias"]
        return order

    def train_order_block_ids(self) -> List[List[int]]:
        # reference simple_models.py:222-226 — hand-made partitions
        if self.qualifier == 18:
            return [[0, 2], [3, 8], [9, 14], [15, 23], [24, 29], [30, 38],
                    [39, 44], [45, 53], [54, 59], [60, 61]]
        return [[0, 2], [3, 8], [9, 14], [15, 17], [18, 23], [24, 29],
                [30, 32], [33, 37]]

    def linear_layer_ids(self) -> List[int]:
        # reference simple_models.py:229-230 (empty)
        return []


def ResNet18(dtype=None, norm: str = "batch") -> ResNet:
    """Reference simple_models.py:233-234."""
    return ResNet(num_blocks=(2, 2, 2, 2), qualifier=18, dtype=dtype,
                  norm=norm)


def ResNet9(dtype=None, norm: str = "batch") -> ResNet:
    """Reference simple_models.py:236-237."""
    return ResNet(num_blocks=(1, 1, 1, 1), qualifier=9, dtype=dtype,
                  norm=norm)
