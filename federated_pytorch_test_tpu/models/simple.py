"""CIFAR10 CNN classifiers: Net, Net1, Net2.

TPU-native (NHWC, Flax) re-designs of the reference model zoo:
  * ``Net``  — LeNet-style CNN, reference simple_models.py:9-39
  * ``Net1`` — mid CNN, reference simple_models.py:42-77
  * ``Net2`` — large CNN, reference simple_models.py:81-128
All use ELU activations (the reference "replaced relu with elu",
simple_models.py:7).  Parameter counts match the reference exactly; kernels
are HWIO and activations NHWC (vs torch OIHW/NCHW) for MXU-friendly layouts.
"""

from __future__ import annotations

from typing import Any, List

import flax.linen as nn
import jax.numpy as jnp

from federated_pytorch_test_tpu.models.base import (
    BlockModule,
    elu,
    flatten,
    max_pool_2x2,
    pairs,
)


class Net(BlockModule):
    """conv(3→6,5) → pool → conv(6→16,5) → pool → fc 400→120→84→10."""

    num_classes: int = 10
    dtype: Any = None  # compute dtype (bf16 on TPU); params & head stay f32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        d = self.dtype
        x = max_pool_2x2(elu(nn.Conv(6, (5, 5), padding="VALID", dtype=d,
                                     name="conv1")(x)))
        x = max_pool_2x2(elu(nn.Conv(16, (5, 5), padding="VALID", dtype=d,
                                     name="conv2")(x)))
        x = flatten(x)  # 5*5*16 = 400
        x = elu(nn.Dense(120, dtype=d, name="fc1")(x))
        x = elu(nn.Dense(84, dtype=d, name="fc2")(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="fc3")(x.astype(jnp.float32))

    def param_order(self) -> List[str]:
        return pairs("conv1", "conv2", "fc1", "fc2", "fc3")

    def linear_layer_ids(self) -> List[int]:
        # reference simple_models.py:29-30 (layer ids over the 0..9 enumeration)
        return [4, 6, 8]

    def train_order_block_ids(self) -> List[List[int]]:
        # reference simple_models.py:38-39
        return [[4, 5], [0, 1], [2, 3], [6, 7], [8, 9]]


class Net1(BlockModule):
    """4 conv (32,32,64,64) + 2 pool + fc 1600→512→10."""

    num_classes: int = 10
    dtype: Any = None  # compute dtype (bf16 on TPU); params & head stay f32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        d = self.dtype
        x = elu(nn.Conv(32, (3, 3), padding="VALID", dtype=d,
                        name="conv1")(x))  # 30x30
        x = elu(nn.Conv(32, (3, 3), padding="VALID", dtype=d,
                        name="conv2")(x))  # 28x28
        x = max_pool_2x2(x)  # 14x14
        x = elu(nn.Conv(64, (3, 3), padding="VALID", dtype=d,
                        name="conv3")(x))  # 12x12
        x = elu(nn.Conv(64, (3, 3), padding="VALID", dtype=d,
                        name="conv4")(x))  # 10x10
        x = max_pool_2x2(x)  # 5x5
        x = flatten(x)  # 64*5*5 = 1600
        x = elu(nn.Dense(512, dtype=d, name="fc1")(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="fc2")(x.astype(jnp.float32))

    def param_order(self) -> List[str]:
        return pairs("conv1", "conv2", "conv3", "conv4", "fc1", "fc2")

    def linear_layer_ids(self) -> List[int]:
        # reference simple_models.py:67-68
        return [8, 10]

    def train_order_block_ids(self) -> List[List[int]]:
        # reference simple_models.py:76-77
        return [[4, 5], [10, 11], [2, 3], [6, 7], [0, 1], [8, 9]]


class Net2(BlockModule):
    """4 padded conv (64→512) + 4 pool + 5 fc (2048→128→256→512→1024→10)."""

    num_classes: int = 10
    dtype: Any = None  # compute dtype (bf16 on TPU); params & head stay f32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        d = self.dtype
        x = max_pool_2x2(elu(nn.Conv(64, (3, 3), padding="SAME", dtype=d,
                                     name="conv1")(x)))  # 16
        x = max_pool_2x2(elu(nn.Conv(128, (3, 3), padding="SAME", dtype=d,
                                     name="conv2")(x)))  # 8
        x = max_pool_2x2(elu(nn.Conv(256, (3, 3), padding="SAME", dtype=d,
                                     name="conv3")(x)))  # 4
        x = max_pool_2x2(elu(nn.Conv(512, (3, 3), padding="SAME", dtype=d,
                                     name="conv4")(x)))  # 2
        x = flatten(x)  # 512*2*2 = 2048
        x = elu(nn.Dense(128, dtype=d, name="fc1")(x))
        x = elu(nn.Dense(256, dtype=d, name="fc2")(x))
        x = elu(nn.Dense(512, dtype=d, name="fc3")(x))
        x = elu(nn.Dense(1024, dtype=d, name="fc4")(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="fc5")(x.astype(jnp.float32))

    def param_order(self) -> List[str]:
        return pairs("conv1", "conv2", "conv3", "conv4", "fc1", "fc2", "fc3", "fc4", "fc5")

    def linear_layer_ids(self) -> List[int]:
        # reference simple_models.py:117-118
        return [12, 14, 16]

    def train_order_block_ids(self) -> List[List[int]]:
        # reference simple_models.py:127-128
        return [[14, 15], [4, 5], [2, 3], [8, 9], [16, 17], [12, 13], [6, 7], [0, 1], [10, 11]]
