"""Variational autoencoder for CIFAR10.

Re-design of reference ``AutoEncoderCNN`` (simple_models.py:243-305):
4 strided convs 32→2 px, fc 384→16→(mu, logvar), decode fc → 4 transposed
convs → sigmoid.  Reparametrisation uses an explicit PRNG key instead of
``torch.cuda.FloatTensor.normal_()`` (simple_models.py:292-301).
"""

from __future__ import annotations

from typing import List, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from federated_pytorch_test_tpu.models.base import BlockModule, elu, flatten, pairs

_P1 = ((1, 1), (1, 1))  # torch padding=1


class AutoEncoderCNN(BlockModule):
    latent_dim: int = 10

    def setup(self):
        self.conv1 = nn.Conv(12, (4, 4), strides=(2, 2), padding=_P1, name="conv1")
        self.conv2 = nn.Conv(24, (4, 4), strides=(2, 2), padding=_P1, name="conv2")
        self.conv3 = nn.Conv(48, (4, 4), strides=(2, 2), padding=_P1, name="conv3")
        self.conv4 = nn.Conv(96, (4, 4), strides=(2, 2), padding=_P1, name="conv4")
        self.fc1 = nn.Dense(16, name="fc1")
        self.fc21 = nn.Dense(self.latent_dim, name="fc21")
        self.fc22 = nn.Dense(self.latent_dim, name="fc22")
        self.fc3 = nn.Dense(384, name="fc3")
        self.tconv1 = nn.ConvTranspose(48, (4, 4), strides=(2, 2), padding="SAME", name="tconv1")
        self.tconv2 = nn.ConvTranspose(24, (4, 4), strides=(2, 2), padding="SAME", name="tconv2")
        self.tconv3 = nn.ConvTranspose(12, (4, 4), strides=(2, 2), padding="SAME", name="tconv3")
        self.tconv4 = nn.ConvTranspose(3, (4, 4), strides=(2, 2), padding="SAME", name="tconv4")

    def encode(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        x = elu(self.conv1(x))  # 16x16x12
        x = elu(self.conv2(x))  # 8x8x24
        x = elu(self.conv3(x))  # 4x4x48
        x = elu(self.conv4(x))  # 2x2x96
        x = flatten(x)  # 384
        x = elu(self.fc1(x))  # 16
        return self.fc21(x), self.fc22(x)  # mu, logvar

    def decode(self, z: jnp.ndarray) -> jnp.ndarray:
        x = self.fc3(z)  # 384
        x = x.reshape((-1, 2, 2, 96))
        x = elu(self.tconv1(x))  # 4x4x48
        x = elu(self.tconv2(x))  # 8x8x24
        x = elu(self.tconv3(x))  # 16x16x12
        x = elu(self.tconv4(x))  # 32x32x3
        return jax.nn.sigmoid(x)

    def reparametrize(self, mu, logvar, rng):
        std = jnp.exp(0.5 * logvar)
        eps = jax.random.normal(rng, std.shape, std.dtype)
        return eps * std + mu

    def __call__(self, x: jnp.ndarray, rng: jax.Array, train: bool = True):
        mu, logvar = self.encode(x)
        z = self.reparametrize(mu, logvar, rng)
        return self.decode(z), mu, logvar

    def param_order(self) -> List[str]:
        return pairs("conv1", "conv2", "conv3", "conv4", "fc1", "fc21", "fc22",
                     "fc3", "tconv1", "tconv2", "tconv3", "tconv4")

    def train_order_block_ids(self) -> List[List[int]]:
        # reference simple_models.py:304-305
        return [[0, 1], [2, 3], [4, 5], [6, 7], [8, 9], [14, 15], [16, 17],
                [18, 19], [20, 21], [22, 23], [10, 11], [12, 13]]
