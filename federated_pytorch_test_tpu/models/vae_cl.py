"""Variational clustering autoencoder (arXiv:2005.04613).

Re-design of reference ``AutoEncoderCNNCL`` (simple_models.py:309-432):
cluster head q(k|x) via softmax, per-cluster encoder q(z|x,k), prior p(z|k)
and likelihood p(x|z) decoders.  The reference's Python loop over all K
clusters building one-hot ``e_k`` tensors (simple_models.py:355-366) is
vectorised with ``vmap`` over the cluster axis — outputs carry a leading
``K`` (cluster) axis instead of dict-of-tensors.
"""

from __future__ import annotations

from typing import List, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from federated_pytorch_test_tpu.models.base import BlockModule, elu, flatten, pairs

_P1 = ((1, 1), (1, 1))
softplus = jax.nn.softplus


class AutoEncoderCNNCL(BlockModule):
    K: int = 10  # clusters
    L: int = 32  # latent dimension

    def setup(self):
        self.conv1 = nn.Conv(12, (4, 4), strides=(2, 2), padding=_P1, name="conv1")
        self.conv2 = nn.Conv(24, (4, 4), strides=(2, 2), padding=_P1, name="conv2")
        self.conv3 = nn.Conv(48, (4, 4), strides=(2, 2), padding=_P1, name="conv3")
        self.conv4 = nn.Conv(96, (4, 4), strides=(2, 2), padding=_P1, name="conv4")

        self.fc11 = nn.Dense(128, name="fc11")
        self.fc12 = nn.Dense(64, name="fc12")
        self.fc13 = nn.Dense(self.K, name="fc13")
        self.fc21 = nn.Dense(128, name="fc21")
        self.fc22 = nn.Dense(128, name="fc22")
        self.fc23 = nn.Dense(self.L, name="fc23")
        self.fc24 = nn.Dense(self.L, name="fc24")

        self.fc14 = nn.Dense(64, name="fc14")
        self.fc15 = nn.Dense(64, name="fc15")
        self.fc16 = nn.Dense(self.L, name="fc16")
        self.fc17 = nn.Dense(self.L, name="fc17")

        self.fc25 = nn.Dense(384, name="fc25")
        self.tconv1 = nn.ConvTranspose(48, (4, 4), strides=(2, 2), padding="SAME", name="tconv1")
        self.tconv2 = nn.ConvTranspose(24, (4, 4), strides=(2, 2), padding="SAME", name="tconv2")
        self.tconv3 = nn.ConvTranspose(12, (4, 4), strides=(2, 2), padding="SAME", name="tconv3")
        self.tconv4 = nn.ConvTranspose(3, (4, 4), strides=(2, 2), padding="SAME", name="tconv4")
        self.tconv5 = nn.ConvTranspose(3, (4, 4), strides=(2, 2), padding="SAME", name="tconv5")

    # -- submodels ----------------------------------------------------------
    def _conv_stack(self, x: jnp.ndarray) -> jnp.ndarray:
        x = elu(self.conv1(x))
        x = elu(self.conv2(x))
        x = elu(self.conv3(x))
        x = elu(self.conv4(x))
        return flatten(x)  # [B, 384]

    def encodeclus(self, x: jnp.ndarray) -> jnp.ndarray:
        """q(k|x): [B, K] softmax — reference simple_models.py:369-380."""
        h = self._conv_stack(x)
        h = elu(self.fc11(h))
        h = elu(self.fc12(h))
        ekhat = elu(self.fc13(h))
        return jax.nn.softmax(ekhat, axis=1)

    def _encode_from_features(self, h: jnp.ndarray, ek: jnp.ndarray):
        y = elu(self.fc21(jnp.concatenate([h, ek], axis=1)))
        y = elu(self.fc22(y))
        y1 = elu(self.fc23(y))
        y2 = elu(self.fc24(y))
        return y1, softplus(y2)

    def encode(self, x: jnp.ndarray, ek: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """q(z|x,k): mu_xi, sig2_xi (softplus) — reference :383-395."""
        return self._encode_from_features(self._conv_stack(x), ek)

    def decode(self, ek: jnp.ndarray, z: jnp.ndarray):
        """p(z|k) and p(x|z) params — reference :397-413."""
        x = elu(self.fc14(ek))
        x = elu(self.fc15(x))
        mu_b = self.fc16(x)
        sig2_b = softplus(self.fc17(x))
        h = elu(self.fc25(z))
        h = h.reshape((-1, 2, 2, 96))
        h = elu(self.tconv1(h))
        h = elu(self.tconv2(h))
        h = elu(self.tconv3(h))
        mu_th = elu(self.tconv4(h))
        sig2_th = softplus(elu(self.tconv5(h)))
        return mu_b, sig2_b, mu_th, sig2_th

    def reparametrize(self, mu, sig2, rng, enabled: bool):
        # Static flag mirroring the reference repr_flag (simple_models.py:415-427).
        # NOTE reference quirk: disable_repr() is a no-op (sets repr_flag=True,
        # simple_models.py:344-345), so the reference ALWAYS reparametrizes;
        # parity drivers therefore pass reparam=True for every block.
        if not enabled:
            return mu
        std = jnp.sqrt(sig2)
        eps = jax.random.normal(rng, std.shape, std.dtype)
        return eps * std + mu

    def __call__(self, x: jnp.ndarray, rng: jax.Array, reparam: bool = True,
                 train: bool = True):
        """Forward over all K clusters, vectorised.

        Returns ``(ekhat, mu_xi, sig2_xi, mu_b, sig2_b, mu_th, sig2_th)``
        where every output except ``ekhat`` has a leading cluster axis [K, ...]
        (the reference returns dicts keyed by cluster, simple_models.py:347-367).
        """
        ekhat = self.encodeclus(x)
        batch = x.shape[0]
        eye = jnp.eye(self.K, dtype=x.dtype)  # one-hot e_k rows
        keys = jax.random.split(rng, self.K)
        # The conv stack is cluster-independent: hoist it out of the cluster
        # loop (the reference recomputes it inside encode() for each of the K
        # clusters, simple_models.py:355-366 — K redundant conv passes).
        h = self._conv_stack(x)

        def per_cluster(ci):
            ek = jnp.broadcast_to(eye[ci], (batch, self.K))
            mu_xi, sig2_xi = self._encode_from_features(h, ek)
            z = self.reparametrize(mu_xi, sig2_xi, keys[ci], reparam)
            mu_b, sig2_b, mu_th, sig2_th = self.decode(ek, z)
            return mu_xi, sig2_xi, mu_b, sig2_b, mu_th, sig2_th

        outs = [per_cluster(ci) for ci in range(self.K)]
        mu_xi, sig2_xi, mu_b, sig2_b, mu_th, sig2_th = (
            jnp.stack(parts) for parts in zip(*outs)
        )
        return ekhat, mu_xi, sig2_xi, mu_b, sig2_b, mu_th, sig2_th

    # -- federation metadata -------------------------------------------------
    def param_order(self) -> List[str]:
        return pairs("conv1", "conv2", "conv3", "conv4",
                     "fc11", "fc12", "fc13", "fc21", "fc22", "fc23", "fc24",
                     "fc14", "fc15", "fc16", "fc17", "fc25",
                     "tconv1", "tconv2", "tconv3", "tconv4", "tconv5")

    def train_order_block_ids(self) -> List[List[int]]:
        # reference simple_models.py:430-432 — encoder, decoder, latent space
        return [[0, 7], [32, 41], [8, 31]]
