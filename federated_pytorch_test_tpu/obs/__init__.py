"""Observability: structured run telemetry for every engine.

- :mod:`.metrics`  — host-side counters / gauges / timers.
- :mod:`.schema`   — versioned run_header / round / summary records.
- :mod:`.sinks`    — JSONL / CSV / stdout / in-memory emitters.
- :mod:`.recorder` — the per-run emitter the engines thread through.
- :mod:`.report`   — ``python -m federated_pytorch_test_tpu.obs.report``.
- :mod:`.trace`    — span timeline → Chrome trace-event JSON exporter.
- :mod:`.health`   — streaming anomaly watchdog (``--health-action``).
- :mod:`.compare`  — cross-run regression CLI (CI gate).
- :mod:`.costs`    — per-jit-site compile/HLO device-cost ledger.
- :mod:`.profile`  — ``python -m federated_pytorch_test_tpu.obs.profile``.
- :mod:`.clients`  — client-grain flight recorder: per-client ledgers,
  deterministic anomaly ranking, cohort rollups
  (``python -m federated_pytorch_test_tpu.obs.clients``).

See README "Observability" for the artifact format and how XProf traces
(``--profile-dir`` + per-round ``StepTraceAnnotation``) correlate with
the JSONL timeline.
"""

from federated_pytorch_test_tpu.obs.clients import (  # noqa: F401
    ClientLedger,
    client_round_fields,
    ledger_from_records,
    summarize_clients,
)
from federated_pytorch_test_tpu.obs.costs import (  # noqa: F401
    CostLedger,
    round_cost_fields,
)
from federated_pytorch_test_tpu.obs.health import (  # noqa: F401
    HEALTH_ACTIONS,
    HealthMonitor,
    RunHealthAbort,
    monitor_from_config,
)
from federated_pytorch_test_tpu.obs.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Metrics,
    Timer,
)
from federated_pytorch_test_tpu.obs.recorder import (  # noqa: F401
    RunRecorder,
    device_memory_stats,
    git_rev,
    make_recorder,
)
from federated_pytorch_test_tpu.obs.schema import (  # noqa: F401
    SCHEMA_VERSION,
    SchemaError,
    json_safe,
    validate_record,
)
from federated_pytorch_test_tpu.obs.sinks import (  # noqa: F401
    CsvSink,
    JsonlSink,
    MemorySink,
    Sink,
    StdoutSink,
    make_sinks,
)
from federated_pytorch_test_tpu.obs.trace import (  # noqa: F401
    to_chrome_trace,
    validate_chrome_trace,
)
