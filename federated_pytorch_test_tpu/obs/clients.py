"""Client-grain flight recorder (schema v11): ledger, ranking, cohorts.

The engines emit one ``client`` record per communication round — the
round record's counters, un-aggregated: parallel length-K lists of
per-client update norms, delta-vs-z distance, loss contribution, guard
verdicts, quarantine state, fault tags, async staleness/admission, and
churn membership (``obs/schema.py`` v10).  Under population federation
(``--population K``, schema v11) each record additionally carries
``registry_ids`` — the registry ids of the sampled cohort occupying the
K device slots that round — and the ledger rekeys every aggregate by
registry id: records stay cohort-sized while the ledger grows to the
set of clients ever sampled, byte-exactly reproducible from the stream
even though K vastly exceeds any single record's length.  This module
is the reader side:

- :class:`ClientLedger` — streaming accumulator over ``client`` records
  (pure function of the stream, float64 host math: replaying the same
  JSONL reproduces every aggregate byte-exactly, across resume/restart
  segments too, because segments simply append records in file order).
- :func:`anomaly_scores` / :meth:`ClientLedger.ranking` — deterministic
  per-client anomaly composite::

      score_k = z(mean_norm_k) + z(mean_staleness_k)
                + 4 * guard_fail_rate_k + 4 * nonfinite_rate_k

  where ``z`` is the population z-score across clients that produced
  the statistic (clients without data score 0 on that term), computed
  in float64 with ties broken by ascending client id.  NaN/inf update
  norms are counted into ``nonfinite_rate`` — a ``corrupt=nan`` client
  tops the ranking even with guards off.
- ``python -m federated_pytorch_test_tpu.obs.clients run.jsonl`` —
  per-client timelines (one glyph per round), the anomaly ranking, and
  an optional ``--cohorts N`` rollup view (contiguous id ranges — the
  shape the ROADMAP's client-virtualization layer will key by cohort).
- :func:`summarize_clients` — the dispersion fields ``obs/report.py``
  and ``obs/compare.py`` surface (max/median norm skew, top offender).

``--selftest`` round-trips a synthetic two-segment stream through the
real recorder and asserts the ranking (chained into tier-1
``report --selftest``).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

#: timeline glyphs, highest-priority first (one per client per round)
_GLYPHS = (
    ("out", "_"),         # not a member this round (churn)
    ("quar", "q"),        # quarantined (sat the round out)
    ("drop", "D"),        # fault: dropped
    ("strag", "S"),       # fault: straggled (shipped stale params)
    ("corr", "C"),        # fault: corrupted delta on the wire
    ("gfail", "!"),       # guard rejected the update
    ("rej", "x"),         # async: arrived too stale, admission rejected
    ("ok", "."),          # participated cleanly
    ("idle", "-"),        # inactive (not sampled / update in flight)
)


def client_round_fields(round_index: int, clients: int, *,
                        update_norm=None, dist_z=None, loss=None,
                        weight=None, active=None, guard_ok=None,
                        quarantine=None, dropped=None, straggled=None,
                        corrupted=None, staleness=None, admitted=None,
                        members=None, registry_ids=None,
                        payload_bytes: Optional[int] = None
                        ) -> Dict[str, Any]:
    """Assemble a schema-v11 ``client`` record body from host arrays.

    Every array argument is optional (advisory fields — absent means
    "that subsystem was off") and is coerced to a plain length-K Python
    list so the record validates and JSON-round-trips (NaN entries
    survive: the JSONL sink writes ``NaN``, ``json.loads`` reads it
    back).  ``staleness`` uses -1 for "no arrival this round".
    ``registry_ids`` (population mode) maps slot k to the registry id
    of the virtual client that occupied it this round.
    """
    fields: Dict[str, Any] = {"round_index": int(round_index),
                              "clients": int(clients)}

    def put(name, arr, cast):
        if arr is None:
            return
        a = np.asarray(arr).reshape(-1)
        if a.shape[0] != clients:
            raise ValueError(f"{name}: expected length {clients}, "
                             f"got {a.shape[0]}")
        fields[name] = [cast(v) for v in a.tolist()]

    put("update_norm", update_norm, float)
    put("dist_z", dist_z, float)
    put("loss_client", loss, float)
    put("weight", weight, float)
    put("active", active, float)
    put("guard_ok", guard_ok, float)
    put("quarantine", quarantine, int)
    put("dropped", dropped, float)
    put("straggled", straggled, float)
    put("corrupted", corrupted, float)
    put("staleness", staleness, int)
    put("admitted", admitted, float)
    put("members", members, float)
    put("registry_ids", registry_ids, int)
    if payload_bytes is not None:
        fields["payload_bytes"] = int(payload_bytes)
    return fields


#: per-client float64 aggregate arrays (one row per ledger client)
_STATS = ("norm_sum", "norm_n", "nonfinite", "dist_sum",
          "dist_n", "loss_sum", "weight_sum", "active_rounds",
          "guard_checks", "guard_fails", "quar_rounds",
          "drops", "straggles", "corrupts", "arrivals",
          "admits", "rejects", "stale_sum", "bytes",
          "member_rounds", "joins", "leaves")


class ClientLedger:
    """Streaming per-client accumulator over ``client`` records.

    Feed records in file order via :meth:`observe` (non-client events
    are ignored, so the whole stream can be piped through).  All
    aggregates are float64 numpy — a pure function of the stream, so
    recomputing from the recorded JSONL reproduces them bit-exactly
    (the replay contract the anomaly ranking inherits).

    Ledger rows are keyed by REGISTRY id: a record with
    ``registry_ids`` (population mode, schema v11) contributes its
    cohort-sized lists to the rows of the sampled clients only; rows
    are allocated on first sighting, so the ledger grows to the set of
    clients ever sampled while every record stays cohort-bounded.
    Records without ``registry_ids`` key slot k to client id k — the
    mapping is the identity for dense streams, so every pre-population
    aggregate is byte-identical.
    """

    def __init__(self):
        self.clients = 0              # distinct clients observed (rows)
        self.records = 0              # client records observed
        self.sparse = False           # saw a registry_ids record
        self._rounds: List[int] = []  # round_index per record, file order
        #: per record: (ledger-row index array, [k] glyphs)
        self._glyphs: List[Any] = []
        self._idmap: Dict[int, int] = {}   # registry id -> ledger row
        self._rids: List[int] = []         # ledger row -> registry id
        self._prev_members = np.zeros(0, bool)
        self._prev_seen = np.zeros(0, bool)

    def _rows(self, rids: List[int]) -> np.ndarray:
        """Ledger rows for this record's ids, allocating new rows (and
        growing every aggregate array) for first-seen clients."""
        pad = 0
        for r in rids:
            if r not in self._idmap:
                self._idmap[r] = len(self._rids)
                self._rids.append(r)
                pad += 1
        if pad:
            z = lambda: np.zeros(pad, np.float64)
            if self.clients == 0:
                for name in _STATS:
                    setattr(self, name, z())
            else:
                for name in _STATS:
                    setattr(self, name,
                            np.concatenate([getattr(self, name), z()]))
            self._prev_members = np.concatenate(
                [self._prev_members, np.zeros(pad, bool)])
            self._prev_seen = np.concatenate(
                [self._prev_seen, np.zeros(pad, bool)])
            self.clients = len(self._rids)
        return np.asarray([self._idmap[r] for r in rids], np.int64)

    def observe(self, rec: Dict[str, Any]) -> None:
        """Accumulate one record; ignores everything but ``client``."""
        if rec.get("event") != "client":
            return
        k = int(rec.get("clients", 0))
        if k <= 0:
            return
        reg = rec.get("registry_ids")
        if isinstance(reg, list) and len(reg) == k:
            rids = [int(r) for r in reg]
            self.sparse = True
        else:
            rids = list(range(k))
        idx = self._rows(rids)
        self.records += 1
        self._rounds.append(int(rec.get("round_index", -1)))

        def arr(name, default=None):
            v = rec.get(name)
            if not isinstance(v, list) or len(v) != k:
                return default
            return np.asarray(v, np.float64)

        norm = arr("update_norm")
        if norm is not None:
            finite = np.isfinite(norm)
            self.norm_sum[idx[finite]] += norm[finite]
            self.norm_n[idx[finite]] += 1.0
            self.nonfinite[idx[~finite]] += 1.0
        dist = arr("dist_z")
        if dist is not None:
            fin = np.isfinite(dist)
            self.dist_sum[idx[fin]] += dist[fin]
            self.dist_n[idx[fin]] += 1.0
        loss = arr("loss_client")
        if loss is not None:
            fin = np.isfinite(loss)
            self.loss_sum[idx[fin]] += loss[fin]
        active = arr("active")
        act = (active > 0) if active is not None else np.zeros(k, bool)
        if active is not None:
            self.active_rounds[idx] += act.astype(np.float64)
        weight = arr("weight")
        if weight is not None:
            self.weight_sum[idx] += weight
        gok = arr("guard_ok")
        gfail = np.zeros(k, bool)
        if gok is not None and active is not None:
            gfail = act & (gok < 0.5)
            self.guard_checks[idx] += act.astype(np.float64)
            self.guard_fails[idx] += gfail.astype(np.float64)
        quar = arr("quarantine")
        quarm = (quar > 0) if quar is not None else np.zeros(k, bool)
        self.quar_rounds[idx] += quarm.astype(np.float64)
        drop = arr("dropped")
        strag = arr("straggled")
        corr = arr("corrupted")
        dropm = (drop > 0) if drop is not None else np.zeros(k, bool)
        stragm = (strag > 0) if strag is not None else np.zeros(k, bool)
        corrm = (corr > 0) if corr is not None else np.zeros(k, bool)
        self.drops[idx] += dropm.astype(np.float64)
        self.straggles[idx] += stragm.astype(np.float64)
        self.corrupts[idx] += corrm.astype(np.float64)
        stale = arr("staleness")
        admitted = arr("admitted")
        rejm = np.zeros(k, bool)
        if stale is not None:
            arrived = stale >= 0
            adm = (admitted > 0) if admitted is not None else arrived
            rejm = arrived & ~adm
            self.arrivals[idx] += arrived.astype(np.float64)
            self.admits[idx] += (arrived & adm).astype(np.float64)
            self.rejects[idx] += rejm.astype(np.float64)
            self.stale_sum[idx[arrived & adm]] += stale[arrived & adm]
        pb = rec.get("payload_bytes")
        if isinstance(pb, (int, float)) and not isinstance(pb, bool):
            self.bytes[idx] += float(pb) * act.astype(np.float64)
        members = arr("members")
        outm = np.zeros(k, bool)
        if members is not None:
            mem = members > 0
            outm = ~mem
            self.member_rounds[idx] += mem.astype(np.float64)
            # join/leave transitions only for rows with a known previous
            # state: a first sighting is baseline, not a transition —
            # exactly the old dense behaviour (no counting on record 1)
            seen = self._prev_seen[idx]
            prev = self._prev_members[idx]
            self.joins[idx[seen & mem & ~prev]] += 1.0
            self.leaves[idx[seen & ~mem & prev]] += 1.0
            self._prev_members[idx] = mem
            self._prev_seen[idx] = True
        else:
            # no churn field: first-seen rows default to member (the
            # old `ones(k)` baseline), known rows keep their last state
            fresh = idx[~self._prev_seen[idx]]
            self._prev_members[fresh] = True
            self._prev_seen[fresh] = True

        # one glyph per client for the timeline view (priority order)
        nonfin = (~np.isfinite(norm)) if norm is not None \
            else np.zeros(k, bool)
        row = []
        for i in range(k):
            if outm[i]:
                g = "_"
            elif quarm[i]:
                g = "q"
            elif dropm[i]:
                g = "D"
            elif stragm[i]:
                g = "S"
            elif corrm[i] or nonfin[i]:
                g = "C"
            elif gfail[i]:
                g = "!"
            elif rejm[i]:
                g = "x"
            elif act[i]:
                g = "."
            else:
                g = "-"
            row.append(g)
        self._glyphs.append((idx, row))

    # -- derived statistics ---------------------------------------------

    def _rate(self, num: np.ndarray, den: np.ndarray) -> np.ndarray:
        return num / np.maximum(den, 1.0)

    def mean_norms(self) -> np.ndarray:
        """Per-client mean of FINITE update norms; NaN when none seen."""
        out = np.full(self.clients, np.nan, np.float64)
        have = self.norm_n > 0
        out[have] = self.norm_sum[have] / self.norm_n[have]
        return out

    def anomaly_scores(self) -> np.ndarray:
        """The deterministic composite (module docstring formula)."""
        k = self.clients
        if k == 0:
            return np.zeros(0, np.float64)

        def zscore(values: np.ndarray, have: np.ndarray) -> np.ndarray:
            z = np.zeros(k, np.float64)
            if have.sum() >= 2:
                v = values[have]
                sd = float(np.std(v))
                if sd > 0.0:
                    z[have] = (v - float(np.mean(v))) / sd
            return z

        mean_norm = self.mean_norms()
        z_norm = zscore(np.nan_to_num(mean_norm, nan=0.0),
                        self.norm_n > 0)
        stale_mean = self._rate(self.stale_sum, self.admits)
        z_stale = zscore(stale_mean, self.admits > 0)
        gfail_rate = self._rate(self.guard_fails, self.guard_checks)
        nobs = self.norm_n + self.nonfinite
        nonfin_rate = self._rate(self.nonfinite, nobs)
        return z_norm + z_stale + 4.0 * gfail_rate + 4.0 * nonfin_rate

    def ids(self) -> List[int]:
        """Observed client (registry) ids, ascending; dense streams
        yield ``0..K-1``."""
        return sorted(self._rids)

    def ranking(self) -> List[Dict[str, Any]]:
        """Clients sorted by anomaly score (desc), ties by id (asc).

        ``client`` is the REGISTRY id (== the dense slot id on
        non-population streams)."""
        scores = self.anomaly_scores()
        rids = np.asarray(self._rids, np.int64).reshape(-1)
        order = np.lexsort((rids, -scores))
        mean_norm = self.mean_norms()
        out = []
        for i in order:
            i = int(i)
            out.append({
                "client": int(rids[i]),
                "score": float(scores[i]),
                "mean_norm": (None if not np.isfinite(mean_norm[i])
                              else float(mean_norm[i])),
                "nonfinite": int(self.nonfinite[i]),
                "guard_fails": int(self.guard_fails[i]),
                "drops": int(self.drops[i]),
                "straggles": int(self.straggles[i]),
                "corrupts": int(self.corrupts[i]),
                "rejects": int(self.rejects[i]),
                "active_rounds": int(self.active_rounds[i]),
                "bytes": int(self.bytes[i]),
            })
        return out

    def summary_fields(self) -> Dict[str, Any]:
        """Dispersion fields for report/compare ({} with no records)."""
        if self.records == 0:
            return {}
        mean_norm = self.mean_norms()
        finite = mean_norm[np.isfinite(mean_norm)]
        scores = self.anomaly_scores()
        rids = np.asarray(self._rids, np.int64).reshape(-1)
        top = int(np.lexsort((rids, -scores))[0])
        out: Dict[str, Any] = {
            "client_records": self.records,
            "clients_observed": self.clients,
            "top_offender": int(rids[top]),
            "top_offender_score": float(scores[top]),
        }
        if finite.size:
            mx, med = float(np.max(finite)), float(np.median(finite))
            out["client_norm_max"] = mx
            out["client_norm_median"] = med
            if med > 0.0:
                out["client_norm_skew"] = mx / med
        if np.any(self.bytes > 0):
            out["client_bytes_max"] = float(np.max(self.bytes))
            out["client_bytes_median"] = float(np.median(self.bytes))
        return out

    def cohorts(self, n: int) -> List[Dict[str, Any]]:
        """Contiguous-id cohort rollup (the virtualization-ready view:
        when clients outnumber chips, a cohort is the scheduling unit
        and the ledger key stays ``client_id``)."""
        k = self.clients
        n = max(1, min(int(n), k)) if k else 0
        out = []
        scores = self.anomaly_scores()
        mean_norm = self.mean_norms()
        rids = np.asarray(self._rids, np.int64).reshape(-1)
        order = np.argsort(rids, kind="stable")   # rows in id order
        bounds = [round(j * k / n) for j in range(n + 1)]
        for j in range(n):
            lo, hi = bounds[j], bounds[j + 1]
            if hi <= lo:
                continue
            rows = order[lo:hi]
            mn = mean_norm[rows]
            mn = mn[np.isfinite(mn)]
            out.append({
                "cohort": j,
                "clients": f"{rids[rows[0]]}..{rids[rows[-1]]}",
                "mean_norm": float(np.mean(mn)) if mn.size else None,
                "faults": int(self.drops[rows].sum()
                              + self.straggles[rows].sum()
                              + self.corrupts[rows].sum()),
                "guard_fails": int(self.guard_fails[rows].sum()),
                "bytes": int(self.bytes[rows].sum()),
                "score_max": float(np.max(scores[rows])),
            })
        return out

    def timelines(self) -> List[str]:
        """One glyph string per client (ascending id — :meth:`ids`
        order), rounds left to right; '-' where a client was not in
        that round's record (population mode: not sampled)."""
        cols = []
        for idx, row in self._glyphs:
            col = np.full(self.clients, "-", dtype="<U1")
            col[idx] = row
            cols.append(col)
        rids = np.asarray(self._rids, np.int64).reshape(-1)
        order = np.argsort(rids, kind="stable")
        return ["".join(col[i] for col in cols) for i in order]


def ledger_from_records(records: Sequence[Dict[str, Any]]) -> ClientLedger:
    led = ClientLedger()
    for rec in records:
        led.observe(rec)
    return led


def summarize_clients(records: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Client-dispersion summary fields of a stream ({} when none)."""
    return ledger_from_records(records).summary_fields()


def format_clients(led: ClientLedger, *, top: int = 10,
                   cohorts: int = 0) -> str:
    """Human-readable flight-recorder view."""
    if led.records == 0:
        return "no client records in stream (client_ledger off, or a " \
               "pre-v10 artifact)"
    if led.sparse:
        lines = [f"client ledger: {led.clients} registry client(s) "
                 f"observed (sparse cohorts), {led.records} round "
                 f"record(s)"]
    else:
        lines = [f"client ledger: K={led.clients}, {led.records} round "
                 f"record(s)"]
    lines.append("  timeline glyphs: " + " ".join(
        f"{g}={name}" for name, g in _GLYPHS))
    tls = led.timelines()
    ids = led.ids()
    width = max(len(str(max(ids))), 2)
    for i, tl in zip(ids, tls):
        lines.append(f"  c{i:<{width}} |{tl}|")
    rank = led.ranking()
    lines.append(f"anomaly ranking (top {min(top, len(rank))}; "
                 "score = z(norm) + z(staleness) + 4*guard_fail_rate "
                 "+ 4*nonfinite_rate):")
    hdr = (f"  {'rank':<5}{'client':<7}{'score':>8}  {'mean_norm':>10}"
           f"  {'nonfin':>6}{'gfail':>6}{'drop':>5}{'strag':>6}"
           f"{'corr':>5}{'rej':>4}  {'bytes':>10}")
    lines.append(hdr)
    for r, row in enumerate(rank[:top], 1):
        mn = ("-" if row["mean_norm"] is None
              else f"{row['mean_norm']:.4g}")
        lines.append(
            f"  {r:<5}{row['client']:<7}{row['score']:>8.3f}  {mn:>10}"
            f"  {row['nonfinite']:>6}{row['guard_fails']:>6}"
            f"{row['drops']:>5}{row['straggles']:>6}{row['corrupts']:>5}"
            f"{row['rejects']:>4}  {row['bytes']:>10}")
    s = led.summary_fields()
    if "client_norm_skew" in s:
        lines.append(f"norm skew: max={s['client_norm_max']:.4g} "
                     f"median={s['client_norm_median']:.4g} "
                     f"skew={s['client_norm_skew']:.3f}")
    if cohorts:
        lines.append(f"cohort rollup ({cohorts} cohort(s)):")
        for c in led.cohorts(cohorts):
            mn = ("-" if c["mean_norm"] is None
                  else f"{c['mean_norm']:.4g}")
            lines.append(
                f"  cohort {c['cohort']} [{c['clients']}] "
                f"mean_norm={mn} faults={c['faults']} "
                f"guard_fails={c['guard_fails']} bytes={c['bytes']} "
                f"score_max={c['score_max']:.3f}")
    return "\n".join(lines)


def selftest() -> str:
    """Synthesize a two-segment stream through the REAL recorder, then
    assert ledger units, ranking determinism, and the JSONL replay
    contract (chained into tier-1 ``report --selftest``)."""
    import os
    import tempfile

    from federated_pytorch_test_tpu.obs.recorder import make_recorder
    from federated_pytorch_test_tpu.obs.report import read_records

    K = 4
    nan = float("nan")

    def emit_round(rec, i, *, resumed_offset=0):
        ri = i + resumed_offset
        rec.round({"round_index": ri, "nloop": 0, "block": 0, "nadmm": ri,
                   "N": 10, "loss": 1.0, "rho": 1.0, "round_seconds": 0.1,
                   "images": 64})
        # client 2 ships NaN every round; client 3 straggles on round 1
        norm = [1.0, 1.1, nan, 0.9]
        rec.client_event(client_round_fields(
            ri, K,
            update_norm=norm,
            dist_z=[0.5, 0.6, nan, 0.4],
            loss=[0.2, 0.3, 0.1, 0.4],
            weight=[1.0, 1.0, 1.0, 1.0],
            active=[1.0, 1.0, 1.0, 0.0 if i == 1 else 1.0],
            guard_ok=[1.0, 1.0, 0.0, 1.0],
            quarantine=[0, 0, 0, 0],
            dropped=[0.0, 0.0, 0.0, 0.0],
            straggled=[0.0, 0.0, 0.0, 1.0 if i == 1 else 0.0],
            corrupted=[0.0, 0.0, 1.0, 0.0],
            staleness=[0, 0, 0, -1],
            admitted=[1.0, 1.0, 1.0, 0.0],
            members=[1.0, 1.0, 1.0, 1.0],
            payload_bytes=40))

    with tempfile.TemporaryDirectory() as d:
        # two segments in one file: a resumed run appends to the stream,
        # and the ledger/ranking must be a pure function of file order
        rec = make_recorder("jsonl", d, run_name="clients_selftest",
                            engine="selftest", algorithm="fedavg")
        rec.open(config={"K": K})
        for i in range(2):
            emit_round(rec, i)
        rec.close(status="aborted")
        rec2 = make_recorder("jsonl", d, run_name="clients_selftest",
                             engine="selftest", algorithm="fedavg")
        rec2.jsonl_path = rec.jsonl_path
        rec2.open(config={"K": K}, resumed=True, rounds_prior=2)
        emit_round(rec2, 0, resumed_offset=2)
        rec2.close()
        path = os.path.join(d, "clients_selftest.jsonl")
        records = read_records(path)
        crecs = [r for r in records if r["event"] == "client"]
        assert len(crecs) == 3, \
            f"segment 2 must append to the same stream: {len(crecs)}"
        led = ledger_from_records(records)
        # ledger units vs hand-computed values (2 rounds + 1 resumed)
        assert led.clients == K and led.records == 3
        assert led.nonfinite[2] == 3 and led.norm_n[2] == 0, \
            (led.nonfinite, led.norm_n)
        assert abs(led.mean_norms()[0] - 1.0) < 1e-12
        assert led.guard_fails.tolist() == [0.0, 0.0, 3.0, 0.0]
        assert led.straggles.tolist() == [0.0, 0.0, 0.0, 1.0]
        assert led.active_rounds.tolist() == [3.0, 3.0, 3.0, 2.0]
        assert led.bytes.tolist() == [120.0, 120.0, 120.0, 80.0]
        rank = led.ranking()
        assert rank[0]["client"] == 2, rank
        assert rank[0]["score"] > rank[1]["score"], rank
        # replay contract: recompute from the SAME parsed stream —
        # byte-identical scores (float64 repr equality)
        led2 = ledger_from_records(read_records(path))
        assert (led.anomaly_scores().tobytes()
                == led2.anomaly_scores().tobytes()), "ranking not replayable"
        s = led.summary_fields()
        assert s["top_offender"] == 2, s
        assert s["client_norm_max"] >= s["client_norm_median"] > 0, s
        cz = led.cohorts(2)
        assert len(cz) == 2 and cz[1]["guard_fails"] == 3, cz
        table = format_clients(led, cohorts=2)
        assert "anomaly ranking" in table and "cohort 1" in table
        tls = led.timelines()
        assert tls[2][0] == "C", tls     # corrupted glyph wins
        assert tls[3][1] == "S", tls     # straggle on round 1

    # sparse population cohorts (schema v11): each record carries only
    # the sampled cohort, keyed by registry id — the ledger grows to
    # the clients ever seen and '-' fills unsampled rounds
    nan = float("nan")
    recs = [dict(event="client", schema=11, run_id="x", round_index=0,
                 clients=2, registry_ids=[3, 900],
                 update_norm=[1.0, 1.0], active=[1.0, 1.0]),
            dict(event="client", schema=11, run_id="x", round_index=1,
                 clients=2, registry_ids=[3, 41],
                 update_norm=[1.0, nan], active=[1.0, 1.0])]
    sled = ledger_from_records(recs)
    assert sled.sparse and sled.clients == 3
    assert sled.ids() == [3, 41, 900]
    assert sled.ranking()[0]["client"] == 41          # NaN shipper, by rid
    assert sled.summary_fields()["top_offender"] == 41
    tl = dict(zip(sled.ids(), sled.timelines()))
    assert tl[3] == ".." and tl[41] == "-C" and tl[900] == ".-", tl
    assert (ledger_from_records(recs).anomaly_scores().tobytes()
            == sled.anomaly_scores().tobytes())
    return "obs clients selftest: OK (NaN client ranks first; replayable)"


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m federated_pytorch_test_tpu.obs.clients",
        description="Per-client flight-recorder view of an obs run JSONL "
                    "(see README 'Observability')")
    p.add_argument("paths", nargs="*",
                   help="run JSONL file(s); multi-segment streams and "
                        "multiple files are folded in argument order")
    p.add_argument("--top", type=int, default=10,
                   help="ranking rows to print (default 10)")
    p.add_argument("--cohorts", type=int, default=0,
                   help="also print an N-cohort contiguous rollup")
    p.add_argument("--expect-top", type=int, default=None, metavar="ID",
                   help="exit 2 unless the anomaly rank-1 client is ID "
                        "(CI assertion hook; ID is the REGISTRY id on "
                        "population streams)")
    p.add_argument("--json", action="store_true",
                   help="print {ranking, summary, cohorts} as one JSON "
                        "object (deterministic: byte-identical across "
                        "recomputations of the same stream)")
    p.add_argument("--no-validate", action="store_true",
                   help="skip schema validation while parsing")
    p.add_argument("--selftest", action="store_true",
                   help="run the built-in selftest and exit")
    args = p.parse_args(argv)
    if args.selftest:
        print(selftest())
        return 0
    if not args.paths:
        p.error("at least one run JSONL path is required (or --selftest)")
    from federated_pytorch_test_tpu.obs.report import read_records
    from federated_pytorch_test_tpu.obs.schema import SchemaError
    led = ClientLedger()
    try:
        for path in args.paths:
            for rec in read_records(path, validate=not args.no_validate):
                led.observe(rec)
    except (OSError, SchemaError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if args.json:
        out = {"ranking": led.ranking(), "summary": led.summary_fields()}
        if args.cohorts:
            out["cohorts"] = led.cohorts(args.cohorts)
        print(json.dumps(out))
    else:
        print(format_clients(led, top=args.top, cohorts=args.cohorts))
    if args.expect_top is not None:
        rank = led.ranking()
        got = rank[0]["client"] if rank else None
        if got != args.expect_top:
            print(f"error: expected client {args.expect_top} at anomaly "
                  f"rank 1, got {got!r}", file=sys.stderr)
            return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
