"""Cross-run regression comparison over obs/bench artifacts.

``python -m federated_pytorch_test_tpu.obs.compare RUN... --baseline B``
diffs N candidate artifacts against a baseline and exits nonzero on
regression, so CI can gate on it.  Accepted inputs (auto-detected):

- an obs run JSONL (``*.jsonl``) — metrics from
  :func:`~.report.summarize`: throughput and rounds/sec (higher is
  better), final loss and comm-overhead fraction (lower is better),
  compression savings (higher).
- a bench.py artifact (``artifacts/bench_*.json``) — the headline
  metric named by its ``metric`` field plus the ``*_ips_chip`` section
  breakdowns and ``mfu`` (all higher-better).
- a ``BENCH_rNN.json`` wrapper (``{n, cmd, rc, tail, parsed}``) — the
  embedded ``parsed`` artifact is unwrapped.
- ``BASELINE.json`` — its ``published`` dict; when that is empty (no
  published numbers yet) the comparison says so instead of inventing a
  verdict.

Honesty about unmeasured data: an artifact with ``measured: false`` has
value 0.0 by construction; comparing it would manufacture a fake
regression.  If it embeds a ``last_measured`` reference the headline is
PROMOTED from there and annotated; otherwise the artifact contributes
no verdict and the report says "unmeasured".

A candidate bench artifact may carry ``baseline_ref`` (bench.py emits
it); when no ``--baseline`` flag is given and exactly one candidate is
compared, that reference is resolved automatically.

Verdicts use a noise-aware relative threshold (``--threshold``, percent,
default 5%): deltas within the band are "ok(noise)", beyond it "improved"
or "REGRESSED".  Exit codes: 0 no regression, 1 regression, 2 usage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

#: metric name -> +1 (higher is better) / -1 (lower is better)
_DIRECTION = {
    "images_per_sec": +1,
    "rounds_per_sec": +1,
    "compression_savings_frac": +1,
    "loss_final": -1,
    "comm_overhead_frac": -1,
    "mfu": +1,
    "value": +1,
    # device-cost ledger metrics (schema v6; obs/profile.py): a compile-
    # time or device-memory regression fails the gate like a throughput
    # regression does
    "compile_seconds": -1,
    "compile_seconds_cold": -1,
    "peak_device_bytes": -1,
    "utilization": +1,
    "cache_hit_rate": +1,
    # soak campaigns (schema v12; bench.py --soak): the availability
    # gate — losing availability or losing more rounds to restarts than
    # the committed SOAK_BASELINE fails CI like a throughput regression
    "availability_pct": +1,
    "rounds_lost": -1,
}


def _direction(name: str) -> int:
    if name in _DIRECTION:
        return _DIRECTION[name]
    if name.endswith("_ips_chip") or name.endswith("_throughput"):
        return +1
    # roofline comm-path gate (bench.py --smoke): predicted byte counts
    # regress UP, compression/savings ratios regress DOWN
    if name.endswith("_wire_bytes"):
        return -1
    if name.endswith("_savings_ratio"):
        return +1
    # chunked robust-agg gate (bench.py --smoke): the predicted gathered
    # working set and the compiled memory_analysis peak both regress UP
    if name.endswith("_gather_bytes"):
        return -1
    if name.endswith("_peak_device_bytes"):
        return -1
    # soak gate fields on bench --soak artifacts (soak_availability_pct
    # headline + soak_rounds_lost section metric)
    if name.endswith("_availability_pct"):
        return +1
    if name.endswith("_rounds_lost"):
        return -1
    # serving-plane gate (schema v13; bench.py --serve-bench): sustained
    # QPS regresses DOWN, tail latency and the hot-swap publish gap
    # regress UP — the rest of the serve_* section (padding waste,
    # request counts) stays info-direction via the startswith passthrough
    if name.startswith("serve_qps"):
        return +1
    if name.startswith("serve_p99"):
        return -1
    if name.startswith("serve_swap_gap"):
        return -1
    return 0        # unknown: report the delta, never a verdict


class CompareError(ValueError):
    """Unusable input (unknown shape, unreadable file)."""


def expand_candidates(paths: List[str]) -> List[str]:
    """Resolve the candidate set: each argument may be a file, a
    directory (all ``*.jsonl`` run streams plus ``bench*.json``
    artifacts directly inside it), or a glob pattern.  Expansion is
    sorted per argument — deterministic ordering, so the bench matrix
    and chaos-test artifact directories gate identically across CI
    runs.  A directory/glob that matches nothing is an error (a silent
    empty candidate set would vacuously pass the gate)."""
    import glob as globlib
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            hits = sorted(globlib.glob(os.path.join(p, "*.jsonl"))) + \
                sorted(globlib.glob(os.path.join(p, "bench*.json")))
            if not hits:
                raise CompareError(
                    f"{p}: directory holds no *.jsonl or bench*.json "
                    "artifacts")
            out.extend(hits)
        elif any(ch in p for ch in "*?["):
            hits = sorted(globlib.glob(p))
            if not hits:
                raise CompareError(f"{p}: glob matched no files")
            out.extend(hits)
        else:
            out.append(p)
    return out


def _num(v) -> Optional[float]:
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v)
    return None


def load_source(path: str) -> Dict[str, Any]:
    """Load one artifact into ``{path, kind, metrics, notes, ...}``."""
    src: Dict[str, Any] = {"path": path, "kind": "?", "metrics": {},
                           "notes": [], "baseline_ref": None}
    if path.endswith(".jsonl"):
        from federated_pytorch_test_tpu.obs.profile import profile_metrics
        from federated_pytorch_test_tpu.obs.report import (
            read_records,
            summarize,
        )

        records = read_records(path)
        s = summarize(records)
        src["kind"] = f"run ({s.get('engine') or '?'}, {s.get('status')})"
        for k in ("images_per_sec", "rounds_per_sec", "loss_final",
                  "comm_overhead_frac", "compression_savings_frac"):
            v = _num(s.get(k))
            if v is not None:
                src["metrics"][k] = v
        # elastic-federation membership (schema v9): info-direction
        # metrics (unknown to _DIRECTION -> delta reported, never a
        # verdict) — a churn run's roster is part of the experiment, so
        # membership differences against a static baseline must show up
        # in the diff without gating it
        for k in ("members_peak", "members_min", "joined_total",
                  "left_total"):
            v = _num(s.get(k))
            if v is not None:
                src["metrics"][k] = v
        if s.get("members_peak") is not None:
            src["notes"].append(
                f"dynamic membership (min {s.get('members_min')} / peak "
                f"{s.get('members_peak')} live members): loss/throughput "
                "diffs vs a static-roster baseline reflect the roster, "
                "not just the code")
        if s.get("reshapes"):
            src["notes"].append(
                f"{s['reshapes']} mesh reshape(s): segments ran on "
                "different device counts; wall-clock metrics span both")
        # client-grain dispersion (schema v10, obs/clients.py): info-
        # direction rows — per-client norm skew and the anomaly-ranking
        # top offender, so "is the same client the outlier in both
        # runs?" is answerable from the diff without gating on it
        for k in ("client_norm_skew", "client_norm_max",
                  "client_norm_median", "top_offender",
                  "top_offender_score"):
            v = _num(s.get(k))
            if v is not None:
                src["metrics"][k] = v
        if s.get("top_offender") is not None:
            src["notes"].append(
                f"client ledger: top offender c{s['top_offender']} "
                f"(score {s.get('top_offender_score', 0.0):.3f}) over "
                f"{s.get('client_records')} client record(s) — compare "
                "across runs for offender stability")
        # soak availability (schema v12): the two gated numbers of the
        # availability contract plus info-direction campaign context, so
        # a soak stream can be gated directly against a baseline stream
        for k in ("availability_pct", "rounds_lost"):
            v = _num(s.get(k))
            if v is not None:
                src["metrics"][k] = v
        for k in ("segments", "campaign_records",
                  "campaign_virtual_hours"):
            v = _num(s.get(k))
            if v is not None:
                src["metrics"][k] = v
        if s.get("campaign_records"):
            src["notes"].append(
                f"soak campaign stream: {s.get('segments')} segment(s), "
                f"{s.get('campaign_virtual_hours')} virtual h, "
                f"availability {s.get('availability_pct')}%")
        # device-cost metrics (schema v6): present only when the run's
        # ledger emitted them, so pre-v6 streams compare unchanged
        for k, val in profile_metrics(records).items():
            v = _num(val)
            if v is not None:
                src["metrics"][k] = v
        if s.get("status") != "completed":
            src["notes"].append(f"status={s.get('status')}")
        # control-plane records (schema v8): a supervised run that
        # restarted or had interventions fire is flagged, never gated —
        # its wall-clock numbers include recovery work and a changed
        # config, so a "regression" verdict would be comparing different
        # experiments
        if s.get("restarts"):
            src["notes"].append(
                f"{s['restarts']} supervised restart(s); wall-clock "
                "metrics include recovery")
        elif s.get("controls"):
            src["notes"].append(
                f"{s['controls']} control intervention(s) fired "
                "mid-run")
        return src
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise CompareError(f"{path}: {e}")
    if not isinstance(obj, dict):
        raise CompareError(f"{path}: expected a JSON object")
    if isinstance(obj.get("parsed"), dict):       # BENCH_rNN.json wrapper
        src["notes"].append(f"BENCH wrapper (iteration {obj.get('n')})")
        obj = obj["parsed"]
    if "metric" in obj and "value" in obj:        # bench.py artifact
        src["kind"] = "bench"
        src["baseline_ref"] = obj.get("baseline_ref")
        headline = str(obj["metric"])
        measured = obj.get("measured", True)
        if measured:
            v = _num(obj.get("value"))
            if v is not None:
                src["metrics"][headline] = v
            for k, val in obj.items():
                # smoke_* covers bench.py --smoke fields: the *_wire_bytes
                # ones gate (direction -1), the rest report as info
                # population_* covers bench.py --population-bench: the
                # *_throughput and *_savings_ratio fields gate by suffix
                # rule, the K/cohort/wall fields report as info
                # soak_* covers bench.py --soak: availability/rounds-lost
                # gate by the direction rules, the rest report as info
                # serve_* covers bench.py --serve-bench: qps/p99/swap-gap
                # gate by the direction rules, the rest report as info
                if (k.endswith("_ips_chip") or k == "mfu"
                        or k.endswith("_wire_bytes")
                        or k.endswith("_savings_ratio")
                        or k.startswith("smoke_")
                        or k.startswith("population_")
                        or k.startswith("soak_")
                        or k.startswith("serve_")):
                    v = _num(val)
                    if v is not None:
                        src["metrics"][k] = v
        else:
            last = obj.get("last_measured")
            v = _num(last.get("value")) if isinstance(last, dict) else None
            if v is not None:
                src["metrics"][headline] = v
                src["notes"].append(
                    "measured=false; headline PROMOTED from "
                    f"{last.get('path', '?')} ({last.get('captured_utc')})")
            else:
                src["notes"].append(
                    "measured=false and no last_measured reference — "
                    "no comparable metrics (unmeasured)")
        return src
    if isinstance(obj.get("published"), dict):    # BASELINE.json
        src["kind"] = "baseline"
        for k, val in obj["published"].items():
            v = _num(val)
            if v is not None:
                src["metrics"][k] = v
        if not src["metrics"]:
            src["notes"].append(
                "BASELINE.json carries no published numbers yet — "
                "nothing to compare against")
        return src
    raise CompareError(f"{path}: unrecognised artifact shape (not a run "
                       "JSONL, bench artifact, BENCH wrapper, or baseline)")


def compare(baseline: Dict[str, Any], candidates: List[Dict[str, Any]],
            threshold_pct: float = 5.0) -> Dict[str, Any]:
    """Per-metric deltas + verdicts.  Returns ``{rows, regressions, notes}``."""
    thr = abs(threshold_pct) / 100.0
    names: List[str] = []
    for source in [baseline] + candidates:
        for k in source["metrics"]:
            if k not in names:
                names.append(k)
    rows = []
    regressions = 0
    for name in names:
        base = baseline["metrics"].get(name)
        cells = []
        for c in candidates:
            v = c["metrics"].get(name)
            if v is None or base is None:
                cells.append({"value": v, "delta": None,
                              "verdict": "n/a" if v is None else "no-base"})
                continue
            delta = (v - base) / abs(base) if base else (0.0 if v == base
                                                         else float("inf"))
            sign = _direction(name)
            if sign == 0:
                verdict = "info"
            elif abs(delta) <= thr:
                verdict = "ok(noise)"
            elif delta * sign > 0:
                verdict = "improved"
            else:
                verdict = "REGRESSED"
                regressions += 1
            cells.append({"value": v, "delta": delta, "verdict": verdict})
        rows.append({"metric": name, "baseline": base, "cells": cells})
    notes = [f"{s['path']}: {n}" for s in [baseline] + candidates
             for n in s["notes"]]
    return {"rows": rows, "regressions": regressions, "notes": notes,
            "threshold_pct": abs(threshold_pct)}


def _fmt(v: Optional[float]) -> str:
    if v is None:
        return "—"
    return f"{v:,.4g}"


def render_markdown(result: Dict[str, Any], baseline: Dict[str, Any],
                    candidates: List[Dict[str, Any]]) -> str:
    """``accuracy_comparison``-style markdown matrix."""
    lines = [f"## Run comparison (threshold ±{result['threshold_pct']:g}%)",
             "",
             f"Baseline: `{baseline['path']}` ({baseline['kind']})", ""]
    hdr = ["metric", "baseline"] + [os.path.basename(c["path"])
                                    for c in candidates]
    lines.append("| " + " | ".join(hdr) + " |")
    lines.append("|" + "---|" * len(hdr))
    for row in result["rows"]:
        cells = [row["metric"], _fmt(row["baseline"])]
        for cell in row["cells"]:
            if cell["delta"] is None:
                cells.append(f"{_fmt(cell['value'])} ({cell['verdict']})")
            else:
                cells.append(f"{_fmt(cell['value'])} "
                             f"({cell['delta']:+.1%}, {cell['verdict']})")
        lines.append("| " + " | ".join(cells) + " |")
    if not result["rows"]:
        lines.append("*(no comparable metrics)*")
    for n in result["notes"]:
        lines.append(f"- note: {n}")
    lines.append("")
    lines.append(f"**{result['regressions']} regression(s)**")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m federated_pytorch_test_tpu.obs.compare",
        description="Diff run/bench artifacts against a baseline; exit 1 "
                    "on regression (CI gate)")
    p.add_argument("paths", nargs="+",
                   help="candidate artifacts (run .jsonl, bench .json, "
                        "BENCH_rNN.json), or a directory / glob of them "
                        "(expanded sorted, so the candidate order is "
                        "deterministic)")
    p.add_argument("--baseline", help="baseline artifact; defaults to the "
                   "single candidate's embedded baseline_ref")
    p.add_argument("--threshold", type=float, default=5.0,
                   help="noise band, percent (default 5)")
    p.add_argument("--json", action="store_true",
                   help="emit the comparison as JSON instead of markdown")
    args = p.parse_args(argv)
    try:
        cand_paths = expand_candidates(args.paths)
        candidates = [load_source(pth) for pth in cand_paths]
        base_path = args.baseline
        if base_path is None:
            refs = [c["baseline_ref"] for c in candidates
                    if c.get("baseline_ref")]
            if len(candidates) == 1 and refs:
                ref = refs[0]
                if not os.path.exists(ref):   # refs are repo-root relative
                    rel = os.path.join(os.path.dirname(cand_paths[0]) or ".",
                                       ref)
                    ref = rel if os.path.exists(rel) else ref
                base_path = ref
                print(f"(baseline from artifact baseline_ref: {base_path})",
                      file=sys.stderr)
        if base_path is None:
            p.error("--baseline is required (no candidate carries a "
                    "baseline_ref)")
        baseline = load_source(base_path)
    except CompareError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    result = compare(baseline, candidates, args.threshold)
    if args.json:
        print(json.dumps({"baseline": baseline["path"],
                          "candidates": [c["path"] for c in candidates],
                          **result}))
    else:
        print(render_markdown(result, baseline, candidates))
    return 1 if result["regressions"] else 0


def selftest() -> None:
    """Self-vs-self exits 0; a synthetic regression exits 1; used by
    ``report --selftest``."""
    import contextlib
    import io
    import tempfile

    def run(argv):
        with contextlib.redirect_stdout(io.StringIO()), \
                contextlib.redirect_stderr(io.StringIO()):
            return main(argv)

    art = {"metric": "cifar10_resnet18_consensus_full_round_throughput",
           "value": 30000.0, "unit": "images/sec/chip", "measured": True,
           "stem_block_ips_chip": 26000.0, "mfu": 0.36}
    with tempfile.TemporaryDirectory() as d:
        base = os.path.join(d, "base.json")
        with open(base, "w") as f:
            json.dump(art, f)
        same = os.path.join(d, "same.json")
        with open(same, "w") as f:
            json.dump(dict(art, baseline_ref=base), f)
        rc = run([same])                        # baseline via baseline_ref
        assert rc == 0, f"self-vs-self must exit 0, got {rc}"
        regressed = os.path.join(d, "regressed.json")
        with open(regressed, "w") as f:
            json.dump(dict(art, value=20000.0, mfu=0.24), f)
        rc = run([regressed, "--baseline", base])
        assert rc == 1, f"regressed artifact must exit 1, got {rc}"
        unmeasured = os.path.join(d, "unmeasured.json")
        with open(unmeasured, "w") as f:
            json.dump({"metric": art["metric"], "value": 0.0,
                       "measured": False}, f)
        rc = run([unmeasured, "--baseline", base])
        assert rc == 0, f"unmeasured artifact must not fake a regression"
        src = load_source(unmeasured)
        assert not src["metrics"] and src["notes"], src
        # directory / glob candidate expansion, deterministic ordering
        hits = expand_candidates([os.path.join(d, "*.json")])
        assert hits == sorted([base, regressed, same, unmeasured]), hits
        rc = run([os.path.join(d, "same.js*"), "--baseline", base])
        assert rc == 0, f"glob candidate must exit 0, got {rc}"
        try:
            expand_candidates([os.path.join(d, "no_such_*")])
        except CompareError:
            pass
        else:
            raise AssertionError("empty glob must raise (vacuous gate)")
        # soak availability gate: losing availability or rounds REGRESSES
        # (direction rules availability_pct/+1, *_rounds_lost/-1)
        soak = {"metric": "soak_availability_pct", "value": 95.0,
                "unit": "percent", "measured": True,
                "soak_rounds_lost": 3.0}
        sbase = os.path.join(d, "soak_base.json")
        with open(sbase, "w") as f:
            json.dump(soak, f)
        ssame = os.path.join(d, "soak_same.json")
        with open(ssame, "w") as f:
            json.dump(dict(soak, baseline_ref=sbase), f)
        assert run([ssame]) == 0, "soak self-vs-self must exit 0"
        sbad = os.path.join(d, "soak_bad.json")
        with open(sbad, "w") as f:
            json.dump(dict(soak, value=70.0, soak_rounds_lost=9.0), f)
        assert run([sbad, "--baseline", sbase]) == 1, \
            "availability drop must exit 1"
        assert _direction("availability_pct") == +1
        assert _direction("rounds_lost") == -1
        assert _direction("soak_availability_pct") == +1
        assert _direction("soak_rounds_lost") == -1
        # serving gate: dropping QPS or growing tail latency / swap gap
        # REGRESSES; padding waste is info-direction (reported, not gated)
        assert _direction("serve_qps_chip") == +1
        assert _direction("serve_throughput") == +1
        assert _direction("serve_p99_ms") == -1
        assert _direction("serve_swap_gap_seconds") == -1
        assert _direction("serve_padding_waste_frac") == 0
        serve = {"metric": "serve_qps_chip", "value": 400.0,
                 "unit": "requests/sec/chip", "measured": True,
                 "serve_p99_ms": 12.0, "serve_swap_gap_seconds": 0.05,
                 "serve_padding_waste_frac": 0.2}
        vbase = os.path.join(d, "serve_base.json")
        with open(vbase, "w") as f:
            json.dump(serve, f)
        vsame = os.path.join(d, "serve_same.json")
        with open(vsame, "w") as f:
            json.dump(dict(serve, baseline_ref=vbase), f)
        assert run([vsame]) == 0, "serve self-vs-self must exit 0"
        vbad = os.path.join(d, "serve_bad.json")
        with open(vbad, "w") as f:
            json.dump(dict(serve, value=200.0, serve_p99_ms=40.0), f)
        assert run([vbad, "--baseline", vbase]) == 1, \
            "QPS drop / p99 growth must exit 1"
        # a padding-waste-only change must NOT gate (info direction)
        vwaste = os.path.join(d, "serve_waste.json")
        with open(vwaste, "w") as f:
            json.dump(dict(serve, serve_padding_waste_frac=0.9), f)
        assert run([vwaste, "--baseline", vbase]) == 0, \
            "padding-waste delta must stay info-direction"


if __name__ == "__main__":
    sys.exit(main())
