"""Per-jit-site device-cost ledger.

Every engine entry point (``train_epoch``/``comm``/``fused_round`` in
train/engine.py and the CPC/VAE equivalents) is assembled through
``analysis.sanitize.instrument_jit``; the :class:`CostLedger` hooks into
that assembly at two points:

- :meth:`CostLedger.mark` wraps the *pre-jit* python callable with a
  per-site trace counter (same trick as ``TraceSentinel``) so a compile
  event is detected exactly — the counter bumps iff jax re-traced the
  function during a dispatch.
- :meth:`CostLedger.instrument` wraps the *jitted* callable with a
  dispatch timer.  Under jax's async dispatch the timed window covers
  trace + compile but not device execution, so when the trace counter
  moved across a dispatch the elapsed wall-seconds *are* the compile
  wall-seconds (plus O(100us) of dispatch overhead).

Per compile event the ledger records wall-seconds, the site's cumulative
trace count (1 == cold), AOT cost-model numbers, and a persistent-
compile-cache hit/miss attribution:

- ``FEDTPU_COST_AOT=lowered`` (default): ``jfn.lower(...)`` +
  ``Lowered.cost_analysis()`` — FLOPs / bytes-accessed /
  transcendentals from the unoptimized HLO.  Nearly free (~10ms) and
  side-effect free; tracing is already cached from the dispatch itself,
  and lowering works even on donated (deleted) argument buffers because
  only avals/shardings are consulted.
- ``FEDTPU_COST_AOT=full``: additionally ``lowered.compile()`` →
  optimized-HLO ``cost_analysis()`` + ``memory_analysis()``
  (argument/output/temp/generated-code bytes and the derived
  ``peak_device_bytes``).  The first AOT compile of a program is a
  *second real compile* (XLA does not share the dispatch executable
  with the AOT path), so this mode roughly doubles compile cost — keep
  it for profiling runs.
- ``FEDTPU_COST_AOT=off``: timing + cache attribution only.

Fields the backend cannot produce are **omitted, never zeroed** — a
reader must treat every cost field as optional (PARITY.md "advisory").

Cache attribution combines two signals: if the persistent compile cache
directory (utils/compile_cache.py) grew across the compile, a fresh
entry was persisted → miss; otherwise a fast compile (below
``FEDTPU_COST_FAST_COMPILE_S``, default 0.15s) is attributed to a cache
hit.  With no cache dir configured the attribution is ``None`` and the
field is omitted.

Math identity: the wrappers never touch values — they time the call and
read AOT analyses of the *same* lowering jax already cached.  Tests
assert bitwise-identical model state with the ledger on/off.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import stat as statmod
import time
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

__all__ = [
    "AOT_MODES",
    "CompileEvent",
    "CostLedger",
    "RoundCosts",
    "round_cost_fields",
]

AOT_MODES = ("off", "lowered", "full")

# Dispatches faster than this that did NOT grow the persistent cache dir
# are attributed to a compile-cache hit (deserialization is ~10-100x
# faster than compilation).  Deliberately generous: a miss that compiles
# this fast costs nothing to misattribute.
DEFAULT_FAST_COMPILE_S = 0.15

_EPS_S = 1e-9


def _env_aot_mode() -> str:
    mode = os.environ.get("FEDTPU_COST_AOT", "").strip().lower()
    return mode if mode in AOT_MODES else "lowered"


@dataclasses.dataclass
class CompileEvent:
    """One observed compile (re-trace) of one jit site."""

    site: str
    seconds: float
    t_start: float
    t_end: float
    trace_count: int  # cumulative traces of this site; 1 == cold start
    cache_hit: Optional[bool] = None  # None == unattributable (no cache dir)
    costs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def record(self, **extra: Any) -> Dict[str, Any]:
        """Flatten to a schema-v6 ``compile`` record body (env fields —
        event/schema/run_id — are the recorder's job)."""
        rec: Dict[str, Any] = {
            "site": self.site,
            "compile_seconds": float(self.seconds),
            "t_start": float(self.t_start),
            "t_end": float(self.t_end),
            "trace_count": int(self.trace_count),
        }
        if self.cache_hit is not None:
            rec["cache_hit"] = bool(self.cache_hit)
        rec.update(self.costs)
        rec.update(extra)
        return rec


class RoundCosts(NamedTuple):
    """One :meth:`CostLedger.drain` window (one round / epoch)."""

    events: Tuple[CompileEvent, ...]
    flops: float  # executed cost-model FLOPs (sum over dispatches)
    bytes_accessed: float  # executed cost-model HLO bytes
    peak_bytes: int  # max per-program peak_device_bytes dispatched


def round_cost_fields(costs: RoundCosts, t_start: float,
                      seconds: float) -> Dict[str, Any]:
    """Schema-v6 round fields for one drained window.

    ``compile_seconds``/``cache_hit`` count only events inside the
    [t_start, t_start+seconds] wall-clock window — events drained late
    (e.g. an eval compile detected next round) belong to the run, not
    this round.  Absent data is omitted, not zeroed.
    """
    out: Dict[str, Any] = {}
    t_hi = t_start + seconds + _EPS_S
    in_window = [e for e in costs.events
                 if e.t_start >= t_start - _EPS_S and e.t_end <= t_hi]
    if in_window:
        out["compile_seconds"] = float(sum(e.seconds for e in in_window))
        known = [e.cache_hit for e in in_window if e.cache_hit is not None]
        if known:
            out["cache_hit"] = all(known)
    if costs.flops > 0:
        out["flops_round"] = float(costs.flops)
    if costs.bytes_accessed > 0:
        out["hlo_bytes_accessed"] = float(costs.bytes_accessed)
    if costs.peak_bytes > 0:
        out["peak_device_bytes"] = int(costs.peak_bytes)
    return out


def _abstract_sig(args: tuple, kwargs: dict) -> Optional[tuple]:
    """Hashable (shape, dtype) signature of a call — keys the AOT memo so
    each (site, signature) pays for analysis once per process."""
    try:
        import jax

        leaves = jax.tree_util.tree_leaves((args, kwargs))
        sig = []
        for leaf in leaves:
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            if shape is not None:
                sig.append((tuple(shape), str(dtype)))
            else:
                sig.append((type(leaf).__name__, repr(leaf)[:64]))
        return tuple(sig)
    except Exception:
        return None


class CostLedger:
    """Per-jit-site compile/cost recorder (see module docstring).

    Thread-compatibility: engines drive all instrumented dispatches from
    the round loop thread; the ledger is intentionally not locked.
    """

    def __init__(self, *, aot_mode: Optional[str] = None,
                 cache_dir: Optional[str] = None,
                 fast_compile_s: Optional[float] = None) -> None:
        self.aot_mode = aot_mode if aot_mode in AOT_MODES else _env_aot_mode()
        self.fast_compile_s = (
            float(os.environ.get("FEDTPU_COST_FAST_COMPILE_S",
                                 DEFAULT_FAST_COMPILE_S))
            if fast_compile_s is None else float(fast_compile_s))
        self._marks: Dict[str, int] = {}  # site -> traces so far
        self._site_costs: Dict[str, Dict[str, Any]] = {}  # site -> last AOT
        self._aot_memo: Dict[tuple, Dict[str, Any]] = {}
        self._events: list = []  # pending (drained per round)
        self.all_events: list = []  # full run history (bench / profile)
        self._exec_flops = 0.0
        self._exec_bytes = 0.0
        self._exec_peak = 0
        self._cache_dir: Optional[str] = cache_dir
        self._cache_dir_resolved = cache_dir is not None
        self._cache_entries: Optional[int] = None

    # ---------------------------------------------------------- wiring

    def mark(self, fn: Callable, site: str) -> Callable:
        """Wrap the *pre-jit* callable with the per-site trace counter.
        Runs only while jax traces ``fn`` — zero steady-state cost."""
        self._marks.setdefault(site, 0)
        marks = self._marks

        @functools.wraps(fn)
        def counted(*args: Any, **kwargs: Any) -> Any:
            marks[site] = marks.get(site, 0) + 1
            return fn(*args, **kwargs)

        return counted

    def instrument(self, jfn: Callable, site: str) -> Callable:
        """Wrap the *jitted* callable with the compile-detecting timer."""
        marks = self._marks
        marks.setdefault(site, 0)

        @functools.wraps(jfn)
        def timed(*args: Any, **kwargs: Any) -> Any:
            n0 = marks.get(site, 0)
            t0 = time.perf_counter()
            out = jfn(*args, **kwargs)
            # Async dispatch: no block_until_ready on purpose — the
            # window must cover trace+compile, NOT device execution.
            t1 = time.perf_counter()  # graftlint: disable=JG104
            if marks.get(site, 0) != n0:
                self._on_compile(site, t0, t1, jfn, args, kwargs)
            self._on_dispatch(site)
            return out

        timed.__wrapped_jit__ = jfn  # AOT access for tests/tools
        return timed

    # ---------------------------------------------------------- events

    def _on_compile(self, site: str, t0: float, t1: float, jfn: Callable,
                    args: tuple, kwargs: dict) -> None:
        hit = self._classify_cache(t1 - t0)
        costs = self._analyze(jfn, site, args, kwargs)
        if costs:
            self._site_costs[site] = costs
        if self.aot_mode == "full":
            # A full-mode AOT compile may itself persist a cache entry;
            # absorb it so the *next* event's delta is clean.
            self._cache_entries = self._scan_cache()
        ev = CompileEvent(site=site, seconds=t1 - t0, t_start=t0, t_end=t1,
                          trace_count=self._marks.get(site, 0),
                          cache_hit=hit, costs=dict(costs))
        self._events.append(ev)
        self.all_events.append(ev)

    def _on_dispatch(self, site: str) -> None:
        costs = self._site_costs.get(site)
        if not costs:
            return
        self._exec_flops += float(costs.get("flops", 0.0))
        self._exec_bytes += float(costs.get("hlo_bytes_accessed", 0.0))
        peak = costs.get("peak_device_bytes")
        if isinstance(peak, int) and peak > self._exec_peak:
            self._exec_peak = peak

    def drain(self) -> RoundCosts:
        """Hand the pending window to the caller and reset accumulators."""
        out = RoundCosts(events=tuple(self._events),
                         flops=self._exec_flops,
                         bytes_accessed=self._exec_bytes,
                         peak_bytes=self._exec_peak)
        self._events = []
        self._exec_flops = 0.0
        self._exec_bytes = 0.0
        self._exec_peak = 0
        return out

    # ------------------------------------------------------ aggregates

    def totals(self) -> Dict[str, Any]:
        evs = self.all_events
        hits = sum(1 for e in evs if e.cache_hit is True)
        misses = sum(1 for e in evs if e.cache_hit is False)
        return {
            "compile_events": len(evs),
            "compile_seconds": float(sum(e.seconds for e in evs)),
            "cache_hits": hits,
            "cache_misses": misses,
            "cache_unknown": len(evs) - hits - misses,
            "sites": len(self._marks),
        }

    def cache_hit_rate(self) -> Optional[float]:
        """Hit fraction over attributable events; None if none were."""
        hits = sum(1 for e in self.all_events if e.cache_hit is True)
        misses = sum(1 for e in self.all_events if e.cache_hit is False)
        if hits + misses == 0:
            return None
        return hits / (hits + misses)

    # ------------------------------------------------- cache hit/miss

    def _resolve_cache_dir(self) -> Optional[str]:
        if not self._cache_dir_resolved:
            self._cache_dir_resolved = True
            try:
                import jax

                self._cache_dir = jax.config.jax_compilation_cache_dir
            except Exception:
                self._cache_dir = None
        return self._cache_dir

    def _scan_cache(self) -> Optional[int]:
        cache_dir = self._resolve_cache_dir()
        if not cache_dir:
            return None
        try:
            count = 0
            for name in os.listdir(cache_dir):
                try:
                    st = os.stat(os.path.join(cache_dir, name))
                except OSError:
                    continue
                if statmod.S_ISREG(st.st_mode):
                    count += 1
            return count
        except OSError:
            return None

    def _classify_cache(self, seconds: float) -> Optional[bool]:
        before = self._cache_entries
        now = self._scan_cache()
        self._cache_entries = now
        if now is None:
            return None  # no persistent cache configured -> omit
        if before is not None and now > before:
            return False  # a fresh entry was persisted -> genuine miss
        return seconds <= self.fast_compile_s

    # -------------------------------------------------------- AOT cost

    def _analyze(self, jfn: Callable, site: str, args: tuple,
                 kwargs: dict) -> Dict[str, Any]:
        if self.aot_mode == "off":
            return {}
        sig = _abstract_sig(args, kwargs)
        key = (site, sig) if sig is not None else None
        if key is not None and key in self._aot_memo:
            return dict(self._aot_memo[key])
        out: Dict[str, Any] = {}
        try:
            lowered = jfn.lower(*args, **kwargs)
        except Exception:
            return out
        self._merge_cost_analysis(out, lowered)
        if self.aot_mode == "full":
            self._merge_compiled(out, lowered)
        if key is not None:
            self._aot_memo[key] = dict(out)
        return out

    @staticmethod
    def _merge_cost_analysis(out: Dict[str, Any], analyzable: Any) -> None:
        """Pull flops / bytes-accessed / transcendentals out of a
        ``cost_analysis()`` result.  jax returns a dict (Lowered) or a
        per-device list of dicts (Compiled, some versions)."""
        try:
            ca = analyzable.cost_analysis()
        except Exception:
            return
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if not isinstance(ca, dict):
            return
        for src, dst in (("flops", "flops"),
                         ("bytes accessed", "hlo_bytes_accessed"),
                         ("transcendentals", "transcendentals")):
            val = ca.get(src)
            if isinstance(val, (int, float)) and not isinstance(val, bool) \
                    and val == val and val >= 0:  # NaN-safe
                out[dst] = float(val)

    @classmethod
    def _merge_compiled(cls, out: Dict[str, Any], lowered: Any) -> None:
        try:
            compiled = lowered.compile()
        except Exception:
            return
        cls._merge_cost_analysis(out, compiled)  # optimized-HLO numbers
        try:
            mem = compiled.memory_analysis()
        except Exception:
            return
        if mem is None:
            return
        total = 0
        have_any = False
        for attr, dst in (("argument_size_in_bytes", "argument_bytes"),
                          ("output_size_in_bytes", "output_bytes"),
                          ("temp_size_in_bytes", "temp_bytes"),
                          ("generated_code_size_in_bytes",
                           "generated_code_bytes")):
            val = getattr(mem, attr, None)
            if isinstance(val, (int, float)) and not isinstance(val, bool) \
                    and val >= 0:
                out[dst] = int(val)
                have_any = True
                if dst != "generated_code_bytes":
                    total += int(val)
        if have_any and total > 0:
            # Live-footprint estimate while the program runs: arguments
            # + outputs + XLA temporaries (code size excluded).
            out["peak_device_bytes"] = total
