"""Streaming run-health watchdog (schema v5).

A :class:`HealthMonitor` taps into :class:`~.recorder.RunRecorder` via
``recorder.attach_health(monitor)`` and evaluates per-round rules on
every round record — in-process and sink-independent, so a doomed run
is caught even when no JSONL sink is configured.  Rules:

- ``nonfinite_loss``      — NaN/inf loss for ``streak`` consecutive rounds
- ``loss_divergence``     — |loss| blows past ``loss_mult`` x a warmed-up
  EMA envelope for ``streak`` rounds
- ``throughput_collapse`` — images/sec drops below ``tput_frac`` x the
  rolling median over ``window`` rounds, for ``streak`` rounds
- ``guard_spike``         — >= half the cohort tripping guards or sitting
  in quarantine, for ``streak`` rounds
- ``buffer_backlog``      — async ``buffer_depth`` strictly growing over
  ``window`` rounds, or exceeding the cohort size
- ``admission_blowup``    — async admission rejecting >= everything that
  arrived, for ``streak`` rounds
- ``zero_progress``       — no client contributed (``n_active``/``n_ok``
  zero) for ``streak`` rounds
- ``nonfinite_residual``  — (opt-in, ``--health-residual``) NaN/inf ADMM
  primal/dual residual for ``streak`` rounds.  Residuals poison the
  consensus fold the same round they appear, one to two rounds BEFORE
  the (staged) loss goes non-finite — tripping here is what keeps a
  clean checkpoint slot alive for the restart supervisor to resume from
- ``serve_drift``         — (serving runs, schema v13) live served
  accuracy below ``tput_frac`` x its own warmed EMA baseline for
  ``streak`` serving rounds.  Fed ``serve`` records through
  ``observe_serve`` (recorder.serve_event) — the eval-stream half of
  the continuous-learning loop; in act mode the control plane answers
  with a ``refresh_serving`` intervention (control/policy.py)

Each trip emits a structured ``alert`` record into the SAME stream the
round records use.  What happens next is ``health_action``:

- ``off``              — no monitor is attached at all
- ``warn`` (default)   — alert records only; the run continues
- ``abort``            — the engine raises :class:`RunHealthAbort`
- ``checkpoint-abort`` — the engine forces a final verified checkpoint
  through the existing sync/async writers, THEN raises

Determinism: the monitor only OBSERVES values the engines already
fetched at round boundaries — it never adds device syncs and never
perturbs training math.  ``observe()`` cannot raise; rule failures
degrade to silence, and the abort is raised by the ENGINE (after
checking ``monitor.tripped``), never from inside the recorder.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Any, Dict, List, Optional

HEALTH_ACTIONS = ("off", "warn", "abort", "checkpoint-abort")


class RunHealthAbort(RuntimeError):
    """A watchdog rule tripped with ``--health-action abort`` or
    ``checkpoint-abort``.  Carries the triggering alert record."""

    def __init__(self, alert: Dict[str, Any]):
        self.alert = dict(alert)
        rule = alert.get("rule", "?")
        msg = alert.get("message", "")
        super().__init__(f"run health abort [{rule}] {msg}")


def _finite(v) -> bool:
    return (isinstance(v, (int, float)) and not isinstance(v, bool)
            and math.isfinite(v))


class HealthMonitor:
    """Per-round rule evaluator; attach via ``recorder.attach_health``."""

    def __init__(self, *, action: str = "warn", streak: int = 3,
                 window: int = 8, loss_mult: float = 10.0,
                 tput_frac: float = 0.25,
                 n_clients: Optional[int] = None,
                 residual_check: bool = False):
        if action not in HEALTH_ACTIONS:
            raise ValueError(f"health action {action!r} not in "
                             f"{HEALTH_ACTIONS}")
        if action == "off":
            raise ValueError("action='off' means: do not attach a monitor")
        self.action = action
        self.streak = max(1, int(streak))
        self.window = max(2, int(window))
        self.loss_mult = float(loss_mult)
        self.tput_frac = float(tput_frac)
        self.n_clients = n_clients
        self.residual_check = bool(residual_check)
        self.recorder = None          # set by RunRecorder.attach_health
        self.tripped: Optional[Dict[str, Any]] = None  # first fatal alert
        self.alerts: List[Dict[str, Any]] = []
        # per-rule consecutive-bad-round counters
        self._streaks: Dict[str, int] = {}
        # loss EMA envelope (warmed up over `window` finite samples)
        self._ema: Optional[float] = None
        self._ema_n = 0
        # rolling throughput window (images/sec, finite-positive only)
        self._ips: deque = deque(maxlen=self.window)
        # async buffer_depth trajectory
        self._depths: deque = deque(maxlen=self.window)
        # served-accuracy EMA baseline (serve_drift, schema v13 serve
        # records via observe_serve — warmed like the loss EMA)
        self._serve_ema: Optional[float] = None
        self._serve_ema_n = 0

    # -- rule plumbing ---------------------------------------------------

    def _bump(self, rule: str, bad: bool) -> int:
        n = self._streaks.get(rule, 0) + 1 if bad else 0
        self._streaks[rule] = n
        return n

    def _fire(self, rec: Dict[str, Any], rule: str, message: str, *,
              observed: float, threshold: float, streak: int) -> None:
        fatal = self.action in ("abort", "checkpoint-abort")
        alert = {
            "rule": rule,
            "round_index": int(rec.get("round_index", -1)),
            "severity": "fatal" if fatal else "warn",
            "message": message,
            "observed": float(observed) if _finite(observed) else -1.0,
            "threshold": float(threshold),
            "streak": int(streak),
            "action": self.action,
        }
        self.alerts.append(alert)
        self._streaks[rule] = 0       # re-arm: alert once per streak
        if self.recorder is not None:
            try:
                self.recorder.alert(alert)
            except Exception:
                pass                  # a sink failure must not kill the run
        if fatal and self.tripped is None:
            self.tripped = alert

    # -- the rules -------------------------------------------------------

    def observe(self, rec: Dict[str, Any]) -> None:
        """Evaluate every rule against one round record.  Never raises."""
        try:
            self._observe(rec)
        except Exception:
            pass

    def _observe(self, rec: Dict[str, Any]) -> None:
        loss = rec.get("loss")
        have_loss = (isinstance(loss, (int, float))
                     and not isinstance(loss, bool))

        # nonfinite_loss
        if have_loss:
            n = self._bump("nonfinite_loss", not math.isfinite(loss))
            if n >= self.streak:
                self._fire(rec, "nonfinite_loss",
                           f"loss non-finite for {n} consecutive rounds",
                           observed=loss, threshold=float(self.streak),
                           streak=n)

        # loss_divergence: EMA envelope, warmed up over `window` samples
        if have_loss and math.isfinite(loss):
            if self._ema_n >= self.window:
                limit = self.loss_mult * max(abs(self._ema), 1e-8)
                n = self._bump("loss_divergence", abs(loss) > limit)
                if n >= self.streak:
                    self._fire(rec, "loss_divergence",
                               f"|loss|={abs(loss):.4g} > {self.loss_mult}x "
                               f"EMA envelope ({limit:.4g}) for {n} rounds",
                               observed=abs(loss), threshold=limit, streak=n)
            alpha = 2.0 / (self.window + 1.0)
            self._ema = (loss if self._ema is None
                         else (1 - alpha) * self._ema + alpha * loss)
            self._ema_n += 1

        # throughput_collapse: rolling-median envelope on images/sec
        images, secs = rec.get("images"), rec.get("round_seconds")
        if (_finite(images) and _finite(secs) and secs > 0 and images > 0):
            ips = images / secs
            if len(self._ips) >= self.window:
                med = sorted(self._ips)[len(self._ips) // 2]
                floor = self.tput_frac * med
                n = self._bump("throughput_collapse", ips < floor)
                if n >= self.streak:
                    self._fire(rec, "throughput_collapse",
                               f"{ips:.1f} img/s < {self.tput_frac}x rolling "
                               f"median ({med:.1f}) for {n} rounds",
                               observed=ips, threshold=floor, streak=n)
            self._ips.append(ips)

        # guard_spike: guard trips + quarantined vs cohort size
        cohort = self.n_clients or rec.get("n_active")
        trips = rec.get("guard_trips")
        quar = rec.get("quarantined")
        if _finite(cohort) and cohort > 0 and (_finite(trips)
                                               or _finite(quar)):
            bad_clients = (trips if _finite(trips) else 0) + (
                quar if _finite(quar) else 0)
            frac = bad_clients / cohort
            n = self._bump("guard_spike", frac >= 0.5)
            if n >= self.streak:
                self._fire(rec, "guard_spike",
                           f"{bad_clients:.0f}/{cohort:.0f} clients tripping "
                           f"guards/quarantined for {n} rounds",
                           observed=frac, threshold=0.5, streak=n)

        # buffer_backlog: async buffer depth growing without bound
        depth = rec.get("buffer_depth")
        if _finite(depth):
            self._depths.append(depth)
            growing = (len(self._depths) == self.window
                       and all(b > a for a, b in zip(self._depths,
                                                     list(self._depths)[1:])))
            over = (_finite(cohort) and cohort > 0 and depth >= cohort)
            if growing or over:
                n = self._bump("buffer_backlog", True)
                self._fire(rec, "buffer_backlog",
                           f"async buffer_depth={depth:.0f} "
                           + ("strictly growing over "
                              f"{self.window} rounds" if growing
                              else f">= cohort size {cohort:.0f}"),
                           observed=depth,
                           threshold=float(cohort if over else self.window),
                           streak=n)
            else:
                self._bump("buffer_backlog", False)

        # admission_blowup: admission rejecting everything that arrives
        rejected = rec.get("admission_rejected")
        arrived = rec.get("async_arrived")
        if _finite(rejected):
            base = arrived if _finite(arrived) else 0
            n = self._bump("admission_blowup",
                           rejected >= max(1, base))
            if n >= self.streak:
                self._fire(rec, "admission_blowup",
                           f"admission rejected {rejected:.0f} of "
                           f"{base:.0f} arrivals for {n} rounds",
                           observed=rejected, threshold=float(max(1, base)),
                           streak=n)

        # nonfinite_residual (opt-in): the consensus fold is already
        # poisoned the round a residual goes NaN — earlier than the
        # staged loss can show it, so the previous checkpoint slot is
        # still clean when the abort fires.
        if self.residual_check:
            primal = rec.get("primal_residual")
            dual = rec.get("dual_residual")
            have = (isinstance(primal, float) or isinstance(dual, float))
            bad = ((isinstance(primal, float) and not math.isfinite(primal))
                   or (isinstance(dual, float) and not math.isfinite(dual)))
            if have:
                n = self._bump("nonfinite_residual", bad)
                if n >= self.streak:
                    self._fire(rec, "nonfinite_residual",
                               f"ADMM residual non-finite for {n} "
                               f"consecutive rounds",
                               observed=(dual if isinstance(dual, float)
                                         else -1.0),
                               threshold=float(self.streak), streak=n)

        # zero_progress: no client contributed
        n_active = rec.get("n_active")
        self._check_zero_progress(rec, n_active)

    def observe_serve(self, rec: Dict[str, Any]) -> None:
        """Evaluate the ``serve_drift`` rule against one ``serve``
        record (schema v13; fed by ``RunRecorder.serve_event`` — the
        round records never reach this path).  Never raises."""
        try:
            self._observe_serve(rec)
        except Exception:
            pass

    def _observe_serve(self, rec: Dict[str, Any]) -> None:
        acc = rec.get("serve_accuracy")
        if not _finite(acc):
            return
        # serve_drift: live served accuracy collapsing below tput_frac x
        # its own warmed EMA baseline — the same envelope discipline as
        # loss_divergence, pointed at the eval stream
        if self._serve_ema_n >= self.window and self._serve_ema is not None \
                and self._serve_ema > 0:
            floor = self.tput_frac * self._serve_ema
            n = self._bump("serve_drift", acc < floor)
            if n >= self.streak:
                self._fire(rec, "serve_drift",
                           f"served accuracy {acc:.4f} < {self.tput_frac}x "
                           f"its EMA baseline ({self._serve_ema:.4f}) for "
                           f"{n} serving rounds",
                           observed=acc, threshold=floor, streak=n)
        alpha = 2.0 / (self.window + 1.0)
        self._serve_ema = (acc if self._serve_ema is None
                           else (1 - alpha) * self._serve_ema + alpha * acc)
        self._serve_ema_n += 1

    def _check_zero_progress(self, rec: Dict[str, Any], n_active) -> None:
        n_ok = rec.get("n_ok")
        if _finite(n_active) or _finite(n_ok):
            stalled = ((_finite(n_active) and n_active <= 0)
                       or (_finite(n_ok) and n_ok <= 0))
            n = self._bump("zero_progress", stalled)
            if n >= self.streak:
                self._fire(rec, "zero_progress",
                           f"no client contributed for {n} rounds",
                           observed=float(n_ok if _finite(n_ok)
                                          else n_active),
                           threshold=0.0, streak=n)


def monitor_from_config(cfg, recorder=None) -> Optional[HealthMonitor]:
    """Build a monitor from a TrainConfig-like object.

    Returns None when ``health_action == "off"`` (nothing is attached —
    the obs stream stays exactly as before).  When ``recorder`` is given
    the monitor is attached to it.
    """
    action = getattr(cfg, "health_action", "warn")
    if action == "off":
        return None
    mon = HealthMonitor(
        action=action,
        streak=getattr(cfg, "health_streak", 3),
        window=getattr(cfg, "health_window", 8),
        loss_mult=getattr(cfg, "health_loss_mult", 10.0),
        tput_frac=getattr(cfg, "health_tput_frac", 0.25),
        n_clients=getattr(cfg, "K", None),
        residual_check=getattr(cfg, "health_residual", False),
    )
    if recorder is not None:
        recorder.attach_health(mon)
    return mon


def selftest() -> None:
    """Synthetic NaN-streak run must alert; used by ``report --selftest``."""
    from federated_pytorch_test_tpu.obs.recorder import RunRecorder
    from federated_pytorch_test_tpu.obs.sinks import MemorySink

    rec = RunRecorder([MemorySink()], engine="selftest",
                      run_name="health_selftest")
    mon = HealthMonitor(action="warn", streak=3, n_clients=4)
    rec.attach_health(mon)
    rec.open()
    for i in range(5):
        rec.round({"round_index": i, "round_seconds": 0.01,
                   "loss": float("nan") if i >= 1 else 1.0,
                   "t_start": float(i), "images": 64})
    rec.close()
    alerts = [r for r in rec.memory if r["event"] == "alert"]
    assert alerts, "NaN streak produced no alert record"
    assert alerts[0]["rule"] == "nonfinite_loss", alerts[0]
    assert mon.tripped is None, "warn action must not trip an abort"
    summary = rec.memory[-1]
    assert summary["event"] == "summary"
    assert summary.get("alerts_total", 0) == len(alerts), summary

    # fatal actions set `tripped` so the engine can raise
    mon2 = HealthMonitor(action="checkpoint-abort", streak=2)
    for i in range(3):
        mon2.observe({"round_index": i, "loss": float("inf")})
    assert mon2.tripped is not None
    try:
        raise RunHealthAbort(mon2.tripped)
    except RunHealthAbort as e:
        assert e.alert["rule"] == "nonfinite_loss"

    # serve_drift: a warmed accuracy baseline then a sustained collapse
    # must alert; the warmup itself must not (cold start != drift)
    mon3 = HealthMonitor(action="warn", streak=2, window=4)
    for i in range(6):
        mon3.observe_serve({"round_index": i, "serve_accuracy": 0.8})
    assert not mon3.alerts, "steady serving accuracy must not alert"
    for i in range(6, 9):
        mon3.observe_serve({"round_index": i, "serve_accuracy": 0.0})
    assert mon3.alerts and mon3.alerts[0]["rule"] == "serve_drift", \
        mon3.alerts
    assert mon3.alerts[0]["round_index"] == 7
