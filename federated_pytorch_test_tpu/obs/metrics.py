"""Lightweight host-side metrics: counters, gauges, timers.

Plain Python objects mutated at round boundaries on the HOST — never
inside jitted code, never via host callbacks — so they are zero-cost to
the math (ISSUE 3 tentpole; FedJAX/FL_PyTorch treat metrics as core
simulator infrastructure).  ``Timer`` uses ``time.monotonic``; a
:class:`Metrics` registry snapshots everything into a flat dict a
summary record can absorb.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict, Union


class Counter:
    """Monotone event count (``inc``); ``reset`` starts a new window."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: Union[int, float] = 1):
        self.value += n
        return self.value

    def reset(self) -> None:
        self.value = 0


class Gauge:
    """Last-write-wins instantaneous value (``set``)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = None

    def set(self, v) -> None:
        self.value = v


class Timer:
    """Accumulating wall-clock timer (``time.monotonic``).

    ``with timer.time(): ...`` or ``timer.observe(dt)``; tracks total,
    call count, and the last observation.
    """

    __slots__ = ("name", "total", "count", "last")

    def __init__(self, name: str):
        self.name = name
        self.total = 0.0
        self.count = 0
        self.last = 0.0

    def observe(self, dt: float) -> None:
        self.total += dt
        self.count += 1
        self.last = dt

    @contextmanager
    def time(self):
        t0 = time.monotonic()
        try:
            yield self
        finally:
            self.observe(time.monotonic() - t0)

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0


class Metrics:
    """A named registry of counters/gauges/timers.

    ``snapshot()`` flattens to a plain dict: counters and gauges by
    name, timers as ``<name>_seconds`` (total) + ``<name>_calls``.
    """

    def __init__(self):
        self._items: Dict[str, object] = {}

    def _get(self, name: str, cls):
        m = self._items.get(name)
        if m is None:
            m = self._items[name] = cls(name)
        elif not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(m).__name__}, not {cls.__name__}")
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer)

    def snapshot(self) -> Dict[str, object]:
        out: Dict[str, object] = {}
        for name, m in self._items.items():
            if isinstance(m, Timer):
                out[name + "_seconds"] = m.total
                out[name + "_calls"] = m.count
            elif m.value is not None:
                out[name] = m.value
        return out
