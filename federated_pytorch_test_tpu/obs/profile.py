"""Device-cost profile CLI over the obs JSONL artifact (schema v6).

``python -m federated_pytorch_test_tpu.obs.profile run.jsonl`` reads the
``compile`` records and cost-annotated ``round`` records the cost ledger
(obs/costs.py) emitted and renders:

- **jit sites** — top-N sites by total compile wall-seconds, with event
  counts, cold (first-trace) vs warm split, per-site cache hits/misses
  and cost-model FLOPs.
- **attribution** — round wall-clock split compile / execute / stage /
  host, summed over rounds; the four segments reconstruct round_seconds
  (the selftest asserts the identity, the CLI prints the coverage %).
- **cache** — persistent-compile-cache effectiveness: hit/miss/unknown
  tallies, hit rate, and the mean compile seconds of hits vs misses.
- **utilization** — achieved FLOP/s and HLO bytes/s per
  (engine, algorithm) over the execute seconds, against peak figures
  from ``FEDTPU_PEAK_FLOPS`` / ``FEDTPU_PEAK_BYTES_PER_S`` (no reliable
  peak is assumed for CPU/GPU; without one the achieved numbers print
  alone).  Cost-model FLOPs are *advisory* (PARITY.md).
- **reconciliation** — predicted ``bytes_on_wire`` from the compress/
  accounting vs the HLO bytes-accessed of the comm-step program(s).
  HLO bytes include parameter/activation traffic, so the ratio is a
  sanity band, not an equality; fused train+comm sites are flagged.
- **pareto** — bytes-on-wire × round-seconds rows per
  (engine, algorithm), front-marked (both-minimizing).

``--selftest`` synthesises a run through the real recorder and asserts
the analysis math (attribution identity, reconciliation ratio,
cold/warm split) — chained into ``report --selftest`` for tier-1.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

from federated_pytorch_test_tpu.obs.report import read_records
from federated_pytorch_test_tpu.obs.schema import SchemaError

#: peak device figures for utilization; only trusted when the operator
#: sets them (per-chip, e.g. FEDTPU_PEAK_FLOPS=1.97e14 for a v5e bf16)
_PEAK_ENV = {"flops": "FEDTPU_PEAK_FLOPS",
             "bytes": "FEDTPU_PEAK_BYTES_PER_S"}

_DEVICE_PHASES = ("train_seconds", "comm_seconds", "sync_seconds",
                  "compute_seconds")


def _num(v) -> Optional[float]:
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v)
    return None


def _peak(kind: str) -> Optional[float]:
    raw = os.environ.get(_PEAK_ENV[kind], "").strip()
    if not raw:
        return None
    try:
        val = float(raw)
    except ValueError:
        return None
    return val if val > 0 else None


def collect(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate compile records + cost-annotated rounds into the
    analysis dict the report sections render from."""
    rounds = [r for r in records if r.get("event") == "round"]
    compiles = [r for r in records if r.get("event") == "compile"]

    # ---- per-site ledger table ------------------------------------
    sites: Dict[str, Dict[str, Any]] = {}
    for c in compiles:
        site = c.get("site") or "?"
        s = sites.setdefault(site, {
            "site": site, "events": 0, "seconds": 0.0, "cold_events": 0,
            "cold_seconds": 0.0, "warm_seconds": 0.0, "cache_hits": 0,
            "cache_misses": 0, "flops": None, "hlo_bytes_accessed": None,
            "peak_device_bytes": None})
        secs = _num(c.get("compile_seconds")) or 0.0
        s["events"] += 1
        s["seconds"] += secs
        if c.get("trace_count") == 1:
            s["cold_events"] += 1
            s["cold_seconds"] += secs
        else:
            s["warm_seconds"] += secs
        if c.get("cache_hit") is True:
            s["cache_hits"] += 1
        elif c.get("cache_hit") is False:
            s["cache_misses"] += 1
        for k in ("flops", "hlo_bytes_accessed", "peak_device_bytes"):
            v = _num(c.get(k))
            if v is not None:
                s[k] = max(v, s[k]) if s[k] is not None else v
    site_rows = sorted(sites.values(), key=lambda s: -s["seconds"])

    # ---- round attribution ----------------------------------------
    # Per round: compile (in-window ledger seconds) | execute (device
    # phases minus compile — the compile wall-time sits inside the
    # train/comm dispatch windows) | stage (H2D) | host (the rest).
    # With no phase breakdown (no-consensus epochs) execute degrades to
    # total - compile so the identity still holds.
    att = {"round_seconds": 0.0, "compile": 0.0, "execute": 0.0,
           "stage": 0.0, "host": 0.0, "rounds": len(rounds),
           "rounds_with_compile": 0}
    for r in rounds:
        total = _num(r.get("round_seconds")) or 0.0
        compile_s = _num(r.get("compile_seconds")) or 0.0
        if compile_s:
            att["rounds_with_compile"] += 1
        stage_s = _num(r.get("stage_seconds")) or 0.0
        device_s = sum(_num(r.get(k)) or 0.0 for k in _DEVICE_PHASES)
        if device_s > 0:
            execute_s = max(0.0, device_s - compile_s)
            host_s = max(0.0, total - stage_s - device_s)
        else:
            execute_s = max(0.0, total - stage_s - compile_s)
            host_s = 0.0
        att["round_seconds"] += total
        att["compile"] += min(compile_s, total)
        att["execute"] += execute_s
        att["stage"] += stage_s
        att["host"] += host_s
    attributed = (att["compile"] + att["execute"] + att["stage"]
                  + att["host"])
    att["attributed"] = attributed
    att["coverage"] = (attributed / att["round_seconds"]
                       if att["round_seconds"] > 0 else None)

    # ---- cache effectiveness --------------------------------------
    hits = [c for c in compiles if c.get("cache_hit") is True]
    misses = [c for c in compiles if c.get("cache_hit") is False]
    cache = {
        "hits": len(hits), "misses": len(misses),
        "unknown": len(compiles) - len(hits) - len(misses),
        "hit_rate": (len(hits) / (len(hits) + len(misses))
                     if hits or misses else None),
        "hit_seconds_mean": (
            sum(_num(c.get("compile_seconds")) or 0.0 for c in hits)
            / len(hits) if hits else None),
        "miss_seconds_mean": (
            sum(_num(c.get("compile_seconds")) or 0.0 for c in misses)
            / len(misses) if misses else None),
    }

    # ---- cold / warm split ----------------------------------------
    cold = [c for c in compiles if c.get("trace_count") == 1]
    warm = [c for c in compiles if c.get("trace_count") not in (None, 1)]
    coldwarm = {
        "cold_events": len(cold),
        "cold_seconds": sum(_num(c.get("compile_seconds")) or 0.0
                            for c in cold),
        "warm_events": len(warm),
        "warm_seconds": sum(_num(c.get("compile_seconds")) or 0.0
                            for c in warm),
    }

    # ---- per-(engine, algorithm) utilization ----------------------
    groups: Dict[tuple, Dict[str, Any]] = {}
    for r in rounds:
        key = (r.get("engine") or "?", r.get("algorithm") or "-")
        g = groups.setdefault(key, {
            "engine": key[0], "algorithm": key[1], "rounds": 0,
            "flops": 0.0, "hlo_bytes": 0.0, "execute_seconds": 0.0,
            "round_seconds": 0.0, "wire_rounds": 0, "wire_bytes": 0.0,
            "peak_device_bytes": None})
        g["rounds"] += 1
        total = _num(r.get("round_seconds")) or 0.0
        g["round_seconds"] += total
        compile_s = _num(r.get("compile_seconds")) or 0.0
        device_s = sum(_num(r.get(k)) or 0.0 for k in _DEVICE_PHASES)
        if device_s > 0:
            g["execute_seconds"] += max(0.0, device_s - compile_s)
        else:
            g["execute_seconds"] += max(
                0.0, total - (_num(r.get("stage_seconds")) or 0.0)
                - compile_s)
        g["flops"] += _num(r.get("flops_round")) or 0.0
        g["hlo_bytes"] += _num(r.get("hlo_bytes_accessed")) or 0.0
        wire = _num(r.get("bytes_on_wire"))
        if wire is not None:
            g["wire_rounds"] += 1
            g["wire_bytes"] += wire
        pk = _num(r.get("peak_device_bytes"))
        if pk is not None:
            g["peak_device_bytes"] = (max(pk, g["peak_device_bytes"])
                                      if g["peak_device_bytes"] is not None
                                      else pk)
    peak_flops, peak_bytes = _peak("flops"), _peak("bytes")
    util_rows = []
    for g in groups.values():
        row = dict(g)
        ex = g["execute_seconds"]
        row["achieved_flops"] = g["flops"] / ex if ex > 0 else None
        row["achieved_bytes"] = g["hlo_bytes"] / ex if ex > 0 else None
        row["flops_utilization"] = (
            row["achieved_flops"] / peak_flops
            if row["achieved_flops"] is not None and peak_flops else None)
        row["bytes_utilization"] = (
            row["achieved_bytes"] / peak_bytes
            if row["achieved_bytes"] is not None and peak_bytes else None)
        util_rows.append(row)
    util_rows.sort(key=lambda r: (r["engine"], r["algorithm"]))

    # ---- bytes-on-wire reconciliation -----------------------------
    # predicted wire bytes (compress/ accounting on the round records)
    # vs the comm-step program's HLO bytes accessed.  HLO bytes include
    # every buffer the program touches, so ratio >> 1 is normal — the
    # row is a sanity band (a predicted figure LARGER than what the
    # program could move is the anomaly).
    wire_rounds = [r for r in rounds
                   if _num(r.get("bytes_on_wire")) is not None]
    wire_mean = (sum(_num(r["bytes_on_wire"]) for r in wire_rounds)
                 / len(wire_rounds)) if wire_rounds else None
    recon_rows = []
    for s in site_rows:
        name = s["site"]
        is_comm = name.startswith("comm[") or name.startswith("round[")
        is_fused = name.startswith("fused_round[")
        if not (is_comm or is_fused):
            continue
        hlo = s["hlo_bytes_accessed"]
        if hlo is None or wire_mean is None:
            continue
        recon_rows.append({
            "site": name, "predicted_wire_bytes": wire_mean,
            "hlo_bytes_accessed": hlo,
            "ratio": hlo / wire_mean if wire_mean > 0 else None,
            "fused": is_fused,
        })

    # ---- bytes-on-wire x round-seconds pareto ---------------------
    pareto_rows = []
    for g in groups.values():
        if not g["wire_rounds"] or not g["rounds"]:
            continue
        pareto_rows.append({
            "engine": g["engine"], "algorithm": g["algorithm"],
            "mean_wire_bytes": g["wire_bytes"] / g["wire_rounds"],
            "mean_round_seconds": g["round_seconds"] / g["rounds"],
        })
    for row in pareto_rows:
        row["pareto"] = not any(
            o is not row
            and o["mean_wire_bytes"] <= row["mean_wire_bytes"]
            and o["mean_round_seconds"] <= row["mean_round_seconds"]
            and (o["mean_wire_bytes"] < row["mean_wire_bytes"]
                 or o["mean_round_seconds"] < row["mean_round_seconds"])
            for o in pareto_rows)
    pareto_rows.sort(key=lambda r: r["mean_wire_bytes"])

    summaries = [r for r in records if r.get("event") == "summary"]
    mem = {}
    if summaries:
        last = summaries[-1]
        for k in ("mem_peak_bytes_watermark", "mem_final_vs_peak_bytes"):
            v = _num(last.get(k))
            if v is not None:
                mem[k] = int(v)

    return {"sites": site_rows, "attribution": att, "cache": cache,
            "coldwarm": coldwarm, "utilization": util_rows,
            "reconciliation": recon_rows, "pareto": pareto_rows,
            "memory": mem, "compile_events": len(compiles),
            "rounds": len(rounds),
            "peak_flops": peak_flops, "peak_bytes": peak_bytes}


def profile_metrics(records: List[Dict[str, Any]]) -> Dict[str, float]:
    """Flat direction-aware metrics for obs/compare.py (present-only:
    a run without ledger data contributes nothing)."""
    a = collect(records)
    out: Dict[str, float] = {}
    if a["compile_events"]:
        out["compile_seconds"] = float(
            sum(s["seconds"] for s in a["sites"]))
        out["compile_seconds_cold"] = float(a["coldwarm"]["cold_seconds"])
    peaks = [s["peak_device_bytes"] for s in a["sites"]
             if s["peak_device_bytes"] is not None]
    peaks += [g["peak_device_bytes"] for g in a["utilization"]
              if g.get("peak_device_bytes") is not None]
    if peaks:
        out["peak_device_bytes"] = float(max(peaks))
    utils = [g["flops_utilization"] for g in a["utilization"]
             if g.get("flops_utilization") is not None]
    if utils:
        out["utilization"] = float(max(utils))
    if a["cache"]["hit_rate"] is not None:
        out["cache_hit_rate"] = float(a["cache"]["hit_rate"])
    return out


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.0f} B" if unit == "B" else f"{n:.1f} {unit}"
        n /= 1024
    return f"{n:.1f} TiB"


def _fmt_rate(n, unit: str) -> str:
    if n is None:
        return "-"
    for prefix, scale in (("T", 1e12), ("G", 1e9), ("M", 1e6),
                          ("k", 1e3)):
        if abs(n) >= scale:
            return f"{n / scale:.2f} {prefix}{unit}"
    return f"{n:.2f} {unit}"


def format_report(a: Dict[str, Any], top: int = 10) -> str:
    """Render the collected analysis as the multi-section text report."""
    lines: List[str] = []
    lines.append(f"device-cost profile · {a['rounds']} round(s), "
                 f"{a['compile_events']} compile event(s)")

    att = a["attribution"]
    if att["round_seconds"] > 0:
        def seg(name, v):
            pct = 100.0 * v / att["round_seconds"]
            return f"{name} {v:.3f}s ({pct:.1f}%)"
        cov = att["coverage"]
        lines.append("attribution      "
                     + "  ".join([seg("compile", att["compile"]),
                                  seg("execute", att["execute"]),
                                  seg("stage", att["stage"]),
                                  seg("host", att["host"])]))
        lines.append(f"                 round wall-clock "
                     f"{att['round_seconds']:.3f}s, attributed "
                     f"{att['attributed']:.3f}s"
                     + (f" ({100.0 * cov:.1f}% coverage)"
                        if cov is not None else ""))

    cw = a["coldwarm"]
    if a["compile_events"]:
        lines.append(f"cold vs warm     cold {cw['cold_events']} event(s) "
                     f"{cw['cold_seconds']:.3f}s · warm "
                     f"{cw['warm_events']} event(s) "
                     f"{cw['warm_seconds']:.3f}s")

    cache = a["cache"]
    if cache["hits"] or cache["misses"] or cache["unknown"]:
        msg = (f"compile cache    hits={cache['hits']} "
               f"misses={cache['misses']} unknown={cache['unknown']}")
        if cache["hit_rate"] is not None:
            msg += f" · hit rate {100.0 * cache['hit_rate']:.0f}%"
        if (cache["hit_seconds_mean"] is not None
                and cache["miss_seconds_mean"] is not None):
            msg += (f" · mean hit {cache['hit_seconds_mean'] * 1e3:.1f}ms"
                    f" vs miss {cache['miss_seconds_mean'] * 1e3:.1f}ms")
        lines.append(msg)

    if a["memory"]:
        m = a["memory"]
        msg = ("device memory    watermark "
               + _fmt_bytes(m.get("mem_peak_bytes_watermark")))
        if "mem_final_vs_peak_bytes" in m:
            msg += (" · final vs peak "
                    + _fmt_bytes(m["mem_final_vs_peak_bytes"]))
        lines.append(msg)

    if a["sites"]:
        lines.append(f"top jit sites by compile seconds "
                     f"(showing {min(top, len(a['sites']))} of "
                     f"{len(a['sites'])}):")
        lines.append("  site                                   "
                     "events  cold   seconds   hit/miss  flops")
        for s in a["sites"][:top]:
            flops = _fmt_rate(s["flops"], "FLOP") if s["flops"] else "-"
            lines.append(
                f"  {s['site']:<38} {s['events']:>6} "
                f"{s['cold_events']:>5} {s['seconds']:>9.3f} "
                f"{s['cache_hits']:>5}/{s['cache_misses']:<4} {flops}")

    if a["utilization"]:
        lines.append("utilization per (engine, algorithm) "
                     "[cost-model FLOPs over execute seconds; advisory]:")
        for g in a["utilization"]:
            fl = _fmt_rate(g["achieved_flops"], "FLOP/s")
            by = _fmt_rate(g["achieved_bytes"], "B/s")
            msg = (f"  {g['engine']}/{g['algorithm']:<12} "
                   f"{fl:>14}  {by:>14}")
            if g["flops_utilization"] is not None:
                msg += f"  {100.0 * g['flops_utilization']:.1f}% of peak"
            elif a["peak_flops"] is None and g["achieved_flops"]:
                msg += "  (set FEDTPU_PEAK_FLOPS for % of peak)"
            lines.append(msg)

    if a["reconciliation"]:
        lines.append("bytes-on-wire reconciliation "
                     "(predicted wire bytes vs comm-step HLO bytes):")
        for r in a["reconciliation"]:
            ratio = (f"{r['ratio']:.2f}x" if r["ratio"] is not None
                     else "-")
            tag = " [fused train+comm]" if r["fused"] else ""
            lines.append(
                f"  {r['site']:<38} predicted "
                f"{_fmt_bytes(r['predicted_wire_bytes']):>10} · HLO "
                f"{_fmt_bytes(r['hlo_bytes_accessed']):>10} · "
                f"{ratio}{tag}")

    if a["pareto"]:
        lines.append("pareto rows (bytes-on-wire x round seconds):")
        for r in a["pareto"]:
            mark = "*" if r["pareto"] else " "
            lines.append(
                f" {mark} {r['engine']}/{r['algorithm']:<12} "
                f"{_fmt_bytes(r['mean_wire_bytes']):>10}/round · "
                f"{r['mean_round_seconds']:.3f} s/round")
    return "\n".join(lines)


def selftest() -> str:
    """Synthesise a cost-annotated run through the real recorder and
    assert the analysis math end to end."""
    import tempfile

    from federated_pytorch_test_tpu.obs.recorder import make_recorder

    with tempfile.TemporaryDirectory() as d:
        rec = make_recorder("jsonl", d, run_name="profselftest",
                            engine="selftest", algorithm="fedavg")
        rec.open(config={"K": 2}, mesh_shape={"clients": 1})
        # round 0: cold compiles for train (0.30s) + comm (0.10s);
        # phases: stage .05 train .60 comm .20 sync .05, total 1.00
        rec.round({"round_index": 0, "round_seconds": 1.0,
                   "stage_seconds": 0.05, "train_seconds": 0.60,
                   "comm_seconds": 0.20, "sync_seconds": 0.05,
                   "compile_seconds": 0.40, "cache_hit": False,
                   "flops_round": 2.0e9, "hlo_bytes_accessed": 3.0e6,
                   "bytes_on_wire": 1000, "images": 256,
                   "t_start": 100.0, "loss": 2.0})
        rec.compile_event({"site": "train_epoch[blk=0]",
                           "compile_seconds": 0.30, "trace_count": 1,
                           "cache_hit": False, "flops": 1.0e9,
                           "hlo_bytes_accessed": 1.5e6,
                           "t_start": 100.05, "t_end": 100.35,
                           "round_index": 0})
        rec.compile_event({"site": "comm[dense,blk=0]",
                           "compile_seconds": 0.10, "trace_count": 1,
                           "cache_hit": False, "flops": 4.0e6,
                           "hlo_bytes_accessed": 1.5e4,
                           "t_start": 100.65, "t_end": 100.75,
                           "round_index": 0})
        # round 1: warm retrace served from the persistent cache
        rec.round({"round_index": 1, "round_seconds": 0.5,
                   "stage_seconds": 0.05, "train_seconds": 0.25,
                   "comm_seconds": 0.10, "sync_seconds": 0.05,
                   "compile_seconds": 0.02, "cache_hit": True,
                   "flops_round": 2.0e9, "hlo_bytes_accessed": 3.0e6,
                   "bytes_on_wire": 3000, "images": 256,
                   "t_start": 101.2, "loss": 1.5})
        rec.compile_event({"site": "train_epoch[blk=1]",
                           "compile_seconds": 0.02, "trace_count": 2,
                           "cache_hit": True, "flops": 1.0e9,
                           "hlo_bytes_accessed": 1.5e6,
                           "t_start": 101.25, "t_end": 101.27,
                           "round_index": 1})
        rec.close()
        path = os.path.join(d, "profselftest.jsonl")
        records = read_records(path)
    a = collect(records)
    assert a["compile_events"] == 3 and a["rounds"] == 2, a
    att = a["attribution"]
    # attribution identity: compile .42 + execute (1.15 device - .42)
    # + stage .10 + host (1.50 - .10 - 1.15) = 1.50 == round total
    assert abs(att["round_seconds"] - 1.5) < 1e-9, att
    assert abs(att["compile"] - 0.42) < 1e-9, att
    assert abs(att["attributed"] - att["round_seconds"]) < 1e-9, att
    assert att["coverage"] is not None and abs(att["coverage"] - 1.0) < 1e-9
    cw = a["coldwarm"]
    assert cw["cold_events"] == 2 and abs(cw["cold_seconds"] - 0.40) < 1e-9
    assert cw["warm_events"] == 1 and abs(cw["warm_seconds"] - 0.02) < 1e-9
    cache = a["cache"]
    assert cache["hits"] == 1 and cache["misses"] == 2, cache
    assert abs(cache["hit_rate"] - 1 / 3) < 1e-9, cache
    # reconciliation: mean predicted wire bytes (1000+3000)/2 = 2000 vs
    # the comm site's 1.5e4 HLO bytes -> ratio 7.5
    recon = [r for r in a["reconciliation"]
             if r["site"] == "comm[dense,blk=0]"]
    assert recon and abs(recon[0]["predicted_wire_bytes"] - 2000.0) < 1e-9
    assert abs(recon[0]["ratio"] - 7.5) < 1e-9, recon
    # utilization: 4e9 flops over execute seconds —
    # (.85 device - .40 compile) + (.40 device - .02 compile) = .83
    util = a["utilization"]
    assert len(util) == 1, util
    assert abs(util[0]["achieved_flops"] - 4.0e9 / 0.83) < 1e-3, util
    assert a["pareto"] and a["pareto"][0]["pareto"] is True, a["pareto"]
    # metric extraction for obs/compare.py
    m = profile_metrics(records)
    assert abs(m["compile_seconds"] - 0.42) < 1e-9, m
    assert abs(m["compile_seconds_cold"] - 0.40) < 1e-9, m
    assert abs(m["cache_hit_rate"] - 1 / 3) < 1e-9, m
    table = format_report(a)
    assert "attribution" in table and "reconciliation" in table, table
    assert "pareto" in table, table
    return "obs profile selftest: OK (cost attribution reconstructs)"


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m federated_pytorch_test_tpu.obs.profile",
        description="Device-cost profile over an obs run JSONL "
                    "(see README 'Device cost observability')")
    p.add_argument("path", nargs="?", help="run JSONL file")
    p.add_argument("--top", type=int, default=10,
                   help="jit sites to show (default 10)")
    p.add_argument("--json", action="store_true",
                   help="print the analysis as one JSON object")
    p.add_argument("--no-validate", action="store_true",
                   help="skip schema validation while parsing")
    p.add_argument("--selftest", action="store_true",
                   help="run the built-in analysis selftest and exit")
    args = p.parse_args(argv)
    if args.selftest:
        print(selftest())
        return 0
    if not args.path:
        p.error("a run JSONL path is required (or --selftest)")
    try:
        records = read_records(args.path, validate=not args.no_validate)
    except (OSError, SchemaError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if not records:
        print(f"error: {args.path} holds no records", file=sys.stderr)
        return 1
    a = collect(records)
    if args.json:
        print(json.dumps(a))
    else:
        print(format_report(a, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
