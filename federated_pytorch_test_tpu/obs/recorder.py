"""RunRecorder: the per-run event emitter the engines thread through.

Lifecycle::

    rec = make_recorder(obs_sinks=cfg.obs_sinks, obs_dir=cfg.obs_dir,
                        run_name="federated_multi", engine="classifier",
                        algorithm="fedavg")
    rec.open(config=dataclasses.asdict(cfg), mesh_shape=dict(mesh.shape),
             resumed=False, rounds_prior=0)
    for ...:
        rec.round({...per-round fields...})       # one per comm round
    rec.close(status="completed")                 # or "aborted"

Everything happens on the HOST at round boundaries — no host callbacks
inside jitted code, no extra device syncs — so with sinks disabled
(``obs_sinks="none"``) the recorder short-circuits to no-ops and the
numerical path is bit-identical by construction.

``round()`` enforces strictly increasing ``round_index`` (the engines
use the global history length, which the mid-run checkpoint restores),
so a resumed run APPENDS monotonically to the same JSONL — never
duplicates.
"""

from __future__ import annotations

import os
import socket
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence

from federated_pytorch_test_tpu.obs.metrics import Metrics
from federated_pytorch_test_tpu.obs.schema import (
    SCHEMA_VERSION,
    SchemaError,
    json_safe,
    validate_record,
)
from federated_pytorch_test_tpu.obs.sinks import MemorySink, Sink, make_sinks

#: round fields summed into *_total summary fields
_SUMMED = ("bytes_on_wire", "bytes_dense", "images", "guard_trips",
           "fault_dropped", "fault_straggled", "fault_corrupted")
_SUMMED_SECONDS = ("round_seconds", "stage_seconds", "comm_seconds")


def device_memory_stats() -> Dict[str, int]:
    """Summed ``memory_stats()`` over ``jax.local_devices()``.

    ``{}`` when the backend reports nothing (CPU) — the round record
    simply omits the fields, per the schema's "where available".
    """
    try:
        import jax

        per = [d.memory_stats() for d in jax.local_devices()]
    except Exception:
        return {}
    per = [s for s in per if s]
    if not per:
        return {}
    out: Dict[str, int] = {}
    in_use = [s.get("bytes_in_use") for s in per]
    peak = [s.get("peak_bytes_in_use") for s in per]
    if all(v is not None for v in in_use):
        out["mem_bytes_in_use"] = int(sum(in_use))
    if all(v is not None for v in peak):
        out["mem_peak_bytes_in_use"] = int(sum(peak))
    return out


def git_rev() -> Optional[str]:
    """Short git rev of the source tree, or None outside a checkout."""
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        p = subprocess.run(["git", "-C", root, "rev-parse", "--short",
                            "HEAD"], capture_output=True, text=True,
                           timeout=5)
    except Exception:
        return None
    rev = p.stdout.strip()
    return rev if p.returncode == 0 and rev else None


class RunRecorder:
    """Validates records against the schema and fans them out to sinks."""

    def __init__(self, sinks: Sequence[Sink], *, engine: str,
                 algorithm: Optional[str] = None, run_name: str = "run",
                 run_id: Optional[str] = None,
                 jsonl_path: Optional[str] = None):
        self.sinks = list(sinks)
        self.engine = engine
        self.algorithm = algorithm
        self.run_name = run_name
        self.run_id = run_id or uuid.uuid4().hex[:8]
        self.jsonl_path = jsonl_path
        self.enabled = bool(self.sinks)
        self.totals = Metrics()
        self._opened = False
        self._closed = False
        self._t0 = None
        self._last_index: Optional[int] = None
        self._loss_first: Optional[float] = None
        self._loss_final: Optional[float] = None
        # live run-health layer (schema v5): the run-level span id every
        # round/phase span parent-links to, the [min, max] host-monotonic
        # extent of the spans seen (the run span emitted at close), the
        # attached streaming watchdog (obs/health.py; sink-independent —
        # it observes round records even when no sink is configured), and
        # the alert tally surfaced on the summary
        self.run_span_id: Optional[str] = None
        self.health = None
        self._span_extent: Optional[List[float]] = None
        self._alerts = 0
        # closed-loop control plane (schema v8): the attached Controller
        # (control/policy.py; sink-independent like the watchdog) and the
        # intervention tally surfaced on the summary
        self.control = None
        self._controls = 0
        # device-cost ledger totals (schema v6): compile events emitted
        # through compile_event(), and the device-memory high-watermark
        # tracked across round records (device_memory_stats is
        # instantaneous; the run-level peak belongs on the summary)
        self._compile_events = 0
        self._compile_seconds = 0.0
        self._cache_hits = 0
        self._cache_misses = 0
        self._mem_watermark: Optional[int] = None
        self._mem_final: Optional[int] = None

    @property
    def memory(self) -> Optional[List[dict]]:
        """Records captured by the first MemorySink, if one is attached."""
        for s in self.sinks:
            if isinstance(s, MemorySink):
                return s.records
        return None

    def _emit(self, rec: Dict[str, Any]) -> Dict[str, Any]:
        validate_record(rec)
        for s in self.sinks:
            s.emit(rec)
        return rec

    def attach_health(self, monitor) -> None:
        """Tap a :class:`~..obs.health.HealthMonitor` into the round
        stream.  In-process and sink-independent: the monitor observes
        every round record (and can trip an abort) even when no sink is
        configured; its alert records only hit disk when sinks exist."""
        self.health = monitor
        if monitor is not None:
            monitor.recorder = self

    def attach_control(self, controller) -> None:
        """Tap a :class:`~..control.policy.Controller` into the round
        stream.  Like the watchdog it is in-process and sink-independent.
        Feed order matters for replay: the controller observes each
        round record BEFORE the health monitor runs on it (the monitor
        may emit alert records, which the controller also observes), so
        the in-process observation order equals the JSONL file order —
        round N, then round N's alerts — and ``control.replay`` can
        re-derive decisions by feeding records in file order."""
        self.control = controller
        if controller is not None:
            controller.recorder = self

    def _grow_extent(self, t_start, t_end) -> None:
        if not (isinstance(t_start, (int, float))
                and isinstance(t_end, (int, float))):
            return
        if self._span_extent is None:
            self._span_extent = [float(t_start), float(t_end)]
        else:
            self._span_extent[0] = min(self._span_extent[0], float(t_start))
            self._span_extent[1] = max(self._span_extent[1], float(t_end))

    def open(self, *, config: Optional[dict] = None,
             mesh_shape: Optional[dict] = None, resumed: bool = False,
             rounds_prior: int = 0,
             extra: Optional[dict] = None) -> Optional[dict]:
        """Emit the run-header event; returns it (None when disabled)."""
        self._opened = True
        self._t0 = time.monotonic()
        self._last_index = rounds_prior - 1 if rounds_prior else None
        self.run_span_id = uuid.uuid4().hex[:12]
        if not self.enabled:
            return None
        import jax
        import jaxlib

        rec: Dict[str, Any] = {
            "event": "run_header", "schema": SCHEMA_VERSION,
            "run_id": self.run_id, "run_name": self.run_name,
            "span_id": self.run_span_id,
            "engine": self.engine, "time_unix": time.time(),
            "devices": jax.device_count(),
            "local_devices": jax.local_device_count(),
            "platform": jax.default_backend(),
            "jax_version": jax.__version__,
            "jaxlib_version": jaxlib.__version__,
            "resumed": bool(resumed), "rounds_prior": int(rounds_prior),
            "host": socket.gethostname(), "pid": os.getpid(),
        }
        if self.algorithm is not None:
            rec["algorithm"] = self.algorithm
        rev = git_rev()
        if rev is not None:
            rec["git_rev"] = rev
        if config is not None:
            rec["config"] = json_safe(config)
        if mesh_shape is not None:
            rec["mesh_shape"] = json_safe(mesh_shape)
        if extra:
            rec.update(json_safe(extra))
        return self._emit(rec)

    def round(self, fields: Dict[str, Any]) -> Optional[dict]:
        """Emit one round record; enforces monotone ``round_index``.

        When the caller includes a numeric ``t_start`` (host
        ``perf_counter`` at round entry) the record doubles as the
        round's SPAN: it gains ``span_id``/``parent_span``/``t_end``
        (schema v5, additive).  Without ``t_start`` the record is
        emitted exactly as in v4 — no span fields, no run span at
        close — so pre-v5 consumers and the lifecycle tests see an
        unchanged stream.
        """
        if (not self.enabled and self.health is None
                and self.control is None):
            return None
        idx = fields.get("round_index")
        if not isinstance(idx, int):
            raise SchemaError(f"round() needs an int round_index, "
                              f"got {idx!r}")
        if self._last_index is not None and idx <= self._last_index:
            raise SchemaError(
                f"round_index went backwards: {idx} after "
                f"{self._last_index} (duplicate or out-of-order round)")
        self._last_index = idx
        rec = {"event": "round", "schema": SCHEMA_VERSION,
               "run_id": self.run_id, "engine": self.engine}
        if self.algorithm is not None:
            rec["algorithm"] = self.algorithm
        rec.update(json_safe(fields))
        t_start = rec.get("t_start")
        if (isinstance(t_start, (int, float))
                and not isinstance(t_start, bool)):
            rec.setdefault("span_id", uuid.uuid4().hex[:12])
            if self.run_span_id is not None:
                rec.setdefault("parent_span", self.run_span_id)
            if "t_end" not in rec:
                secs = rec.get("round_seconds")
                if isinstance(secs, (int, float)):
                    rec["t_end"] = float(t_start) + float(secs)
            self._grow_extent(t_start, rec.get("t_end", t_start))
        if self.enabled:
            self.totals.counter("rounds").inc()
            for k in _SUMMED:
                v = rec.get(k)
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    self.totals.counter(k + "_total").inc(v)
            for k in _SUMMED_SECONDS:
                v = rec.get(k)
                if isinstance(v, (int, float)):
                    self.totals.timer(k[: -len("_seconds")]).observe(v)
            if isinstance(rec.get("quarantined"), int):
                self.totals.gauge("quarantined_last").set(rec["quarantined"])
            for k in ("mem_peak_bytes_in_use", "mem_bytes_in_use"):
                v = rec.get(k)
                if isinstance(v, int) and not isinstance(v, bool):
                    if self._mem_watermark is None or v > self._mem_watermark:
                        self._mem_watermark = v
                    break  # prefer the backend's peak over instantaneous
            v = rec.get("mem_bytes_in_use")
            if isinstance(v, int) and not isinstance(v, bool):
                self._mem_final = v
            loss = rec.get("loss")
            if isinstance(loss, (int, float)):
                if self._loss_first is None:
                    self._loss_first = float(loss)
                self._loss_final = float(loss)
            out = self._emit(rec)
        else:
            out = rec  # watchdog-only mode: observe, never write
        if self.control is not None:
            # BEFORE health: the monitor may emit alert records during
            # observe(), and the controller must see round N before
            # round N's alerts (file order — see attach_control)
            self.control.observe(rec)
        if self.health is not None:
            self.health.observe(rec)
        return out

    def span(self, name: str, t_start: float, t_end: float, *,
             cat: str = "phase", round_index: Optional[int] = None,
             parent_span: Optional[str] = None,
             span_id: Optional[str] = None,
             extra: Optional[dict] = None) -> Optional[dict]:
        """Emit a phase/sub-operation span record (schema v5).

        Timestamps are host-monotonic (``time.perf_counter``); device
        phases must bound them with the engines' EXISTING ``_obs_sync``
        barriers — ``span()`` itself never touches the device.
        """
        if not self.enabled:
            return None
        rec: Dict[str, Any] = {
            "event": "span", "schema": SCHEMA_VERSION,
            "run_id": self.run_id,
            "span_id": span_id or uuid.uuid4().hex[:12],
            "name": str(name), "cat": str(cat),
            "t_start": float(t_start), "t_end": float(t_end),
        }
        parent = parent_span or self.run_span_id
        if parent is not None:
            rec["parent_span"] = parent
        if round_index is not None:
            rec["round_index"] = int(round_index)
        if extra:
            rec.update(json_safe(extra))
        self._grow_extent(rec["t_start"], rec["t_end"])
        return self._emit(rec)

    def alert(self, fields: Dict[str, Any]) -> Optional[dict]:
        """Emit a watchdog alert record (schema v5).

        Counted toward the summary's ``alerts_total`` even when no sink
        is attached (the watchdog still ran); written only when one is.
        """
        self._alerts += 1
        if self.control is not None:
            # the alert is policy input too (the HealthMonitor tap);
            # fed whether or not a sink writes it — replay sees it in
            # the stream at exactly this position.  json_safe first so
            # the controller sees bit-identical values in-process and
            # from a parsed file.
            self.control.observe(json_safe(dict(fields, event="alert")))
        if not self.enabled:
            return None
        rec = {"event": "alert", "schema": SCHEMA_VERSION,
               "run_id": self.run_id, "time_unix": time.time()}
        rec.update(json_safe(fields))
        return self._emit(rec)

    def control_event(self, fields: Dict[str, Any]) -> Optional[dict]:
        """Emit one ``control`` record (schema v8; control/).

        Counted toward the summary's ``interventions_total`` even when
        no sink is attached (the decision was still made); written only
        when one is.  Deliberately NO ``time_unix``: a control record
        is a pure function of recorded telemetry + round index, the
        determinism contract ``control.replay`` checks.
        """
        self._controls += 1
        if not self.enabled:
            return None
        rec = {"event": "control", "schema": SCHEMA_VERSION,
               "run_id": self.run_id}
        rec.update(json_safe(fields))
        return self._emit(rec)

    def client_event(self, fields: Dict[str, Any]) -> Optional[dict]:
        """Emit one ``client`` record (schema v10; obs/clients.py).

        ``fields`` is a :func:`~..obs.clients.client_round_fields` body:
        ``round_index`` + ``clients`` plus the advisory length-K lists.
        Emitted right after the round record it describes, so file
        order equals replay order.  Like alerts, the record is policy
        input: it is fed to the controller (json_safe first, so replay
        from a parsed file sees bit-identical values) whether or not a
        sink writes it.  Deliberately NO ``time_unix`` — the ledger and
        its anomaly ranking are pure functions of the stream.
        """
        rec = {"event": "client", "schema": SCHEMA_VERSION,
               "run_id": self.run_id}
        rec.update(json_safe(fields))
        if self.control is not None:
            self.control.observe(rec)
        if not self.enabled:
            return None
        return self._emit(rec)

    def campaign_event(self, fields: Dict[str, Any]) -> Optional[dict]:
        """Emit one ``campaign`` record (schema v12; campaign/).

        ``fields`` is a :meth:`~..campaign.schedule.CampaignSchedule.
        record_fields` body: the hour-quantized schedule window the
        engine applied from this round on.  Emitted right after the
        round record of the window's first round, so file order equals
        replay order.  Deliberately NO ``time_unix`` and NOT fed to the
        controller: the window is a pure function of (campaign seed,
        round_index) that ``control.replay`` re-derives from the header
        config alone, and the live policy engine must see exactly the
        record sequence replay feeds it (round/alert/client).
        """
        if not self.enabled:
            return None
        rec = {"event": "campaign", "schema": SCHEMA_VERSION,
               "run_id": self.run_id}
        rec.update(json_safe(fields))
        return self._emit(rec)

    def serve_event(self, fields: Dict[str, Any]) -> Optional[dict]:
        """Emit one ``serve`` record (schema v13; serve/).

        ``fields`` is a serving-plane round tick: the pure subset
        (:data:`~..serve.batcher.SERVE_FIELDS`) plus advisory
        latency/QPS/eval telemetry.  Emitted right after the campaign
        record slot in the round fan-out, so file order equals replay
        order.  NOT fed to the controller — the pure subset is a
        function of (serve_spec, round_index) that ``control.replay``
        re-derives from the header alone, and the live policy engine
        must see exactly the record sequence replay feeds it
        (round/alert/client).  The eval-stream loop reaches the
        controller through the health monitor instead: like ``round()``
        the record IS fed to the watchdog's ``observe_serve`` (which
        may emit a ``serve_drift`` alert — and alerts are policy input)
        even when no sink is configured.
        """
        if not self.enabled and self.health is None:
            return None
        rec = {"event": "serve", "schema": SCHEMA_VERSION,
               "run_id": self.run_id}
        rec.update(json_safe(fields))
        out = self._emit(rec) if self.enabled else rec
        if self.health is not None:
            observe = getattr(self.health, "observe_serve", None)
            if observe is not None:
                observe(rec)
        return out

    def compile_event(self, fields: Dict[str, Any], *,
                      parent_span: Optional[str] = None) -> Optional[dict]:
        """Emit one ``compile`` record (schema v6; obs/costs.py).

        ``fields`` is a :meth:`~..obs.costs.CompileEvent.record` body:
        ``site`` + ``compile_seconds`` required, AOT cost fields
        optional.  When it carries ``t_start``/``t_end`` the record
        doubles as a span — parented to ``parent_span`` (the enclosing
        round) or, for events drained outside any round window, to the
        run span, keeping the Chrome-trace nesting laminar.
        """
        if not self.enabled:
            return None
        rec: Dict[str, Any] = {"event": "compile", "schema": SCHEMA_VERSION,
                               "run_id": self.run_id, "engine": self.engine}
        if self.algorithm is not None:
            rec["algorithm"] = self.algorithm
        rec.update(json_safe(fields))
        t0, t1 = rec.get("t_start"), rec.get("t_end")
        if (isinstance(t0, (int, float)) and not isinstance(t0, bool)
                and isinstance(t1, (int, float))
                and not isinstance(t1, bool)):
            rec.setdefault("span_id", uuid.uuid4().hex[:12])
            parent = parent_span or self.run_span_id
            if parent is not None:
                rec.setdefault("parent_span", parent)
            self._grow_extent(t0, t1)
        self._compile_events += 1
        secs = rec.get("compile_seconds")
        if isinstance(secs, (int, float)) and not isinstance(secs, bool):
            self._compile_seconds += float(secs)
        hit = rec.get("cache_hit")
        if hit is True:
            self._cache_hits += 1
        elif hit is False:
            self._cache_misses += 1
        return self._emit(rec)

    def close(self, status: str = "completed",
              extra: Optional[dict] = None) -> Optional[dict]:
        """Emit the summary event and close every sink. Idempotent."""
        if self._closed:
            return None
        self._closed = True
        if not self.enabled:
            return None
        if self._span_extent is not None and self.run_span_id is not None:
            # the run-level span closes the hierarchy; extent is the
            # min/max of observed span timestamps (perf_counter clock —
            # NOT self._t0, which is time.monotonic with a different base)
            self._emit({
                "event": "span", "schema": SCHEMA_VERSION,
                "run_id": self.run_id, "span_id": self.run_span_id,
                "name": "run", "cat": "run",
                "t_start": self._span_extent[0],
                "t_end": self._span_extent[1],
            })
        snap = self.totals.snapshot()
        rounds = int(snap.get("rounds", 0))
        rec: Dict[str, Any] = {
            "event": "summary", "schema": SCHEMA_VERSION,
            "run_id": self.run_id, "status": status, "rounds": rounds,
            "time_unix": time.time(),
        }
        if self._t0 is not None:
            rec["total_seconds"] = time.monotonic() - self._t0
        for k in _SUMMED:
            if k + "_total" in snap:
                v = snap[k + "_total"]
                rec[k + "_total"] = (int(v) if float(v).is_integer()
                                     else float(v))
        for k in _SUMMED_SECONDS:
            base = k[: -len("_seconds")]
            if base + "_seconds" in snap:
                rec[k + "_total"] = snap[base + "_seconds"]
        if "quarantined_last" in snap:
            rec["quarantined_last"] = snap["quarantined_last"]
        if self._loss_first is not None:
            rec["loss_first"] = self._loss_first
            rec["loss_final"] = self._loss_final
        if self._alerts or self.health is not None:
            rec["alerts_total"] = self._alerts
        if self._controls or self.control is not None:
            rec["interventions_total"] = self._controls
        if self._compile_events:
            rec["compile_events_total"] = self._compile_events
            rec["compile_seconds_total"] = self._compile_seconds
            if self._cache_hits or self._cache_misses:
                rec["cache_hits_total"] = self._cache_hits
                rec["cache_misses_total"] = self._cache_misses
        if self._mem_watermark is not None:
            rec["mem_peak_bytes_watermark"] = int(self._mem_watermark)
            if self._mem_final is not None:
                rec["mem_final_vs_peak_bytes"] = int(
                    self._mem_watermark - self._mem_final)
        rs = rec.get("round_seconds_total", 0.0)
        if rounds and rs:
            rec["rounds_per_sec"] = rounds / rs
            if rec.get("images_total"):
                rec["images_per_sec"] = rec["images_total"] / rs
            if "comm_seconds_total" in rec:
                rec["comm_overhead_frac"] = rec["comm_seconds_total"] / rs
        if rec.get("bytes_dense_total"):
            rec["compression_savings_frac"] = (
                1.0 - rec.get("bytes_on_wire_total", 0)
                / rec["bytes_dense_total"])
        if extra:
            rec.update(json_safe(extra))
        out = self._emit(rec)
        for s in self.sinks:
            s.close()
        return out


def make_recorder(obs_sinks: str = "auto", obs_dir: Optional[str] = None,
                  *, run_name: str = "run", engine: str = "run",
                  algorithm: Optional[str] = None,
                  extra_sinks: Sequence[Sink] = ()) -> RunRecorder:
    """Build a RunRecorder from the ``--obs-sinks``/``--obs-dir`` knobs."""
    sinks, jsonl_path = make_sinks(obs_sinks, obs_dir, run_name)
    sinks.extend(extra_sinks)
    return RunRecorder(sinks, engine=engine, algorithm=algorithm,
                       run_name=run_name, jsonl_path=jsonl_path)
