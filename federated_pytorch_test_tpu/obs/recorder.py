"""RunRecorder: the per-run event emitter the engines thread through.

Lifecycle::

    rec = make_recorder(obs_sinks=cfg.obs_sinks, obs_dir=cfg.obs_dir,
                        run_name="federated_multi", engine="classifier",
                        algorithm="fedavg")
    rec.open(config=dataclasses.asdict(cfg), mesh_shape=dict(mesh.shape),
             resumed=False, rounds_prior=0)
    for ...:
        rec.round({...per-round fields...})       # one per comm round
    rec.close(status="completed")                 # or "aborted"

Everything happens on the HOST at round boundaries — no host callbacks
inside jitted code, no extra device syncs — so with sinks disabled
(``obs_sinks="none"``) the recorder short-circuits to no-ops and the
numerical path is bit-identical by construction.

``round()`` enforces strictly increasing ``round_index`` (the engines
use the global history length, which the mid-run checkpoint restores),
so a resumed run APPENDS monotonically to the same JSONL — never
duplicates.
"""

from __future__ import annotations

import os
import socket
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence

from federated_pytorch_test_tpu.obs.metrics import Metrics
from federated_pytorch_test_tpu.obs.schema import (
    SCHEMA_VERSION,
    SchemaError,
    json_safe,
    validate_record,
)
from federated_pytorch_test_tpu.obs.sinks import MemorySink, Sink, make_sinks

#: round fields summed into *_total summary fields
_SUMMED = ("bytes_on_wire", "bytes_dense", "images", "guard_trips",
           "fault_dropped", "fault_straggled", "fault_corrupted")
_SUMMED_SECONDS = ("round_seconds", "stage_seconds", "comm_seconds")


def device_memory_stats() -> Dict[str, int]:
    """Summed ``memory_stats()`` over ``jax.local_devices()``.

    ``{}`` when the backend reports nothing (CPU) — the round record
    simply omits the fields, per the schema's "where available".
    """
    try:
        import jax

        per = [d.memory_stats() for d in jax.local_devices()]
    except Exception:
        return {}
    per = [s for s in per if s]
    if not per:
        return {}
    out: Dict[str, int] = {}
    in_use = [s.get("bytes_in_use") for s in per]
    peak = [s.get("peak_bytes_in_use") for s in per]
    if all(v is not None for v in in_use):
        out["mem_bytes_in_use"] = int(sum(in_use))
    if all(v is not None for v in peak):
        out["mem_peak_bytes_in_use"] = int(sum(peak))
    return out


def git_rev() -> Optional[str]:
    """Short git rev of the source tree, or None outside a checkout."""
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        p = subprocess.run(["git", "-C", root, "rev-parse", "--short",
                            "HEAD"], capture_output=True, text=True,
                           timeout=5)
    except Exception:
        return None
    rev = p.stdout.strip()
    return rev if p.returncode == 0 and rev else None


class RunRecorder:
    """Validates records against the schema and fans them out to sinks."""

    def __init__(self, sinks: Sequence[Sink], *, engine: str,
                 algorithm: Optional[str] = None, run_name: str = "run",
                 run_id: Optional[str] = None,
                 jsonl_path: Optional[str] = None):
        self.sinks = list(sinks)
        self.engine = engine
        self.algorithm = algorithm
        self.run_name = run_name
        self.run_id = run_id or uuid.uuid4().hex[:8]
        self.jsonl_path = jsonl_path
        self.enabled = bool(self.sinks)
        self.totals = Metrics()
        self._opened = False
        self._closed = False
        self._t0 = None
        self._last_index: Optional[int] = None
        self._loss_first: Optional[float] = None
        self._loss_final: Optional[float] = None

    @property
    def memory(self) -> Optional[List[dict]]:
        """Records captured by the first MemorySink, if one is attached."""
        for s in self.sinks:
            if isinstance(s, MemorySink):
                return s.records
        return None

    def _emit(self, rec: Dict[str, Any]) -> Dict[str, Any]:
        validate_record(rec)
        for s in self.sinks:
            s.emit(rec)
        return rec

    def open(self, *, config: Optional[dict] = None,
             mesh_shape: Optional[dict] = None, resumed: bool = False,
             rounds_prior: int = 0,
             extra: Optional[dict] = None) -> Optional[dict]:
        """Emit the run-header event; returns it (None when disabled)."""
        self._opened = True
        self._t0 = time.monotonic()
        self._last_index = rounds_prior - 1 if rounds_prior else None
        if not self.enabled:
            return None
        import jax
        import jaxlib

        rec: Dict[str, Any] = {
            "event": "run_header", "schema": SCHEMA_VERSION,
            "run_id": self.run_id, "run_name": self.run_name,
            "engine": self.engine, "time_unix": time.time(),
            "devices": jax.device_count(),
            "local_devices": jax.local_device_count(),
            "platform": jax.default_backend(),
            "jax_version": jax.__version__,
            "jaxlib_version": jaxlib.__version__,
            "resumed": bool(resumed), "rounds_prior": int(rounds_prior),
            "host": socket.gethostname(), "pid": os.getpid(),
        }
        if self.algorithm is not None:
            rec["algorithm"] = self.algorithm
        rev = git_rev()
        if rev is not None:
            rec["git_rev"] = rev
        if config is not None:
            rec["config"] = json_safe(config)
        if mesh_shape is not None:
            rec["mesh_shape"] = json_safe(mesh_shape)
        if extra:
            rec.update(json_safe(extra))
        return self._emit(rec)

    def round(self, fields: Dict[str, Any]) -> Optional[dict]:
        """Emit one round record; enforces monotone ``round_index``."""
        if not self.enabled:
            return None
        idx = fields.get("round_index")
        if not isinstance(idx, int):
            raise SchemaError(f"round() needs an int round_index, "
                              f"got {idx!r}")
        if self._last_index is not None and idx <= self._last_index:
            raise SchemaError(
                f"round_index went backwards: {idx} after "
                f"{self._last_index} (duplicate or out-of-order round)")
        self._last_index = idx
        rec = {"event": "round", "schema": SCHEMA_VERSION,
               "run_id": self.run_id, "engine": self.engine}
        if self.algorithm is not None:
            rec["algorithm"] = self.algorithm
        rec.update(json_safe(fields))
        self.totals.counter("rounds").inc()
        for k in _SUMMED:
            v = rec.get(k)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                self.totals.counter(k + "_total").inc(v)
        for k in _SUMMED_SECONDS:
            v = rec.get(k)
            if isinstance(v, (int, float)):
                self.totals.timer(k[: -len("_seconds")]).observe(v)
        if isinstance(rec.get("quarantined"), int):
            self.totals.gauge("quarantined_last").set(rec["quarantined"])
        loss = rec.get("loss")
        if isinstance(loss, (int, float)):
            if self._loss_first is None:
                self._loss_first = float(loss)
            self._loss_final = float(loss)
        return self._emit(rec)

    def close(self, status: str = "completed",
              extra: Optional[dict] = None) -> Optional[dict]:
        """Emit the summary event and close every sink. Idempotent."""
        if self._closed:
            return None
        self._closed = True
        if not self.enabled:
            return None
        snap = self.totals.snapshot()
        rounds = int(snap.get("rounds", 0))
        rec: Dict[str, Any] = {
            "event": "summary", "schema": SCHEMA_VERSION,
            "run_id": self.run_id, "status": status, "rounds": rounds,
            "time_unix": time.time(),
        }
        if self._t0 is not None:
            rec["total_seconds"] = time.monotonic() - self._t0
        for k in _SUMMED:
            if k + "_total" in snap:
                v = snap[k + "_total"]
                rec[k + "_total"] = (int(v) if float(v).is_integer()
                                     else float(v))
        for k in _SUMMED_SECONDS:
            base = k[: -len("_seconds")]
            if base + "_seconds" in snap:
                rec[k + "_total"] = snap[base + "_seconds"]
        if "quarantined_last" in snap:
            rec["quarantined_last"] = snap["quarantined_last"]
        if self._loss_first is not None:
            rec["loss_first"] = self._loss_first
            rec["loss_final"] = self._loss_final
        rs = rec.get("round_seconds_total", 0.0)
        if rounds and rs:
            rec["rounds_per_sec"] = rounds / rs
            if rec.get("images_total"):
                rec["images_per_sec"] = rec["images_total"] / rs
            if "comm_seconds_total" in rec:
                rec["comm_overhead_frac"] = rec["comm_seconds_total"] / rs
        if rec.get("bytes_dense_total"):
            rec["compression_savings_frac"] = (
                1.0 - rec.get("bytes_on_wire_total", 0)
                / rec["bytes_dense_total"])
        if extra:
            rec.update(json_safe(extra))
        out = self._emit(rec)
        for s in self.sinks:
            s.close()
        return out


def make_recorder(obs_sinks: str = "auto", obs_dir: Optional[str] = None,
                  *, run_name: str = "run", engine: str = "run",
                  algorithm: Optional[str] = None,
                  extra_sinks: Sequence[Sink] = ()) -> RunRecorder:
    """Build a RunRecorder from the ``--obs-sinks``/``--obs-dir`` knobs."""
    sinks, jsonl_path = make_sinks(obs_sinks, obs_dir, run_name)
    sinks.extend(extra_sinks)
    return RunRecorder(sinks, engine=engine, algorithm=algorithm,
                       run_name=run_name, jsonl_path=jsonl_path)
