"""Run-summary CLI over the obs JSONL artifact.

``python -m federated_pytorch_test_tpu.obs.report run.jsonl`` parses,
schema-validates, and summarises one run file (throughput, comm
overhead %, bytes saved by compression, fault/guard tallies) — the same
numbers bench.py embeds in its artifact, derived from the same records.

``--selftest`` synthesises a tiny run through the real
recorder→JSONL→parse→validate→summarise pipeline and asserts the
round-trip, so the tier-1 flow can keep this CLI from rotting without
needing a prior training run.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from federated_pytorch_test_tpu.obs.schema import (
    SchemaError,
    validate_record,
)


def read_records(path: str, validate: bool = True) -> List[Dict[str, Any]]:
    """Parse a JSONL run file; optionally schema-validate every record."""
    records = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise SchemaError(f"{path}:{lineno}: not JSON ({e})")
            if validate:
                try:
                    validate_record(rec)
                except SchemaError as e:
                    raise SchemaError(f"{path}:{lineno}: {e}")
            records.append(rec)
    return records


def record_ips(rec: Dict[str, Any], n_chips: int = 1) -> float:
    """images/sec(/chip) of one round record (bench throughput unit).

    ``round_seconds == 0`` is possible on very fast fused rounds and on
    synthetic selftest records — report inf-safe throughput (``inf`` if
    any images moved, else 0.0) instead of raising ZeroDivisionError.
    """
    secs = rec["round_seconds"]
    if secs == 0:
        return float("inf") if rec["images"] else 0.0
    return rec["images"] / secs / max(n_chips, 1)


def summarize(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate a record stream into one stats dict.

    Totals are recomputed from the ``round`` records (the embedded
    ``summary`` events are reported but not trusted), so a truncated
    file — killed run, no summary — still summarises.  Handles multiple
    header/summary segments (a resumed run appends a new segment to the
    same file).
    """
    headers = [r for r in records if r.get("event") == "run_header"]
    rounds = [r for r in records if r.get("event") == "round"]
    summaries = [r for r in records if r.get("event") == "summary"]
    idx = [r["round_index"] for r in rounds]
    monotonic = all(b > a for a, b in zip(idx, idx[1:]))

    def tot(key):
        vals = [r[key] for r in rounds if isinstance(r.get(key), (int, float))]
        return sum(vals) if vals else None

    out: Dict[str, Any] = {
        "path_schema": max((r.get("schema", 0) for r in records), default=0),
        "headers": len(headers),
        "summaries": len(summaries),
        "rounds": len(rounds),
        "round_index_first": idx[0] if idx else None,
        "round_index_last": idx[-1] if idx else None,
        "monotonic": monotonic,
        "engine": headers[-1].get("engine") if headers else
                  (rounds[-1].get("engine") if rounds else None),
        "algorithm": headers[-1].get("algorithm") if headers else None,
        "run_id": headers[-1].get("run_id") if headers else None,
        "status": summaries[-1].get("status") if summaries else "truncated",
    }
    for key in ("round_seconds", "stage_seconds", "comm_seconds",
                "bytes_on_wire", "bytes_dense", "images", "guard_trips",
                "fault_dropped", "fault_straggled", "fault_corrupted",
                "bytes_fused", "overlap_seconds"):
        out[key + "_total"] = tot(key)
    losses = [r["loss"] for r in rounds
              if isinstance(r.get("loss"), (int, float))]
    out["loss_first"] = losses[0] if losses else None
    out["loss_final"] = losses[-1] if losses else None
    q = [r["quarantined"] for r in rounds
         if isinstance(r.get("quarantined"), int)]
    out["quarantined_last"] = q[-1] if q else None
    rs = out["round_seconds_total"]
    if rounds and rs:
        out["rounds_per_sec"] = len(rounds) / rs
        if out["images_total"]:
            out["images_per_sec"] = out["images_total"] / rs
        if out["comm_seconds_total"] is not None:
            out["comm_overhead_frac"] = out["comm_seconds_total"] / rs
    if out["bytes_dense_total"]:
        out["compression_savings_frac"] = (
            1.0 - (out["bytes_on_wire_total"] or 0)
            / out["bytes_dense_total"])
    # buffered-async telemetry (schema v4)
    async_rounds = [r for r in rounds if r.get("async_mode")]
    out["async_rounds"] = len(async_rounds)
    depths = [r["buffer_depth"] for r in rounds
              if isinstance(r.get("buffer_depth"), int)]
    out["buffer_depth_peak"] = max(depths) if depths else None
    out["admission_rejected_total"] = tot("admission_rejected")
    hists = [r["staleness_hist"] for r in rounds
             if isinstance(r.get("staleness_hist"), list)]
    if hists:
        width = max(len(h) for h in hists)
        total = [0] * width
        for h in hists:
            for i, v in enumerate(h):
                if isinstance(v, (int, float)):
                    total[i] += int(v)
        out["staleness_hist_total"] = total
    else:
        out["staleness_hist_total"] = None
    # elastic-federation membership (schema v9; join=/leave= families):
    # peak/min live members over the run, total transitions, and the
    # reshape count from the supervisor control records.  All None/0 on
    # static-roster streams so pre-v9 summaries are unchanged.
    members = [r["members_active"] for r in rounds
               if isinstance(r.get("members_active"), int)]
    out["members_peak"] = max(members) if members else None
    out["members_min"] = min(members) if members else None
    out["joined_total"] = tot("joined")
    out["left_total"] = tot("left")
    # client-grain dispersion (schema v10, obs/clients.py): max/median
    # per-client mean update norm, their skew, and the anomaly-ranking
    # top offender.  All absent-keys-stay-absent on pre-v10 streams
    # (summarize_clients returns {} with no client records), so v9
    # summaries are unchanged.
    from federated_pytorch_test_tpu.obs.clients import summarize_clients
    out.update(summarize_clients(records))
    # watchdog alerts (schema v5)
    alerts = [r for r in records if r.get("event") == "alert"]
    out["alerts"] = len(alerts)
    out["alert_rules"] = sorted({a.get("rule", "?") for a in alerts})
    # control-plane interventions (schema v8)
    controls = [r for r in records if r.get("event") == "control"]
    out["controls"] = len(controls)
    out["control_interventions"] = sorted(
        {c.get("intervention", "?") for c in controls})
    out["restarts"] = sum(1 for c in controls
                          if c.get("intervention") == "restart")
    out["reshapes"] = sum(1 for c in controls
                          if c.get("intervention") == "reshape")
    # soak campaigns (schema v12): restart-segment structure, the
    # availability gate's two numbers (bench --soak / obs.compare
    # direction rules), the campaign window rollup, the intervention
    # timeline, and cohort health drift.  A round index appearing in
    # two segments means the later segment REPLAYED it after a restart
    # (work done twice), so rounds lost = replayed indices + one round
    # of lost progress per restart; availability is the distinct-round
    # fraction of that total.
    seg_rounds: List[List[int]] = []
    for r in records:
        if r.get("event") == "run_header":
            seg_rounds.append([])
        elif (r.get("event") == "round"
              and isinstance(r.get("round_index"), int)):
            if not seg_rounds:
                seg_rounds.append([])
            seg_rounds[-1].append(r["round_index"])
    out["segments"] = len(seg_rounds)
    out["segment_round_ranges"] = [
        [s[0], s[-1]] if s else None for s in seg_rounds]
    distinct = len(set(idx))
    out["rounds_distinct"] = distinct
    out["rounds_replayed"] = len(idx) - distinct
    out["rounds_lost"] = out["rounds_replayed"] + out["restarts"]
    out["availability_pct"] = (
        round(100.0 * distinct / (distinct + out["rounds_lost"]), 2)
        if distinct else None)
    camps = [r for r in records if r.get("event") == "campaign"]
    out["campaign_records"] = len(camps)
    out["campaign_virtual_hours"] = None
    if camps:
        slope = [r["virtual_seconds"] / r["round_index"] for r in camps
                 if isinstance(r.get("round_index"), int)
                 and r["round_index"] > 0
                 and isinstance(r.get("virtual_seconds"), (int, float))]
        vs = [r["virtual_seconds"] for r in camps
              if isinstance(r.get("virtual_seconds"), (int, float))]
        if slope and idx:
            # virtual seconds per round is linear in the round index, so
            # the campaign's span covers one window past the last round
            out["campaign_virtual_hours"] = round(
                (max(idx) + 1) * slope[-1] / 3600.0, 2)
        elif vs:
            out["campaign_virtual_hours"] = round(max(vs) / 3600.0, 2)
        out["campaign_phases"] = sorted(
            {str(r.get("phase")) for r in camps if r.get("phase")})
        out["campaign_storm_windows"] = sum(
            1 for r in camps if r.get("storm"))
        out["campaign_burst_windows"] = sum(
            1 for r in camps if r.get("burst"))
        out["campaign_preempts"] = sum(
            1 for r in camps if r.get("preempt_now"))
    # serving plane (schema v13; serve/): request/batch totals, the
    # blended padding-waste fraction (padded slots over dispatched
    # slots, NOT a mean of per-round fractions — rounds with more
    # traffic weigh more), latency/QPS telemetry, the hot-swap count
    # and worst publish gap, and the closed-loop drift signals.  All
    # absent on serving-off streams so pre-v13 summaries are unchanged.
    serves = [r for r in records if r.get("event") == "serve"]
    out["serve_records"] = len(serves)
    if serves:
        def stot(key):
            vals = [r[key] for r in serves
                    if isinstance(r.get(key), (int, float))
                    and not isinstance(r.get(key), bool)]
            return sum(vals) if vals else None

        def svals(key):
            return [r[key] for r in serves
                    if isinstance(r.get(key), (int, float))
                    and not isinstance(r.get(key), bool)]

        out["serve_requests_total"] = stot("requests")
        out["serve_batches_total"] = stot("batches")
        padded = stot("padded_slots") or 0
        req = out["serve_requests_total"] or 0
        out["serve_padding_waste_frac"] = (
            round(padded / (req + padded), 6) if req + padded else None)
        qps = svals("serve_qps")
        out["serve_qps_mean"] = (
            round(sum(qps) / len(qps), 3) if qps else None)
        p50 = svals("serve_p50_ms")
        out["serve_p50_ms_mean"] = (
            round(sum(p50) / len(p50), 3) if p50 else None)
        p99 = svals("serve_p99_ms")
        out["serve_p99_ms_max"] = round(max(p99), 3) if p99 else None
        gaps = svals("swap_gap_seconds")
        out["serve_swap_gap_max"] = (
            round(max(gaps), 6) if gaps else None)
        out["serve_swaps"] = sum(1 for r in serves if r.get("swap"))
        out["serve_forced_refreshes"] = sum(
            1 for r in serves if r.get("forced_refresh"))
        vers = [r["weights_version"] for r in serves
                if isinstance(r.get("weights_version"), int)]
        out["serve_weights_version_last"] = vers[-1] if vers else None
        acc = svals("serve_accuracy")
        out["serve_accuracy_last"] = (
            round(acc[-1], 6) if acc else None)
        out["serve_drift_rounds"] = sum(
            1 for r in serves if r.get("drift_injected"))
        out["serve_drift_alerts"] = sum(
            1 for a in alerts if a.get("rule") == "serve_drift")
    out["intervention_timeline"] = [
        {"round_index": c.get("round_index"), "source": c.get("source"),
         "intervention": c.get("intervention"), "param": c.get("param"),
         "from_value": c.get("from_value"), "to_value": c.get("to_value")}
        for c in controls]
    # cohort health drift: mean finite per-client update norm, late half
    # of the stream vs early half (None without ≥2 client records)
    cnorms = []
    for r in records:
        if r.get("event") != "client":
            continue
        v = r.get("update_norm")
        if isinstance(v, list):
            fin = [x for x in v if isinstance(x, (int, float))
                   and x == x and abs(x) != float("inf")]
            if fin:
                cnorms.append(sum(fin) / len(fin))
    out["client_norm_drift_frac"] = None
    if len(cnorms) >= 2:
        half = len(cnorms) // 2
        early = sum(cnorms[:half]) / half
        late = sum(cnorms[half:]) / (len(cnorms) - half)
        if early > 0:
            out["client_norm_drift_frac"] = round(late / early - 1.0, 4)
    # device-cost ledger (schema v6): compile totals recomputed from the
    # round records; the memory watermark is the max across the rounds'
    # instantaneous stats (matches the recorder's summary field)
    compiles = [r for r in records if r.get("event") == "compile"]
    out["compile_events"] = len(compiles)
    out["compile_seconds_total"] = tot("compile_seconds")
    mem_peaks = []
    mem_in_use = []
    for r in rounds:
        for key, dst in (("mem_peak_bytes_in_use", mem_peaks),
                         ("mem_bytes_in_use", mem_in_use)):
            v = r.get(key)
            if isinstance(v, int) and not isinstance(v, bool):
                dst.append(v)
    out["mem_peak_bytes_watermark"] = (
        max(mem_peaks) if mem_peaks
        else (max(mem_in_use) if mem_in_use else None))
    out["mem_final_vs_peak_bytes"] = (
        out["mem_peak_bytes_watermark"] - mem_in_use[-1]
        if out["mem_peak_bytes_watermark"] is not None and mem_in_use
        else None)
    return out


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n:.0f} B"
        n /= 1024
    return f"{n:.1f} GiB"


def format_report(s: Dict[str, Any]) -> str:
    """Human-readable summary table (stable two-column layout)."""
    lines = [
        f"run {s.get('run_id') or '?'} · engine={s.get('engine') or '?'}"
        f" · algo={s.get('algorithm') or '?'}"
        f" · schema v{s.get('path_schema')} · status={s.get('status')}",
    ]

    def row(label, value):
        lines.append(f"  {label:<22}{value}")

    mono = "monotonic" if s.get("monotonic") else "NON-MONOTONIC"
    row("rounds", f"{s['rounds']}  (indices {s.get('round_index_first')}"
        f"..{s.get('round_index_last')}, {mono}; "
        f"{s['headers']} header(s), {s['summaries']} summary(ies))")
    rs = s.get("round_seconds_total")
    if rs:
        per = rs / max(s["rounds"], 1)
        row("wall clock", f"{rs:.2f} s  ({per:.3f} s/round, "
            f"{s.get('rounds_per_sec', 0.0):.2f} rounds/s)")
    if s.get("images_total"):
        row("throughput", f"{s.get('images_per_sec', 0.0):,.0f} images/s"
            f"  ({s['images_total']:,} images)")
    if s.get("comm_seconds_total") is not None and rs:
        row("comm overhead", f"{100.0 * s.get('comm_overhead_frac', 0.0):.1f} %"
            f"  ({s['comm_seconds_total']:.2f} s in comm+sync)")
    if s.get("bytes_on_wire_total") is not None:
        msg = _fmt_bytes(s["bytes_on_wire_total"])
        if s.get("bytes_dense_total"):
            msg += (f"  (dense {_fmt_bytes(s['bytes_dense_total'])}, "
                    f"saved {100.0 * s.get('compression_savings_frac', 0.0):.1f}%)")
        row("bytes on wire", msg)
    faults = {k: s.get(k + "_total") for k in
              ("guard_trips", "fault_dropped", "fault_straggled",
               "fault_corrupted")}
    if any(v for v in faults.values()) or s.get("quarantined_last"):
        row("guards/faults",
            f"trips={faults['guard_trips'] or 0:g} "
            f"drop={faults['fault_dropped'] or 0} "
            f"straggle={faults['fault_straggled'] or 0} "
            f"corrupt={faults['fault_corrupted'] or 0} "
            f"quarantined_last={s.get('quarantined_last') or 0}")
    if s.get("async_rounds"):
        msg = (f"{s['async_rounds']} async round(s), "
               f"peak buffer_depth={s.get('buffer_depth_peak') or 0}, "
               f"admission_rejected={s.get('admission_rejected_total') or 0}")
        if s.get("staleness_hist_total"):
            msg += f", staleness_hist={s['staleness_hist_total']}"
        row("async", msg)
    if s.get("bytes_fused_total"):
        row("bytes fused", _fmt_bytes(s["bytes_fused_total"])
            + "  (stayed packed across the reduction)")
    if s.get("overlap_seconds_total"):
        row("comm overlap", f"{s['overlap_seconds_total']:.2f} s hidden "
            "behind staging")
    if s.get("members_peak") is not None:
        row("membership",
            f"peak={s['members_peak']} min={s.get('members_min')} "
            f"joined={s.get('joined_total') or 0} "
            f"left={s.get('left_total') or 0} "
            f"reshapes={s.get('reshapes') or 0}")
    if s.get("client_records"):
        msg = (f"{s['client_records']} record(s), "
               f"K={s.get('clients_observed')}, "
               f"top_offender=c{s.get('top_offender')} "
               f"(score {s.get('top_offender_score', 0.0):.3f})")
        if s.get("client_norm_skew") is not None:
            msg += (f", norm max/median="
                    f"{s['client_norm_max']:.4g}/"
                    f"{s['client_norm_median']:.4g} "
                    f"(skew {s['client_norm_skew']:.2f})")
        row("client ledger", msg)
    if s.get("alerts"):
        row("health alerts",
            f"{s['alerts']} alert(s): {', '.join(s.get('alert_rules') or [])}")
    if s.get("controls"):
        row("control plane",
            f"{s['controls']} record(s), {s.get('restarts', 0)} restart(s)"
            f": {', '.join(s.get('control_interventions') or [])}")
    if s.get("segments", 0) > 1 or s.get("rounds_lost"):
        ranges = ", ".join(
            "-" if rr is None else f"{rr[0]}..{rr[1]}"
            for rr in s.get("segment_round_ranges") or [])
        row("segments", f"{s.get('segments')} restart segment(s): "
            f"rounds {ranges}")
        if s.get("availability_pct") is not None:
            row("availability",
                f"{s['availability_pct']:.2f} %  "
                f"({s.get('rounds_distinct')} distinct round(s); "
                f"{s.get('rounds_lost')} lost = "
                f"{s.get('rounds_replayed')} replayed + "
                f"{s.get('restarts', 0)} restart(s))")
    if s.get("campaign_records"):
        msg = f"{s['campaign_records']} window record(s)"
        if s.get("campaign_virtual_hours") is not None:
            msg += f", {s['campaign_virtual_hours']:.1f} virtual h"
        msg += (f", storms={s.get('campaign_storm_windows', 0)} "
                f"bursts={s.get('campaign_burst_windows', 0)} "
                f"preempts={s.get('campaign_preempts', 0)}; phases: "
                + ", ".join(s.get("campaign_phases") or []))
        row("campaign", msg)
    if s.get("serve_records"):
        msg = (f"{s['serve_records']} tick(s), "
               f"{s.get('serve_requests_total') or 0:,} request(s)")
        if s.get("serve_qps_mean") is not None:
            msg += f", {s['serve_qps_mean']:,.1f} qps"
        if s.get("serve_p50_ms_mean") is not None:
            msg += (f", p50 {s['serve_p50_ms_mean']:.2f} ms / "
                    f"p99 {s.get('serve_p99_ms_max', 0.0):.2f} ms")
        row("serving", msg)
        msg = (f"{s.get('serve_swaps', 0)} swap(s) to "
               f"v{s.get('serve_weights_version_last')}")
        if s.get("serve_swap_gap_max") is not None:
            msg += f", worst gap {1e3 * s['serve_swap_gap_max']:.1f} ms"
        if s.get("serve_forced_refreshes"):
            msg += (f", {s['serve_forced_refreshes']} forced "
                    "refresh(es)")
        if s.get("serve_padding_waste_frac") is not None:
            msg += (f", padding waste "
                    f"{100.0 * s['serve_padding_waste_frac']:.1f} %")
        row("serve swaps", msg)
        if (s.get("serve_drift_rounds") or s.get("serve_drift_alerts")
                or s.get("serve_accuracy_last") is not None):
            msg = ""
            if s.get("serve_accuracy_last") is not None:
                msg += f"accuracy_last={s['serve_accuracy_last']:.4f} "
            msg += (f"drift_rounds={s.get('serve_drift_rounds', 0)} "
                    f"drift_alerts={s.get('serve_drift_alerts', 0)}")
            row("serve drift", msg)
    if s.get("client_norm_drift_frac") is not None:
        row("cohort drift",
            f"{100.0 * s['client_norm_drift_frac']:+.1f} % mean "
            "update-norm, late vs early half")
    timeline = s.get("intervention_timeline") or []
    if timeline:
        row("interventions", f"{len(timeline)} event(s):")
        for ev in timeline[:12]:
            msg = (f"round {ev.get('round_index')}: "
                   f"{ev.get('source')}/{ev.get('intervention')}")
            if ev.get("param") is not None:
                msg += (f" {ev['param']}: {ev.get('from_value')!r}"
                        f" -> {ev.get('to_value')!r}")
            lines.append(f"    {msg}")
        if len(timeline) > 12:
            lines.append(f"    ... {len(timeline) - 12} more")
    if s.get("compile_events") or s.get("compile_seconds_total"):
        msg = f"{s.get('compile_events', 0)} event(s)"
        if s.get("compile_seconds_total") is not None:
            msg += f", {s['compile_seconds_total']:.2f} s"
        msg += "  (details: python -m federated_pytorch_test_tpu.obs.profile)"
        row("compile", msg)
    if s.get("mem_peak_bytes_watermark") is not None:
        msg = "watermark " + _fmt_bytes(s["mem_peak_bytes_watermark"])
        if s.get("mem_final_vs_peak_bytes") is not None:
            msg += (", final vs peak "
                    + _fmt_bytes(s["mem_final_vs_peak_bytes"]))
        row("device memory", msg)
    if s.get("loss_first") is not None:
        row("loss", f"first={s['loss_first']:.6g} "
            f"final={s['loss_final']:.6g}")
    return "\n".join(lines)


def selftest() -> str:
    """Recorder → JSONL → parse → validate → summarise round-trip, plus
    the trace-exporter, watchdog, compare, cost-profile, and
    control-replay selftests (tier-1 runs this, so the whole
    live-health + device-cost + control-plane layer is exercised
    without a prior training run)."""
    import os
    import tempfile

    from federated_pytorch_test_tpu.obs.recorder import make_recorder

    with tempfile.TemporaryDirectory() as d:
        rec = make_recorder("jsonl", d, run_name="selftest",
                            engine="selftest", algorithm="fedavg")
        rec.open(config={"K": 2, "Nadmm": 3}, mesh_shape={"clients": 1})
        for i in range(3):
            rec.round({"round_index": i, "nloop": 0, "block": 0,
                       "nadmm": i, "N": 100, "loss": 2.0 - 0.5 * i,
                       "rho": 1.0, "round_seconds": 0.5,
                       "stage_seconds": 0.01, "comm_seconds": 0.1,
                       "bytes_on_wire": 100, "bytes_dense": 400,
                       "bytes_fused": 50, "overlap_seconds": 0.02,
                       "images": 256, "guard_trips": 1 if i == 2 else 0,
                       "quarantined": 0,
                       "async_mode": True, "max_staleness": 2,
                       "async_arrived": 2, "admission_rejected": i,
                       "buffer_depth": i, "staleness_hist": [2, 0, 0],
                       "members_active": 2 - (i == 1), "joined": 0,
                       "left": 1 if i == 1 else 0})
            # serving tick (schema v13): the pure subset + advisory
            # telemetry, validated by the same read_records pass below
            rec.serve_event({"round_index": i, "weights_version":
                             1 + i // 2, "requests": 10 + i, "batches": 2,
                             "padded_slots": 3, "padding_waste_frac": 0.2,
                             "serve_p50_ms": 1.0, "serve_p99_ms": 2.0 + i,
                             "serve_qps": 100.0, "serve_accuracy": 0.9,
                             "drift_score": 0.0, "drift_injected": False,
                             "swap": i % 2 == 0,
                             **({"swap_gap_seconds": 0.01}
                                if i % 2 == 0 else {})})
        rec.close()
        path = os.path.join(d, "selftest.jsonl")
        records = read_records(path)
        assert len(records) == 8, f"expected 8 records, got {len(records)}"
        s = summarize(records)
        assert s["rounds"] == 3 and s["monotonic"], s
        assert s["bytes_on_wire_total"] == 300, s
        assert s["bytes_dense_total"] == 1200, s
        assert abs(s["compression_savings_frac"] - 0.75) < 1e-9, s
        assert s["guard_trips_total"] == 1, s
        assert s["loss_final"] == 1.0, s
        assert s["status"] == "completed", s
        assert s["async_rounds"] == 3, s
        assert s["buffer_depth_peak"] == 2, s
        assert s["admission_rejected_total"] == 3, s
        assert s["staleness_hist_total"] == [6, 0, 0], s
        assert s["bytes_fused_total"] == 150, s
        assert abs(s["overlap_seconds_total"] - 0.06) < 1e-9, s
        assert s["members_peak"] == 2 and s["members_min"] == 1, s
        assert s["joined_total"] == 0 and s["left_total"] == 1, s
        assert s["reshapes"] == 0, s
        assert s["serve_records"] == 3, s
        assert s["serve_requests_total"] == 33, s
        assert s["serve_swaps"] == 2, s
        assert s["serve_weights_version_last"] == 2, s
        assert s["serve_p99_ms_max"] == 4.0, s
        assert abs(s["serve_padding_waste_frac"] - 9 / 42) < 1e-6, s
        assert s["serve_swap_gap_max"] == 0.01, s
        table = format_report(s)
        assert "async" in table, table
        assert "bytes fused" in table, table
        assert "comm overlap" in table, table
        assert "membership" in table, table
        assert "serving" in table and "serve swaps" in table, table
    assert record_ips({"images": 256, "round_seconds": 0}) == float("inf")
    assert record_ips({"images": 0, "round_seconds": 0}) == 0.0

    # soak aggregation: a synthetic two-segment campaign stream — the
    # restart replays rounds 2..3, so 6 distinct rounds cost 8 round
    # records + 1 restart -> availability 6/(6+3)
    from federated_pytorch_test_tpu.campaign.schedule import (
        CampaignSchedule)
    sched = CampaignSchedule.parse(
        "hours=3,round_minutes=30,diurnal=0.5,drop=0.2,seed=9")

    def rr(i):
        return {"event": "round", "round_index": i, "round_seconds": 1.0,
                "images": 64, "loss": 1.0}

    camp = [dict({"event": "campaign", "schema": 12, "run_id": "x"},
                 **fields)
            for _, fields in sched.expected_emissions(range(6))]
    soak = ([{"event": "run_header", "run_id": "x", "schema": 12}]
            + [rr(i) for i in range(4)] + camp[:2]
            + [{"event": "control", "run_id": "x", "schema": 12,
                "round_index": 3, "source": "supervisor", "mode": "act",
                "intervention": "restart", "param": "run", "attempt": 1,
                "backoff_seconds": 1.0, "reason": "selftest"}]
            + [{"event": "run_header", "run_id": "x", "schema": 12}]
            + [rr(i) for i in range(2, 6)] + camp[2:]
            + [{"event": "client", "run_id": "x", "schema": 12,
                "round_index": i, "clients": 2,
                "update_norm": [1.0 + 0.5 * (i >= 3)] * 2}
               for i in range(6)])
    ss = summarize(soak)
    assert ss["segments"] == 2, ss
    assert ss["segment_round_ranges"] == [[0, 3], [2, 5]], ss
    assert ss["rounds_distinct"] == 6, ss
    assert ss["rounds_replayed"] == 2 and ss["restarts"] == 1, ss
    assert ss["rounds_lost"] == 3, ss
    assert ss["availability_pct"] == round(100.0 * 6 / 9, 2), ss
    assert ss["campaign_records"] == len(camp) == 3, ss
    assert ss["campaign_virtual_hours"] == 3.0, ss
    assert len(ss["intervention_timeline"]) == 1, ss
    assert ss["client_norm_drift_frac"] == 0.5, ss
    soak_table = format_report(ss)
    assert "availability" in soak_table, soak_table
    assert "2 restart segment(s)" in soak_table, soak_table
    assert "campaign" in soak_table, soak_table
    assert "supervisor/restart" in soak_table, soak_table

    # serve drift aggregation: injected rounds and the watchdog's
    # serve_drift alerts both surface in the summary/table
    drift_stream = (
        [{"event": "serve", "schema": 13, "run_id": "x",
          "round_index": i, "weights_version": 1, "requests": 8,
          "serve_accuracy": 1.0 - 0.5 * (i >= 2),
          "drift_injected": i >= 2} for i in range(4)]
        + [{"event": "alert", "schema": 13, "run_id": "x",
            "round_index": 3, "rule": "serve_drift", "severity": "warn",
            "message": "selftest", "action": "warn"}])
    ds = summarize(drift_stream)
    assert ds["serve_drift_rounds"] == 2, ds
    assert ds["serve_drift_alerts"] == 1, ds
    assert ds["serve_accuracy_last"] == 0.5, ds
    assert "serve drift" in format_report(ds), format_report(ds)

    from federated_pytorch_test_tpu.campaign import clock as campaign_clock
    from federated_pytorch_test_tpu.campaign import (
        harness as campaign_harness)
    from federated_pytorch_test_tpu.campaign import (
        schedule as campaign_schedule)
    from federated_pytorch_test_tpu.control import replay as control_replay
    from federated_pytorch_test_tpu.obs import (
        clients, compare, health, profile, trace,
    )
    from federated_pytorch_test_tpu.serve import (
        batcher as serve_batcher,
        evalstream as serve_evalstream,
        infer as serve_infer,
        swap as serve_swap,
    )

    trace.selftest()
    health.selftest()
    compare.selftest()
    profile.selftest()
    control_replay.selftest()
    clients.selftest()
    campaign_schedule.selftest()
    campaign_clock.selftest()
    campaign_harness.selftest()
    serve_batcher.selftest()
    serve_swap.selftest()
    serve_infer.selftest()
    serve_evalstream.selftest()

    from federated_pytorch_test_tpu.analysis import lint as analysis_lint
    assert analysis_lint.selftest() == 0, \
        "graftcheck determinism-contract selftest failed"

    return (table
            + "\nobs trace selftest: OK (Chrome trace valid)"
            + "\nobs health selftest: OK (NaN streak alerted)"
            + "\nobs compare selftest: OK (regression gate works)"
            + "\nobs profile selftest: OK (cost attribution reconstructs)"
            + "\ncontrol replay selftest: OK (decisions reproduce)"
            + "\nobs clients selftest: OK (anomaly ranking replayable)"
            + "\ncampaign selftests: OK (schedule pure; clock scales "
            "wall time only; harness maps knobs)"
            + "\nserve selftests: OK (batcher deterministic; swap "
            "never torn; predictor pads to buckets; drift scored)"
            + "\ngraftcheck contract selftest: OK (JG117-JG121 canaries "
            "fire; contract tables in sync)"
            + "\nobs report selftest: OK")


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m federated_pytorch_test_tpu.obs.report",
        description="Summarise an obs run JSONL (see README "
                    "'Observability')")
    p.add_argument("path", nargs="?", help="run JSONL file")
    p.add_argument("--json", action="store_true",
                   help="print the summary as one JSON object")
    p.add_argument("--no-validate", action="store_true",
                   help="skip schema validation while parsing")
    p.add_argument("--selftest", action="store_true",
                   help="run the built-in round-trip selftest and exit")
    args = p.parse_args(argv)
    if args.selftest:
        print(selftest())
        return 0
    if not args.path:
        p.error("a run JSONL path is required (or --selftest)")
    try:
        records = read_records(args.path, validate=not args.no_validate)
    except (OSError, SchemaError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if not records:
        print(f"error: {args.path} holds no records", file=sys.stderr)
        return 1
    s = summarize(records)
    print(json.dumps(s) if args.json else format_report(s))
    return 0


if __name__ == "__main__":
    sys.exit(main())
