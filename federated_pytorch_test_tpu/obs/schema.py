"""Versioned record schema for run telemetry.

One run = one JSONL stream of ten event kinds:

- ``run_header``  — emitted once when a run (or resumed segment) opens:
  config snapshot, mesh shape, jax/backend versions, git rev.
- ``round``       — one per communication round (or per epoch on the
  no-consensus path): loop coordinates, loss/residuals/rho, wall-clock
  phase timings, ``bytes_on_wire``, guard/fault/quarantine counters,
  device memory stats where the backend reports them.
- ``summary``     — emitted once when the run closes (``completed`` or
  ``aborted``): totals and derived rates.
- ``span``        — one per phase/sub-span (schema v5): a parent-linked
  node of the run -> round -> phase timeline; export with
  ``python -m federated_pytorch_test_tpu.obs.trace``.
- ``alert``       — a streaming-watchdog verdict (schema v5;
  ``obs/health.py``): which rule tripped, on which round, and what the
  configured ``--health-action`` did about it.
- ``compile``     — one per observed jit compile event (schema v6;
  ``obs/costs.py``): site label, compile wall-seconds, trace count,
  AOT cost-model / memory-analysis numbers where available, and
  persistent-compile-cache hit/miss attribution.
- ``control``     — one per control-plane decision (schema v8;
  ``control/``): a typed intervention from the deterministic policy
  engine or the restart supervisor — which knob, from/to values,
  scope, whether it was applied, and the telemetry that justified it.
  Pure function of the recorded stream (no wall clock): replay with
  ``python -m federated_pytorch_test_tpu.control.replay``.
- ``client``      — one per communication round (schema v10;
  ``obs/clients.py``): the client-grain flight recorder.  Parallel
  length-K list fields carry per-client update norms, delta-vs-z
  distance, loss contribution, guard verdicts and quarantine state,
  fault tags, async staleness/admission, and membership — the round
  record's counters, un-aggregated.  Emitted right AFTER the round
  record it describes, so file order is the replay order.
- ``campaign``    — one per schedule-window transition (schema v12;
  ``campaign/``): the hour-quantized slice of the trace-driven soak
  schedule the engine applied from this round on — diurnal arrival
  fraction, derived fault/churn probabilities, storm/burst flags,
  deterministic preemption marker.  Pure function of (campaign seed,
  round_index): ``control.replay`` re-derives the whole campaign from
  the run header's ``campaign_spec``.
- ``serve``       — one per communication round while the serving plane
  is on (schema v13; ``serve/``): the seeded traffic draw, the greedy
  pad-to-bucket batch plan, the hot-swap weights version, and advisory
  p50/p99/QPS/swap-gap/eval-stream telemetry.  The pure subset
  re-derives from the run header's ``serve_spec`` + round index alone.

The schema unifies what ``engine.py``, ``cpc_engine.py`` and
``vae_engine.py`` used to build as ad-hoc dicts; every record carries
``schema`` (the version) and validates via :func:`validate_record`.
Unknown fields are ALLOWED (forward compatibility — a newer writer must
not break an older reader); known fields are type-checked.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

# v2 (additive): optional per-round `jit_retraces` — cumulative jit
# retrace count from the engine's retrace sentinel
# (analysis/sanitize.py), present when --retrace-sentinel is on.
# v3 (additive): optional per-round `host_dispatches` — how many jitted
# step dispatches the host issued for the round (fused rounds: exactly 1
# for the train+comm phase vs Nepoch+1 unfused) — and `ckpt_write_seconds`
# — wall-clock the round spent in the mid-run save call (async
# checkpointing: snapshot+enqueue only, so near zero unless the writer's
# backpressure barrier engaged).
# v4 (additive): buffered-async federation telemetry (--async-rounds) —
# per-round `async_mode`/`max_staleness` (the mode stamp), `async_arrived`
# (deliveries this round), `admission_rejected` (staler than
# max_staleness, discarded), `buffer_depth` (updates still in flight
# after the round), and `staleness_hist` (admitted deliveries bucketed by
# staleness 0..max_staleness).
# v5 (additive): the live run-health layer — parent-linked span ids
# (`span_id` on run_header/round, `parent_span` + host-monotonic
# `t_start`/`t_end` on round records), a new `span` record kind (the
# run -> round -> phase timeline, exported to Chrome trace-event JSON by
# obs/trace.py and keyed to the same `round_index` the XProf round_trace
# annotations use), a new `alert` record kind (obs/health.py streaming
# watchdog verdicts), and `alerts_total` on the summary.
# v6 (additive): the device-cost ledger (obs/costs.py) — a new `compile`
# record kind (one per observed jit compile: `site`, `compile_seconds`,
# `trace_count`, AOT cost-model `flops` / `hlo_bytes_accessed` /
# `transcendentals` and memory_analysis byte fields where the backend
# supports them, `cache_hit` persistent-cache attribution; carries
# span_id/parent_span/t_start/t_end so compile events render as bubbles
# inside rounds in the Chrome-trace export), per-round `compile_seconds`
# / `flops_round` / `hlo_bytes_accessed` / `peak_device_bytes` /
# `cache_hit`, and summary compile/cache totals plus the device-memory
# high-watermark pair.  ALL cost fields are advisory: absent means "the
# backend/mode did not produce it", never zero (PARITY.md).
# v7 (additive): the roofline comm path (--fused-collective /
# --overlap-staging) — per-round `bytes_fused` (predicted device-to-device
# bytes the fused packed collective moves for the round: every ppermute
# hop's packed payload + scale sidecar, ops/packed_reduce.py
# fused_bytes_on_wire; a DIFFERENT quantity from the uplink model
# `bytes_on_wire`, which counts K client payloads once) and
# `overlap_seconds` (host wall-clock the round spent pre-staging the next
# round's first epoch while the comm dispatch was in flight; present only
# when --overlap-staging is on, 0.0 when there was nothing left to
# prestage).
# v8 (additive): the closed-loop control plane (control/) — a new
# `control` record kind, one per policy decision or supervisor restart
# action.  `source` says who decided ("policy" = the deterministic
# in-run rule engine, "supervisor" = the restart wrapper between run
# segments); `intervention`/`param`/`from_value`/`to_value`/`scope`
# describe the typed knob change; `mode` ("observe"|"act") and
# `applied` record whether the engine actually took it; `reason`
# carries the rule text; `observed`/`threshold`/`streak` reuse the
# alert-field semantics for the triggering telemetry.  Supervisor
# records add `attempt` (1-based restart count), `backoff_seconds`
# (seeded deterministic backoff) and `ladder_stage`.  Control records
# deliberately carry NO time_unix: every field is a pure function of
# recorded telemetry + round index, so control.replay can re-derive
# the decision sequence bit-exactly from the stream.  The summary
# gains `interventions_total`.
# v9 (additive): elastic federation (train/faults.py churn families +
# mesh-reshaping resume) — round records gain `members_active` (live
# churn-ledger members after this round's tick), `joined` and `left`
# (this round's membership transitions).  Present only when a
# join=/leave= fault family is configured, so static-roster streams are
# byte-identical to v8.  Reshape restarts reuse the existing v8 control
# fields (`intervention="reshape"`, param/from_value/to_value/scope/
# attempt/reason); control.replay cross-checks them against consecutive
# run_header `mesh_shape` values.
# v10 (additive): the client-grain flight recorder (obs/clients.py) — a
# new `client` record kind, at most one per communication round, emitted
# immediately AFTER the round record it describes (file order == replay
# order; control.replay feeds both in sequence).  Scalar `clients` is
# the cohort size K; every other payload field is a parallel length-K
# list indexed by client id: `update_norm` (||x_k - z|| BEFORE guard
# neutralisation, so NaN/inf corruption stays visible), `dist_z`
# (||x_k - z_new|| after the consensus fold), `loss_client`, `weight`
# (the mean weight incl. participation and staleness decay), `active`,
# `guard_ok` (guard verdicts, only when --update-guard is on),
# `quarantine` (rounds remaining), fault tags `dropped`/`straggled`/
# `corrupted`, async `staleness`/`admitted`, and churn `members`.
# `payload_bytes` is the per-participant uplink cost of the round.
# ALL list fields are advisory (absent means "that subsystem was off",
# never zeroed — PARITY.md); streams with client_ledger=False are
# byte-identical to v9.  The record is derived from host values the
# engine already fetched plus one optional probe output, and the
# anomaly ranking in obs/clients.py is a pure function of the stream.
# v11 (additive): population federation (population/) — `client` records
# gain optional `registry_ids`, a parallel length-`clients` list mapping
# each slot to the REGISTRY id of the virtual client that occupied it
# this round (``--population K`` decouples registered clients from
# device slots; the sampled cohort changes every round).  When present,
# obs/clients.py keys its ledger/ranking/timelines by registry id and
# aggregates byte-exactly over the full population even though each
# record only carries the sampled cohort.  Absent on population-off
# streams, which therefore stay byte-identical to v10.
# v12 (additive): soak campaigns (campaign/) — a new `campaign` record
# kind, emitted right after the round record whenever the trace-driven
# schedule's hour-quantized window transitions (first round of a
# segment, every virtual-hour boundary, and any post-resume re-run of a
# preempted round).  Carries the window the engine actually applied:
# `virtual_seconds` (round_index * round_minutes * 60 — virtual time is
# a pure function of the round index), `arrival_frac` (the diurnal
# curve), the derived per-family probabilities `drop_p`/`straggle_p`/
# `corrupt_p`/`join_p`/`leave_p`, the correlated-event flags `storm`/
# `burst` (seeded tags 73/79), `preempt_now`, and the human-facing
# `phase` label.  Deliberately NO time_unix: every field is a pure
# function of (campaign seed, round_index), so control.replay
# re-derives the whole campaign schedule bit-exactly from the header
# config's campaign_spec alone.  Campaign-off streams carry no
# `campaign` records and stay byte-identical to v11.
# v13 (additive): the serving plane (serve/) — a new `serve` record
# kind, one per communication round while serving is on, emitted right
# after the campaign record slot in the round fan-out (file order ==
# replay order).  The record splits into a PURE subset and advisory
# telemetry.  Pure (re-derived bit-exactly by control.replay from the
# header config's serve_spec + the round index alone): `weights_version`
# (1 + round_index // swap_every — forced refreshes republish at the
# SAME version, keeping the sequence resume-free), `requests` (the
# seeded diurnal traffic draw, tag 83), `batches`/`padded_slots`/
# `padding_waste_frac` (the greedy pad-to-bucket plan), `drift_injected`
# (round_index >= drift_at) and `swap` (round_index % swap_every == 0).
# Advisory (wall-clock/model-dependent — never replay-checked):
# `serve_p50_ms`/`serve_p99_ms` request latency, `serve_qps`,
# `swap_gap_seconds` (double-buffered publish gap), `serve_accuracy`/
# `drift_score` (the eval-stream loop into obs/health.py's serve_drift
# rule) and `forced_refresh` (a control-plane serve_swap intervention
# republished the weights this round).  Serving-off streams carry no
# `serve` records and stay byte-identical to v12.
# v14 (additive): whole-round compute/comm overlap (--overlap-round) —
# per-round `overlap_dispatch_seconds`, the host wall-clock spent
# enqueueing the NEXT round's first train epoch while this round's comm
# collective was still executing on-device (train/engine.py
# _predispatch_round).  Advisory (a host timing, like overlap_seconds);
# present only when --overlap-round is active, 0.0 on the last round of
# a block (the pre-dispatch is gated to same-block successors) and
# whenever the lookahead cache was already spent.  Overlap-off streams
# carry no such field and stay byte-identical to v13.
# v1..v13 records remain valid: validate_record accepts ver <= SCHEMA_VERSION.
SCHEMA_VERSION = 14

EVENTS = ("run_header", "round", "summary", "span", "alert", "compile",
          "control", "client", "campaign", "serve")


class SchemaError(ValueError):
    """A record fails schema validation (missing/ill-typed field)."""


# bool is an int subclass: _INT/_NUM must not silently admit True/False
_NUM = (int, float)      # numeric (counters may arrive as float from psum)
_INT = (int,)
_STR = (str,)
_BOOL = (bool,)
_LIST = (list,)
_DICT = (dict,)
_ANY = None              # any JSON value

#: known fields -> (event kinds they may appear on, allowed types)
FIELDS: Dict[str, Any] = {
    # envelope
    "event":        (EVENTS, _STR),
    "schema":       (EVENTS, _INT),
    "run_id":       (EVENTS, _STR),
    "run_name":     (("run_header",), _STR),
    "engine":       (("run_header", "round", "compile"), _STR),
    "algorithm":    (("run_header", "round", "compile"), _STR),
    # header
    "time_unix":    (("run_header", "summary", "alert"), _NUM),
    "config":       (("run_header",), _DICT),
    "mesh_shape":   (("run_header",), _DICT),
    "devices":      (("run_header",), _INT),
    "local_devices": (("run_header",), _INT),
    "platform":     (("run_header",), _STR),
    "jax_version":  (("run_header",), _STR),
    "jaxlib_version": (("run_header",), _STR),
    "git_rev":      (("run_header",), _STR),
    "resumed":      (("run_header",), _BOOL),
    "rounds_prior": (("run_header",), _INT),
    "host":         (("run_header",), _STR),
    "pid":          (("run_header",), _INT),
    # round coordinates (spans and alerts are keyed to the same index the
    # XProf round_trace annotations use, so all three timelines correlate)
    "round_index":  (("round", "span", "alert", "compile", "control",
                      "client", "campaign", "serve"), _INT),
    "nloop":        (("round",), _INT),
    "block":        (("round",), _INT),
    "nadmm":        (("round",), _INT),
    "epoch":        (("round",), _INT),
    "model":        (("round",), _STR),   # CPC submodel name
    "N":            (("round",), _INT),
    "label":        (("round",), _STR),   # bench section tag
    # round measurements
    "loss":         (("round",), _NUM),
    "rho":          (("round",), _NUM),
    "dual_residual": (("round",), _NUM),
    "primal_residual": (("round",), _NUM),
    "accuracy":     (("round",), _LIST),
    "images":       (("round",), _INT),
    # wall-clock phase segments (time.monotonic/perf_counter on host;
    # they sum to ~round_seconds — see README "Observability" for the
    # single-host-sync attribution caveat)
    "round_seconds": (("round",), _NUM),
    "stage_seconds": (("round",), _NUM),
    "train_seconds": (("round",), _NUM),
    "comm_seconds": (("round",), _NUM),
    "sync_seconds": (("round",), _NUM),
    "compute_seconds": (("round",), _NUM),
    "epoch_seconds": (("round",), _NUM),
    # recompilation sentinel (schema v2; --retrace-sentinel)
    "jit_retraces": (("round",), _INT),
    "host_dispatches": (("round",), _INT),
    "ckpt_write_seconds": (("round",), _NUM),
    # communication volume
    "bytes_on_wire": (("round",), _INT),
    "bytes_dense":  (("round",), _INT),
    # roofline comm path (schema v7; --fused-collective/--overlap-staging)
    "bytes_fused":  (("round",), _INT),
    "overlap_seconds": (("round",), _NUM),
    # whole-round overlap (schema v14; --overlap-round)
    "overlap_dispatch_seconds": (("round",), _NUM),
    # fault / guard counters
    "guard_trips":  (("round",), _NUM),
    "guard_norm_mean": (("round",), _NUM),
    "n_ok":         (("round",), _NUM),
    "n_active":     (("round",), _NUM),
    "n_comm":       (("round",), _INT),
    "quarantined":  (("round",), _INT),
    "fault_dropped": (("round",), _INT),
    "fault_straggled": (("round",), _INT),
    "fault_corrupted": (("round",), _INT),
    # elastic federation churn ledger (schema v9; join=/leave= families)
    "members_active": (("round",), _INT),
    "joined":       (("round",), _INT),
    "left":         (("round",), _INT),
    # buffered-async federation (schema v4; --async-rounds)
    "async_mode":   (("round",), _BOOL),
    "max_staleness": (("round",), _INT),
    "async_arrived": (("round",), _INT),
    "admission_rejected": (("round",), _INT),
    "buffer_depth": (("round",), _INT),
    "staleness_hist": (("round",), _LIST),
    # device memory (absent when the backend reports none, e.g. CPU)
    "mem_bytes_in_use": (("round",), _INT),
    "mem_peak_bytes_in_use": (("round",), _INT),
    # device-cost ledger (schema v6; obs/costs.py).  Round-level fields
    # aggregate the compile events and executed cost-model numbers of
    # that round's dispatch window; `compile` records carry the per-event
    # detail.  Every one of these is optional — omitted, never zeroed,
    # when the backend/AOT mode does not produce it.
    "site":         (("compile",), _STR),     # jit site label
    "compile_seconds": (("round", "compile"), _NUM),
    "trace_count":  (("compile",), _INT),     # cumulative; 1 == cold
    "flops":        (("compile",), _NUM),     # per-dispatch cost model
    "flops_round":  (("round",), _NUM),       # executed (sum over window)
    "hlo_bytes_accessed": (("round", "compile"), _NUM),
    "transcendentals": (("compile",), _NUM),
    "argument_bytes": (("compile",), _INT),   # memory_analysis (full AOT)
    "output_bytes": (("compile",), _INT),
    "temp_bytes":   (("compile",), _INT),
    "generated_code_bytes": (("compile",), _INT),
    "peak_device_bytes": (("round", "compile"), _INT),
    "cache_hit":    (("round", "compile"), _BOOL),
    # span tracing (schema v5; obs/trace.py).  `span_id`/`parent_span`
    # ride additively on existing records; `t_start`/`t_end` are HOST
    # MONOTONIC (time.perf_counter) stamps taken at the phase boundaries
    # the engines already time — device-phase durations come from the
    # existing `_obs_sync` sync points, no new syncs are introduced.
    "span_id":      (("run_header", "round", "span", "compile"), _STR),
    "parent_span":  (("round", "span", "compile"), _STR),
    "t_start":      (("round", "span", "compile"), _NUM),
    "t_end":        (("round", "span", "compile"), _NUM),
    "name":         (("span",), _STR),        # phase/sub-span label
    "cat":          (("span",), _STR),        # run|round|phase|comm|ckpt|...
    # streaming watchdog verdicts (schema v5; obs/health.py)
    "rule":         (("alert",), _STR),
    "severity":     (("alert",), _STR),       # warn|fatal
    "message":      (("alert",), _STR),
    "observed":     (("alert", "control"), _NUM),  # triggering value
    "threshold":    (("alert", "control"), _NUM),
    "streak":       (("alert", "control"), _INT),  # consecutive bad rounds
    "action":       (("alert",), _STR),       # health_action at trip time
    # closed-loop control plane (schema v8; control/).  NO time_unix on
    # purpose: a control record is a pure function of recorded telemetry
    # and the round index, so control.replay reproduces it bit-exactly.
    "source":       (("control",), _STR),     # policy|supervisor
    "intervention": (("control",), _STR),     # typed action name
    "param":        (("control",), _STR),     # cfg knob it targets
    "from_value":   (("control",), _ANY),
    "to_value":     (("control",), _ANY),
    "reason":       (("control",), _STR),
    "mode":         (("control",), _STR),     # observe|act
    "applied":      (("control",), _BOOL),    # engine took the action
    "scope":        (("control",), _STR),     # round|block|restart
    "attempt":      (("control",), _INT),     # supervisor: restart count
    "backoff_seconds": (("control",), _NUM),  # supervisor: seeded backoff
    "ladder_stage": (("control",), _INT),     # supervisor: degradation rung
    # client-grain flight recorder (schema v10; obs/clients.py).  All
    # list fields are parallel, length `clients`, indexed by client id;
    # each is advisory — present only when its subsystem ran.
    "clients":      (("client",), _INT),      # cohort size K
    "update_norm":  (("client",), _LIST),     # ||x_k - z|| pre-guard
    "dist_z":       (("client",), _LIST),     # ||x_k - z_new|| post-fold
    "loss_client":  (("client",), _LIST),
    "weight":       (("client",), _LIST),     # mean weight (partic+stale)
    "active":       (("client",), _LIST),     # 0/1 contributed this round
    "guard_ok":     (("client",), _LIST),     # guard verdicts (guard on)
    "quarantine":   (("client",), _LIST),     # rounds remaining
    "dropped":      (("client",), _LIST),     # fault tags this round
    "straggled":    (("client",), _LIST),
    "corrupted":    (("client",), _LIST),
    "staleness":    (("client",), _LIST),     # async: rounds stale
    "admitted":     (("client",), _LIST),     # async: admission outcome
    "members":      (("client",), _LIST),     # churn roster after tick
    "registry_ids": (("client",), _LIST),     # population: slot -> rid (v11)
    "payload_bytes": (("client",), _INT),     # uplink bytes/participant
    # soak-campaign schedule windows (schema v12; campaign/).  One per
    # window TRANSITION, right after the round record it rides with; no
    # time_unix — every field is a pure function of (campaign seed,
    # round_index), re-derived bit-exactly by control.replay from the
    # header config's campaign_spec.
    "virtual_seconds": (("campaign",), _NUM),  # round_index * round secs
    "arrival_frac": (("campaign",), _NUM),     # diurnal curve, [0, 1]
    "drop_p":       (("campaign",), _NUM),     # derived family probs
    "straggle_p":   (("campaign",), _NUM),
    "corrupt_p":    (("campaign",), _NUM),
    "join_p":       (("campaign",), _NUM),
    "leave_p":      (("campaign",), _NUM),
    "storm":        (("campaign",), _BOOL),    # seeded tag-73 event live
    "burst":        (("campaign",), _BOOL),    # seeded tag-79 event live
    "preempt_now":  (("campaign",), _BOOL),    # deterministic preempt_at
    "phase":        (("campaign",), _STR),     # trough|shoulder|peak|...
    # serving plane (schema v13; serve/).  Pure subset first (re-derived
    # by control.replay from the header serve_spec + round index), then
    # the advisory timing/eval telemetry; no time_unix on the record —
    # wall-clock facts ride ONLY in advisory fields.
    "weights_version": (("serve",), _INT),     # 1 + ridx // swap_every
    "requests":     (("serve",), _INT),        # seeded traffic draw (tag 83)
    "batches":      (("serve",), _INT),        # dispatched micro-batches
    "padded_slots": (("serve",), _INT),        # bucket slots left empty
    "padding_waste_frac": (("serve",), _NUM),  # padded / total slots
    "drift_injected": (("serve",), _BOOL),     # ridx >= drift_at
    "swap":         (("serve",), _BOOL),       # ridx % swap_every == 0
    "serve_p50_ms": (("serve",), _NUM),        # advisory from here down
    "serve_p99_ms": (("serve",), _NUM),
    "serve_qps":    (("serve",), _NUM),
    "swap_gap_seconds": (("serve",), _NUM),    # double-buffer publish gap
    "serve_accuracy": (("serve",), _NUM),      # eval-stream live accuracy
    "drift_score":  (("serve",), _NUM),        # 1 - acc/EMA, floored at 0
    "forced_refresh": (("serve",), _BOOL),     # control-plane republish
    # summary totals / rates
    "status":       (("summary",), _STR),
    "rounds":       (("summary",), _INT),
    "total_seconds": (("summary",), _NUM),
    "round_seconds_total": (("summary",), _NUM),
    "stage_seconds_total": (("summary",), _NUM),
    "comm_seconds_total": (("summary",), _NUM),
    "bytes_on_wire_total": (("summary",), _INT),
    "bytes_dense_total": (("summary",), _INT),
    "images_total": (("summary",), _INT),
    "guard_trips_total": (("summary",), _NUM),
    "fault_dropped_total": (("summary",), _INT),
    "fault_straggled_total": (("summary",), _INT),
    "fault_corrupted_total": (("summary",), _INT),
    "quarantined_last": (("summary",), _INT),
    "loss_first":   (("summary",), _NUM),
    "loss_final":   (("summary",), _NUM),
    "rounds_per_sec": (("summary",), _NUM),
    "images_per_sec": (("summary",), _NUM),
    "comm_overhead_frac": (("summary",), _NUM),
    "compression_savings_frac": (("summary",), _NUM),
    "alerts_total": (("summary",), _INT),
    "interventions_total": (("summary",), _INT),
    # device-cost + memory-watermark summary (schema v6)
    "compile_events_total": (("summary",), _INT),
    "compile_seconds_total": (("summary",), _NUM),
    "cache_hits_total": (("summary",), _INT),
    "cache_misses_total": (("summary",), _INT),
    "mem_peak_bytes_watermark": (("summary",), _INT),
    "mem_final_vs_peak_bytes": (("summary",), _INT),
}

REQUIRED = {
    "run_header": ("event", "schema", "run_id", "engine", "time_unix"),
    "round": ("event", "schema", "run_id", "round_index", "engine",
              "round_seconds"),
    "summary": ("event", "schema", "run_id", "status", "rounds"),
    "span": ("event", "schema", "run_id", "span_id", "name", "t_start",
             "t_end"),
    "alert": ("event", "schema", "run_id", "rule", "round_index"),
    "compile": ("event", "schema", "run_id", "site", "compile_seconds"),
    "control": ("event", "schema", "run_id", "round_index", "source",
                "intervention"),
    "client": ("event", "schema", "run_id", "round_index", "clients"),
    "campaign": ("event", "schema", "run_id", "round_index",
                 "virtual_seconds"),
    "serve": ("event", "schema", "run_id", "round_index",
              "weights_version", "requests"),
}

# ------------------------------------------------------------------- #
# Machine-readable determinism contract (graftcheck JG117-JG121).
#
# The contract pass (analysis/contracts.py) reads these tables via
# ast.literal_eval — it never imports this module — so every table below
# MUST stay a pure literal (no comprehensions, no function calls, no
# name references).  The lint selftest cross-checks the extracted values
# against the live module to keep the two views from drifting.

#: fields that are wall-clock / host-measured / model-dependent by
#: design and therefore exempt from the replay contract: they may be fed
#: by time.* or measurement state, and control/replay.py never compares
#: them.  Everything NOT in this tuple (or ENVELOPE_FIELDS) is a core
#: field: a pure function of (seed, config, round coordinates), and
#: JG117/JG119/JG121 flag any entropy, iteration-order or rogue-PRNG
#: taint flowing into it.  PARITY.md pins this list as part of the
#: v0.15 contract — additions need a schema-comment + PARITY note.
ADVISORY_FIELDS = (
    # wall-clock stamps + per-round host timings (v1..v7)
    "time_unix", "round_seconds", "stage_seconds", "train_seconds",
    "comm_seconds", "sync_seconds", "compute_seconds", "epoch_seconds",
    "ckpt_write_seconds", "overlap_seconds", "overlap_dispatch_seconds",
    "compile_seconds", "t_start", "t_end",
    # serving-plane latency/throughput telemetry (v13)
    "serve_p50_ms", "serve_p99_ms", "serve_qps", "swap_gap_seconds",
    "serve_accuracy", "drift_score", "forced_refresh",
    # summary wall-clock totals and derived rates
    "total_seconds", "round_seconds_total", "stage_seconds_total",
    "comm_seconds_total", "compile_seconds_total",
    "rounds_per_sec", "images_per_sec", "comm_overhead_frac",
    # bench artifact fields, declared here rather than silently
    # exempted: the capture timestamp and the relay's last error text
    # are operator-facing diagnostics, never replay-checked
    "captured_utc", "last_error",
)

#: run/record identity fields stamped by the recorder envelope — host
#: facts (pid, git rev, jax versions) and the uuid-derived span ids.
#: They identify *which* run produced a stream; replay compares streams
#: only within one run, so envelope fields are outside the taint rules.
ENVELOPE_FIELDS = (
    "event", "schema", "run_id", "run_name", "span_id", "parent_span",
    "engine", "algorithm", "host", "pid", "git_rev", "devices",
    "local_devices", "platform", "jax_version", "jaxlib_version",
    "resumed", "rounds_prior", "config", "mesh_shape",
)

#: out-of-band diagnostic emissions that look like records (they carry
#: an "event" key for grep-ability) but never enter a telemetry stream —
#: JG118's emit-coverage check allows them without a replay checker
DIAGNOSTIC_KINDS = ("sink_degraded",)

#: checkpoint-meta key namespaces reserved for one owner module (JG120):
#: a namespace ending in "_" is a prefix, anything else an exact key;
#: the owner tuple lists module-path suffixes allowed to write it
RESERVED_META_NAMESPACES = (
    ("pop_", ("population.registry",)),
    ("geom_", ("utils.checkpoint",)),
    ("members", ("utils.checkpoint",)),
)

#: the additive version history, machine-readable (the prose history
#: lives in the comment block above SCHEMA_VERSION).  JG118 asserts the
#: ladder is strictly increasing, carries no "removed_fields"/
#: "removed_kinds" entries (additive-only discipline), tops out at
#: SCHEMA_VERSION, and that every EVENTS kind was introduced by exactly
#: one rung and has a non-empty REQUIRED core.
VERSION_LADDER = (
    {"version": 1,
     "added_kinds": ("run_header", "round", "summary"),
     "added_fields": ()},
    {"version": 2, "added_kinds": (),
     "added_fields": ("jit_retraces",)},
    {"version": 3, "added_kinds": (),
     "added_fields": ("host_dispatches", "ckpt_write_seconds")},
    {"version": 4, "added_kinds": (),
     "added_fields": ("async_mode", "max_staleness", "async_arrived",
                      "admission_rejected", "buffer_depth",
                      "staleness_hist")},
    {"version": 5, "added_kinds": ("span", "alert"),
     "added_fields": ("span_id", "parent_span", "t_start", "t_end",
                      "alerts_total")},
    {"version": 6, "added_kinds": ("compile",),
     "added_fields": ("site", "compile_seconds", "trace_count", "flops",
                      "hlo_bytes_accessed", "transcendentals",
                      "cache_hit")},
    {"version": 7, "added_kinds": (),
     "added_fields": ("bytes_fused", "overlap_seconds")},
    {"version": 8, "added_kinds": ("control",),
     "added_fields": ("source", "intervention", "param", "from_value",
                      "to_value", "scope", "mode", "applied", "reason",
                      "attempt", "backoff_seconds", "ladder_stage",
                      "interventions_total")},
    {"version": 9, "added_kinds": (),
     "added_fields": ("members_active", "joined", "left")},
    {"version": 10, "added_kinds": ("client",),
     "added_fields": ("clients", "update_norm", "dist_z", "loss_client",
                      "weight", "active", "guard_ok", "quarantine",
                      "dropped", "straggled", "corrupted", "staleness",
                      "admitted", "members", "payload_bytes")},
    {"version": 11, "added_kinds": (),
     "added_fields": ("registry_ids",)},
    {"version": 12, "added_kinds": ("campaign",),
     "added_fields": ("virtual_seconds", "arrival_frac", "drop_p",
                      "straggle_p", "corrupt_p", "join_p", "leave_p",
                      "storm", "burst", "preempt_now", "phase")},
    {"version": 13, "added_kinds": ("serve",),
     "added_fields": ("weights_version", "requests", "batches",
                      "padded_slots", "padding_waste_frac",
                      "drift_injected", "swap", "serve_p50_ms",
                      "serve_p99_ms", "serve_qps", "swap_gap_seconds",
                      "serve_accuracy", "drift_score",
                      "forced_refresh")},
    {"version": 14, "added_kinds": (),
     "added_fields": ("overlap_dispatch_seconds",)},
)


def json_safe(obj):
    """Coerce ``obj`` into JSON-serialisable types.

    numpy arrays/scalars become lists/Python scalars, tuples become
    lists, dataclasses become dicts, anything else falls back to
    ``repr`` — so a config snapshot or an ``accuracy`` ndarray can ride
    in a record without the caller caring.
    """
    import numpy as np

    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, dict):
        return {str(k): json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set)):
        return [json_safe(v) for v in obj]
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return json_safe(dataclasses.asdict(obj))
    return repr(obj)


def _type_ok(value, types) -> bool:
    if types is _ANY or types is None:
        return True
    if isinstance(value, bool) and bool not in types:
        return False            # bool passes isinstance(int) checks
    if isinstance(value, types):
        return True
    # json round-trips ints inside float fields and vice versa
    if float in types and isinstance(value, int):
        return True
    return False


def validate_record(rec: Dict[str, Any]) -> Dict[str, Any]:
    """Validate one record against the schema; returns it unchanged.

    Raises :class:`SchemaError` on: non-dict input, unknown/missing
    ``event``, missing ``schema`` version or one newer than this reader,
    a missing required field, or a known field of the wrong type.
    Unknown fields pass (forward compatibility).
    """
    if not isinstance(rec, dict):
        raise SchemaError(f"record must be a dict, got {type(rec).__name__}")
    event = rec.get("event")
    if event not in EVENTS:
        raise SchemaError(f"unknown event {event!r}; expected one of {EVENTS}")
    ver = rec.get("schema")
    if not isinstance(ver, int) or isinstance(ver, bool) or ver < 1:
        raise SchemaError(f"bad schema version {ver!r}")
    if ver > SCHEMA_VERSION:
        raise SchemaError(
            f"record schema v{ver} is newer than this reader "
            f"(v{SCHEMA_VERSION})")
    for name in REQUIRED[event]:
        if rec.get(name) is None:
            raise SchemaError(f"{event} record missing required {name!r}")
    for name, value in rec.items():
        spec = FIELDS.get(name)
        if spec is None or value is None:
            continue                       # unknown field / JSON null: pass
        kinds, types = spec
        if event not in kinds:
            raise SchemaError(
                f"field {name!r} is not valid on a {event!r} record")
        if not _type_ok(value, types):
            raise SchemaError(
                f"field {name!r} on {event!r} has type "
                f"{type(value).__name__}, expected one of "
                f"{tuple(t.__name__ for t in types)}")
    return rec
