"""Pluggable event sinks for run telemetry.

A sink receives every schema-validated record (``run_header`` /
``round`` / ``summary``) from a :class:`~.recorder.RunRecorder`:

- :class:`JsonlSink`  — one JSON object per line, append mode (a
  resumed run extends the same file), flushed per record so a killed
  run keeps everything up to its last completed round.  A transient
  ``OSError`` on the per-record write is retried with bounded backoff;
  a persistently failing filesystem degrades the sink to an in-memory
  overflow buffer (one structured warning, the run keeps going —
  telemetry must never kill training).  ``close()`` makes one last
  attempt to land the overflow on disk.
- :class:`CsvSink`    — ``round`` records only; columns fixed by the
  first round record (later extra keys are dropped, missing keys blank)
  so the file stays loadable by anything that reads CSV.
- :class:`StdoutSink` — raw JSONL to stdout (pipe into ``obs.report``).
- :class:`MemorySink` — in-process list, for tests.

``make_sinks`` parses the ``--obs-sinks`` spec (comma-separated; see
``SINK_CHOICES``).  ``"auto"`` resolves to ``jsonl`` when an
``--obs-dir`` is set and to ``none`` otherwise, which is what makes
observability default-on for driver runs but file-free for bare
engine-API callers (unit tests).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import IO, List, Optional, Tuple

SINK_CHOICES = ("auto", "none", "jsonl", "csv", "stdout", "memory")


class Sink:
    """Interface: ``emit`` one validated record dict; ``close`` once."""

    def emit(self, record: dict) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class JsonlSink(Sink):
    #: per-record write attempts before the sink degrades; backoff is
    #: ``retry_backoff * 2**i`` between attempts (tiny — this guards
    #: against transient EAGAIN/ENOSPC blips, not outages)
    RETRIES = 3
    #: overflow cap: a degraded long run must not eat the heap; the
    #: newest records win because the tail is what post-mortems read
    OVERFLOW_CAP = 10_000

    def __init__(self, path: str, retry_backoff: float = 0.05,
                 sleep=time.sleep):
        self.path = path
        self.retry_backoff = float(retry_backoff)
        self._sleep = sleep
        self._f: Optional[IO[str]] = None
        self.degraded = False
        self.overflow: List[dict] = []
        self.dropped = 0

    def _write_line(self, line: str) -> None:
        if self._f is None:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            self._f = open(self.path, "a")
        self._f.write(line)
        self._f.flush()

    def _buffer(self, record: dict) -> None:
        if len(self.overflow) >= self.OVERFLOW_CAP:
            self.overflow.pop(0)
            self.dropped += 1
        self.overflow.append(record)

    def emit(self, record: dict) -> None:
        if self.degraded:
            self._buffer(record)
            return
        line = json.dumps(record) + "\n"
        last: Optional[OSError] = None
        for i in range(self.RETRIES):
            try:
                self._write_line(line)
                return
            except OSError as e:
                last = e
                # a failed write leaves the handle in an unknown state;
                # drop it so the retry reopens (append mode, no loss)
                try:
                    if self._f is not None:
                        self._f.close()
                except OSError:
                    pass
                self._f = None
                if i + 1 < self.RETRIES and self.retry_backoff > 0:
                    self._sleep(self.retry_backoff * (2.0 ** i))
        # persistent failure: degrade to the in-memory overflow buffer
        # with ONE structured warning — telemetry never kills the run
        self.degraded = True
        self._buffer(record)
        print(json.dumps({"event": "sink_degraded", "sink": "jsonl",
                          "path": self.path, "retries": self.RETRIES,
                          "error": str(last)}),
              file=sys.stderr, flush=True)

    def close(self) -> None:
        if self.degraded and self.overflow:
            # one last attempt: the filesystem may have come back
            try:
                self._write_line("".join(json.dumps(r) + "\n"
                                         for r in self.overflow))
                self.overflow = []
                self.degraded = False
            except OSError:
                pass
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None


class CsvSink(Sink):
    def __init__(self, path: str):
        self.path = path
        self._f: Optional[IO[str]] = None
        self._writer = None
        self._columns: Optional[List[str]] = None

    def emit(self, record: dict) -> None:
        import csv

        if record.get("event") != "round":
            return
        if self._f is None:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            # append mode like JsonlSink; a resumed run whose first new
            # record has the same shape just keeps extending the table
            new = not os.path.exists(self.path)
            self._f = open(self.path, "a", newline="")
            self._columns = list(record.keys())
            self._writer = csv.DictWriter(self._f, self._columns,
                                          extrasaction="ignore",
                                          restval="")
            if new:
                self._writer.writeheader()
        row = {k: record.get(k, "") for k in self._columns}
        # lists (e.g. accuracy) would explode the cell; keep them JSON
        row = {k: json.dumps(v) if isinstance(v, (list, dict)) else v
               for k, v in row.items()}
        self._writer.writerow(row)
        self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class StdoutSink(Sink):
    def emit(self, record: dict) -> None:
        print(json.dumps(record), flush=True)


class MemorySink(Sink):
    def __init__(self):
        self.records: List[dict] = []

    def emit(self, record: dict) -> None:
        self.records.append(record)


def make_sinks(spec: str, obs_dir: Optional[str] = None,
               run_name: str = "run") -> Tuple[List[Sink], Optional[str]]:
    """Build sinks from a comma-separated spec.

    Returns ``(sinks, jsonl_path)`` — the path is reported back so
    callers (bench.py) can record where the artifact went.  File sinks
    land in ``obs_dir`` (created on first write) as
    ``<run_name>.jsonl`` / ``<run_name>.csv``; requesting one without
    an ``obs_dir`` defaults to ``./obs``.
    """
    tokens = [t.strip() for t in (spec or "auto").split(",") if t.strip()]
    resolved: List[str] = []
    for t in tokens:
        if t not in SINK_CHOICES:
            raise ValueError(
                f"unknown obs sink {t!r}; expected one of {SINK_CHOICES}")
        if t == "auto":
            t = "jsonl" if obs_dir else "none"
        if t != "none" and t not in resolved:
            resolved.append(t)
    sinks: List[Sink] = []
    jsonl_path = None
    for t in resolved:
        if t in ("jsonl", "csv") and obs_dir is None:
            obs_dir = "obs"
        if t == "jsonl":
            jsonl_path = os.path.join(obs_dir, run_name + ".jsonl")
            sinks.append(JsonlSink(jsonl_path))
        elif t == "csv":
            sinks.append(CsvSink(os.path.join(obs_dir, run_name + ".csv")))
        elif t == "stdout":
            sinks.append(StdoutSink())
        elif t == "memory":
            sinks.append(MemorySink())
    return sinks, jsonl_path
