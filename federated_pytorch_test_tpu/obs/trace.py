"""Span timeline exporter: obs JSONL → Chrome trace-event JSON.

Schema v5 gives the run stream a span hierarchy::

    run (span record at close, id stamped on the run_header)
    └── round N        (the round record itself, when it carries t_start)
        ├── train / stage / comm / sync ...   (span records, cat="phase")
        ├── compile <site>   (schema v6 compile records: bubbles showing
        │                     where jit compiles landed inside the round;
        │                     out-of-window events parent to the RUN span)
        └── ...
    └── ckpt           (parented to the RUN span: the mid-run save runs
                        after round_seconds is measured, so hanging it
                        off the round would break laminar nesting)

``python -m federated_pytorch_test_tpu.obs.trace run.jsonl -o trace.json``
converts that into Chrome trace-event / Perfetto JSON (load in
``chrome://tracing`` or https://ui.perfetto.dev).  Round spans carry
``round_index`` in their args — the same index the XProf ``round_trace``
annotations use — so the host-side JSONL timeline and a device-side
XProf capture correlate round-for-round.

Timestamps: ``t_start``/``t_end`` are host ``time.perf_counter`` stamps.
A resumed run appends a new segment (new ``run_header``) whose
perf_counter base belongs to a DIFFERENT process, so segments are split
at headers — one trace pid per segment — and anchored to wall clock via
the headers' ``time_unix`` deltas.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

from federated_pytorch_test_tpu.obs.schema import SchemaError

_EPS_US = 1e-3   # float-roundoff tolerance for nesting checks (µs)


def _segments(records: List[Dict[str, Any]]) -> List[List[Dict[str, Any]]]:
    """Split a record stream at run_headers (resumed runs append)."""
    segs: List[List[Dict[str, Any]]] = []
    cur: List[Dict[str, Any]] = []
    for r in records:
        if r.get("event") == "run_header" and cur:
            segs.append(cur)
            cur = []
        cur.append(r)
    if cur:
        segs.append(cur)
    return segs


def _spans_in(seg: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Round records with timing + explicit span records, as one list."""
    out = []
    for r in seg:
        ev = r.get("event")
        t0, t1 = r.get("t_start"), r.get("t_end")
        if not (isinstance(t0, (int, float)) and isinstance(t1, (int, float))):
            continue
        if ev == "round":
            out.append({"span_id": r.get("span_id"),
                        "parent_span": r.get("parent_span"),
                        "name": f"round {r.get('round_index')}",
                        "cat": "round", "t_start": float(t0),
                        "t_end": float(t1),
                        "round_index": r.get("round_index"),
                        "loss": r.get("loss")})
        elif ev == "span":
            out.append({"span_id": r.get("span_id"),
                        "parent_span": r.get("parent_span"),
                        "name": r.get("name", "span"),
                        "cat": r.get("cat", "phase"),
                        "t_start": float(t0), "t_end": float(t1),
                        "round_index": r.get("round_index")})
        elif ev == "compile":
            # schema v6: compile events render as bubbles inside their
            # round (in-window) or directly under the run span (events
            # drained outside any round window, e.g. eval compiles)
            out.append({"span_id": r.get("span_id"),
                        "parent_span": r.get("parent_span"),
                        "name": f"compile {r.get('site', '?')}",
                        "cat": "compile",
                        "t_start": float(t0), "t_end": float(t1),
                        "round_index": r.get("round_index")})
    return out


def to_chrome_trace(records: List[Dict[str, Any]],
                    run_name: str = "run") -> Dict[str, Any]:
    """Build a Chrome trace-event JSON object from an obs record stream."""
    events: List[Dict[str, Any]] = []
    wall0: Optional[float] = None
    # supervisor-restart attempt per segment: the dying segment writes
    # the restart control record (with its 1-based `attempt`), so the
    # segment that FOLLOWS it is that attempt's run.  Tracked across the
    # whole stream so a segment's process name is stable no matter how
    # many empty segments the exporter skips.
    next_attempt: Optional[int] = None
    for pid, seg in enumerate(_segments(records), start=1):
        header = next((r for r in seg if r.get("event") == "run_header"), {})
        attempt = next_attempt
        for r in seg:
            if (r.get("event") == "control"
                    and r.get("intervention") == "restart"
                    and isinstance(r.get("attempt"), int)):
                next_attempt = r["attempt"]
        spans = _spans_in(seg)
        if not spans:
            continue
        # anchor this segment's perf_counter clock to wall time so
        # resumed segments land after the original instead of on top
        wall = header.get("time_unix")
        if wall0 is None and isinstance(wall, (int, float)):
            wall0 = float(wall)
        seg_t0 = min(s["t_start"] for s in spans)
        off_us = ((float(wall) - wall0) * 1e6
                  if isinstance(wall, (int, float)) and wall0 is not None
                  else 0.0)
        label = header.get("run_name") or run_name
        # stable human-readable process name: segment-<n> is the
        # position in the FULL stream (empty segments included, so
        # names never renumber when a segment gains its first span),
        # plus the supervisor restart attempt that produced it and
        # whether it resumed from a checkpoint
        seg_name = f"segment-{pid}"
        if isinstance(attempt, int):
            seg_name += f" restart-attempt-{attempt}"
        elif header.get("resumed"):
            seg_name += " resumed"
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0,
                       "args": {"name": f"{label} ({seg_name}, "
                                        f"run {header.get('run_id', '?')})"}})
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": 1, "args": {"name": "rounds"}})
        for s in spans:
            args: Dict[str, Any] = {"span_id": s["span_id"]}
            if s.get("parent_span"):
                args["parent_span"] = s["parent_span"]
            if s.get("round_index") is not None:
                args["round_index"] = s["round_index"]
            if s.get("loss") is not None:
                args["loss"] = s["loss"]
            events.append({
                "ph": "X", "name": s["name"], "cat": s["cat"],
                "pid": pid, "tid": 1,
                "ts": (s["t_start"] - seg_t0) * 1e6 + off_us,
                "dur": max(0.0, (s["t_end"] - s["t_start"]) * 1e6),
                "args": args,
            })
        # alerts become instant markers at their round's end
        by_round = {s["round_index"]: s for s in spans
                    if s["cat"] == "round"}
        for r in seg:
            if r.get("event") != "alert":
                continue
            anchor = by_round.get(r.get("round_index"))
            ts = ((anchor["t_end"] - seg_t0) * 1e6 + off_us
                  if anchor else off_us)
            events.append({"ph": "i", "name": f"alert:{r.get('rule')}",
                           "cat": "alert", "pid": pid, "tid": 1,
                           "ts": ts, "s": "p",
                           "args": {"rule": r.get("rule"),
                                    "severity": r.get("severity"),
                                    "message": r.get("message"),
                                    "round_index": r.get("round_index")}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def validate_chrome_trace(trace: Dict[str, Any]) -> None:
    """Well-formedness check: shape, laminar nesting, parent containment.

    Raises :class:`SchemaError` on the first violation.  "Laminar": on
    each (pid, tid) lane any two complete events are either disjoint or
    one contains the other — the invariant trace viewers assume when
    they stack slices.
    """
    if not isinstance(trace, dict) or "traceEvents" not in trace:
        raise SchemaError("trace must be a dict with 'traceEvents'")
    events = trace["traceEvents"]
    if not isinstance(events, list):
        raise SchemaError("traceEvents must be a list")
    lanes: Dict[Tuple[int, int], List[Tuple[float, float, str]]] = {}
    by_id: Dict[str, Tuple[float, float]] = {}
    xs = []
    for i, e in enumerate(events):
        if not isinstance(e, dict) or "ph" not in e:
            raise SchemaError(f"event {i}: not a trace event")
        if e["ph"] != "X":
            continue
        for k in ("name", "ts", "dur", "pid", "tid"):
            if k not in e:
                raise SchemaError(f"event {i} ({e.get('name')!r}): "
                                  f"missing {k!r}")
        if e["ts"] < 0 or e["dur"] < 0:
            raise SchemaError(f"event {i} ({e['name']!r}): negative ts/dur")
        lo, hi = float(e["ts"]), float(e["ts"]) + float(e["dur"])
        lanes.setdefault((e["pid"], e["tid"]), []).append((lo, hi, e["name"]))
        sid = (e.get("args") or {}).get("span_id")
        if sid:
            by_id[str(sid)] = (lo, hi)
        xs.append(e)
    for lane, ivals in lanes.items():
        # widest-first on ties so a parent sharing its child's start
        # time is on the stack before the child arrives
        ivals.sort(key=lambda t: (t[0], -t[1]))
        stack: List[Tuple[float, float, str]] = []
        for lo, hi, name in ivals:
            while stack and stack[-1][1] <= lo + _EPS_US:
                stack.pop()
            if stack and hi > stack[-1][1] + _EPS_US:
                raise SchemaError(
                    f"lane {lane}: {name!r} [{lo:.1f}, {hi:.1f}] "
                    f"straddles {stack[-1][2]!r} "
                    f"[{stack[-1][0]:.1f}, {stack[-1][1]:.1f}] "
                    f"(nesting not laminar)")
            stack.append((lo, hi, name))
    for e in xs:
        args = e.get("args") or {}
        parent = args.get("parent_span")
        if not parent or str(parent) not in by_id:
            continue
        plo, phi = by_id[str(parent)]
        lo, hi = float(e["ts"]), float(e["ts"]) + float(e["dur"])
        if lo < plo - _EPS_US or hi > phi + _EPS_US:
            raise SchemaError(
                f"span {e['name']!r} [{lo:.1f}, {hi:.1f}] escapes its "
                f"parent {parent} [{plo:.1f}, {phi:.1f}]")


def export(path: str, out_path: str, validate: bool = True) -> int:
    """Read a run JSONL, write Chrome trace JSON; returns #X events."""
    from federated_pytorch_test_tpu.obs.report import read_records

    records = read_records(path)
    trace = to_chrome_trace(records)
    if validate:
        validate_chrome_trace(trace)
    with open(out_path, "w") as f:
        json.dump(trace, f)
    return sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")


def selftest() -> None:
    """Recorder → JSONL → exporter round-trip on a resumed two-segment
    file; used by ``report --selftest``."""
    import os
    import tempfile

    from federated_pytorch_test_tpu.obs.recorder import make_recorder

    with tempfile.TemporaryDirectory() as d:
        for seg in range(2):                      # second open() resumes
            rec = make_recorder("jsonl", d, run_name="trace_selftest",
                                engine="selftest")
            rec.open(resumed=seg > 0, rounds_prior=2 * seg)
            for i in range(2 * seg, 2 * seg + 2):
                t0 = 10.0 * seg + float(i)
                rid = f"r{i:04d}aaaaaaaa"
                rec.round({"round_index": i, "round_seconds": 0.8,
                           "loss": 1.0, "t_start": t0, "span_id": rid})
                rec.span("train", t0 + 0.01, t0 + 0.6, cat="phase",
                         round_index=i, parent_span=rid)
                rec.span("comm", t0 + 0.6, t0 + 0.75, cat="comm",
                         round_index=i, parent_span=rid)
            rec.close()
        src = os.path.join(d, "trace_selftest.jsonl")
        out = os.path.join(d, "trace.json")
        n = export(src, out)
        assert n == 14, f"expected 14 X events (2 segments), got {n}"
        with open(out) as f:
            trace = json.load(f)
        validate_chrome_trace(trace)
        rounds = [e for e in trace["traceEvents"] if e.get("ph") == "X"
                  and e.get("cat") == "round"]
        assert sorted(e["args"]["round_index"] for e in rounds) == [0, 1, 2, 3]
        pids = {e["pid"] for e in rounds}
        assert len(pids) == 2, f"resumed run must split segments: {pids}"
        names = {e["pid"]: e["args"]["name"]
                 for e in trace["traceEvents"]
                 if e.get("ph") == "M" and e.get("name") == "process_name"}
        assert "segment-1" in names[1], names
        assert "segment-2 resumed" in names[2], names


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m federated_pytorch_test_tpu.obs.trace",
        description="Export an obs run JSONL to Chrome trace-event JSON "
                    "(chrome://tracing / Perfetto)")
    p.add_argument("path", help="run JSONL file")
    p.add_argument("-o", "--output", help="output .json path "
                   "(default: <input>.trace.json)")
    p.add_argument("--no-validate", action="store_true",
                   help="skip the nesting/containment validation pass")
    args = p.parse_args(argv)
    out = args.output or (args.path + ".trace.json")
    try:
        n = export(args.path, out, validate=not args.no_validate)
    except (OSError, SchemaError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(f"wrote {out}: {n} span event(s)")
    if n == 0:
        print("note: no spans found — the run predates schema v5 or ran "
              "with spans disabled", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
