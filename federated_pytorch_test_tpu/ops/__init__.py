"""TPU kernel ops (Pallas).

Hand-written Pallas kernels for the framework's hot ops, with XLA fallbacks
so every op runs identically on CPU/interpret mode.  Currently:

  * :func:`info_nce_fused` — fused InfoNCE (CPC contrastive loss): Gram
    matmul + normalisation + online log-softmax + diagonal gather in one
    VMEM-resident kernel.
"""

from federated_pytorch_test_tpu.ops.infonce import (  # noqa: F401
    force_infonce_impl,
    info_nce_fused,
)

__all__ = ["info_nce_fused", "force_infonce_impl"]
