"""Comm-path kernel suite: fused quantize / dequantize-accumulate /
Gram-distance Pallas TPU kernels (ISSUE 20 tentpole).

PR 11 left the packed-collective hot path (ops/packed_reduce.py) as XLA
fusions plus one experimental single-block quantize kernel gated behind
``FEDTPU_FUSED_PALLAS=1``.  This module promotes that experiment into a
first-class suite with the ``ops/infonce.py`` dispatch contract:

- :func:`quantize_chunks` — ONE kernel computes the per-chunk max-abs
  scale AND the round-to-nearest int8 quantization in a single VMEM
  residency.  The old experiment read ``vv`` twice from HBM (XLA max
  reduce, then the divide/round/clip kernel); here each row tile is
  loaded once.
- :func:`dequant_add` — the reduce-scatter hop's ``acc + decode(q, s)``
  (the "partial reduce" of the fused transport): dequantize and
  accumulate without materializing the dense decoded buffer in HBM
  between two XLA fusions.
- :func:`gram_matrix` — the krum distance pass's ``A @ A.T`` streamed
  over column chunks: each grid step loads one ``[K, CHUNK]`` slab and
  accumulates the ``[K, K]`` Gram block in VMEM, so the full activation
  row never needs to be co-resident with the output
  (parallel/comm.py robust_federated_mean_chunked).

Dispatch (:func:`force_comm_kernels_impl`): ``None`` = auto (Pallas on
TPU when the working set fits VMEM, XLA elsewhere); tests force
``"pallas_interpret"`` to run the kernels on CPU.  The XLA paths are the
LITERAL pre-suite jnp chains and stay the tolerance reference:

- quantize/dequant: interpret mode is bit-identical to XLA (same f32
  ops in the same order); on real TPU hardware the max reduce may
  re-associate — PARITY.md carries the allclose contract.
- gram: the chunked accumulation re-associates the contraction, so
  Pallas (either mode) is allclose to the one-shot XLA matmul, not
  bitwise (documented in PARITY.md).
"""

from __future__ import annotations

import contextlib
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_LANES = 128                 # f32/int8 lane width
_ROW_TILE = 32               # int8 sublane multiple (covers f32's 8)
_GRAM_CHUNK = 512            # contraction slab per grid step
_VMEM_BUDGET = 12 * 2**20    # headroom under the ~16 MB/core VMEM

# None = auto (TPU -> pallas, else XLA); "xla" | "pallas" | "pallas_interpret"
_FORCE_IMPL = None


@contextlib.contextmanager
def force_comm_kernels_impl(impl: str):
    """Force the comm-kernel implementation ("xla" | "pallas" |
    "pallas_interpret") — tests run the kernels on CPU via interpret
    mode, exactly the ``ops/infonce.py`` contract."""
    global _FORCE_IMPL
    prev, _FORCE_IMPL = _FORCE_IMPL, impl
    try:
        yield
    finally:
        _FORCE_IMPL = prev


def _resolve_impl(fits: bool) -> str:
    """"xla" | "pallas" | "pallas_interpret" for this call site; a
    forced impl (tests, benches) wins unconditionally."""
    impl = _FORCE_IMPL
    if impl is None:
        return "pallas" if (jax.default_backend() == "tpu" and fits) else "xla"
    return impl


def _pad2(a, rows: int, cols: int):
    return jnp.pad(a, ((0, rows - a.shape[0]), (0, cols - a.shape[1])))


# ----------------------------------------------------------------------
# fused quantize: per-chunk max-abs scale + round/clip in one residency
# ----------------------------------------------------------------------
def _quantize_xla(vv, qmax: int):
    """The literal pack_chunks math (ops/packed_reduce.py) — the
    reference path and the interpret-parity oracle."""
    scale = jnp.max(jnp.abs(vv), axis=1) / qmax
    safe = jnp.where(scale > 0, scale, 1.0).astype(vv.dtype)
    q = jnp.clip(jnp.round(vv / safe[:, None]), -qmax, qmax).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def _quantize_kernel(qmax: int, cols: int, v_ref, q_ref, s_ref):
    """One ``[R, C_pad]`` row tile: scale, quantize, emit both.

    ``cols`` (static) is the true chunk width; pad columns hold zeros,
    which can never raise the max-|.| (magnitudes are >= 0), and their
    quantized value is 0 — the caller slices them off."""
    v = v_ref[...]                                     # [R, C_pad] f32
    scale = jnp.max(jnp.abs(v), axis=1) / qmax         # [R]
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(v / safe[:, None]), -qmax, qmax)
    q_ref[...] = q.astype(jnp.int8)
    # lane-replicated scale row: a [R, 1] output block would fall below
    # the f32 tile floor on hardware; 128 copies cost nothing next to
    # the payload and the caller reads lane 0
    s_ref[...] = jnp.broadcast_to(scale[:, None], (v.shape[0], _LANES))
    del cols


def _quantize_fits(rows: int, cols: int) -> bool:
    # v tile f32 + q tile int8 + scale lanes, per program
    per_program = 4 * _ROW_TILE * cols + _ROW_TILE * cols \
        + 4 * _ROW_TILE * _LANES
    del rows
    return per_program <= _VMEM_BUDGET


def _quantize_pallas(vv, qmax: int, interpret: bool = False):
    c, w = vv.shape
    c_pad = pl.cdiv(c, _ROW_TILE) * _ROW_TILE
    w_pad = pl.cdiv(w, _LANES) * _LANES
    q, s = pl.pallas_call(
        functools.partial(_quantize_kernel, qmax, w),
        grid=(c_pad // _ROW_TILE,),
        in_specs=[pl.BlockSpec((_ROW_TILE, w_pad), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((_ROW_TILE, w_pad), lambda i: (i, 0)),
            pl.BlockSpec((_ROW_TILE, _LANES), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((c_pad, w_pad), jnp.int8),
            jax.ShapeDtypeStruct((c_pad, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(_pad2(vv, c_pad, w_pad))
    return q[:c, :w], s[:c, 0]


def quantize_chunks(vv, qmax: int):
    """``(q, scale)`` of the ``[c, chunk]`` row matrix: per-row
    ``scale = max|row| / qmax`` and round-to-nearest
    ``q = clip(round(row / safe), ±qmax)`` int8 — the deterministic
    transport codec of ops/packed_reduce.py, fused."""
    impl = _resolve_impl(_quantize_fits(*vv.shape))
    if impl == "xla":
        return _quantize_xla(vv, qmax)
    return _quantize_pallas(vv, qmax, interpret=impl == "pallas_interpret")


# ----------------------------------------------------------------------
# fused dequantize + accumulate: the reduce-scatter hop's partial reduce
# ----------------------------------------------------------------------
def _dequant_add_xla(acc, q, scale):
    """Literal hop math: ``acc + q * safe`` (ops/packed_reduce.py
    unpack_chunks followed by the add), the parity oracle."""
    safe = jnp.where(scale > 0, scale, 1.0)
    return acc + q.astype(jnp.float32) * safe[:, None]


def _dequant_add_kernel(a_ref, q_ref, s_ref, o_ref):
    safe_row = s_ref[:, 0]                             # lane-replicated in
    safe = jnp.where(safe_row > 0, safe_row, 1.0)
    o_ref[...] = a_ref[...] + q_ref[...].astype(jnp.float32) * safe[:, None]


def _dequant_fits(rows: int, cols: int) -> bool:
    # acc + out f32, q int8, scale lanes, per program
    per_program = 2 * 4 * _ROW_TILE * cols + _ROW_TILE * cols \
        + 4 * _ROW_TILE * _LANES
    del rows
    return per_program <= _VMEM_BUDGET


def _dequant_add_pallas(acc, q, scale, interpret: bool = False):
    c, w = acc.shape
    c_pad = pl.cdiv(c, _ROW_TILE) * _ROW_TILE
    w_pad = pl.cdiv(w, _LANES) * _LANES
    s_lanes = jnp.broadcast_to(
        jnp.pad(scale, (0, c_pad - c))[:, None], (c_pad, _LANES))
    out = pl.pallas_call(
        _dequant_add_kernel,
        grid=(c_pad // _ROW_TILE,),
        in_specs=[
            pl.BlockSpec((_ROW_TILE, w_pad), lambda i: (i, 0)),
            pl.BlockSpec((_ROW_TILE, w_pad), lambda i: (i, 0)),
            pl.BlockSpec((_ROW_TILE, _LANES), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((_ROW_TILE, w_pad), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((c_pad, w_pad), jnp.float32),
        interpret=interpret,
    )(_pad2(acc, c_pad, w_pad), _pad2(q, c_pad, w_pad), s_lanes)
    return out[:c, :w]


def dequant_add(acc, q, scale):
    """``acc + dequantize(q, scale)`` for ``[c, chunk]`` rows — the
    packed reduce-scatter hop's accumulate, without an HBM round-trip
    for the decoded buffer.  ``q`` is int8 rows (q4 payloads are
    nibble-unfolded by the caller; the fold is a pure byte shuffle XLA
    keeps inside the surrounding fusion either way)."""
    impl = _resolve_impl(_dequant_fits(*acc.shape))
    if impl == "xla":
        return _dequant_add_xla(acc, q, scale)
    return _dequant_add_pallas(acc, q, scale,
                               interpret=impl == "pallas_interpret")


# ----------------------------------------------------------------------
# chunk-streamed Gram matrix: the krum distance pass
# ----------------------------------------------------------------------
def _gram_xla(a):
    """One-shot ``A @ A.T`` — the dense reference (and the tolerance
    oracle: the chunked kernel re-associates the contraction)."""
    return a @ a.T


def _gram_kernel(a_ref, g_ref):
    """Accumulate one ``[K_pad, CHUNK]`` slab's Gram contribution.

    The TPU grid runs sequentially, so the output block accumulates
    across steps (``ops/infonce.py`` ``_grad_kernel`` pattern); pad
    rows/columns are zeros and contribute exactly nothing."""
    j = pl.program_id(0)
    a = a_ref[...]
    g = lax.dot_general(a, a, dimension_numbers=(((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)

    @pl.when(j == 0)
    def _init():
        g_ref[...] = g

    @pl.when(j > 0)
    def _acc():
        g_ref[...] += g


def _gram_fits(k_pad: int) -> bool:
    per_program = 4 * (k_pad * _GRAM_CHUNK + k_pad * k_pad)
    return per_program <= _VMEM_BUDGET


def _gram_pallas(a, interpret: bool = False):
    k, n = a.shape
    # K rides both sublanes and lanes of the [K_pad, K_pad] output:
    # pad to the lane width once, K is small (the client count)
    k_pad = pl.cdiv(k, _LANES) * _LANES
    n_pad = pl.cdiv(n, _GRAM_CHUNK) * _GRAM_CHUNK
    g = pl.pallas_call(
        _gram_kernel,
        grid=(n_pad // _GRAM_CHUNK,),
        in_specs=[pl.BlockSpec((k_pad, _GRAM_CHUNK), lambda j: (0, j))],
        out_specs=pl.BlockSpec((k_pad, k_pad), lambda j: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((k_pad, k_pad), jnp.float32),
        interpret=interpret,
    )(_pad2(a, k_pad, n_pad))
    return g[:k, :k]


def gram_matrix(a):
    """``A @ A.T`` of a ``[K, n]`` client stack, streamed over column
    chunks on TPU so only one ``[K, CHUNK]`` slab is VMEM-resident per
    grid step.  Chunked accumulation re-associates the contraction:
    Pallas output is allclose to the XLA matmul, not bitwise
    (PARITY.md)."""
    k = a.shape[0]
    impl = _resolve_impl(_gram_fits(pl.cdiv(k, _LANES) * _LANES))
    if impl == "xla":
        return _gram_xla(a)
    return _gram_pallas(a, interpret=impl == "pallas_interpret")
