"""Tap-gather lowering for small-input dilated convolutions.

The CPC encoder's stem (reference simple_models.py:441-460) runs five
parallel 4x4 convs with kernel dilation up to 16 on a 32x32 patch.  At
dilation 16 the effective receptive span is 1 + 3*16 = 49 px — wider
than the input — so XLA:TPU's conv lowering (space-to-batch style) pads
the operand far beyond its payload and has been observed to compile
pathologically inside the jitted CPC round at reference width
(README.md "Known issues").

For these shapes the convolution is cheaper to state directly as im2col:
the k*k dilated taps of the (padded) input are strided slices, and the
conv is ONE [B*Oh*Ow, k*k*Ci] x [k*k*Ci, Co] matmul — a shape the MXU
handles natively with nothing for the compiler to get clever about.
This module provides

  * :func:`dilated_conv_taps` — functional NHWC conv, numerically
    equivalent to ``lax.conv_general_dilated`` with ``rhs_dilation``
    (same accumulation order per output element, f32);
  * :class:`TapConv` — a flax module exposing the SAME param tree as
    ``nn.Conv`` (``kernel`` [kh,kw,ci,co], ``bias`` [co]) so swapping it
    into a model changes neither checkpoints nor the flat codec.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp


def dilated_conv_taps(x: jnp.ndarray, kernel: jnp.ndarray,
                      bias: Optional[jnp.ndarray] = None, *,
                      strides: Tuple[int, int] = (1, 1),
                      dilation: Tuple[int, int] = (1, 1),
                      padding: Sequence[Tuple[int, int]] = ((0, 0), (0, 0)),
                      ) -> jnp.ndarray:
    """NHWC convolution with kernel (rhs) dilation via tap gather + matmul.

    Equivalent to ``lax.conv_general_dilated(x, kernel,
    window_strides=strides, padding=padding, rhs_dilation=dilation)``
    with NHWC/HWIO/NHWC dimension numbers.

    x: [B, H, W, Ci]; kernel: [kh, kw, Ci, Co]; bias: [Co] or None.
    """
    kh, kw, ci, co = kernel.shape
    (pt, pb), (pl, pr) = padding
    sh, sw = strides
    dh, dw = dilation
    xp = jnp.pad(x, ((0, 0), (pt, pb), (pl, pr), (0, 0)))
    span_h = (kh - 1) * dh + 1
    span_w = (kw - 1) * dw + 1
    oh = (xp.shape[1] - span_h) // sh + 1
    ow = (xp.shape[2] - span_w) // sw + 1
    # taps in (ky, kx) row-major order to match kernel.reshape's
    # (kh, kw, ci) row-major flattening
    taps = [
        xp[:, ky * dh: ky * dh + sh * (oh - 1) + 1: sh,
           kx * dw: kx * dw + sw * (ow - 1) + 1: sw, :]
        for ky in range(kh) for kx in range(kw)
    ]
    xcol = jnp.concatenate(taps, axis=-1)          # [B, oh, ow, kh*kw*ci]
    w = kernel.reshape(kh * kw * ci, co)
    y = jnp.einsum("bhwc,cf->bhwf", xcol, w,
                   preferred_element_type=x.dtype)
    if bias is not None:
        y = y + bias
    return y


class TapConv(nn.Module):
    """Drop-in for ``nn.Conv`` (NHWC, explicit padding) lowered via
    :func:`dilated_conv_taps`.  Param tree matches ``nn.Conv`` exactly:
    ``kernel`` [kh, kw, Ci, features] (lecun_normal), ``bias``
    [features] (zeros, present iff ``use_bias``)."""

    features: int
    kernel_size: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    kernel_dilation: Tuple[int, int] = (1, 1)
    padding: Sequence[Tuple[int, int]] = ((0, 0), (0, 0))
    use_bias: bool = True
    #: mirror nn.Conv's mixed-precision knobs: params are STORED in
    #: ``param_dtype`` and compute runs in ``dtype`` (None = promote to
    #: the operands' common dtype) — without these a bf16 model reusing
    #: TapConv would silently accumulate in a different precision than
    #: its nn.Conv layers (ADVICE.md item 1)
    dtype: Optional[jnp.dtype] = None
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        kh, kw = self.kernel_size
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (kh, kw, x.shape[-1], self.features), self.param_dtype)
        bias = (self.param("bias", nn.initializers.zeros,
                           (self.features,), self.param_dtype)
                if self.use_bias else None)
        # nn.Conv semantics: dtype=None promotes operands to a common
        # dtype rather than downcasting params to x.dtype
        x, kernel, bias = nn.dtypes.promote_dtype(x, kernel, bias,
                                                  dtype=self.dtype)
        return dilated_conv_taps(
            x, kernel, bias,
            strides=self.strides, dilation=self.kernel_dilation,
            padding=self.padding)
