"""Fused InfoNCE (CPC contrastive loss) as a Pallas TPU kernel.

The reference computes the (P x P) normalised inner-product matrix with
nested Python loops (federated_cpc.py:149-180); the framework's XLA path
(ops/infonce_core.py) is one matmul + log-softmax.  This module fuses the
whole per-row pipeline into ONE kernel so the score matrix never leaves
VMEM:

    scores_tile = (Z_tile^T @ Zhat) / (||Z_tile|| ||Zhat||)   (MXU)
    log_p_row   = diag(scores) - logsumexp_row(scores)        (VPU)

i.e. column norms, the Gram matmul, the numerically-stable row softmax
and the positive-pair (diagonal) gather all happen in one VMEM residency
— the [P, P] matrix is never materialised in HBM.  The grid tiles rows of
the score matrix (T=128 = MXU edge); each program reads its [D, T] column
slab of Z plus the full [D, P] Zhat.

Gradients: the op carries a ``jax.custom_vjp`` with a hand-derived
backward built from the saved ``log_p`` residual (one matmul to rebuild
the score matrix — unavoidable, the softmax Jacobian needs it — but no
forward re-run and no logsumexp recompute), so the kernel drops into the
CPC training closure (LBFGS re-evaluates value_and_grad inside
``lax.while_loop``) with no tracing restrictions and no extra forward.
The backward is ALSO a Pallas kernel (``_grad_kernel``): the training
path calls ``value_and_grad`` on every LBFGS closure evaluation, so the
backward dominates wall-clock — it rebuilds each [T, P] score-matrix
row tile in VMEM, forms the softmax-Jacobian product there, and writes
only the [D, P] gradients to HBM (the XLA backward materialises several
P x P intermediates).  The dZhat term needs a sum over row tiles; the
kernel accumulates it across the sequential TPU grid.

Dispatch: the Pallas path runs when the default backend is TPU and the
working set fits the VMEM budget; otherwise the XLA path runs (identical
result).  Tests exercise the kernel on CPU via ``interpret=True``
(:func:`force_infonce_impl`).
"""

from __future__ import annotations

import contextlib
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from federated_pytorch_test_tpu.ops.infonce_core import (
    flat_patch_matrix,
    log_p_flat,
    safe_norms,
)

_TILE = 128                 # row tile = MXU edge
_SUBLANE = 8                # float32 sublane multiple
_VMEM_BUDGET = 12 * 2**20   # leave headroom under the ~16 MB/core VMEM

# None = auto (TPU -> pallas, else XLA); "xla" | "pallas" | "pallas_interpret"
_FORCE_IMPL = None


@contextlib.contextmanager
def force_infonce_impl(impl: str):
    """Force the InfoNCE implementation ("xla" | "pallas" |
    "pallas_interpret") — tests run the kernel on CPU via interpret mode."""
    global _FORCE_IMPL
    prev, _FORCE_IMPL = _FORCE_IMPL, impl
    try:
        yield
    finally:
        _FORCE_IMPL = prev


def _loss_from_log_p(log_p: jnp.ndarray) -> jnp.ndarray:
    """-sum log(softmax_diag + 1e-6) — the reference adds 1e-6 inside the
    log (federated_cpc.py:178)."""
    return -jnp.sum(jnp.log(jnp.exp(log_p) + 1e-6))


def _log_p_kernel(P: int, z_ref, zhat_ref, out_ref):
    """One [T, P_pad] row-tile of the score matrix, reduced to log_p [T].

    ``P`` (static) is the true column count; pad columns are masked to
    -inf before the row logsumexp.  Pad columns have zero norm, so the
    divisor is made pad-safe (the masked scores never contribute).
    """
    i = pl.program_id(0)
    a = z_ref[:, :]          # [D_pad, T]   this tile's columns of Z
    zh = zhat_ref[:, :]      # [D_pad, P_pad]
    zn = jnp.sqrt(jnp.sum(a * a, axis=0))       # [T]
    zhn = jnp.sqrt(jnp.sum(zh * zh, axis=0))    # [P_pad]
    zn = jnp.where(zn == 0.0, 1.0, zn)
    zhn = jnp.where(zhn == 0.0, 1.0, zhn)
    # contract over D without an explicit transpose: [T, P_pad] on the MXU
    zz = jax.lax.dot_general(
        a, zh, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) / (zn[:, None] * zhn[None, :])

    t = zz.shape[0]
    col = jax.lax.broadcasted_iota(jnp.int32, (t, zz.shape[1]), 1)
    row = jax.lax.broadcasted_iota(jnp.int32, (t, zz.shape[1]), 0) + i * t
    valid = col < P
    zzm = jnp.where(valid, zz, -jnp.inf)
    m = jnp.max(zzm, axis=1, keepdims=True)            # [T, 1]
    lse = m[:, 0] + jnp.log(jnp.sum(jnp.exp(zzm - m), axis=1))
    diag = jnp.sum(jnp.where(col == row, zz, 0.0), axis=1)
    out_ref[0, :] = diag - lse


def _padded_dims(D: int, P: int) -> tuple:
    """(D_pad, P_pad): D to the f32 sublane multiple, P to the row tile."""
    return pl.cdiv(D, _SUBLANE) * _SUBLANE, pl.cdiv(P, _TILE) * _TILE


def _pallas_fits(D_pad: int, P_pad: int) -> bool:
    per_program = 4 * (D_pad * (_TILE + P_pad) + _TILE * P_pad)
    return per_program <= _VMEM_BUDGET


def _pallas_bwd_fits(D_pad: int, P_pad: int) -> bool:
    """VMEM estimate for ``_grad_kernel``: Z tile + dZ tile [D, T] each,
    Zhat + dZhat accumulator + dZhat partial [D, P] each, and ~4 [T, P]
    score-sized temporaries (zz, s, G, Gn)."""
    per_program = 4 * (2 * D_pad * _TILE + 3 * D_pad * P_pad
                       + 4 * _TILE * P_pad)
    return per_program <= _VMEM_BUDGET


def _log_p_pallas(Z: jnp.ndarray, Zhat: jnp.ndarray,
                  interpret: bool = False) -> jnp.ndarray:
    D, P = Z.shape
    D_pad, P_pad = _padded_dims(D, P)
    Zp = jnp.pad(Z, ((0, D_pad - D), (0, P_pad - P)))
    Zhp = jnp.pad(Zhat, ((0, D_pad - D), (0, P_pad - P)))
    out = pl.pallas_call(
        functools.partial(_log_p_kernel, P),
        grid=(P_pad // _TILE,),
        in_specs=[
            pl.BlockSpec((D_pad, _TILE), lambda i: (0, i)),
            pl.BlockSpec((D_pad, P_pad), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, _TILE), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, P_pad), jnp.float32),
        interpret=interpret,
    )(Zp, Zhp)
    return out[0, :P]


def _dispatch_log_p(Z: jnp.ndarray, Zhat: jnp.ndarray) -> jnp.ndarray:
    impl = _resolve_impl(_pallas_fits(*_padded_dims(*Z.shape)))
    if impl == "xla":
        return log_p_flat(Z, Zhat)          # shared core, ops/infonce_core.py
    return _log_p_pallas(Z, Zhat, interpret=impl == "pallas_interpret")


def _resolve_impl(fits: bool) -> str:
    """"xla" | "pallas" | "pallas_interpret" for this call site.

    ``fits`` is the caller's VMEM estimate; forward and backward have
    different working sets, so under auto dispatch a shape can run the
    fused forward while its backward falls back to XLA (results agree
    either way).  A forced impl (tests, benches) wins unconditionally.
    """
    impl = _FORCE_IMPL
    if impl is None:
        return "pallas" if (jax.default_backend() == "tpu" and fits) else "xla"
    return impl


@jax.custom_vjp
def _fused_flat(Z: jnp.ndarray, Zhat: jnp.ndarray) -> jnp.ndarray:
    return _loss_from_log_p(_dispatch_log_p(Z, Zhat))


def _fused_flat_fwd(Z, Zhat):
    log_p = _dispatch_log_p(Z, Zhat)
    return _loss_from_log_p(log_p), (Z, Zhat, log_p)


def _grads_xla(Z, Zhat, log_p, ghat):
    """XLA backward (the fallback path of ``_dispatch_grads``)."""
    # same zero-norm guard as every forward path (infonce_core.safe_norms):
    # a guarded column has zz ≡ 0, so the norm-path terms (dzn/dzhn)
    # vanish and only the finite numerator path contributes — no NaNs
    zn = safe_norms(Z)
    zhn = safe_norms(Zhat)
    denom = zn[:, None] * zhn[None, :]
    zz = (Z.T @ Zhat) / denom
    lse = jnp.diag(zz) - log_p
    s = jnp.exp(zz - lse[:, None])                    # softmax rows
    G = ghat[:, None] * (jnp.eye(zz.shape[0], dtype=zz.dtype) - s)
    Gn = G / denom
    dzn = -jnp.sum(G * zz, axis=1) / zn
    dzhn = -jnp.sum(G * zz, axis=0) / zhn
    dZ = Zhat @ Gn.T + Z * (dzn / zn)[None, :]
    dZhat = Z @ Gn + Zhat * (dzhn / zhn)[None, :]
    return dZ, dZhat


def _grad_kernel(P: int, z_ref, zhat_ref, logp_ref, ghat_ref,
                 dz_ref, dzhat_ref):
    """One [T, P_pad] row tile of the backward: rebuild the tile's scores,
    form the softmax-Jacobian product G in VMEM, and emit this tile's
    [D_pad, T] slab of dZ plus its additive contribution to dZhat.

    dZhat needs a sum over ALL row tiles (column reduction of G); the TPU
    grid runs sequentially, so the kernel accumulates into ``dzhat_ref``
    (initialised by the first program).  Pad rows are inert by
    construction: their ghat is staged as 0, so their G row vanishes; pad
    columns are masked out of the softmax like the forward.
    """
    i = pl.program_id(0)
    a = z_ref[:, :]            # [D_pad, T]   this tile's columns of Z
    zh = zhat_ref[:, :]        # [D_pad, P_pad]
    logp = logp_ref[0, :]      # [T]
    ghat = ghat_ref[0, :]      # [T]          0 on pad rows
    zn = jnp.sqrt(jnp.sum(a * a, axis=0))       # [T]
    zhn = jnp.sqrt(jnp.sum(zh * zh, axis=0))    # [P_pad]
    zn = jnp.where(zn == 0.0, 1.0, zn)          # infonce_core.safe_norms
    zhn = jnp.where(zhn == 0.0, 1.0, zhn)
    denom = zn[:, None] * zhn[None, :]
    zz = jax.lax.dot_general(
        a, zh, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) / denom                                   # [T, P_pad]

    t = zz.shape[0]
    col = jax.lax.broadcasted_iota(jnp.int32, zz.shape, 1)
    row = jax.lax.broadcasted_iota(jnp.int32, zz.shape, 0) + i * t
    on_diag = col == row
    diag = jnp.sum(jnp.where(on_diag, zz, 0.0), axis=1)      # [T]
    lse = diag - logp                           # forward residual identity
    # pad rows: zz ≡ 0 (zero Z column, guarded norm) and logp staged 0, so
    # lse = 0 and s stays bounded — no inf/NaN can leak into the masked G
    s = jnp.where(col < P, jnp.exp(zz - lse[:, None]), 0.0)
    G = ghat[:, None] * (jnp.where(on_diag, 1.0, 0.0) - s)   # [T, P_pad]
    Gn = G / denom
    dzn = -jnp.sum(G * zz, axis=1) / (zn * zn)               # [T]
    dz_ref[:, :] = jax.lax.dot_general(
        zh, Gn, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + a * dzn[None, :]
    part = jax.lax.dot_general(
        a, Gn, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + zh * (-jnp.sum(G * zz, axis=0) / (zhn * zhn))[None, :]

    @pl.when(i == 0)
    def _init():
        dzhat_ref[:, :] = part

    @pl.when(i > 0)
    def _acc():
        dzhat_ref[:, :] += part


def _grads_pallas(Z, Zhat, log_p, ghat, interpret: bool = False):
    D, P = Z.shape
    D_pad, P_pad = _padded_dims(D, P)
    pad2 = lambda m: jnp.pad(m, ((0, D_pad - D), (0, P_pad - P)))
    pad_row = lambda v: jnp.pad(v, (0, P_pad - P))[None, :]
    dZ, dZhat = pl.pallas_call(
        functools.partial(_grad_kernel, P),
        grid=(P_pad // _TILE,),
        in_specs=[
            pl.BlockSpec((D_pad, _TILE), lambda i: (0, i)),
            pl.BlockSpec((D_pad, P_pad), lambda i: (0, 0)),
            pl.BlockSpec((1, _TILE), lambda i: (0, i)),
            pl.BlockSpec((1, _TILE), lambda i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((D_pad, _TILE), lambda i: (0, i)),
            pl.BlockSpec((D_pad, P_pad), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((D_pad, P_pad), jnp.float32),
            jax.ShapeDtypeStruct((D_pad, P_pad), jnp.float32),
        ],
        interpret=interpret,
    )(pad2(Z), pad2(Zhat), pad_row(log_p), pad_row(ghat))
    return dZ[:D, :P], dZhat[:D, :P]


def _fused_flat_bwd(res, ct):
    """Hand-derived VJP from the saved ``log_p`` residual.

    The LBFGS closure evaluates value_and_grad on every (re-)evaluation,
    so the backward matters: rebuilding the score matrix costs one matmul
    (unavoidable — the softmax Jacobian needs it), but the saved log_p
    recovers the row logsumexp as ``diag(zz) - log_p``, so no reduction
    or forward pass is re-run.  With L = -sum_i log(exp(g_i) + 1e-6),
    g_i = zz_ii - lse_i and zz = (Z^T Zhat) / (zn zhn^T):

        dL/dzz_ij = ghat_i (delta_ij - softmax_i(zz)_ij),
        ghat_i    = -ct * exp(g_i) / (exp(g_i) + 1e-6)

    then the quotient rule routes dL/dzz into Z, Zhat both through the
    Gram numerator and the column norms.  On TPU the whole product is a
    Pallas kernel (``_grad_kernel``) — the [P, P] intermediates (scores,
    softmax, G) live only in VMEM, tile by tile.
    """
    Z, Zhat, log_p = res
    c = jnp.exp(log_p)
    ghat = -ct * c / (c + 1e-6)                       # [P]
    impl = _resolve_impl(_pallas_bwd_fits(*_padded_dims(*Z.shape)))
    if impl == "xla":
        return _grads_xla(Z, Zhat, log_p, ghat)
    return _grads_pallas(Z, Zhat, log_p, ghat,
                         interpret=impl == "pallas_interpret")


_fused_flat.defvjp(_fused_flat_fwd, _fused_flat_bwd)


def info_nce_fused(z: jnp.ndarray, zhat: jnp.ndarray) -> jnp.ndarray:
    """InfoNCE over patch positions, same contract as
    :func:`train.cpc_losses.info_nce` (z, zhat: [B, px, py, R] NHWC;
    reference federated_cpc.py:149-180) — Pallas-fused on TPU."""
    return _fused_flat(flat_patch_matrix(z), flat_patch_matrix(zhat))
