"""InfoNCE loss core for CPC (reference federated_cpc.py:149-180) — XLA path.

The reference builds the (P x P) normalized inner-product matrix with nested
Python loops over patch positions — O(P^2) separate torch ops.  Here it is
one matmul + a log-softmax-style reduction: identical math, MXU-shaped.

This is a LEAF module (jax-only imports) so that both the Pallas op
(ops/infonce.py) and the training layer (train/cpc_losses.py re-exports it)
can share one reference implementation without an ops<->train import cycle.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.scipy.special import logsumexp


def flat_patch_matrix(z: jnp.ndarray) -> jnp.ndarray:
    """[B, px, py, R] NHWC -> [B*R, P]: column p stacks (batch x channel)
    values of patch position p (the reference's zz-matrix layout,
    federated_cpc.py:149-180)."""
    B, px, py, R = z.shape
    return z.transpose(0, 3, 1, 2).reshape(-1, px * py)


def safe_norms(Z: jnp.ndarray) -> jnp.ndarray:
    """Column L2 norms with zero columns mapped to 1.

    The reference divides by the raw norm, so an all-zero patch column
    (e.g. dead features early in training) yields 0/0 = NaN there
    (federated_cpc.py:160-166); guarding keeps every dispatch path of the
    fused op (ops/infonce.py) finite and mutually identical.

    The guard sits INSIDE the sqrt: ``where`` on the squared sum makes the
    VJP finite too (guarding after ``jnp.linalg.norm`` leaves the norm's
    x/||x|| backward evaluating 0/0 = NaN at a zero column even though the
    primal is masked, so autodiff through :func:`log_p_flat` would NaN).
    """
    sq = jnp.sum(Z * Z, axis=0)
    return jnp.sqrt(jnp.where(sq == 0.0, 1.0, sq))


def log_p_flat(Z: jnp.ndarray, Zhat: jnp.ndarray) -> jnp.ndarray:
    """Per-position log softmax-diagonal [P] from flat [D, P] matrices —
    the single XLA reference core shared by :func:`info_nce` and the
    Pallas op's fallback/backward (ops/infonce.py)."""
    zz = (Z.T @ Zhat) / (safe_norms(Z)[:, None] * safe_norms(Zhat)[None, :])
    return jnp.diag(zz) - logsumexp(zz, axis=1)


def info_nce(z: jnp.ndarray, zhat: jnp.ndarray) -> jnp.ndarray:
    """z, zhat: [B, px, py, R] (NHWC; the reference is [B, C, px, py]).

    zz[i, j] = <Z[:,i], Zhat[:,j]> / (||Z[:,i]|| ||Zhat[:,j]||);
    positives on the diagonal; loss = -sum_i log(softmax_row_i[i] + 1e-6)
    (the reference adds 1e-6 inside the log, federated_cpc.py:178).
    """
    log_p = log_p_flat(flat_patch_matrix(z), flat_patch_matrix(zhat))
    return -jnp.sum(jnp.log(jnp.exp(log_p) + 1e-6))
