"""Fused quantized/sparse collectives: compressed payloads stay packed
across the wire (ISSUE 11 tentpole 1).

The unfused path (`parallel/comm.py:compressed_federated_mean` and the
engine's decode-then-``global_update`` route) decodes every client's
payload to dense f32 *before* the ``psum``, so the collective itself
never benefits from compression — the wire carries ``4*N`` bytes per
hop regardless of ``--compress``.  This module keeps the reduction
itself quantized:

- **Dense q8/q4** (:func:`packed_fused_mean`): a recursive-halving
  (butterfly) reduce-scatter over ``ppermute`` for power-of-2 device
  counts — each of the ``log2(D)`` steps sends a *packed* int8/int4
  half-buffer plus per-chunk f32 scales instead of dense f32 — followed
  by one all-gather of the packed owned shards.  Non-power-of-2 meshes
  take a ``D-1``-step quantized ring reduce-scatter instead.  Every
  device decodes the SAME gathered bytes, so the result is replicated
  by construction (the same argument `robust_federated_mean` relies on
  for its ``out_specs=P()``).
- **Sparse top-k** (:func:`make_sparse_fused_mean`): all-gather the
  fixed-shape ``{idx, val}`` payloads (``8k`` bytes per client) and
  scatter-add once on every device — never densifying ``[K, N]`` per
  client before the collective.

The re-quantization at each hop makes the fused dense mean a *lossy*
transport: it is allclose to the unfused mean, not bitwise (tolerance
documented in PARITY.md; roughly ``(log2(D)+1)`` grid steps of the
per-chunk scale for q8).  The transport codec is deliberately
**deterministic round-to-nearest** — key-free, unlike the stochastic
client-side encoder — so a fused run is replayable and kill/resume
exact without threading PRNG state through the collective.

CPU fallback is the same code path: ``ppermute``/``all_gather`` lower
fine on the virtual CPU mesh, and ``D == 1`` skips collectives
entirely.  The quantize and dequantize-accumulate steps dispatch
through ``ops/comm_kernels.py`` (fused Pallas kernels on TPU, the
literal jnp chain elsewhere — auto-selected, no env flag; tests pin
either side via ``force_comm_kernels_impl``).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax.numpy as jnp
from jax import lax

from federated_pytorch_test_tpu.ops.comm_kernels import (
    dequant_add,
    quantize_chunks,
)
from federated_pytorch_test_tpu.parallel.mesh import CLIENT_AXIS

__all__ = [
    "transport_params",
    "pack_chunks",
    "unpack_chunks",
    "packed_fused_mean",
    "make_fused_mean",
    "make_sparse_fused_mean",
    "fused_bytes_on_wire",
]


def _inner(compressor):
    """Look through the ErrorFeedback wrapper to the transport codec."""
    return getattr(compressor, "inner", None) or compressor


def transport_params(compressor) -> Optional[Tuple[int, int]]:
    """``(bits, chunk)`` of the wire codec matching ``compressor``, or
    None when it has no dense quantized transport (identity / sparse).

    Prefers the compressor's own declaration
    (``Compressor.transport_params``, compress/base.py) so the wire
    contract lives with the codec; falls back to duck-typed (bits,
    chunk) attributes for third-party compressors."""
    declared = getattr(compressor, "transport_params", None)
    if callable(declared):
        tp = declared()
        if tp is not None:
            bits, chunk = tp
            return int(bits), int(chunk)
    inner = _inner(compressor)
    bits = getattr(inner, "bits", None)
    chunk = getattr(inner, "chunk", None)
    if bits in (4, 8) and chunk:
        return int(bits), int(chunk)
    return None


def pack_chunks(v, chunk: int, bits: int):
    """Deterministic per-chunk transport encode of ``v`` (``[m]`` f32,
    ``m % chunk == 0``): returns ``(q, scale)`` with the same chunk
    layout as compress/quantize.py (scale = max|chunk|/qmax, int4
    payloads nibble-packed two-per-byte).  Scale + round/clip run as
    ONE fused kernel (ops/comm_kernels.quantize_chunks); the nibble
    fold is a pure byte shuffle that XLA keeps inside the surrounding
    fusion either way."""
    qmax = 2 ** (bits - 1) - 1
    c = v.shape[0] // chunk
    q, scale = quantize_chunks(v.reshape(c, chunk), qmax)
    if bits == 4:
        nib = (q + 8).astype(jnp.uint8)
        q = (nib[:, 0::2] << 4) | nib[:, 1::2]
    return q, scale


def _unfold_rows(q, bits: int):
    """Nibble-unfold q4 payload rows back to int8 rows (identity for
    q8) — the byte shuffle stays outside the fused kernels."""
    if bits == 4:
        hi = (q >> 4).astype(jnp.int8) - 8
        lo = (q & 0xF).astype(jnp.int8) - 8
        q = jnp.stack([hi, lo], axis=-1).reshape(q.shape[0], -1)
    return q


def unpack_chunks(q, scale, chunk: int, bits: int):
    """Inverse of :func:`pack_chunks` → flat ``[c*chunk]`` f32."""
    q = _unfold_rows(q, bits)
    safe = jnp.where(scale > 0, scale, 1.0)
    return (q.astype(jnp.float32) * safe[:, None]).reshape(-1)


def _hop_accumulate(acc, q, scale, chunk: int, bits: int):
    """The reduce-scatter hop's ``acc + decode(q, scale)`` as one fused
    dequantize-accumulate (ops/comm_kernels.dequant_add).  Bitwise the
    old ``acc + unpack_chunks(...)`` on the XLA path: reshape commutes
    with the elementwise add."""
    c = scale.shape[0]
    out = dequant_add(acc.reshape(c, chunk), _unfold_rows(q, bits), scale)
    return out.reshape(-1)


def _seg_elems(n: int, D: int, chunk: int) -> int:
    """Per-device segment length: N split D ways, rounded up to a whole
    number of codec chunks so per-chunk scales align at every level."""
    return -(-n // (D * chunk)) * chunk


def _butterfly_reduce_scatter(buf, D: int, seg: int, chunk: int, bits: int,
                              axis_name: str):
    """Recursive-halving reduce-scatter over packed payloads (power-of-2
    ``D``).  Returns ``(buf, lo)`` where ``buf[lo:lo+seg]`` is device
    ``me``'s fully-reduced segment (``lo == me*seg``)."""
    me = lax.axis_index(axis_name)
    lo = jnp.zeros((), jnp.int32)
    half = D // 2
    while half >= 1:
        width = half * seg
        bit = (me & half) > 0                 # my side of this exchange
        keep_lo = lo + jnp.where(bit, width, 0)
        send_lo = lo + jnp.where(bit, 0, width)
        send = lax.dynamic_slice(buf, (send_lo,), (width,))
        q, s = pack_chunks(send, chunk, bits)
        perm = [(i, i ^ half) for i in range(D)]
        q = lax.ppermute(q, axis_name, perm)
        s = lax.ppermute(s, axis_name, perm)
        kept = lax.dynamic_slice(buf, (keep_lo,), (width,))
        kept = _hop_accumulate(kept, q, s, chunk, bits)
        buf = lax.dynamic_update_slice(buf, kept, (keep_lo,))
        lo = keep_lo
        half //= 2
    return buf, lo


def _ring_reduce_scatter(buf, D: int, seg: int, chunk: int, bits: int,
                         axis_name: str):
    """Quantized ring reduce-scatter for non-power-of-2 ``D``: ``D-1``
    neighbor exchanges; device ``me`` ends owning segment
    ``(me+1) % D``.  Returns ``(buf, own_lo)``."""
    me = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % D) for i in range(D)]
    for t in range(D - 1):
        send_lo = ((me - t) % D) * seg
        send = lax.dynamic_slice(buf, (send_lo,), (seg,))
        q, s = pack_chunks(send, chunk, bits)
        q = lax.ppermute(q, axis_name, perm)
        s = lax.ppermute(s, axis_name, perm)
        recv_lo = ((me - 1 - t) % D) * seg
        acc = lax.dynamic_slice(buf, (recv_lo,), (seg,))
        acc = _hop_accumulate(acc, q, s, chunk, bits)
        buf = lax.dynamic_update_slice(buf, acc, (recv_lo,))
    return buf, ((me + 1) % D) * seg


def packed_fused_mean(local, div, D: int, bits: int, chunk: int,
                      axis_name: str = CLIENT_AXIS):
    """Quantized allreduce-mean of per-device partial sums.

    ``local``: ``[N]`` f32 per-device partial sum; ``div``: replicated
    divisor (already guarded against zero).  Reduce-scatter ships packed
    payloads, the divide runs on each device's owned ``[seg]`` shard,
    the shard is packed ONCE and all-gathered still packed; every device
    decodes the identical bytes, so the ``[N]`` result is replicated.
    """
    n = local.shape[-1]
    if D == 1:
        return local / div
    seg = _seg_elems(n, D, chunk)
    buf = jnp.pad(local, (0, D * seg - n))
    if D & (D - 1) == 0:
        buf, lo = _butterfly_reduce_scatter(buf, D, seg, chunk, bits,
                                            axis_name)
        own = lax.dynamic_slice(buf, (lo,), (seg,)) / div
        q, s = pack_chunks(own, chunk, bits)
        # butterfly leaves device i owning segment i: tiled gather is
        # already in segment order
        qg = lax.all_gather(q, axis_name, tiled=True)
        sg = lax.all_gather(s, axis_name, tiled=True)
        return unpack_chunks(qg, sg, chunk, bits)[:n]
    buf, lo = _ring_reduce_scatter(buf, D, seg, chunk, bits, axis_name)
    own = lax.dynamic_slice(buf, (lo,), (seg,)) / div
    q, s = pack_chunks(own, chunk, bits)
    # ring leaves device i owning segment (i+1)%D: gather untiled and
    # roll one slot so row j holds segment j before decoding
    qg = jnp.roll(lax.all_gather(q, axis_name), 1, axis=0)
    sg = jnp.roll(lax.all_gather(s, axis_name), 1, axis=0)
    c_seg = seg // chunk
    return unpack_chunks(qg.reshape((D * c_seg,) + q.shape[1:]),
                         sg.reshape(D * c_seg), chunk, bits)[:n]


def _weighted_local_sum(stack, w, K: int, axis_name: str):
    """Local numerator + replicated divisor matching
    ``algorithms._active_mean``: plain ``sum/K`` when ``w`` is None,
    else ``sum(w*x) / max(psum(sum(w)), 1)``."""
    if w is None:
        return jnp.sum(stack, axis=0), jnp.float32(K)
    local = jnp.sum(w[:, None] * stack, axis=0)
    n_act = lax.psum(jnp.sum(w), axis_name)
    return local, jnp.where(n_act > 0, n_act, 1.0)


def make_fused_mean(compressor, D: int, K: int,
                    axis_name: str = CLIENT_AXIS) -> Callable:
    """``mean_fn(stack, w)`` for ``Algorithm._agg`` that runs the whole
    aggregation as a quantized fused collective (dense q8/q4 codecs)."""
    tp = transport_params(compressor)
    if tp is None:
        raise ValueError(
            f"fused collective needs a dense quantized codec; "
            f"{compressor.name!r} has no (bits, chunk) transport")
    bits, chunk = tp

    def mean_fn(stack, w):
        local, div = _weighted_local_sum(stack, w, K, axis_name)
        return packed_fused_mean(local, div, D, bits, chunk, axis_name)

    return mean_fn


def make_sparse_fused_mean(payload, z, K: int,
                           axis_name: str = CLIENT_AXIS) -> Callable:
    """Per-round ``mean_fn(stack, w)`` for sparse top-k payloads.

    Valid ONLY when the aggregated stack is ``x = z + decode(payload)``
    (FedAvg/FedProx — the engine falls back to the unfused path for
    dual-state algorithms): the closure ignores ``stack`` and rebuilds
    the mean from the gathered ``{idx, val}`` pairs directly, one
    scatter-add on every device instead of K dense decodes + psum.
    NaN hygiene matches the guard contract: corrupted payload rows can
    hold NaN vals while only ``x`` was neutralized, so excluded rows
    (``w == 0``) are where-selected out, never multiplied by 0.
    """
    idx, val = payload["idx"], payload["val"]
    n = z.shape[0]

    def mean_fn(stack, w):
        del stack                              # x is implied by (z, payload)
        ig = lax.all_gather(idx, axis_name, tiled=True)
        vg = lax.all_gather(val, axis_name, tiled=True)
        if w is None:
            acc = jnp.zeros((n,), vg.dtype)
            acc = acc.at[ig.reshape(-1)].add(vg.reshape(-1))
            return z + acc / K
        wg = lax.all_gather(w, axis_name, tiled=True)
        vw = jnp.where(wg[:, None] > 0, vg * wg[:, None], 0.0)
        acc = jnp.zeros((n,), vg.dtype)
        acc = acc.at[ig.reshape(-1)].add(vw.reshape(-1))
        total = jnp.sum(wg)
        # all-excluded rounds zero the aggregate, matching _active_mean's
        # 0-numerator/1-divisor result (the engine carries z over anyway)
        return jnp.where(total > 0, z + acc / jnp.where(total > 0, total, 1.0),
                         0.0)

    return mean_fn


def fused_bytes_on_wire(compressor, n: int, D: int, K: int) -> int:
    """Estimated total wire bytes of one fused aggregation round.

    Dense: butterfly/ring reduce-scatter moves ``(D-1)*seg`` packed
    elements per device, the all-gather the same again →
    ``2*D*(D-1)*(seg*bits/8 + 4*seg/chunk)``.  Sparse: the all-gather
    broadcasts each client's ``8k``-byte payload to the other ``D-1``
    devices.  ``D == 1`` moves nothing.
    """
    if D <= 1:
        return 0
    inner = _inner(compressor)
    if getattr(compressor, "sparse", False):
        k = inner.k_for(n)
        return (D - 1) * K * 8 * k
    tp = transport_params(compressor)
    if tp is None:
        return 0
    bits, chunk = tp
    seg = _seg_elems(n, D, chunk)
    per_seg = seg * bits // 8 + 4 * (seg // chunk)
    return 2 * D * (D - 1) * per_seg
