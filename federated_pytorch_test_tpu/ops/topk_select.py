"""On-device chunked top-|v| selection for the sparse comm path.

``compress/topk.py`` used to run one global ``lax.top_k`` over the full
flat block vector.  On TPU that lowers to a monolithic sort-based
selection whose working set is the whole ``[n]`` vector plus the sort
scratch — for the block sizes the sparse path carries (hundreds of
thousands of coordinates) that is the single largest temporary in the
encode program.  The chunked kernel here runs the textbook two-stage
exact algorithm instead:

1. reshape to ``[c, chunk]`` and take each chunk's local top-``min(k,
   chunk)`` (one vectorized ``lax.top_k`` over the minor axis — the
   shape XLA:TPU tiles well),
2. run one final ``lax.top_k`` over the ``c * min(k, chunk)``
   candidates.

Any global top-k element is, by definition, inside its own chunk's
local top-k, so the result set is exact.  Tie-breaking is ALSO exact:
``lax.top_k`` breaks value ties toward the lower index, candidates are
laid out chunk-major (ascending global index), and stage 2 breaks its
ties toward the lower candidate position — which is the lower global
index.  The dispatch therefore promises **bitwise** identity with the
single-shot reference, and tests assert it (ties included).

Dispatch follows ``ops/infonce.py``: ``force_topk_impl`` pins
``"xla"`` (single-shot ``lax.top_k``) or ``"chunked"``; auto picks
chunked on TPU for vectors past the chunk size, single-shot elsewhere.
"""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp
from jax import lax

_CHUNK = 2048                # per-stage-1 slab; multiple of the 128 lanes

# None = auto (TPU + large n -> chunked); "xla" | "chunked"
_FORCE_IMPL = None


@contextlib.contextmanager
def force_topk_impl(impl: str):
    """Force the top-k implementation ("xla" | "chunked") — tests pin
    both sides and assert bitwise equality."""
    global _FORCE_IMPL
    prev, _FORCE_IMPL = _FORCE_IMPL, impl
    try:
        yield
    finally:
        _FORCE_IMPL = prev


def _resolve_impl(n: int) -> str:
    impl = _FORCE_IMPL
    if impl is None:
        return "chunked" if (jax.default_backend() == "tpu"
                             and n > _CHUNK) else "xla"
    return impl


def _topk_abs_xla(vec, k: int):
    """The seed path: one global sort-based selection."""
    _, idx = lax.top_k(jnp.abs(vec), k)
    return idx.astype(jnp.int32)


def _topk_abs_chunked(vec, k: int):
    n = vec.shape[0]
    c = -(-n // _CHUNK)
    # pad with -1: magnitudes are >= 0, so a pad slot can only be
    # selected when fewer than k real candidates exist — and k <= n
    mag = jnp.pad(jnp.abs(vec), (0, c * _CHUNK - n), constant_values=-1.0)
    mag = mag.reshape(c, _CHUNK)
    kc = min(k, _CHUNK)
    cand_v, cand_i = lax.top_k(mag, kc)                     # [c, kc]
    cand_g = cand_i + (jnp.arange(c, dtype=cand_i.dtype) * _CHUNK)[:, None]
    # chunk-major flatten keeps candidates in ascending-global-index
    # order within each value class, so stage 2's lower-position
    # tie-break IS the lower-global-index tie-break
    _, pos = lax.top_k(cand_v.reshape(-1), k)
    return cand_g.reshape(-1)[pos].astype(jnp.int32)


def top_k_abs_indices(vec, k: int):
    """Indices of the ``k`` largest ``|vec|`` entries, sorted by
    descending magnitude with ties broken toward the lower index —
    bitwise the single-shot ``lax.top_k(|vec|, k)`` result on every
    implementation."""
    if _resolve_impl(vec.shape[0]) == "xla":
        return _topk_abs_xla(vec, k)
    return _topk_abs_chunked(vec, k)
