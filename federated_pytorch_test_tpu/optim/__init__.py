"""Optimizers.

``LBFGSNew`` — jit-compatible stochastic L-BFGS, the TPU-native re-design of
the reference's custom optimizer (lbfgsnew.py; paper README.md:4,
ieeexplore 8755567).
"""

from federated_pytorch_test_tpu.optim.lbfgs import LBFGSNew, LBFGSState  # noqa: F401
