"""Jit-compatible stochastic L-BFGS (re-design of reference lbfgsnew.py).

The reference optimizer mutates torch parameters in place, keeps Python-list
curvature history, and runs data-dependent Python line-search loops
(lbfgsnew.py:124-196, :507-765) — none of which trace under ``jit``
(SURVEY.md section 7, "Hard parts" #1).  This version is a pure function on a
*flat parameter vector*:

  * curvature history is a fixed-size circular buffer ``[M, N]`` (static
    shapes; invalid slots masked in the two-loop recursion);
  * the inner iteration loop and both backtracking line-search phases are
    bounded ``lax.while_loop``s with an explicit done-flag for the
    reference's ``break`` conditions;
  * the closure is a JAX ``loss_fn(x) -> scalar``; re-evaluations are
    ``value_and_grad`` calls (the reference pays a full fwd+bwd per closure
    call; XLA fuses ours into the surrounding computation).

Semantics follow the reference exactly (same constants, same quirks):

  * batch-mode trust region ``y += lm0*s``, lm0=1e-6 (lbfgsnew.py:558-560,
    :594-595);
  * batch-change detection ``n_iter==1 and state['n_iter']>1`` (:600);
  * online inter-batch grad mean/variance -> max step
    ``alphabar = 1/(1 + Var/((n-1)*||g||))`` (:601-615), where ``||g||`` is
    the 2-norm of the gradient at *step entry* (the reference's ``grad_nrm``
    is computed once per ``step()`` and never refreshed — :563);
  * curvature pairs stored only when ``ys > 1e-10*||s||^2`` and the batch
    did not change (:618-630);
  * backtracking line search with Armijo c1=1e-4, <=35 halvings shared
    across the positive and negative phases, and the negative-step probe
    when the decrease is below ``|c1*g.d|`` (:124-196);
  * step-size init ``min(1, 1/sum|g|)*lr`` on the global first iteration,
    else ``lr`` (:672-675);
  * convergence tests on max_eval / sum|g| / directional derivative /
    ``sum|t*d|`` / loss change (:731-747).

The full-batch cubic strong-Wolfe search (lbfgsnew.py:201-504) is not yet
ported; only ``batch_mode=True`` paths are exercised by the reference's
active drivers (federated_cpc.py:238-248, federated_vae_cl.py:205).  With
``line_search_fn=False`` a fixed step ``t`` is used, as in the reference.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


class LBFGSState(NamedTuple):
    """Persistent optimizer state (reference: ``self.state[params[0]]``,
    lbfgsnew.py:749-762).  All arrays are fixed-shape for jit."""

    n_iter_total: jnp.ndarray      # state['n_iter'] — across step() calls
    func_evals: jnp.ndarray
    d: jnp.ndarray                 # [N] last direction
    t: jnp.ndarray                 # last accepted step size
    hist_y: jnp.ndarray            # [M, N] circular curvature buffers
    hist_s: jnp.ndarray            # [M, N]
    hist_len: jnp.ndarray
    hist_head: jnp.ndarray         # index of the OLDEST valid entry
    H_diag: jnp.ndarray
    prev_grad: jnp.ndarray         # [N]
    prev_loss: jnp.ndarray
    running_avg: jnp.ndarray       # [N] inter-batch grad mean (batch mode)
    running_avg_sq: jnp.ndarray    # [N] accumulated second moment
    alphabar: jnp.ndarray          # adaptive max step (batch mode)


def _dot(a, b):
    return jnp.vdot(a, b)


@dataclasses.dataclass(frozen=True)
class LBFGSNew:
    """Stochastic L-BFGS on a flat parameter vector.

    Usage::

        opt = LBFGSNew(history_size=7, max_iter=2, batch_mode=True,
                       line_search_fn=True)
        state = opt.init(x0)
        x, state, loss = opt.step(loss_fn, x, state)   # jittable
    """

    lr: float = 1.0
    max_iter: int = 10
    max_eval: Optional[int] = None
    tolerance_grad: float = 1e-5
    tolerance_change: float = 1e-9
    history_size: int = 7
    line_search_fn: bool = False
    batch_mode: bool = False

    def __post_init__(self):
        if self.line_search_fn and not self.batch_mode:
            raise NotImplementedError(
                "full-batch cubic strong-Wolfe line search "
                "(reference lbfgsnew.py:201-504) is not ported yet; use "
                "batch_mode=True (backtracking) or line_search_fn=False "
                "(fixed step)")

    def _max_eval(self) -> int:
        return self.max_eval if self.max_eval is not None else self.max_iter * 5 // 4

    # ------------------------------------------------------------------
    def init(self, x: jnp.ndarray) -> LBFGSState:
        n = x.shape[-1]
        m = self.history_size
        f = x.dtype
        z = lambda *s: jnp.zeros(s, f)
        return LBFGSState(
            n_iter_total=jnp.int32(0), func_evals=jnp.int32(0),
            d=z(n), t=jnp.asarray(self.lr, f),
            hist_y=z(m, n), hist_s=z(m, n),
            hist_len=jnp.int32(0), hist_head=jnp.int32(0),
            H_diag=jnp.asarray(1.0, f),
            prev_grad=z(n), prev_loss=jnp.asarray(0.0, f),
            running_avg=z(n), running_avg_sq=z(n),
            alphabar=jnp.asarray(self.lr, f),
        )

    # ------------------------------------------------------------------
    def _two_loop(self, g, hist_y, hist_s, hist_len, head, H_diag):
        """d = -H*g via the two-loop recursion over the circular buffer
        (reference lbfgsnew.py:645-659), invalid slots masked out."""
        M = self.history_size

        def safe_ro(y, s, valid):
            ys = _dot(y, s)
            return jnp.where(valid, 1.0 / jnp.where(ys == 0, 1.0, ys), 0.0)

        q = -g
        al = jnp.zeros((M,), g.dtype)

        def bwd(j, carry):
            q, al = carry
            valid = j < hist_len
            li = hist_len - 1 - j          # logical: newest first
            pi = (head + li) % M
            ro = safe_ro(hist_y[pi], hist_s[pi], valid)
            a = ro * _dot(hist_s[pi], q)
            a = jnp.where(valid, a, 0.0)
            return q - a * hist_y[pi], al.at[pi].set(a)

        q, al = lax.fori_loop(0, M, bwd, (q, al))
        r = H_diag * q

        def fwd(j, r):
            valid = j < hist_len
            pi = (head + j) % M            # logical: oldest first
            ro = safe_ro(hist_y[pi], hist_s[pi], valid)
            be = ro * _dot(hist_y[pi], r)
            delta = jnp.where(valid, al[pi] - be, 0.0)
            return r + delta * hist_s[pi]

        return lax.fori_loop(0, M, fwd, r)

    def _push(self, hist_y, hist_s, hist_len, head, y, s):
        """Append (y, s); evict the oldest when full (lbfgsnew.py:618-627)."""
        M = self.history_size
        full = hist_len == M
        idx = jnp.where(full, head, (head + hist_len) % M)
        return (hist_y.at[idx].set(y), hist_s.at[idx].set(s),
                jnp.where(full, hist_len, hist_len + 1),
                jnp.where(full, (head + 1) % M, head))

    # ------------------------------------------------------------------
    def _backtrack(self, value_fn, x, d, g, alphabar, f_old):
        """Backtracking line search with negative-step probe
        (reference _linesearch_backtrack, lbfgsnew.py:124-196).

        Returns (alphak, n_value_evals).  ``value_fn`` is loss-only (the
        reference disables grad during line search, :694-699).
        """
        c1 = jnp.asarray(1e-4, x.dtype)
        citer = 35
        prodterm = c1 * _dot(g, d)

        def phase(alpha0, ci0):
            """Halve alpha until Armijo holds or the shared budget runs out."""
            f0 = value_fn(x + alpha0 * d)

            def cond(c):
                alpha, f_new, ci = c
                bad = jnp.isnan(f_new) | (f_new > f_old + alpha * prodterm)
                return (ci < citer) & bad

            def body(c):
                alpha, _, ci = c
                alpha = 0.5 * alpha
                return alpha, value_fn(x + alpha * d), ci + 1

            return lax.while_loop(cond, body, (alpha0, f0, ci0))

        alphak, f_new, ci = phase(alphabar, jnp.int32(0))

        def neg_probe(args):
            alphak, f_new, ci = args
            alphak1, f_new1, ci = phase(-alphabar, ci)
            take_neg = f_new1 < f_new
            return jnp.where(take_neg, alphak1, alphak), ci

        def no_probe(args):
            alphak, _, ci = args
            return alphak, ci

        alphak, ci = lax.cond(
            f_old - f_new < jnp.abs(prodterm), neg_probe, no_probe,
            (alphak, f_new, ci))
        return alphak, ci

    # ------------------------------------------------------------------
    def step(self, loss_fn: Callable[[jnp.ndarray], jnp.ndarray],
             x: jnp.ndarray, state: LBFGSState
             ) -> Tuple[jnp.ndarray, LBFGSState, jnp.ndarray]:
        """One optimization step (reference ``step(closure)``,
        lbfgsnew.py:507-765).  Jittable; ``loss_fn`` must be pure."""
        cfg = self
        vg = jax.value_and_grad(loss_fn)
        dt = x.dtype
        lm0 = jnp.asarray(1e-6, dt)
        lr = jnp.asarray(cfg.lr, dt)

        loss0, g0 = vg(x)                       # closure #1 (:536)
        abs_sum0 = jnp.sum(jnp.abs(g0))
        grad_nrm = jnp.linalg.norm(g0)          # step-entry norm (:563)

        # alphabar resets to lr at every step() entry (:557-558); only the
        # running mean/variance persists across steps
        st = state._replace(func_evals=state.func_evals + 1,
                            alphabar=jnp.asarray(cfg.lr, dt))

        # carry: x, g, loss, abs_grad_sum, n_iter, evals, done + state fields
        Carry = Tuple
        def cond(c):
            (x, g, loss, abs_sum, n_iter, evals, done, st) = c
            return (n_iter < cfg.max_iter) & ~done & ~jnp.isnan(grad_nrm)

        def body(c):
            (x, g, loss, abs_sum, n_iter, evals, done, st) = c
            n_iter = n_iter + 1
            total = st.n_iter_total + 1

            # ---- direction (:566-659)
            first = total == 1

            def first_dir(_):
                return (-g, st.hist_y * 0, st.hist_s * 0, jnp.int32(0),
                        jnp.int32(0), jnp.asarray(1.0, dt),
                        st.running_avg * 0, st.running_avg_sq * 0, st.alphabar)

            def lbfgs_dir(_):
                y = g - st.prev_grad
                s = st.d * st.t
                if cfg.batch_mode:
                    y = y + lm0 * s             # trust region (:594-595)
                ys = _dot(y, s)
                sn2 = _dot(s, s)
                batch_changed = jnp.asarray(
                    cfg.batch_mode, bool) & (n_iter == 1) & (total > 1)

                # online inter-batch grad mean/variance (:601-615)
                def upd_stats(_):
                    g_old = g - st.running_avg
                    avg = st.running_avg + g_old / total.astype(dt)
                    g_new = g - avg
                    avg_sq = st.running_avg_sq + g_new * g_old
                    alphabar = 1.0 / (1.0 + jnp.sum(avg_sq)
                                      / ((total - 1).astype(dt) * grad_nrm))
                    return avg, avg_sq, alphabar

                def keep_stats(_):
                    return st.running_avg, st.running_avg_sq, st.alphabar

                avg, avg_sq, alphabar = lax.cond(
                    batch_changed, upd_stats, keep_stats, None)

                # curvature-pair memory (:618-630)
                store = (ys > 1e-10 * sn2) & ~batch_changed

                def do_push(_):
                    hy, hs, hl, hh = self._push(
                        st.hist_y, st.hist_s, st.hist_len, st.hist_head, y, s)
                    return hy, hs, hl, hh, ys / _dot(y, y)

                def no_push(_):
                    return (st.hist_y, st.hist_s, st.hist_len, st.hist_head,
                            st.H_diag)

                hy, hs, hl, hh, H_diag = lax.cond(store, do_push, no_push, None)
                d = self._two_loop(g, hy, hs, hl, hh, H_diag)
                return d, hy, hs, hl, hh, H_diag, avg, avg_sq, alphabar

            d, hy, hs, hl, hh, H_diag, avg, avg_sq, alphabar = lax.cond(
                first, first_dir, lbfgs_dir, None)

            prev_grad, prev_loss = g, loss

            # ---- step length (:672-675)
            t = jnp.where(first,
                          jnp.minimum(jnp.asarray(1.0, dt), 1.0 / abs_sum) * lr,
                          lr)
            gtd = _dot(g, d)

            ls_evals = jnp.int32(0)
            if cfg.line_search_fn and cfg.batch_mode:
                t_ls, n_ls = self._backtrack(loss_fn, x, d, g, alphabar, loss)
                t = jnp.where(jnp.isnan(t_ls), lr, t_ls)   # (:701-703)
                ls_evals = n_ls
            # (full-batch cubic search not yet ported; fixed t otherwise)

            x = x + t * d                                   # _add_grad (:704)

            # ---- re-eval unless last inner iteration (:713-721)
            last = n_iter == cfg.max_iter

            def reval(_):
                l2, g2 = vg(x)
                return l2, g2, jnp.sum(jnp.abs(g2)), jnp.int32(1)

            def keep(_):
                return loss, g, abs_sum, jnp.int32(0)

            loss, g, abs_sum, re = lax.cond(last, keep, reval, None)
            # the max_eval budget counts only closure re-evals (reference
            # current_evals, :544, :727-729); line-search trials are tracked
            # in func_evals stats only (:195)
            evals = evals + re

            # ---- break conditions (:731-747)
            done = (jnp.isnan(abs_sum)
                    | (evals >= cfg._max_eval())
                    | (abs_sum <= cfg.tolerance_grad)
                    | (gtd > -cfg.tolerance_change)
                    | (jnp.sum(jnp.abs(t * d)) <= cfg.tolerance_change)
                    | (jnp.abs(loss - prev_loss) < cfg.tolerance_change))

            st = LBFGSState(
                n_iter_total=total,
                func_evals=st.func_evals + 1 + re + ls_evals,
                d=d, t=t, hist_y=hy, hist_s=hs, hist_len=hl, hist_head=hh,
                H_diag=H_diag, prev_grad=prev_grad,
                prev_loss=jnp.asarray(prev_loss, dt),
                running_avg=avg, running_avg_sq=avg_sq, alphabar=alphabar)
            return (x, g, loss, abs_sum, n_iter, evals, done, st)

        init = (x, g0, loss0, abs_sum0, jnp.int32(0), jnp.int32(1),
                abs_sum0 <= cfg.tolerance_grad, st)
        x, g, loss, abs_sum, n_iter, evals, done, st = lax.while_loop(
            cond, body, init)
        # reference returns the loss of the FIRST closure call (:536, :765)
        return x, st, loss0
