"""Jit-compatible stochastic L-BFGS (re-design of reference lbfgsnew.py).

The reference optimizer mutates torch parameters in place, keeps Python-list
curvature history, and runs data-dependent Python line-search loops
(lbfgsnew.py:124-196, :507-765) — none of which trace under ``jit``
(SURVEY.md section 7, "Hard parts" #1).  This version is a pure function on a
*flat parameter vector*:

  * curvature history is a fixed-size circular buffer ``[M, N]`` (static
    shapes; invalid slots masked in the two-loop recursion);
  * the inner iteration loop and both backtracking line-search phases are
    bounded ``lax.while_loop``s with an explicit done-flag for the
    reference's ``break`` conditions;
  * the closure is a JAX ``loss_fn(x) -> scalar``; re-evaluations are
    ``value_and_grad`` calls (the reference pays a full fwd+bwd per closure
    call; XLA fuses ours into the surrounding computation).

Semantics follow the reference exactly (same constants, same quirks):

  * batch-mode trust region ``y += lm0*s``, lm0=1e-6 (lbfgsnew.py:558-560,
    :594-595);
  * batch-change detection ``n_iter==1 and state['n_iter']>1`` (:600);
  * online inter-batch grad mean/variance -> max step
    ``alphabar = 1/(1 + Var/((n-1)*||g||))`` (:601-615), where ``||g||`` is
    the 2-norm of the gradient at *step entry* (the reference's ``grad_nrm``
    is computed once per ``step()`` and never refreshed — :563);
  * curvature pairs stored only when ``ys > 1e-10*||s||^2`` and the batch
    did not change (:618-630);
  * backtracking line search with Armijo c1=1e-4, <=35 halvings shared
    across the positive and negative phases, and the negative-step probe
    when the decrease is below ``|c1*g.d|`` (:124-196);
  * step-size init ``min(1, 1/sum|g|)*lr`` on the global first iteration,
    else ``lr`` (:672-675);
  * convergence tests on max_eval / sum|g| / directional derivative /
    ``sum|t*d|`` / loss change (:731-747).

  * full-batch cubic strong-Wolfe search (Fletcher): bracketing phase with
    sigma=0.1, rho=0.01, t1=9, t2=0.1, t3=0.5, alpha1=10*lr, cubic
    interpolation and a bounded zoom (lbfgsnew.py:201-325, :328-414,
    :421-504).  One deliberate improvement: the reference estimates every
    directional derivative phi'(a) by TWO extra closure calls (central
    differences with step 1e-6, :230-238, :348-368, :467-474); here
    ``value_and_grad`` gives the exact phi'(a) = grad . d in ONE fused
    evaluation — fewer evals and no differencing noise.  The reference's
    ``step`` parameter survives only as the Fletcher roundoff-termination
    tolerance ``(aj-alphaj)*phi'_j <= step`` (:480).

With ``line_search_fn=False`` a fixed step ``t`` is used, as in the
reference.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


class LBFGSState(NamedTuple):
    """Persistent optimizer state (reference: ``self.state[params[0]]``,
    lbfgsnew.py:749-762).  All arrays are fixed-shape for jit."""

    n_iter_total: jnp.ndarray      # state['n_iter'] — across step() calls
    func_evals: jnp.ndarray
    d: jnp.ndarray                 # [N] last direction
    t: jnp.ndarray                 # last accepted step size
    hist_y: jnp.ndarray            # [M, N] circular curvature buffers
    hist_s: jnp.ndarray            # [M, N]
    hist_len: jnp.ndarray
    hist_head: jnp.ndarray         # index of the OLDEST valid entry
    H_diag: jnp.ndarray
    prev_grad: jnp.ndarray         # [N]
    prev_loss: jnp.ndarray
    running_avg: jnp.ndarray       # [N] inter-batch grad mean (batch mode)
    running_avg_sq: jnp.ndarray    # [N] accumulated second moment
    alphabar: jnp.ndarray          # adaptive max step (batch mode)


def _dot(a, b):
    return jnp.vdot(a, b)


@dataclasses.dataclass(frozen=True)
class LBFGSNew:
    """Stochastic L-BFGS on a flat parameter vector.

    Usage::

        opt = LBFGSNew(history_size=7, max_iter=2, batch_mode=True,
                       line_search_fn=True)
        state = opt.init(x0)
        x, state, loss = opt.step(loss_fn, x, state)   # jittable
    """

    lr: float = 1.0
    max_iter: int = 10
    max_eval: Optional[int] = None
    tolerance_grad: float = 1e-5
    tolerance_change: float = 1e-9
    history_size: int = 7
    line_search_fn: bool = False
    batch_mode: bool = False

    def _max_eval(self) -> int:
        return self.max_eval if self.max_eval is not None else self.max_iter * 5 // 4

    # ------------------------------------------------------------------
    def init(self, x: jnp.ndarray) -> LBFGSState:
        n = x.shape[-1]
        m = self.history_size
        f = x.dtype
        z = lambda *s: jnp.zeros(s, f)
        return LBFGSState(
            n_iter_total=jnp.int32(0), func_evals=jnp.int32(0),
            d=z(n), t=jnp.asarray(self.lr, f),
            hist_y=z(m, n), hist_s=z(m, n),
            hist_len=jnp.int32(0), hist_head=jnp.int32(0),
            H_diag=jnp.asarray(1.0, f),
            prev_grad=z(n), prev_loss=jnp.asarray(0.0, f),
            running_avg=z(n), running_avg_sq=z(n),
            alphabar=jnp.asarray(self.lr, f),
        )

    # ------------------------------------------------------------------
    def _two_loop(self, g, hist_y, hist_s, hist_len, head, H_diag):
        """d = -H*g via the two-loop recursion over the circular buffer
        (reference lbfgsnew.py:645-659), invalid slots masked out."""
        M = self.history_size

        def safe_ro(y, s, valid):
            ys = _dot(y, s)
            return jnp.where(valid, 1.0 / jnp.where(ys == 0, 1.0, ys), 0.0)

        q = -g
        al = jnp.zeros((M,), g.dtype)

        def bwd(j, carry):
            q, al = carry
            valid = j < hist_len
            li = hist_len - 1 - j          # logical: newest first
            pi = (head + li) % M
            ro = safe_ro(hist_y[pi], hist_s[pi], valid)
            a = ro * _dot(hist_s[pi], q)
            a = jnp.where(valid, a, 0.0)
            return q - a * hist_y[pi], al.at[pi].set(a)

        q, al = lax.fori_loop(0, M, bwd, (q, al))
        r = H_diag * q

        def fwd(j, r):
            valid = j < hist_len
            pi = (head + j) % M            # logical: oldest first
            ro = safe_ro(hist_y[pi], hist_s[pi], valid)
            be = ro * _dot(hist_y[pi], r)
            delta = jnp.where(valid, al[pi] - be, 0.0)
            return r + delta * hist_s[pi]

        return lax.fori_loop(0, M, fwd, r)

    def _push(self, hist_y, hist_s, hist_len, head, y, s):
        """Append (y, s); evict the oldest when full (lbfgsnew.py:618-627)."""
        M = self.history_size
        full = hist_len == M
        idx = jnp.where(full, head, (head + hist_len) % M)
        return (hist_y.at[idx].set(y), hist_s.at[idx].set(s),
                jnp.where(full, hist_len, hist_len + 1),
                jnp.where(full, (head + 1) % M, head))

    # ------------------------------------------------------------------
    def _backtrack(self, value_fn, x, d, g, alphabar, f_old):
        """Backtracking line search with negative-step probe
        (reference _linesearch_backtrack, lbfgsnew.py:124-196).

        Returns (alphak, n_value_evals).  ``value_fn`` is loss-only (the
        reference disables grad during line search, :694-699).
        """
        c1 = jnp.asarray(1e-4, x.dtype)
        citer = 35
        prodterm = c1 * _dot(g, d)

        def phase(alpha0, ci0):
            """Halve alpha until Armijo holds or the shared budget runs out."""
            f0 = value_fn(x + alpha0 * d)

            def cond(c):
                alpha, f_new, ci = c
                bad = jnp.isnan(f_new) | (f_new > f_old + alpha * prodterm)
                return (ci < citer) & bad

            def body(c):
                alpha, _, ci = c
                alpha = 0.5 * alpha
                return alpha, value_fn(x + alpha * d), ci + 1

            return lax.while_loop(cond, body, (alpha0, f0, ci0))

        alphak, f_new, ci = phase(alphabar, jnp.int32(0))

        def neg_probe(args):
            alphak, f_new, ci = args
            alphak1, f_new1, ci = phase(-alphabar, ci)
            take_neg = f_new1 < f_new
            return jnp.where(take_neg, alphak1, alphak), ci

        def no_probe(args):
            alphak, _, ci = args
            return alphak, ci

        alphak, ci = lax.cond(
            f_old - f_new < jnp.abs(prodterm), neg_probe, no_probe,
            (alphak, f_new, ci))
        return alphak, ci

    # ------------------------------------------------------------------
    # full-batch cubic strong-Wolfe line search (reference
    # _linesearch_cubic / _cubic_interpolate / _linesearch_zoom,
    # lbfgsnew.py:201-325, :328-414, :421-504).  phi(a) = loss(x + a*d),
    # phi'(a) = grad(x + a*d) . d — exact via value_and_grad, replacing the
    # reference's central-difference probes (see module docstring).
    # ------------------------------------------------------------------
    def _cubic_interpolate(self, vg_phi, phi, a, b):
        """Cubic minimizer in [a,b] (or [b,a]); returns (alpha, n_evals).

        Reference lbfgsnew.py:328-414.  Quirks reproduced: when the
        predicted minimizer ``z0`` (an *absolute* step) lands inside the
        interval, the reference probes the loss at ``a + z0*(b-a)`` — i.e.
        it re-reads z0 as a *fraction* (:385-387); and an out-of-interval
        z0 scores ``fz0 = f0+f1`` so the better endpoint wins (:382-383).
        Division guards return the reference's fallbacks ((a+b)/2 at
        :377-378) instead of propagating inf.
        """
        f0, f0d = vg_phi(a)
        f1, f1d = vg_phi(b)
        ab = b - a
        aa = 3.0 * (f0 - f1) / jnp.where(ab == 0, 1.0, ab) + f1d - f0d
        disc = aa * aa - f0d * f1d

        def pos(_):
            cc = jnp.sqrt(disc)
            denom = f1d - f0d + 2.0 * cc
            z0 = b - (f1d + cc - aa) * ab / jnp.where(denom == 0.0, 1.0,
                                                      denom)
            hi, lo = jnp.maximum(a, b), jnp.minimum(a, b)
            outside = (z0 > hi) | (z0 < lo)
            fz0, ne = lax.cond(
                outside,
                lambda _: (f0 + f1, jnp.int32(0)),
                lambda _: (phi(a + z0 * ab), jnp.int32(1)), None)
            res = jnp.where((f0 < f1) & (f0 < fz0), a,
                            jnp.where(f1 < fz0, b, z0))
            res = jnp.where(denom == 0.0, 0.5 * (a + b), res)
            return res, jnp.int32(2) + ne

        def neg(_):
            return jnp.where(f0 < f1, a, b), jnp.int32(2)

        return lax.cond(disc > 0.0, pos, neg, None)

    def _zoom(self, vg_phi, phi, a, b, phi_0, gphi_0, step):
        """Fletcher zoom on the bracket [a,b]; returns (alphak, n_evals).

        Reference lbfgsnew.py:421-504: <=4 rounds (:445, "FIXME original
        10"); each round interpolates in [aj+t2*(bj-aj), bj-t3*(bj-aj)],
        shrinks the bracket on an Armijo/monotonicity failure (:464-465),
        and otherwise tests the roundoff guard ``(aj-alphaj)*phi'_j <=
        step`` (:480) and the strong-Wolfe curvature bound (:485).  If no
        step was accepted the last alphaj is returned (:498-499).
        """
        sigma, rho = 0.1, 0.01
        t2, t3 = 0.1, 0.5

        def cond(c):
            _, _, _, found, ci, _ = c
            return (ci < 4) & ~found

        def body(c):
            aj, bj, _, _, ci, ne = c
            p01 = aj + t2 * (bj - aj)
            p02 = bj - t3 * (bj - aj)
            alphaj, ne_i = self._cubic_interpolate(vg_phi, phi, p01, p02)
            phi_j = phi(alphaj)
            phi_aj = phi(aj)
            shrink = (phi_j > phi_0 + rho * alphaj * gphi_0) | (
                phi_j >= phi_aj)

            def sh(_):
                return aj, alphaj, jnp.bool_(False), jnp.int32(0)

            def el(_):
                _, gphi_j = vg_phi(alphaj)
                found = ((aj - alphaj) * gphi_j <= step) | (
                    jnp.abs(gphi_j) <= -sigma * gphi_0)
                bj_new = jnp.where(gphi_j * (bj - aj) >= 0.0, aj, bj)
                return alphaj, bj_new, found, jnp.int32(1)

            aj_n, bj_n, found, ne_g = lax.cond(shrink, sh, el, None)
            return aj_n, bj_n, alphaj, found, ci + 1, ne + ne_i + 2 + ne_g

        init = (a, b, a, jnp.bool_(False), jnp.int32(0), jnp.int32(0))
        _, _, alphak, _, _, ne = lax.while_loop(cond, body, init)
        return alphak, ne

    def _cubic_search(self, loss_fn, x, d, phi_0, gphi_0):
        """Strong-Wolfe bracketing phase; returns (alphak, n_evals).

        Reference _linesearch_cubic, lbfgsnew.py:201-325.  Constants:
        alpha1=10*lr (:212), tol=min(0.01*phi_0, 1e-6) (:228),
        mu=(tol-phi_0)/(rho*gphi_0) (:244), <=3 bracketing rounds (:258,
        "FIXME").  Quirks reproduced: the Armijo test of the bracketing
        phase omits rho (:269); when advancing by interpolation alphai1 is
        NOT updated (:306-310); degenerate phi'(0) (|.|<1e-12) or
        non-finite mu returns step 1.0 (:241-247).  phi_0/gphi_0 come in
        from the caller instead of the reference's closure + central
        difference re-evaluations (:227-238).
        """
        dt = x.dtype
        lr = jnp.asarray(self.lr, dt)
        alpha1 = 10.0 * lr                      # (:212)
        sigma, rho, t1 = 0.1, 0.01, 9.0
        step = jnp.asarray(1e-6, dt)            # roundoff tol (see _zoom)

        vgf = jax.value_and_grad(loss_fn)

        def vg_phi(alpha):
            v, gg = vgf(x + alpha * d)
            return v, _dot(gg, d)

        def phi(alpha):
            return loss_fn(x + alpha * d)

        tol = jnp.minimum(phi_0 * 0.01, jnp.asarray(1e-6, dt))
        mu = (tol - phi_0) / (rho * gphi_0)

        def run(_):
            def cond(c):
                _, _, _, _, done, ci, _ = c
                return (ci < 4) & ~done

            def body(c):
                alphai, alphai1, phi_ai1, alphak, done, ci, ne = c
                phi_ai = phi(alphai)
                cond0 = phi_ai < tol
                cond1 = (phi_ai > phi_0 + alphai * gphi_0) | (
                    (ci > 1) & (phi_ai >= phi_ai1))     # rho-less (:269)

                def br0(_):  # condition 0: below tol (:264-268)
                    return (alphai, alphai1, phi_ai1, alphai,
                            jnp.bool_(True), jnp.int32(0))

                def br1(_):  # condition 1: bracket [alphai1, alphai]
                    ak, nz = self._zoom(vg_phi, phi, alphai1, alphai,
                                        phi_0, gphi_0, step)
                    return (alphai, alphai1, phi_ai1, ak, jnp.bool_(True),
                            nz)

                def rest(_):
                    _, gphi_i = vg_phi(alphai)
                    cond2 = jnp.abs(gphi_i) <= -sigma * gphi_0
                    cond3 = gphi_i >= 0.0

                    def br2(_):  # condition 2: curvature met (:288-292)
                        return (alphai, alphai1, phi_ai1, alphai,
                                jnp.bool_(True), jnp.int32(1))

                    def br3(_):  # condition 3: bracket [alphai, alphai1]
                        ak, nz = self._zoom(vg_phi, phi, alphai, alphai1,
                                            phi_0, gphi_0, step)
                        return (alphai, alphai1, phi_ai1, ak,
                                jnp.bool_(True), nz + 1)

                    def adv(_):  # advance the trial step (:303-313)
                        take_mu = mu <= 2.0 * alphai - alphai1

                        def mub(_):
                            return mu, alphai, jnp.int32(0)

                        def itp(_):
                            p01 = 2.0 * alphai - alphai1
                            p02 = jnp.minimum(
                                mu, alphai + t1 * (alphai - alphai1))
                            ai_new, nei = self._cubic_interpolate(
                                vg_phi, phi, p01, p02)
                            # alphai1 intentionally NOT advanced (:306-310)
                            return ai_new, alphai1, nei

                        ai_new, ai1_new, nei = lax.cond(take_mu, mub, itp,
                                                        None)
                        return (ai_new, ai1_new, phi_ai, alphak,
                                jnp.bool_(False), nei + 1)

                    return lax.cond(
                        cond2, br2,
                        lambda _: lax.cond(cond3, br3, adv, None), None)

                out = lax.cond(
                    cond0, br0,
                    lambda _: lax.cond(cond1, br1, rest, None), None)
                alphai_n, alphai1_n, phi_ai1_n, alphak_n, done_n, ne_i = out
                return (alphai_n, alphai1_n, phi_ai1_n, alphak_n, done_n,
                        ci + 1, ne + 1 + ne_i)

            init = (alpha1, jnp.asarray(0.0, dt), phi_0, lr,
                    jnp.bool_(False), jnp.int32(1), jnp.int32(0))
            _, _, _, alphak, _, _, ne = lax.while_loop(cond, body, init)
            return alphak, ne

        bad = (jnp.abs(gphi_0) < 1e-12) | ~jnp.isfinite(mu)   # (:241-247)
        return lax.cond(
            bad, lambda _: (jnp.asarray(1.0, dt), jnp.int32(0)), run, None)

    # ------------------------------------------------------------------
    def step(self, loss_fn: Callable[[jnp.ndarray], jnp.ndarray],
             x: jnp.ndarray, state: LBFGSState
             ) -> Tuple[jnp.ndarray, LBFGSState, jnp.ndarray]:
        """One optimization step (reference ``step(closure)``,
        lbfgsnew.py:507-765).  Jittable; ``loss_fn`` must be pure."""
        cfg = self
        vg = jax.value_and_grad(loss_fn)
        dt = x.dtype
        lm0 = jnp.asarray(1e-6, dt)
        lr = jnp.asarray(cfg.lr, dt)

        loss0, g0 = vg(x)                       # closure #1 (:536)
        abs_sum0 = jnp.sum(jnp.abs(g0))
        grad_nrm = jnp.linalg.norm(g0)          # step-entry norm (:563)

        # alphabar resets to lr at every step() entry (:557-558); only the
        # running mean/variance persists across steps
        st = state._replace(func_evals=state.func_evals + 1,
                            alphabar=jnp.asarray(cfg.lr, dt))

        # carry: x, g, loss, abs_grad_sum, n_iter, evals, done + state fields
        Carry = Tuple
        def cond(c):
            (x, g, loss, abs_sum, n_iter, evals, done, st) = c
            return (n_iter < cfg.max_iter) & ~done & ~jnp.isnan(grad_nrm)

        def body(c):
            (x, g, loss, abs_sum, n_iter, evals, done, st) = c
            n_iter = n_iter + 1
            total = st.n_iter_total + 1

            # ---- direction (:566-659)
            first = total == 1

            def first_dir(_):
                return (-g, st.hist_y * 0, st.hist_s * 0, jnp.int32(0),
                        jnp.int32(0), jnp.asarray(1.0, dt),
                        st.running_avg * 0, st.running_avg_sq * 0, st.alphabar)

            def lbfgs_dir(_):
                y = g - st.prev_grad
                s = st.d * st.t
                if cfg.batch_mode:
                    y = y + lm0 * s             # trust region (:594-595)
                ys = _dot(y, s)
                sn2 = _dot(s, s)
                batch_changed = jnp.asarray(
                    cfg.batch_mode, bool) & (n_iter == 1) & (total > 1)

                # online inter-batch grad mean/variance (:601-615)
                def upd_stats(_):
                    g_old = g - st.running_avg
                    avg = st.running_avg + g_old / total.astype(dt)
                    g_new = g - avg
                    avg_sq = st.running_avg_sq + g_new * g_old
                    alphabar = 1.0 / (1.0 + jnp.sum(avg_sq)
                                      / ((total - 1).astype(dt) * grad_nrm))
                    return avg, avg_sq, alphabar

                def keep_stats(_):
                    return st.running_avg, st.running_avg_sq, st.alphabar

                avg, avg_sq, alphabar = lax.cond(
                    batch_changed, upd_stats, keep_stats, None)

                # curvature-pair memory (:618-630)
                store = (ys > 1e-10 * sn2) & ~batch_changed

                def do_push(_):
                    hy, hs, hl, hh = self._push(
                        st.hist_y, st.hist_s, st.hist_len, st.hist_head, y, s)
                    return hy, hs, hl, hh, ys / _dot(y, y)

                def no_push(_):
                    return (st.hist_y, st.hist_s, st.hist_len, st.hist_head,
                            st.H_diag)

                hy, hs, hl, hh, H_diag = lax.cond(store, do_push, no_push, None)
                d = self._two_loop(g, hy, hs, hl, hh, H_diag)
                return d, hy, hs, hl, hh, H_diag, avg, avg_sq, alphabar

            d, hy, hs, hl, hh, H_diag, avg, avg_sq, alphabar = lax.cond(
                first, first_dir, lbfgs_dir, None)

            prev_grad, prev_loss = g, loss

            # ---- step length (:672-675)
            t = jnp.where(first,
                          jnp.minimum(jnp.asarray(1.0, dt), 1.0 / abs_sum) * lr,
                          lr)
            gtd = _dot(g, d)

            ls_evals = jnp.int32(0)
            if cfg.line_search_fn:
                if cfg.batch_mode:
                    t_ls, n_ls = self._backtrack(loss_fn, x, d, g, alphabar,
                                                 loss)
                else:
                    # full-batch cubic strong-Wolfe (:695-696); phi_0 is
                    # the current loss and gphi_0 the exact g.d, replacing
                    # the reference's closure + central-difference probes
                    t_ls, n_ls = self._cubic_search(loss_fn, x, d, loss, gtd)
                t = jnp.where(jnp.isnan(t_ls), lr, t_ls)   # (:701-703)
                ls_evals = n_ls

            x = x + t * d                                   # _add_grad (:704)

            # ---- re-eval unless last inner iteration (:713-721)
            last = n_iter == cfg.max_iter

            def reval(_):
                l2, g2 = vg(x)
                return l2, g2, jnp.sum(jnp.abs(g2)), jnp.int32(1)

            def keep(_):
                return loss, g, abs_sum, jnp.int32(0)

            loss, g, abs_sum, re = lax.cond(last, keep, reval, None)
            # the max_eval budget counts only closure re-evals (reference
            # current_evals, :544, :727-729); line-search trials are tracked
            # in func_evals stats only (:195)
            evals = evals + re

            # ---- break conditions (:731-747)
            done = (jnp.isnan(abs_sum)
                    | (evals >= cfg._max_eval())
                    | (abs_sum <= cfg.tolerance_grad)
                    | (gtd > -cfg.tolerance_change)
                    | (jnp.sum(jnp.abs(t * d)) <= cfg.tolerance_change)
                    | (jnp.abs(loss - prev_loss) < cfg.tolerance_change))

            # step-entry closure #1 is already counted at state._replace
            # above; per-iteration evals are the optional re-eval plus the
            # line-search trials (reference current_evals/:544/:725-726)
            st = LBFGSState(
                n_iter_total=total,
                func_evals=st.func_evals + re + ls_evals,
                d=d, t=t, hist_y=hy, hist_s=hs, hist_len=hl, hist_head=hh,
                H_diag=H_diag, prev_grad=prev_grad,
                prev_loss=jnp.asarray(prev_loss, dt),
                running_avg=avg, running_avg_sq=avg_sq, alphabar=alphabar)
            return (x, g, loss, abs_sum, n_iter, evals, done, st)

        init = (x, g0, loss0, abs_sum0, jnp.int32(0), jnp.int32(1),
                abs_sum0 <= cfg.tolerance_grad, st)
        x, g, loss, abs_sum, n_iter, evals, done, st = lax.while_loop(
            cond, body, init)
        # reference returns the loss of the FIRST closure call (:536, :765)
        return x, st, loss0
