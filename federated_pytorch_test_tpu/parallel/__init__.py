"""Device-mesh parallelism: the client axis and federated collectives.

The reference's "distributed" layer is a sequential Python loop over a dict of
models with in-memory tensor averaging (federated_multi.py:168, :208-211) —
there is no communication backend at all (SURVEY.md section 2).  Here the K
federated clients live on a ``jax.sharding.Mesh`` axis ``'clients'``; parameter
exchange is ``lax.pmean``/``psum`` riding ICI (DCN across slices on multi-host,
same code), and the bandwidth-proportional-to-active-block property is kept by
exchanging only the masked flat block vector.
"""

from federated_pytorch_test_tpu.parallel.mesh import (  # noqa: F401
    CLIENT_AXIS,
    client_mesh,
    client_sharding,
    fetch,
    initialize_multihost,
    local_client_rows,
    replicated_sharding,
    shard_clients,
    stage_client_rows,
    stage_global,
    stage_tree_global,
)
from federated_pytorch_test_tpu.parallel.comm import (  # noqa: F401
    all_clients_dot,
    federated_mean,
    federated_sum,
)
