"""Federated collectives — the communication backend.

Replaces the reference's Python accumulation loop ``znew += x_dict[ck];
znew /= K`` (federated_multi.py:208-211) with XLA collectives over the
``'clients'`` mesh axis.  These helpers are designed to be called *inside*
``shard_map``: each device holds a local block of ``K_local = K / D`` clients
stacked on the leading axis; a "federated" reduction is a local reduction over
that axis followed by a ``lax.psum`` across the mesh.

Exchanging only the masked flat block vector (see utils/codec.py) keeps the
communicated bytes proportional to the active block — the reference's core
bandwidth-reduction claim (README.md:2).

These helpers are pure functions of their operands (no aliasing, no
captured arrays), which is what lets the engine donate the buffers feeding
them: under ``--fused-rounds`` the same bodies run inside the one fused
round dispatch (train/engine.py ``_build_fused``) with the client state
and block vars donated, and XLA is free to reuse the input HBM in place.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from federated_pytorch_test_tpu.ops.comm_kernels import gram_matrix
from federated_pytorch_test_tpu.parallel.mesh import (    # noqa: F401
    CLIENT_AXIS, CollectiveTimeoutError, bounded_wait)
# CollectiveTimeoutError/bounded_wait re-exported here: comm.py is the
# collective entry-point module callers import, and the bounded-wait
# wrapper (parallel/mesh.py) is how a hung multi-process collective
# surfaces as a typed error instead of an infinite wedge.

#: CLI surface — drivers/common.py derives --robust-agg choices from this
#: so the flag and the factory cannot drift.
ROBUST_AGG_CHOICES = ("none", "trim", "median", "clip", "krum", "geomed")

#: Weiszfeld iterations for kind="geomed" — static so the estimator jits
#: to a fixed program; 16 is ample for the post-trim deltas we feed it.
GEOMED_ITERS = 16


def federated_sum(tree, axis_name: str = CLIENT_AXIS):
    """Sum over ALL clients: local sum over the leading axis, then psum.

    ``tree`` leaves are [K_local, ...]; the result drops the client axis.
    """
    local = jax.tree.map(lambda x: jnp.sum(x, axis=0), tree)
    return lax.psum(local, axis_name)


def federated_mean(tree, K: int, axis_name: str = CLIENT_AXIS):
    """``z = sum_k x_k / K`` — the FedAvg global update (federated_multi.py:208-211)."""
    return jax.tree.map(lambda x: x / K, federated_sum(tree, axis_name))


def per_client_norms(stack: jnp.ndarray, ref: jnp.ndarray) -> jnp.ndarray:
    """``||x_k - ref||_2`` for every local client: [K_local, n] -> [K_local].

    The client-ledger probe (obs/clients.py): computed on the exact
    tensors the round folds — before guard neutralization, so NaN/inf
    corruption stays visible per-client even when the guard rewrites
    the offending row to ``z``.  Shard-local (no collective); the
    [K_local] output rides the client-sharded out-spec to a global [K].
    """
    d = stack - ref[None, :]
    return jnp.sqrt(jnp.sum(d * d, axis=1))


def decode_stack(payloads, compressor, n: int, scratch=None) -> jnp.ndarray:
    """Dense reconstructions [K_local, n] of a client-stacked payload tree.

    Every payload leaf carries the local client axis in front (the encode
    side is vmapped the same way), so one vmap of the compressor's decode
    recovers the per-client dense vectors.

    ``scratch`` ([K_local, n], ZEROED) routes sparse decodes through
    ``Compressor.decode_into`` so the scatter-add accumulates into a
    caller-owned (typically donated) buffer instead of materializing
    fresh zeros — bitwise the same result, the base is zeros either way.
    """
    if scratch is not None:
        return jax.vmap(compressor.decode_into)(payloads, scratch)
    return jax.vmap(lambda p: compressor.decode(p, n))(payloads)


def compressed_federated_mean(payloads, compressor, n: int, K: int,
                              axis_name: str = CLIENT_AXIS, w=None,
                              scratch=None):
    """Mean over clients of the decoded payloads -> dense [n].

    Two reduction shapes, picked by the payload structure:

    - quantized/dense payloads: decode is fused into the per-device partial
      sum, so only ONE dense [n] vector per device enters the ``psum``
      (decode-after-psum: the collective never sees per-client density);
    - sparse top-k payloads ({"idx","val"}): the local clients' coordinates
      are scatter-added into a single dense accumulator (gather-then-
      scatter), then psum'd — the wire stays k-sized per client, the
      all-reduce stays one dense vector.

    ``w`` ([K_local] activity/weight vector) masks clients out of both the
    sum and the divisor (partial participation).  ``scratch`` ([n],
    ZEROED) supplies the sparse path's dense accumulator base so a caller
    threading a donated buffer avoids the fresh-zeros materialization.
    """
    if getattr(compressor, "sparse", False):
        val = payloads["val"]
        if w is not None:
            val = val * w[:, None]
        base = jnp.zeros((n,), val.dtype) if scratch is None else scratch
        local = base.at[
            payloads["idx"].reshape(-1)].add(val.reshape(-1))
    else:
        d = decode_stack(payloads, compressor, n)
        if w is not None:
            d = d * w[:, None]
        local = jnp.sum(d, axis=0)
    total = lax.psum(local, axis_name)
    if w is None:
        return total / K
    return total / lax.psum(jnp.sum(w), axis_name)


def sharded_federated_mean(stack, w=None, *, K: int, D: int,
                           axis_name: str = CLIENT_AXIS) -> jnp.ndarray:
    """Cross-replica sharded server update (arXiv:2004.13336) — the
    ``--sharded-update`` drop-in for the plain psum mean.

    The replicated formulation makes every device reduce and divide the
    FULL [N] consensus vector; here each device owns a 1/D segment:
    ``psum_scatter`` sums while scattering (each device receives only its
    segment of the global sum), the weighted divide runs on the owned
    shard, and a tiled ``all_gather`` re-replicates the result for the
    algorithm updates downstream.  Same wire volume as psum (reduce-
    scatter + all-gather IS how XLA lowers an all-reduce) but 1/D of the
    update arithmetic and reduction memory per chip — the win 2004.13336
    reports for replicated weight-update state, which is exactly what
    z/y/rho are.  Result is allclose to the replicated mean, NOT bitwise
    (a different reduction association order); see PARITY.md.

    ``stack`` is the client-stacked [K_local, N] flat block inside
    ``shard_map``; ``w`` follows the ``_active_mean`` contract
    (train/algorithms.py): ``None`` divides by ``K``, else by the psum'd
    weight total with the all-rejected round mapped to the zero vector.
    """
    n = stack.shape[-1]
    if w is None:
        local = jnp.sum(stack, axis=0)
        div = jnp.float32(K)
    else:
        # all-rejected rounds need no special case: every w row is 0, so
        # the scattered sum is already the zero vector and div stays 1
        local = jnp.sum(w[:, None] * stack, axis=0)
        n_act = lax.psum(jnp.sum(w), axis_name)
        div = jnp.where(n_act > 0, n_act, 1.0)
    if D == 1:
        return local / div
    seg = -(-n // D)
    buf = jnp.pad(local, (0, D * seg - n))
    shard = lax.psum_scatter(buf, axis_name, scatter_dimension=0,
                             tiled=True)
    out = lax.all_gather(shard / div, axis_name, tiled=True)
    return out[:n]


def robust_federated_mean(x: jnp.ndarray, w=None, *, kind: str,
                          trim_frac: float = 0.1, clip_mult: float = 3.0,
                          axis_name: str = CLIENT_AXIS) -> jnp.ndarray:
    """Byzantine-robust drop-in for the plain ``psum`` mean.

    ``x`` is the client-stacked flat stack ``[K_local, N]`` inside
    ``shard_map``; the result is the replicated robust aggregate ``[N]``.
    All three estimators start from a FIXED-SHAPE ``all_gather`` of the
    client axis (the [K, N] stack lands on every device), so they jit on
    the virtual mesh and on hardware alike — no data-dependent shapes.

    Kinds and what they tolerate (``m`` = active clients this round):

    - ``trim``: coordinate-wise trimmed mean, dropping the
      ``t = floor(trim_frac * m)`` largest and smallest values per
      coordinate.  Breakdown point: up to ``t`` arbitrarily corrupted
      clients per coordinate, i.e. attacker fraction < ``trim_frac``
      (and ``trim_frac`` must stay < 1/2 or nothing is left).
    - ``median``: coordinate-wise median — the ``trim_frac -> 1/2``
      limit, breakdown point just under ``m/2`` corrupted clients, at
      the price of higher variance on honest rounds.
    - ``clip``: norm-clipped mean — every client vector is rescaled to
      at most ``clip_mult x`` the median active norm, then plainly
      averaged.  Bounds the damage of a scaled (magnitude) attack to a
      ``clip_mult``-sized pull; does NOT defend against direction-only
      attacks (sign flips survive with unit scale).
    - ``krum``: multi-Krum selection (Blanchard et al., NeurIPS'17) —
      each client is scored by the summed squared distance to its
      ``m - f - 2`` nearest active neighbours with ``f = floor(
      trim_frac * m)`` the assumed attacker count, and the ``m - f``
      best-scored clients are averaged.  Selection is per-CLIENT, not
      per-coordinate, so coordinated colluders (identical copies that
      out-vote trim/median coordinate-wise) are discarded whole as
      long as ``f`` covers the colluding subset... with the standard
      caveat that a large enough identical cluster is also maximally
      mutually-near; keep ``trim_frac`` above the colluding fraction.
    - ``geomed``: geometric median via ``GEOMED_ITERS`` fixed
      Weiszfeld iterations from the weighted-mean start.  Rotation-
      invariant breakdown point 1/2 in the per-client (not per-
      coordinate) sense — the minimiser of summed distances cannot be
      dragged far by any minority, coordinated or not.

    Defensive by construction against non-finite updates: a client row
    containing any NaN/Inf is folded out of the weight vector entirely
    (it cannot be ranked), so a poisoned update never reaches the sort
    or the sum.  ``w`` ([K_local] activity weights — 0/1 masks, or
    fractional staleness weights under ``--async-rounds``) masks
    participation the same way; inactive rows are keyed to ``+inf`` and
    excluded by the dynamic trim window, never multiplied (``0 * inf``
    would manufacture the NaN this function exists to stop).  Rank
    windows (trim/median/krum) count rows with ``w > 0`` — a
    downweighted straggler still occupies one rank slot — while the
    surviving rows are averaged with their actual weights, so for 0/1
    weights every estimator is bit-identical to the unweighted form.
    An all-rejected round returns the zero vector — the engine's guard
    layer (train/engine.py) carries ``z`` over in that case.
    """
    if kind not in ROBUST_AGG_CHOICES[1:]:
        raise ValueError(f"unknown robust aggregation {kind!r}; expected "
                         f"one of {ROBUST_AGG_CHOICES[1:]}")
    xg = lax.all_gather(x, axis_name, tiled=True)            # [K, N]
    K = xg.shape[0]
    if w is None:
        wg = jnp.ones((K,), xg.dtype)
    else:
        wg = lax.all_gather(w, axis_name, tiled=True)        # [K]
    finite = jax.vmap(lambda v: jnp.all(jnp.isfinite(v)))(xg)
    wg = wg * finite.astype(xg.dtype)
    act = wg > 0
    m = jnp.sum(act.astype(xg.dtype))                        # active count
    wsum = jnp.sum(wg)                                       # active weight

    if kind == "clip":
        safe = jnp.where(finite[:, None], xg, 0.0)
        nrm = jax.vmap(jnp.linalg.norm)(safe)
        c = clip_mult * _masked_median(nrm, wg)
        scl = jnp.where(nrm > c, c / jnp.maximum(nrm, 1e-30), 1.0)
        clipped = jnp.where(act[:, None], wg[:, None] * safe * scl[:, None],
                            0.0)
        return jnp.sum(clipped, axis=0) / jnp.where(wsum > 0, wsum, 1.0)

    if kind == "krum":
        safe = jnp.where(act[:, None], xg, 0.0)
        sq = jnp.sum(safe * safe, axis=1)
        d2 = jnp.maximum(sq[:, None] + sq[None, :] - 2.0 * (safe @ safe.T),
                         0.0)                                # [K, K]
        # self-distances and inactive columns can never be neighbours
        d2 = jnp.where(jnp.eye(K, dtype=bool) | ~act[None, :], jnp.inf, d2)
        f = jnp.floor(trim_frac * m)
        n_nb = jnp.maximum(m - f - 2.0, 1.0)
        posr = jnp.arange(K, dtype=xg.dtype)[None, :]
        ds = jnp.sort(d2, axis=1)
        score = jnp.sum(jnp.where(posr < n_nb, ds, 0.0), axis=1)
        # m == 1 leaves a lone client with no finite neighbour: clamp its
        # +inf score so the selection below still picks it
        score = jnp.where(act, jnp.minimum(score, 1e30), jnp.inf)
        idx = jnp.arange(K)
        better = ((score[None, :] < score[:, None])
                  | ((score[None, :] == score[:, None])
                     & (idx[None, :] < idx[:, None])))
        rank = jnp.sum(better.astype(xg.dtype), axis=1)
        sel = (rank < jnp.maximum(m - f, 1.0)) & act
        num = jnp.sum(jnp.where(sel[:, None], wg[:, None] * safe, 0.0),
                      axis=0)
        den = jnp.sum(jnp.where(sel, wg, 0.0))
        return num / jnp.where(den > 0, den, 1.0)

    if kind == "geomed":
        safe = jnp.where(act[:, None], xg, 0.0)
        v0 = (jnp.sum(safe * wg[:, None], axis=0)
              / jnp.where(wsum > 0, wsum, 1.0))

        def _weiszfeld(v, _):
            r = jnp.sqrt(jnp.sum((safe - v[None, :]) ** 2, axis=1))
            inv = wg / jnp.maximum(r, 1e-8)
            den = jnp.sum(inv)
            vn = (jnp.sum(safe * inv[:, None], axis=0)
                  / jnp.where(den > 0, den, 1.0))
            return vn, None

        v, _ = lax.scan(_weiszfeld, v0, None, length=GEOMED_ITERS)
        return v

    # sort-based estimators: key inactive/non-finite rows to +inf so the
    # active values occupy the first m sorted positions per coordinate
    key = jnp.where(act[:, None], xg, jnp.inf)
    order = jnp.argsort(key, axis=0)                         # [K, N]
    s = jnp.take_along_axis(key, order, axis=0)
    sw = jnp.take_along_axis(
        jnp.broadcast_to(wg[:, None], key.shape), order, axis=0)
    pos = jnp.arange(K, dtype=xg.dtype)[:, None]
    if kind == "median":
        lo = jnp.floor((m - 1.0) / 2.0)
        hi = jnp.floor(m / 2.0)
        # & (pos < m): at m == 0 the lo/hi window would otherwise pick
        # position 0 — a +inf key — instead of the documented zero vector
        inc = ((pos == lo) | (pos == hi)) & (pos < m)
    else:                                                    # trim
        t = jnp.floor(trim_frac * m)
        inc = (pos >= t) & (pos < m - t)
    den = jnp.sum(jnp.where(inc, sw, 0.0), axis=0)
    return (jnp.sum(jnp.where(inc, sw * s, 0.0), axis=0)
            / jnp.where(den > 0, den, 1.0))


def robust_federated_mean_chunked(x: jnp.ndarray, w=None, *, kind: str,
                                  trim_frac: float = 0.1,
                                  clip_mult: float = 3.0, D: int,
                                  axis_name: str = CLIENT_AXIS
                                  ) -> jnp.ndarray:
    """Segment-owned robust aggregation: the ``--robust-chunked`` path.

    :func:`robust_federated_mean` starts from ``all_gather`` — every
    device materializes the full ``[K, N]`` client matrix, which is the
    single largest temporary of the comm program (the exact buffer
    ISSUE 11 eliminated from the *plain* mean via ``psum_scatter``).
    Here one tiled ``all_to_all`` transposes ownership instead: device
    ``d`` receives column segment ``d`` of every client's vector — a
    ``[K, ceil(N/D)]`` slab, ``1/D`` of the gathered matrix — computes
    the robust estimate for its own coordinates, and one tiled
    ``all_gather`` of the ``[seg]`` results re-replicates the ``[N]``
    aggregate.  Same wire volume as the gather (every element still
    crosses the wire once, plus the small result gather); ``1/D`` the
    peak working set — gated by compiled ``memory_analysis``
    ``peak_device_bytes`` in the tests, not prose.

    Per-kind determinism contract vs the dense path (PARITY.md):

    - ``trim`` / ``median`` are coordinate-wise: each coordinate sees
      the identical K values, sort and window arithmetic included, so
      the chunked result is **bitwise** the dense result.
    - ``clip`` / ``geomed`` reduce per-client norms across the segment
      axis via ``psum`` (re-associated sums), and ``krum`` accumulates
      its Gram matrix per segment (through the
      ``ops/comm_kernels.gram_matrix`` dispatch on top) — allclose,
      not bitwise.

    The non-finite screen is exact, not approximated: per-segment
    non-finite counts are psum'd, so a client with a NaN anywhere in
    its row is folded out on every device, exactly as the dense path's
    full-row ``isfinite`` scan.  ``krum``'s distance pass reads
    ``sq_i = G_ii`` off the psum'd Gram diagonal instead of a separate
    norm pass — one streamed kernel feeds both the norms and the
    cross-terms (the "fused guard + distance" shape of the ISSUE).
    """
    if kind not in ROBUST_AGG_CHOICES[1:]:
        raise ValueError(f"unknown robust aggregation {kind!r}; expected "
                         f"one of {ROBUST_AGG_CHOICES[1:]}")
    n = x.shape[-1]
    if D <= 1:
        # single device: the "gathered" matrix IS the local stack; the
        # dense program is already minimal
        return robust_federated_mean(x, w, kind=kind, trim_frac=trim_frac,
                                     clip_mult=clip_mult,
                                     axis_name=axis_name)
    seg = -(-n // D)
    xp = jnp.pad(x, ((0, 0), (0, D * seg - n)))
    # tiled all_to_all: split the (padded) coordinate axis D ways, land
    # the pieces on the client axis — rows stay in global client order
    # (source-device-major, the all_gather ordering)
    xs = lax.all_to_all(xp, axis_name, split_axis=1, concat_axis=0,
                        tiled=True)                          # [K, seg]
    K = xs.shape[0]
    if w is None:
        wg = jnp.ones((K,), xs.dtype)
    else:
        wg = lax.all_gather(w, axis_name, tiled=True)        # [K]
    nonfinite = jnp.sum((~jnp.isfinite(xs)).astype(xs.dtype), axis=1)
    finite = lax.psum(nonfinite, axis_name) == 0
    wg = wg * finite.astype(xs.dtype)
    act = wg > 0
    m = jnp.sum(act.astype(xs.dtype))
    wsum = jnp.sum(wg)

    def _replicate(seg_result):
        return lax.all_gather(seg_result, axis_name, tiled=True)[:n]

    if kind == "clip":
        safe = jnp.where(finite[:, None], xs, 0.0)
        sq = lax.psum(jnp.sum(safe * safe, axis=1), axis_name)
        nrm = jnp.sqrt(sq)
        c = clip_mult * _masked_median(nrm, wg)
        scl = jnp.where(nrm > c, c / jnp.maximum(nrm, 1e-30), 1.0)
        clipped = jnp.where(act[:, None], wg[:, None] * safe * scl[:, None],
                            0.0)
        out = jnp.sum(clipped, axis=0) / jnp.where(wsum > 0, wsum, 1.0)
        return _replicate(out)

    if kind == "krum":
        safe = jnp.where(act[:, None], xs, 0.0)
        g = lax.psum(gram_matrix(safe), axis_name)           # [K, K]
        sq = jnp.diagonal(g)
        d2 = jnp.maximum(sq[:, None] + sq[None, :] - 2.0 * g, 0.0)
        d2 = jnp.where(jnp.eye(K, dtype=bool) | ~act[None, :], jnp.inf, d2)
        f = jnp.floor(trim_frac * m)
        n_nb = jnp.maximum(m - f - 2.0, 1.0)
        posr = jnp.arange(K, dtype=xs.dtype)[None, :]
        ds = jnp.sort(d2, axis=1)
        score = jnp.sum(jnp.where(posr < n_nb, ds, 0.0), axis=1)
        score = jnp.where(act, jnp.minimum(score, 1e30), jnp.inf)
        idx = jnp.arange(K)
        better = ((score[None, :] < score[:, None])
                  | ((score[None, :] == score[:, None])
                     & (idx[None, :] < idx[:, None])))
        rank = jnp.sum(better.astype(xs.dtype), axis=1)
        sel = (rank < jnp.maximum(m - f, 1.0)) & act
        num = jnp.sum(jnp.where(sel[:, None], wg[:, None] * safe, 0.0),
                      axis=0)
        den = jnp.sum(jnp.where(sel, wg, 0.0))
        return _replicate(num / jnp.where(den > 0, den, 1.0))

    if kind == "geomed":
        safe = jnp.where(act[:, None], xs, 0.0)
        v0 = (jnp.sum(safe * wg[:, None], axis=0)
              / jnp.where(wsum > 0, wsum, 1.0))

        def _weiszfeld(v, _):
            part = jnp.sum((safe - v[None, :]) ** 2, axis=1)
            r = jnp.sqrt(lax.psum(part, axis_name))
            inv = wg / jnp.maximum(r, 1e-8)
            den = jnp.sum(inv)
            vn = (jnp.sum(safe * inv[:, None], axis=0)
                  / jnp.where(den > 0, den, 1.0))
            return vn, None

        v, _ = lax.scan(_weiszfeld, v0, None, length=GEOMED_ITERS)
        return _replicate(v)

    # trim/median: identical per-coordinate arithmetic on the segment's
    # columns — bitwise the dense path for every owned coordinate
    key = jnp.where(act[:, None], xs, jnp.inf)
    order = jnp.argsort(key, axis=0)
    s = jnp.take_along_axis(key, order, axis=0)
    sw = jnp.take_along_axis(
        jnp.broadcast_to(wg[:, None], key.shape), order, axis=0)
    pos = jnp.arange(K, dtype=xs.dtype)[:, None]
    if kind == "median":
        lo = jnp.floor((m - 1.0) / 2.0)
        hi = jnp.floor(m / 2.0)
        inc = ((pos == lo) | (pos == hi)) & (pos < m)
    else:                                                    # trim
        t = jnp.floor(trim_frac * m)
        inc = (pos >= t) & (pos < m - t)
    den = jnp.sum(jnp.where(inc, sw, 0.0), axis=0)
    out = (jnp.sum(jnp.where(inc, sw * s, 0.0), axis=0)
           / jnp.where(den > 0, den, 1.0))
    return _replicate(out)


def robust_gather_bytes(kind: str, K: int, n: int, D: int,
                        chunked: bool) -> int:
    """Per-device bytes of the robust-agg gathered working set — the
    pure-python byte model behind the bench smoke prediction (the
    compiled ``memory_analysis`` gate lives in the tests).

    Dense: the ``[K, N]`` f32 all-gathered matrix.  Chunked: the
    ``[K, ceil(N/D)]`` f32 segment slab (krum's psum'd ``[K, K]`` Gram
    block rides along — it is what replaces the matrix product over the
    full rows)."""
    if kind == "none":
        return 0
    if not chunked or D <= 1:
        return 4 * K * n
    seg = -(-n // D)
    extra = 4 * K * K if kind == "krum" else 0
    return 4 * K * seg + extra


def _masked_median(v: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """Median of ``v`` [K] over entries with ``w > 0`` (replicated input)."""
    m = jnp.sum(w)
    s = jnp.sort(jnp.where(w > 0, v, jnp.inf))
    pos = jnp.arange(v.shape[0], dtype=v.dtype)
    lo = jnp.floor((m - 1.0) / 2.0)
    hi = jnp.floor(m / 2.0)
    inc = ((pos == lo) | (pos == hi)) & (pos < m)
    return jnp.sum(jnp.where(inc, s, 0.0)) / jnp.maximum(jnp.sum(inc), 1.0)


def make_robust_mean(kind: str, *, trim_frac: float = 0.1,
                     clip_mult: float = 3.0, axis_name: str = CLIENT_AXIS,
                     chunked: bool = False, D: int = 1):
    """Factory behind ``--robust-agg`` (choices = ``ROBUST_AGG_CHOICES``).

    Returns ``None`` for ``"none"`` (the algorithms then keep their
    LITERAL plain-mean path — reference parity), else a ``(stack, w) ->
    aggregate`` callable handed to ``Algorithm.global_update`` as
    ``mean_fn``.  ``trim_frac`` doubles as krum's assumed attacker
    fraction ``f/m``.  Validated here so a bad flag fails at trainer
    construction, not mid-run inside jit.

    ``chunked=True`` selects :func:`robust_federated_mean_chunked`
    (``--robust-chunked``): segment-owned estimation that never
    materializes the ``[K, N]`` gathered matrix; ``D`` is the mesh
    size, so the engine re-invokes this factory once the mesh exists.
    """
    if kind not in ROBUST_AGG_CHOICES:
        raise ValueError(f"unknown robust aggregation {kind!r}; expected "
                         f"one of {ROBUST_AGG_CHOICES}")
    if kind == "none":
        if chunked:
            raise ValueError(
                "--robust-chunked needs a robust estimator; it re-shapes "
                "robust aggregation and has no effect on the plain mean "
                "(use --robust-agg trim/median/clip/krum/geomed)")
        return None
    if not 0.0 <= trim_frac < 0.5:
        raise ValueError(f"trim_frac={trim_frac} must be in [0, 0.5) "
                         "(trimming half or more leaves nothing to average)")
    if clip_mult <= 0.0:
        raise ValueError(f"clip_mult={clip_mult} must be positive")
    if chunked:
        return functools.partial(robust_federated_mean_chunked, kind=kind,
                                 trim_frac=trim_frac, clip_mult=clip_mult,
                                 D=D, axis_name=axis_name)
    return functools.partial(robust_federated_mean, kind=kind,
                             trim_frac=trim_frac, clip_mult=clip_mult,
                             axis_name=axis_name)


def all_clients_dot(a: jnp.ndarray, b: jnp.ndarray,
                    axis_name: str = CLIENT_AXIS) -> jnp.ndarray:
    """``sum_k <a_k, b_k>`` summed over ALL clients, for [K_local, N] stacks.

    Note the BB inner products (consensus_multi.py:248-256) are *per-client*
    — see train/algorithms.py bb_rho_update — so they do NOT use this; this
    is the collective for globally-summed dots (e.g. global penalty norms).
    """
    local = jnp.sum(a * b)
    return lax.psum(local, axis_name)
