"""Federated collectives — the communication backend.

Replaces the reference's Python accumulation loop ``znew += x_dict[ck];
znew /= K`` (federated_multi.py:208-211) with XLA collectives over the
``'clients'`` mesh axis.  These helpers are designed to be called *inside*
``shard_map``: each device holds a local block of ``K_local = K / D`` clients
stacked on the leading axis; a "federated" reduction is a local reduction over
that axis followed by a ``lax.psum`` across the mesh.

Exchanging only the masked flat block vector (see utils/codec.py) keeps the
communicated bytes proportional to the active block — the reference's core
bandwidth-reduction claim (README.md:2).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from federated_pytorch_test_tpu.parallel.mesh import CLIENT_AXIS


def federated_sum(tree, axis_name: str = CLIENT_AXIS):
    """Sum over ALL clients: local sum over the leading axis, then psum.

    ``tree`` leaves are [K_local, ...]; the result drops the client axis.
    """
    local = jax.tree.map(lambda x: jnp.sum(x, axis=0), tree)
    return lax.psum(local, axis_name)


def federated_mean(tree, K: int, axis_name: str = CLIENT_AXIS):
    """``z = sum_k x_k / K`` — the FedAvg global update (federated_multi.py:208-211)."""
    return jax.tree.map(lambda x: x / K, federated_sum(tree, axis_name))


def all_clients_dot(a: jnp.ndarray, b: jnp.ndarray,
                    axis_name: str = CLIENT_AXIS) -> jnp.ndarray:
    """``sum_k <a_k, b_k>`` summed over ALL clients, for [K_local, N] stacks.

    Note the BB inner products (consensus_multi.py:248-256) are *per-client*
    — see train/algorithms.py bb_rho_update — so they do NOT use this; this
    is the collective for globally-summed dots (e.g. global penalty norms).
    """
    local = jnp.sum(a * b)
    return lax.psum(local, axis_name)
