"""Federated collectives — the communication backend.

Replaces the reference's Python accumulation loop ``znew += x_dict[ck];
znew /= K`` (federated_multi.py:208-211) with XLA collectives over the
``'clients'`` mesh axis.  These helpers are designed to be called *inside*
``shard_map``: each device holds a local block of ``K_local = K / D`` clients
stacked on the leading axis; a "federated" reduction is a local reduction over
that axis followed by a ``lax.psum`` across the mesh.

Exchanging only the masked flat block vector (see utils/codec.py) keeps the
communicated bytes proportional to the active block — the reference's core
bandwidth-reduction claim (README.md:2).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from federated_pytorch_test_tpu.parallel.mesh import CLIENT_AXIS


def federated_sum(tree, axis_name: str = CLIENT_AXIS):
    """Sum over ALL clients: local sum over the leading axis, then psum.

    ``tree`` leaves are [K_local, ...]; the result drops the client axis.
    """
    local = jax.tree.map(lambda x: jnp.sum(x, axis=0), tree)
    return lax.psum(local, axis_name)


def federated_mean(tree, K: int, axis_name: str = CLIENT_AXIS):
    """``z = sum_k x_k / K`` — the FedAvg global update (federated_multi.py:208-211)."""
    return jax.tree.map(lambda x: x / K, federated_sum(tree, axis_name))


def decode_stack(payloads, compressor, n: int) -> jnp.ndarray:
    """Dense reconstructions [K_local, n] of a client-stacked payload tree.

    Every payload leaf carries the local client axis in front (the encode
    side is vmapped the same way), so one vmap of the compressor's decode
    recovers the per-client dense vectors.
    """
    return jax.vmap(lambda p: compressor.decode(p, n))(payloads)


def compressed_federated_mean(payloads, compressor, n: int, K: int,
                              axis_name: str = CLIENT_AXIS, w=None):
    """Mean over clients of the decoded payloads -> dense [n].

    Two reduction shapes, picked by the payload structure:

    - quantized/dense payloads: decode is fused into the per-device partial
      sum, so only ONE dense [n] vector per device enters the ``psum``
      (decode-after-psum: the collective never sees per-client density);
    - sparse top-k payloads ({"idx","val"}): the local clients' coordinates
      are scatter-added into a single dense accumulator (gather-then-
      scatter), then psum'd — the wire stays k-sized per client, the
      all-reduce stays one dense vector.

    ``w`` ([K_local] activity/weight vector) masks clients out of both the
    sum and the divisor (partial participation).
    """
    if getattr(compressor, "sparse", False):
        val = payloads["val"]
        if w is not None:
            val = val * w[:, None]
        local = jnp.zeros((n,), val.dtype).at[
            payloads["idx"].reshape(-1)].add(val.reshape(-1))
    else:
        d = decode_stack(payloads, compressor, n)
        if w is not None:
            d = d * w[:, None]
        local = jnp.sum(d, axis=0)
    total = lax.psum(local, axis_name)
    if w is None:
        return total / K
    return total / lax.psum(jnp.sum(w), axis_name)


def all_clients_dot(a: jnp.ndarray, b: jnp.ndarray,
                    axis_name: str = CLIENT_AXIS) -> jnp.ndarray:
    """``sum_k <a_k, b_k>`` summed over ALL clients, for [K_local, N] stacks.

    Note the BB inner products (consensus_multi.py:248-256) are *per-client*
    — see train/algorithms.py bb_rho_update — so they do NOT use this; this
    is the collective for globally-summed dots (e.g. global penalty norms).
    """
    local = jnp.sum(a * b)
    return lax.psum(local, axis_name)
